"""Runtime collector: in-process serving counters -> Prometheus.

TPUChannel and BatchingChannel keep their hot-path counters in plain
dicts (``stats()``) so recording costs an increment under a lock the
path already holds. Until this module, those numbers were visible only
to offline perf scripts that diffed ``stats()`` dicts by hand
(perf/profile_serving_overlap.py, perf/profile_serving_decomp.py).
``RuntimeCollector`` is the bridge:

- ``snapshot()`` / ``delta()`` — one structured read of everything
  (channel, batcher, HBM, jit compile events, error counts), used by
  the perf scripts AND by the Prometheus export, so offline and
  production read identical numbers;
- Prometheus custom collector — registered into a (per-server)
  registry, it converts each snapshot into typed gauge/counter
  families at scrape time: no background thread, no double
  bookkeeping, scrape-time consistency with ``stats()``.

Compile events ride ``jax.monitoring``: every
``.../backend_compile_duration`` event increments a process-global
counter (count + cumulative seconds), so a recompile storm — e.g. an
unbucketed shape leaking one executable per batch size — shows up as a
climbing ``tpu_serving_jit_compiles_total`` instead of mystery tail
latency.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger(__name__)

# Every family the collector always exports, name -> prometheus type.
# Families exist (HELP/TYPE lines) even when their component is absent
# or idle, so a refactor that drops a series fails the smoke test
# (tests/test_telemetry.py) instead of silently blanking a dashboard.
# Device HBM gauges are deliberately NOT here: they exist only on
# backends whose devices report memory_stats() (TPU/GPU, not CPU).
METRIC_TYPES: dict[str, str] = {
    # server / request plane
    "tpu_serving_inflight_requests": "gauge",
    "tpu_serving_request_errors_total": "counter",
    # TPUChannel staging slots
    "tpu_serving_inflight_batches": "gauge",
    "tpu_serving_staging_slots_active": "gauge",
    "tpu_serving_pipeline_depth": "gauge",
    "tpu_serving_staged_requests_total": "counter",
    "tpu_serving_launched_batches_total": "counter",
    "tpu_serving_donated_launches_total": "counter",
    "tpu_serving_stage_slot_waits_total": "counter",
    "tpu_serving_slot_occupancy_launches_total": "counter",
    # serving mesh shape (ShardedTPUChannel: batches split over the
    # data axis; 1/1 on a single-executable channel, 0 when no channel)
    "tpu_serving_data_axis_size": "gauge",
    "tpu_serving_mesh_devices": "gauge",
    # BatchingChannel formation
    "tpu_serving_queue_depth": "gauge",
    "tpu_serving_batch_active_slots": "gauge",
    "tpu_serving_batch_fill_ratio": "gauge",
    "tpu_serving_batch_merges_total": "counter",
    "tpu_serving_batched_frames_total": "counter",
    "tpu_serving_padded_frames_total": "counter",
    "tpu_serving_batch_launch_frees_total": "counter",
    "tpu_serving_merge_occupancy_total": "counter",
    # dispatcher stall watchdog (round 15): the heartbeat age and its
    # thresholded boolean — a wedged dispatcher (batcher_stall fault, a
    # hung device call) previously queued requests forever in silence
    "tpu_serving_dispatcher_stalled": "gauge",
    "tpu_serving_dispatcher_last_progress_seconds": "gauge",
    # padding-tax plane (ISSUE 8): pad_fraction is the headline share
    # of device rows that were padding; batch_occupancy is the merge
    # occupancy as a real histogram (the BENCH_r05 smear, live);
    # ragged_* count the packed-batch path where padding is replaced by
    # a segment table (pad rows there are alignment slack only)
    "tpu_serving_pad_fraction": "gauge",
    "tpu_serving_batch_occupancy": "histogram",
    "tpu_serving_ragged_batches_total": "counter",
    "tpu_serving_ragged_rows_total": "counter",
    "tpu_serving_ragged_pad_rows_total": "counter",
    # per-model precision policy + quantized param footprint (round 10:
    # a bf16/int8 registration should visibly shrink param_bytes — the
    # HBM-occupancy regression check in tests/test_precision.py)
    "tpu_serving_model_precision_info": "gauge",
    "tpu_serving_model_param_bytes": "gauge",
    # jit compile events (process-global)
    "tpu_serving_jit_compiles_total": "counter",
    "tpu_serving_jit_compile_seconds_total": "counter",
    # tracer ring buffer
    "tpu_serving_traces_finished_total": "counter",
    "tpu_serving_trace_buffered": "gauge",
    # SLO observability ring (round 11): per model x stage latency
    # histograms fed from finished trace spans, attainment counters per
    # (model, priority, outcome), the tail-exemplar ring depth, and
    # launches whose request deadline had already expired at launch time
    "tpu_serving_latency_seconds": "histogram",
    "tpu_serving_slo_requests_total": "counter",
    "tpu_serving_slo_tail_buffered": "gauge",
    "tpu_serving_deadline_expired_launches_total": "counter",
    # overload-control plane (round 12): requests deliberately shed at
    # each stage of the pipeline (admission door / bounded queue /
    # batch merge / pre-launch / breaker), per-model circuit-breaker
    # state (0 closed, 1 half-open, 2 open) and cumulative opens,
    # admission queue depth, and the drain flag orchestrators watch
    "tpu_serving_shed_total": "counter",
    "tpu_serving_breaker_state": "gauge",
    "tpu_serving_breaker_opens_total": "counter",
    "tpu_serving_admission_queue_depth": "gauge",
    "tpu_serving_draining": "gauge",
    # multi-tenant lifecycle plane (round 13): the HBM paging budget
    # and what currently occupies it (total + per tenant), model counts
    # per lifecycle state, promotion/eviction churn with the promotion
    # latency distribution (the cold-start tax a capacity plan must
    # price), per-tenant admission sheds and served frames (fair-share
    # goodput per tenant, the Gemma-comparison discipline: capacity is
    # a number per tenant at SLO)
    "tpu_serving_hbm_budget_bytes": "gauge",
    "tpu_serving_hbm_resident_bytes": "gauge",
    "tpu_serving_tenant_hbm_bytes": "gauge",
    "tpu_serving_lifecycle_models": "gauge",
    "tpu_serving_model_promotions_total": "counter",
    "tpu_serving_model_evictions_total": "counter",
    "tpu_serving_promotion_seconds": "histogram",
    "tpu_serving_tenant_shed_total": "counter",
    "tpu_serving_tenant_served_frames_total": "counter",
    # device-time attribution plane (ISSUE 11): cumulative device-
    # execute seconds per model×tenant (the standing account the trace
    # plane's device_execute spans only showed per request), the
    # rolling-window busy ratio over elapsed wall × devices, and live
    # per-model MFU against the precision policy's analytic peak — the
    # same per-chip accounting the bench records, now on the scrape
    "tpu_serving_device_seconds_total": "counter",
    "tpu_serving_device_utilization_ratio": "gauge",
    "tpu_serving_mfu": "gauge",
    # host-transport plane (round 13): which transport carried each
    # request's tensors (grpc / uds / shm / uds+shm), payload bytes by
    # path (the wire-vs-shm mix a host-gap regression shows up in
    # first), and the multi-frame stream group-size distribution
    "tpu_serving_transport_info": "gauge",
    "tpu_serving_transport_requests_total": "counter",
    "tpu_serving_wire_bytes_total": "counter",
    "tpu_serving_shm_bytes_total": "counter",
    "tpu_serving_stream_group_size": "histogram",
    # kernel-attribution plane (ISSUE 14): per-XLA-op device time over
    # the continuous sampler's last capture window (top-K by model, op,
    # fusion kind), the window length and capture/skip counters, the
    # per-model roofline placement from cost_analysis()-measured
    # flops/bytes (arithmetic intensity, binding ceiling class,
    # attainable-fps ceiling), and the metric-history ring depth
    # streaming-session plane (ISSUE 15): device-resident per-stream
    # tracker slots — live occupancy of the bounded pool, in-flight
    # session frames, slot churn (created/restarted/ended/expired/
    # LRU-reclaimed/rejected), frames advanced through session state,
    # and track births/deaths folded from device counters at scrape
    # time (per-stream device-seconds ride the device_seconds_total
    # tenant axis as stream:<id>)
    "tpu_serving_sessions_active": "gauge",
    "tpu_serving_session_slot_occupancy": "gauge",
    "tpu_serving_session_inflight_frames": "gauge",
    "tpu_serving_sessions_total": "counter",
    "tpu_serving_sessions_rejected_total": "counter",
    "tpu_serving_session_frames_total": "counter",
    "tpu_serving_track_births_total": "counter",
    "tpu_serving_track_deaths_total": "counter",
    # temporal-reuse plane (ISSUE 19): per-frame reuse decisions
    # (full detector / tracker-coast / ROI-tile partial recompute),
    # the per-stream adaptive keyframe interval, reuse auto-disables
    # (per-stream ID-churn gate, quality-plane window violations),
    # cross-camera suppressed views, and the ROI tile economy
    "tpu_serving_frames_total": "counter",
    "tpu_serving_stream_effective_k": "gauge",
    "tpu_serving_temporal_disabled_total": "counter",
    "tpu_serving_suppressed_views_total": "counter",
    "tpu_serving_partial_tiles_total": "counter",
    "tpu_serving_op_device_seconds": "gauge",
    "tpu_serving_op_sample_window_seconds": "gauge",
    "tpu_serving_op_samples_total": "counter",
    "tpu_serving_op_sample_skips_total": "counter",
    "tpu_serving_model_roofline_info": "gauge",
    "tpu_serving_model_arithmetic_intensity": "gauge",
    "tpu_serving_model_attainable_fps": "gauge",
    "tpu_serving_history_buffered": "gauge",
    # continuous quality plane (ISSUE 17): shadow-scored online
    # accuracy in rolling windows per model x served variant (mAP vs
    # the f32 reference as pseudo-GT, CenterPoint velocity MAE,
    # tracking ID-switch delta), the shadow sidecar's throughput/lag/
    # drop accounting, and the canary lifecycle (hash-sliced traffic
    # fraction, state info gauge, promote/rollback counters) — the
    # accuracy column published next to every capacity family, own
    # tpu_quality namespace so dashboards can select the plane whole
    "tpu_quality_map50": "gauge",
    "tpu_quality_map": "gauge",
    "tpu_quality_velocity_mae": "gauge",
    "tpu_quality_id_switch_rate": "gauge",
    "tpu_quality_scored_frames_total": "counter",
    "tpu_quality_shadow_lag_seconds": "gauge",
    "tpu_quality_shadow_dropped_total": "counter",
    "tpu_quality_canary_fraction": "gauge",
    "tpu_quality_canary_info": "gauge",
    "tpu_quality_promotions_total": "counter",
    "tpu_quality_rollbacks_total": "counter",
}

_HBM_KINDS = ("bytes_in_use", "bytes_limit", "peak_bytes_in_use")


class CompileEvents:
    """Process-global jit compile-event counter (jax.monitoring).

    One listener per process, installed lazily on first use; jax has no
    listener removal API short of clear_event_listeners, so the
    singleton stays for the process lifetime — which is exactly the
    scope a compile counter wants."""

    _instance: "CompileEvents | None" = None
    _install_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_seconds = 0.0

    @classmethod
    def install(cls) -> "CompileEvents":
        with cls._install_lock:
            if cls._instance is None:
                inst = cls()
                try:
                    import jax.monitoring

                    jax.monitoring.register_event_duration_secs_listener(
                        inst._on_event
                    )
                except Exception:  # jax absent/too old: counter stays 0
                    pass
                cls._instance = inst
            return cls._instance

    def _on_event(self, name: str, duration: float, **kwargs) -> None:
        # "/jax/core/compile/backend_compile_duration" fires once per
        # XLA compilation; the other /jax/core/compile/* events are
        # tracing/lowering stages we fold out to keep 1 event == 1
        # executable.
        if name.endswith("backend_compile_duration"):
            with self._lock:
                self.compiles += 1
                self.compile_seconds += float(duration)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_seconds": self.compile_seconds,
            }


def _split_channel(channel):
    """(BatchingChannel | None, TPUChannel | None) from a channel stack.

    Duck-typed: the batcher is anything with ``inner`` + ``stats``; the
    staging channel is anything with ``stats`` + ``pipeline_depth``."""
    batching, tpu, c = None, None, channel
    if c is not None and hasattr(c, "inner") and hasattr(c, "stats"):
        batching = c
        c = c.inner
    if c is not None and hasattr(c, "stats") and hasattr(c, "pipeline_depth"):
        tpu = c
    return batching, tpu


class RuntimeCollector:
    """One structured read of the serving plane's runtime state.

    Works with or without prometheus_client: ``snapshot()``/``delta()``
    are plain dicts (the perf-script API); passing ``registry=``
    additionally registers this object as a Prometheus custom collector
    whose families are generated from a snapshot at scrape time."""

    def __init__(
        self,
        channel=None,
        tracer=None,
        namespace: str = "tpu_serving",
        registry=None,
        repository=None,
        histograms=None,
        slo=None,
        admission=None,
        lifecycle=None,
        device_time=None,
    ) -> None:
        """``histograms``: an obs.histogram.HistogramFamily of per
        (model, stage) latency histograms; ``slo``: an obs.slo.
        SLOTracker; ``admission``: a runtime.admission.
        AdmissionController; ``lifecycle``: a runtime.lifecycle.
        ModelLifecycleManager; ``device_time``: an obs.device_time.
        DeviceTimeLedger. All optional — their metric families export
        empty (HELP/TYPE only) when absent, so the family inventory
        test keeps pinning the series names either way."""
        self._batching, self._tpu = _split_channel(channel)
        self._tracer = tracer
        self._repository = repository
        self._histograms = histograms
        self._slo = slo
        self._admission = admission
        self._lifecycle = lifecycle
        self._device_time = device_time
        self._ns = namespace
        self._compile = CompileEvents.install()
        self._lock = threading.Lock()
        self._inflight_requests = 0
        self._errors: dict[tuple[str, str], int] = {}
        # admission-door sheds ("model|priority|stage"); the channel
        # and batcher keep their own stage sheds, merged at snapshot
        self._shed: dict[str, int] = {}
        # host-transport mix: requests per negotiated transport label,
        # input payload bytes split wire vs shm, and the multi-frame
        # stream group-size occupancy
        self._transport_requests: dict[str, int] = {}
        self._wire_bytes = 0
        self._shm_bytes = 0
        self._stream_groups: dict[int, int] = {}
        # kernel-attribution plane: the sampler's last per-op window
        # (gauges show the latest capture; the counter accumulates) and
        # the optional sampler/history components attached post-build
        self._op_rows: list = []
        self._op_window_s = 0.0
        self._op_samples = 0
        self._sampler = None
        self._history = None
        self._quality = None
        self._temporal = None
        self._draining = False
        self._registry = None
        if registry is not None:
            registry.register(self)
            self._registry = registry

    # -- request-plane hooks (called by the server) ---------------------------

    def request_started(self) -> None:
        with self._lock:
            self._inflight_requests += 1

    def request_finished(self) -> None:
        with self._lock:
            self._inflight_requests -= 1

    def record_error(self, model: str, code: str) -> None:
        with self._lock:
            key = (model, code)
            self._errors[key] = self._errors.get(key, 0) + 1

    def record_shed(self, model: str, priority: int, stage: str) -> None:
        """One request deliberately rejected at ``stage`` (the server
        calls this for admission-door sheds; channel/batcher stages
        count their own and are merged at snapshot time)."""
        with self._lock:
            key = f"{model}|{int(priority)}|{stage}"
            self._shed[key] = self._shed.get(key, 0) + 1

    def record_transport(
        self, transport: str, wire_bytes: int, shm_bytes: int
    ) -> None:
        """One inference request's transport mix: the negotiated label
        (grpc/uds/shm/uds+shm) and how many input-payload bytes each
        path moved."""
        with self._lock:
            self._transport_requests[transport] = (
                self._transport_requests.get(transport, 0) + 1
            )
            self._wire_bytes += int(wire_bytes)
            self._shm_bytes += int(shm_bytes)

    def record_stream_group(self, size: int) -> None:
        """One packed multi-frame stream message of ``size`` frames."""
        with self._lock:
            self._stream_groups[int(size)] = (
                self._stream_groups.get(int(size), 0) + 1
            )

    def record_op_sample(self, rows, window_s: float) -> None:
        """The continuous sampler's sink: the top-K per-op rows of one
        capture window (obs.opstats.summarize row shape). Gauges export
        the LAST window; the samples counter accumulates."""
        with self._lock:
            self._op_rows = list(rows or [])
            self._op_window_s = float(window_s or 0.0)
            self._op_samples += 1

    def attach_sampler(self, sampler) -> None:
        """Wire the ContinuousSampler whose stats() (skips, duty cycle)
        this collector exports — attached after construction because the
        sampler itself takes the collector as its sink."""
        self._sampler = sampler

    def attach_history(self, history) -> None:
        """Wire the MetricHistory whose ring depth this collector
        exports."""
        self._history = history

    def attach_temporal(self, temporal) -> None:
        """Wire the temporal reuse plane (runtime/temporal.py) whose
        per-stream coast/partial/suppression decisions export as the
        ``tpu_serving_frames_total``-family metrics and land under
        ``/snapshot["temporal"]`` (ISSUE 19)."""
        self._temporal = temporal

    def attach_quality(self, quality, legacy_eval: bool = True) -> None:
        """Wire the continuous quality plane (eval/quality_plane.py)
        whose rolling windows export as the ``tpu_quality_*`` families
        and land under ``/snapshot["quality"]``.

        ``legacy_eval``: also fold the reference's eval Summaries
        (``model_precision``/``model_recall``/``model_ap``/...) into
        THIS collector's registry — the ISSUE 17 satellite retiring the
        standalone port-7658 exporter: one scrape endpoint serves both
        spellings from the same windows."""
        self._quality = quality
        if legacy_eval and self._registry is not None:
            try:
                from triton_client_tpu.eval import prometheus_export

                if prometheus_export.available():
                    quality.attach_legacy_exporter(
                        prometheus_export.EvalPrometheusExporter(
                            registry=self._registry
                        )
                    )
            except Exception:  # pragma: no cover - registry collisions
                log.debug(
                    "legacy eval summaries not folded", exc_info=True
                )

    def hlo_modules(self) -> dict[str, str]:
        """``{hlo_module: model_name}`` over every registered model —
        the op->model attribution map the sampler and /profile hand to
        obs.opstats (each spec.extra's ``hlo_module`` is recorded at
        launcher build by obs.roofline.record_launch_cost)."""
        out: dict[str, str] = {}
        if self._repository is None:
            return out
        try:
            listing = self._repository.list_models()
        except Exception:
            return out
        for name, version in listing:
            try:
                extra = self._repository.get(name, version).spec.extra
            except Exception:
                continue
            module = extra.get("hlo_module")
            if module:
                out[str(module)] = name
        return out

    def set_draining(self, draining: bool) -> None:
        with self._lock:
            self._draining = bool(draining)

    # -- snapshot API (perf scripts + scrape share this) ----------------------

    def snapshot(self) -> dict:
        with self._lock:
            inflight = self._inflight_requests
            errors = {f"{m}|{c}": n for (m, c), n in self._errors.items()}
            shed = dict(self._shed)
            draining = self._draining
            transport = {
                "requests": dict(self._transport_requests),
                "wire_bytes": self._wire_bytes,
                "shm_bytes": self._shm_bytes,
                "stream_groups": dict(self._stream_groups),
            }
            op_sample = {
                "rows": list(self._op_rows),
                "window_s": self._op_window_s,
                "samples": self._op_samples,
            }
        snap = {
            "channel": self._tpu.stats() if self._tpu is not None else None,
            "batching": (
                self._batching.stats() if self._batching is not None else None
            ),
            "inflight_requests": inflight,
            "errors": errors,
            "compile": self._compile.snapshot(),
            "memory": self._memory(),
        }
        # one shed ledger across the whole pipeline: admission-door
        # sheds (recorded here) + the queue/merge/launch/breaker stages
        # the batcher and staged channel count in their own stats()
        for src in (snap["channel"], snap["batching"]):
            for key, n in ((src or {}).get("shed") or {}).items():
                shed[key] = shed.get(key, 0) + n
        snap["shed"] = shed
        snap["draining"] = int(draining)
        snap["transport"] = transport
        if self._admission is not None:
            snap["admission"] = self._admission.stats()
        if self._lifecycle is not None:
            snap["lifecycle"] = self._lifecycle.stats()
        if self._tracer is not None:
            snap["tracer"] = self._tracer.stats()
        if self._device_time is not None:
            snap["device_time"] = self._device_time.snapshot()
        sessions = (
            getattr(self._tpu, "sessions", None)
            if self._tpu is not None
            else None
        )
        if sessions is not None:
            # stats() drains the deferred device-counter folds — the
            # only host read of tracker state, at scrape time, never on
            # the frame path
            snap["sessions"] = sessions.stats()
        snap["op_sample"] = op_sample
        if self._sampler is not None:
            snap["sampler"] = self._sampler.stats()
        if self._history is not None:
            snap["history"] = self._history.stats()
        if self._quality is not None:
            snap["quality"] = self._quality.snapshot()
        if self._temporal is not None:
            snap["temporal"] = self._temporal.stats()
        if self._histograms is not None:
            # numeric-leaved per-(model|stage) bucket counts + sum:
            # delta() of two snapshots is the WINDOW's histogram, and
            # obs.histogram.quantile_from_snapshot reads percentiles
            # off either form — perf scripts get p99 through the same
            # path as every counter
            snap["histograms"] = self._histograms.snapshot()
        if self._slo is not None:
            snap["slo"] = self._slo.stats()
        models = self._models()
        if models is not None:
            snap["models"] = models
        return snap

    def _models(self) -> list | None:
        """Per-registered-model precision + param footprint rows (round
        10), read from each ModelSpec's extra at snapshot time so a
        model reload is reflected on the next scrape."""
        if self._repository is None:
            return None
        rows = []
        try:
            listing = self._repository.list_models()
        except Exception:
            return None
        for name, version in listing:
            try:
                extra = self._repository.get(name, version).spec.extra
            except Exception:
                continue
            row = {
                "model": name,
                "version": version,
                "precision": str(extra.get("precision", "f32")),
                "param_bytes": int(extra.get("param_bytes", 0) or 0),
            }
            # roofline placement once the channel has recorded the
            # XLA-measured launch cost (obs.roofline.record_launch_cost
            # at first launch; absent until then / without a cost model)
            if extra.get("measured_flops_per_call") is not None:
                try:
                    from triton_client_tpu.obs.roofline import model_row

                    row["roofline"] = model_row(extra)
                except Exception:
                    pass
            rows.append(row)
        return rows

    @staticmethod
    def delta(new: dict, old: dict) -> dict:
        """Recursive numeric diff of two snapshots, zero/empty leaves
        dropped — the structured replacement for the hand-rolled
        ``stats()`` delta-diffing the perf scripts used to do."""

        def diff(n, o):
            if isinstance(n, dict):
                o = o if isinstance(o, dict) else {}
                out = {}
                for k, v in n.items():
                    r = diff(v, o.get(k))
                    if r not in (None, 0, 0.0, {}):
                        out[k] = r
                return out
            if isinstance(n, bool) or not isinstance(n, (int, float)):
                return None
            base = o if isinstance(o, (int, float)) and not isinstance(o, bool) else 0
            return n - base

        return diff(new, old if isinstance(old, dict) else {})

    def _memory(self) -> dict:
        """Per-device memory_stats() (HBM on TPU; None/absent on CPU)."""
        out = {}
        try:
            if self._tpu is not None:
                devices = list(self._tpu.fetch_channel().devices.flat)
            else:
                import jax

                devices = jax.local_devices()
        except Exception:
            return out
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                out[str(getattr(d, "id", d))] = {
                    k: v for k, v in ms.items() if isinstance(v, (int, float))
                }
        return out

    # -- Prometheus custom-collector protocol ---------------------------------

    def describe(self):
        # Registered as an "unchecked" collector: families are dynamic
        # (labels appear as models/depths are observed), so describe()
        # returns nothing rather than a stale inventory.
        return []

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
            HistogramMetricFamily,
        )

        snap = self.snapshot()
        chan = snap["channel"] or {}
        bat = snap["batching"] or {}
        ns = self._ns

        def gauge(name, doc, value, labels=None, samples=()):
            fam = GaugeMetricFamily(name, doc, labels=labels or [])
            if labels:
                for lv, v in samples:
                    fam.add_metric(lv, v)
            else:
                fam.add_metric([], value)
            return fam

        def counter(name, doc, value, labels=None, samples=()):
            fam = CounterMetricFamily(name, doc, labels=labels or [])
            if labels:
                for lv, v in samples:
                    fam.add_metric(lv, v)
            else:
                fam.add_metric([], value)
            return fam

        yield gauge(
            f"{ns}_inflight_requests",
            "gRPC requests currently being served",
            snap["inflight_requests"],
        )
        yield counter(
            f"{ns}_request_errors_total",
            "failed requests by model and gRPC status code",
            0,
            labels=["model", "code"],
            samples=[
                (key.split("|", 1), n) for key, n in snap["errors"].items()
            ],
        )

        # TPUChannel staging slots
        yield gauge(
            f"{ns}_inflight_batches",
            "launched, not-yet-retired device batches",
            chan.get("inflight", 0),
        )
        yield gauge(
            f"{ns}_staging_slots_active",
            "staging slots currently held (stage..retire)",
            chan.get("slots_active", 0),
        )
        yield gauge(
            f"{ns}_pipeline_depth",
            "configured staging pipeline depth",
            chan.get("pipeline_depth", 0),
        )
        yield counter(
            f"{ns}_staged_requests_total",
            "requests staged onto the device mesh",
            chan.get("staged", 0),
        )
        yield counter(
            f"{ns}_launched_batches_total",
            "device batches launched",
            chan.get("launched", 0),
        )
        yield counter(
            f"{ns}_donated_launches_total",
            "launches through the donated-buffer jit path",
            chan.get("donated_launches", 0),
        )
        yield counter(
            f"{ns}_stage_slot_waits_total",
            "stage() calls that blocked on a staging slot",
            chan.get("stage_slot_waits", 0),
        )
        yield counter(
            f"{ns}_slot_occupancy_launches_total",
            "launches observed at each in-flight depth",
            0,
            labels=["inflight"],
            samples=[
                ([str(k)], v)
                for k, v in (chan.get("slot_occupancy") or {}).items()
            ],
        )
        yield gauge(
            f"{ns}_data_axis_size",
            "mesh data-axis width request batches shard over "
            "(1 = single-executable channel, 0 = no channel)",
            chan.get("data_axis_size", 0),
        )
        yield gauge(
            f"{ns}_mesh_devices",
            "devices claimed by the serving mesh",
            chan.get("mesh_devices", 0),
        )

        # BatchingChannel formation
        queue_depth = bat.get("ready_depth", 0) + bat.get("queue_depth", 0)
        yield gauge(
            f"{ns}_queue_depth",
            "requests admitted or staged, awaiting dispatch",
            queue_depth,
        )
        yield gauge(
            f"{ns}_batch_active_slots",
            "batcher execution slots currently active",
            bat.get("active_slots", 0),
        )
        yield gauge(
            f"{ns}_dispatcher_stalled",
            "1 when the dispatch loop's heartbeat is older than the "
            "stall threshold (watchdog also logs the episode)",
            bat.get("dispatcher_stalled", 0),
        )
        yield gauge(
            f"{ns}_dispatcher_last_progress_seconds",
            "seconds since the dispatch loop last made progress",
            bat.get("dispatcher_last_progress_age_s", 0.0),
        )
        merges = bat.get("merges", 0)
        fill = 0.0
        if merges and bat.get("max_merge"):
            fill = bat.get("merged_frames", 0) / merges / bat["max_merge"]
        yield gauge(
            f"{ns}_batch_fill_ratio",
            "mean merged frames per dispatch / max_merge",
            fill,
        )
        yield counter(
            f"{ns}_batch_merges_total",
            "device batches formed at dispatch time",
            merges,
        )
        yield counter(
            f"{ns}_batched_frames_total",
            "frames merged into device batches",
            bat.get("merged_frames", 0),
        )
        by_model = bat.get("padded_by_model")
        if by_model is None and bat.get("padded_frames"):
            # a duck-typed batcher without the per-model ledger: keep
            # the total visible rather than dropping the series
            by_model = {"unknown": bat["padded_frames"]}
        yield counter(
            f"{ns}_padded_frames_total",
            "pad rows added by bucket padding, per model",
            0,
            labels=["model"],
            samples=[([m], n) for m, n in (by_model or {}).items()],
        )
        yield counter(
            f"{ns}_batch_launch_frees_total",
            "execution slots freed at launch (pre-readback)",
            bat.get("launch_frees", 0),
        )
        yield counter(
            f"{ns}_merge_occupancy_total",
            "dispatches observed at each merged frame count",
            0,
            labels=["frames"],
            samples=[
                ([str(k)], v)
                for k, v in (bat.get("merge_occupancy") or {}).items()
            ],
        )
        # the padding-tax plane (ISSUE 8): headline pad share + the
        # occupancy distribution as a real histogram, so dashboards get
        # quantiles without scraping the labeled counter above
        yield gauge(
            f"{ns}_pad_fraction",
            "share of device rows shipped as padding "
            "(dense bucket pad + ragged alignment slack)",
            bat.get("pad_fraction", 0.0),
        )
        occ_hist = HistogramMetricFamily(
            f"{ns}_batch_occupancy",
            "real frames per formed device batch",
            labels=[],
        )
        occ = {int(k): v for k, v in (bat.get("merge_occupancy") or {}).items()}
        cum, cum_buckets = 0, []
        for bound in (1, 2, 4, 8, 16, 32, 64, 128):
            cum += sum(v for k, v in occ.items() if bound / 2 < k <= bound)
            cum_buckets.append((repr(float(bound)), cum))
        cum_buckets.append(("+Inf", sum(occ.values())))
        occ_hist.add_metric(
            [], cum_buckets, float(sum(k * v for k, v in occ.items()))
        )
        yield occ_hist
        yield counter(
            f"{ns}_ragged_batches_total",
            "packed ragged batches dispatched (segment-table execution)",
            bat.get("ragged_batches", 0),
        )
        yield counter(
            f"{ns}_ragged_rows_total",
            "real rows executed through packed ragged batches",
            bat.get("ragged_rows", 0),
        )
        yield counter(
            f"{ns}_ragged_pad_rows_total",
            "alignment pad rows shipped with packed ragged batches",
            bat.get("ragged_pad_rows", 0),
        )

        # per-model precision + param footprint (empty families when no
        # repository is wired — the HELP/TYPE lines still export so the
        # telemetry smoke test pins the series names)
        models = snap.get("models") or []
        yield gauge(
            f"{ns}_model_precision_info",
            "serving precision policy per registered model (info gauge)",
            0,
            labels=["model", "version", "precision"],
            samples=[
                ([m["model"], m["version"], m["precision"]], 1)
                for m in models
            ],
        )
        yield gauge(
            f"{ns}_model_param_bytes",
            "registered parameter bytes per model (post-quantization)",
            0,
            labels=["model", "version"],
            samples=[
                ([m["model"], m["version"]], m["param_bytes"])
                for m in models
            ],
        )

        # jit compile events
        comp = snap["compile"]
        yield counter(
            f"{ns}_jit_compiles_total",
            "XLA backend compilations observed (jax.monitoring)",
            comp["compiles"],
        )
        yield counter(
            f"{ns}_jit_compile_seconds_total",
            "cumulative seconds spent in XLA backend compilation",
            comp["compile_seconds"],
        )

        # tracer ring buffer
        tr = snap.get("tracer") or {}
        yield counter(
            f"{ns}_traces_finished_total",
            "request traces finished",
            tr.get("finished", 0),
        )
        yield gauge(
            f"{ns}_trace_buffered",
            "request traces held in the export ring buffer",
            tr.get("buffered", 0),
        )

        # SLO observability ring: per model x stage latency histograms
        # (fed from finished trace spans) and attainment counters. The
        # families export even when the components are absent so the
        # series names stay pinned by the telemetry smoke test.
        lat = HistogramMetricFamily(
            f"{ns}_latency_seconds",
            "request latency per model and pipeline stage "
            "(queue_delay/merge_wait/device_execute/readback/e2e)",
            labels=["model", "stage"],
        )
        for key, h in (snap.get("histograms") or {}).items():
            model, _, stage = key.partition("|")
            cum, cum_buckets = 0, []
            for bound, c in sorted(
                (float(b), n)
                for b, n in h["buckets"].items()
                if b != "inf"
            ):
                cum += c
                cum_buckets.append((repr(bound), cum))
            cum_buckets.append(("+Inf", h["count"]))
            lat.add_metric([model, stage], cum_buckets, h["sum"])
        yield lat
        slo = snap.get("slo") or {}
        yield counter(
            f"{ns}_slo_requests_total",
            "requests scored against their latency SLO, by outcome",
            0,
            labels=["model", "priority", "outcome"],
            samples=[
                (key.split("|", 1) + [outcome], cell[outcome])
                for key, cell in (slo.get("requests") or {}).items()
                for outcome in ("met", "missed")
            ],
        )
        yield gauge(
            f"{ns}_slo_tail_buffered",
            "SLO-violating / p99+ exemplar traces held in the tail ring",
            slo.get("tail_buffered", 0),
        )
        yield counter(
            f"{ns}_deadline_expired_launches_total",
            "batches launched after their request deadline had passed",
            chan.get("deadline_expired_launches", 0),
        )

        # overload-control plane: sheds by pipeline stage, breaker
        # state machine, admission queue depth, drain flag
        yield counter(
            f"{ns}_shed_total",
            "requests deliberately rejected, by model, priority, and "
            "pipeline stage (admission/queue/merge/launch/breaker)",
            0,
            labels=["model", "priority", "stage"],
            samples=[
                (key.split("|", 2), n)
                for key, n in (snap.get("shed") or {}).items()
            ],
        )
        breaker = chan.get("breaker") or {}
        yield gauge(
            f"{ns}_breaker_state",
            "per-model circuit-breaker state "
            "(0 closed, 1 half-open, 2 open)",
            0,
            labels=["model"],
            samples=[([m], c["state"]) for m, c in breaker.items()],
        )
        yield counter(
            f"{ns}_breaker_opens_total",
            "circuit-breaker open transitions per model",
            0,
            labels=["model"],
            samples=[([m], c["opens"]) for m, c in breaker.items()],
        )
        adm = snap.get("admission") or {}
        yield gauge(
            f"{ns}_admission_queue_depth",
            "admitted-but-unfinished requests per model "
            "(the admission controller's queue-depth knee input)",
            0,
            labels=["model"],
            samples=[
                ([m], d) for m, d in (adm.get("inflight") or {}).items()
            ],
        )
        yield gauge(
            f"{ns}_draining",
            "1 while the server is draining (SIGTERM / drain())",
            snap.get("draining", 0),
        )

        # multi-tenant lifecycle plane: HBM budget/residency, lifecycle
        # state counts, promotion/eviction churn + promotion latency,
        # per-tenant sheds and served frames. Families export empty
        # when no lifecycle manager is wired.
        lc = snap.get("lifecycle") or {}
        yield gauge(
            f"{ns}_hbm_budget_bytes",
            "configured HBM paging budget (0 = unbudgeted)",
            lc.get("budget_bytes", 0),
        )
        yield gauge(
            f"{ns}_hbm_resident_bytes",
            "estimated bytes of WARM model params under the budget",
            lc.get("resident_bytes", 0),
        )
        yield gauge(
            f"{ns}_tenant_hbm_bytes",
            "resident model bytes billed to each tenant",
            0,
            labels=["tenant"],
            samples=[
                ([t], b)
                for t, b in (lc.get("tenant_resident_bytes") or {}).items()
            ],
        )
        yield gauge(
            f"{ns}_lifecycle_models",
            "registered models per lifecycle state "
            "(cold/warming/warm/evicting)",
            0,
            labels=["state"],
            samples=[([s], n) for s, n in (lc.get("states") or {}).items()],
        )
        lc_models = [
            (key.partition(":"), row)
            for key, row in (lc.get("models") or {}).items()
        ]
        yield counter(
            f"{ns}_model_promotions_total",
            "COLD -> WARM promotions per model",
            0,
            labels=["model", "version"],
            samples=[
                ([name, version], row["promotions"])
                for (name, _, version), row in lc_models
            ],
        )
        yield counter(
            f"{ns}_model_evictions_total",
            "WARM -> COLD evictions per model",
            0,
            labels=["model", "version"],
            samples=[
                ([name, version], row["evictions"])
                for (name, _, version), row in lc_models
            ],
        )
        promo = HistogramMetricFamily(
            f"{ns}_promotion_seconds",
            "COLD -> WARM promotion latency (make-room + page-in)",
            labels=[],
        )
        ph = lc.get("promotion_latency") or {"buckets": {}, "sum": 0.0,
                                             "count": 0}
        cum, cum_buckets = 0, []
        for bound, c in sorted(
            (float(b), n) for b, n in ph["buckets"].items() if b != "inf"
        ):
            cum += c
            cum_buckets.append((repr(bound), cum))
        cum_buckets.append(("+Inf", ph["count"]))
        promo.add_metric([], cum_buckets, ph["sum"])
        yield promo
        yield counter(
            f"{ns}_tenant_shed_total",
            "requests shed at the admission door per tenant "
            "(in-flight cap + per-model knees)",
            0,
            labels=["tenant"],
            samples=[
                ([t], n)
                for t, n in (adm.get("tenant_rejects") or {}).items()
            ],
        )
        yield counter(
            f"{ns}_tenant_served_frames_total",
            "frames dispatched per tenant by the fair-share scheduler",
            0,
            labels=["tenant"],
            samples=[
                ([t], n)
                for t, n in (bat.get("tenant_served_frames") or {}).items()
            ],
        )

        # device-time attribution plane: cumulative device-seconds per
        # model×tenant, rolling-window utilization, live per-model MFU
        dt = snap.get("device_time") or {}
        dt_window = dt.get("window") or {}
        yield counter(
            f"{ns}_device_seconds_total",
            "cumulative device-execute seconds per model and tenant",
            0,
            labels=["model", "tenant"],
            samples=[
                (key.split("|", 1), v)
                for key, v in (dt.get("device_seconds") or {}).items()
            ],
        )
        yield gauge(
            f"{ns}_device_utilization_ratio",
            "rolling-window busy device-seconds over elapsed wall x "
            "devices (the live device-time ceiling of ROADMAP item 1)",
            dt_window.get("utilization", 0.0),
        )
        yield gauge(
            f"{ns}_mfu",
            "live model flops utilization over the rolling window, per "
            "model (analytic flops against the precision policy peak)",
            0,
            labels=["model"],
            samples=[
                ([m], v) for m, v in (dt_window.get("mfu") or {}).items()
            ],
        )

        # streaming-session plane: the bounded slot pool's live state
        # plus churn/track counters (per-stream device-seconds already
        # ride device_seconds_total's tenant axis as stream:<id>)
        ses = snap.get("sessions") or {}
        yield gauge(
            f"{ns}_sessions_active",
            "streaming sessions currently holding a device-resident "
            "tracker slot",
            ses.get("active_sessions", 0),
        )
        yield gauge(
            f"{ns}_session_slot_occupancy",
            "active sessions over the slot pool size",
            ses.get("slot_occupancy", 0.0),
        )
        yield gauge(
            f"{ns}_session_inflight_frames",
            "session frames between launch and resolve (slot refcounts)",
            ses.get("inflight_frames", 0),
        )
        yield counter(
            f"{ns}_sessions_total",
            "session slot transitions by event (created / restarted / "
            "ended / expired / reclaimed)",
            0,
            labels=["event"],
            samples=[
                ([ev], ses.get(f"{ev}_total", 0))
                for ev in (
                    "created", "restarted", "ended", "expired", "reclaimed"
                )
            ],
        )
        yield counter(
            f"{ns}_sessions_rejected_total",
            "session frames shed because the slot pool was full and "
            "unreclaimable",
            ses.get("rejected_total", 0),
        )
        yield counter(
            f"{ns}_session_frames_total",
            "frames advanced through device-resident session state",
            ses.get("frames_total", 0),
        )
        yield counter(
            f"{ns}_track_births_total",
            "tracks born across all sessions (device counters folded at "
            "scrape/end, never on the frame path)",
            ses.get("track_births_total", 0),
        )
        yield counter(
            f"{ns}_track_deaths_total",
            "tracks retired across all sessions",
            ses.get("track_deaths_total", 0),
        )

        # temporal-reuse plane (ISSUE 19): every frame's reuse decision
        # (full detector / tracker coast / ROI-tile partial recompute),
        # the per-stream adaptive keyframe interval, reuse disables by
        # reason, cross-camera suppression, and the tile economy
        tmp = snap.get("temporal") or {}
        yield counter(
            f"{ns}_frames_total",
            "stream frames by reuse decision: full detector pass, "
            "tracker-coast, or ROI-tile partial recompute",
            0,
            labels=["mode"],
            samples=[
                (["full"], tmp.get("frames_full_total", 0)),
                (["coast"], tmp.get("frames_coast_total", 0)),
                (["partial"], tmp.get("frames_partial_total", 0)),
            ],
        )
        yield gauge(
            f"{ns}_stream_effective_k",
            "current adaptive keyframe interval per stream (frames "
            "between full detector passes; 1 = every frame)",
            0,
            labels=["stream"],
            samples=[
                ([str(sid)], k)
                for sid, k in sorted(
                    (tmp.get("effective_k") or {}).items()
                )
            ],
        )
        yield counter(
            f"{ns}_temporal_disabled_total",
            "streams/models where temporal reuse auto-disabled: "
            "per-stream ID-churn gate (churn) or quality-plane window "
            "violation (quality)",
            0,
            labels=["reason"],
            samples=[
                (["churn"], tmp.get("auto_disabled_total", 0)),
                (["quality"], tmp.get("quality_disabled_total", 0)),
            ],
        )
        yield counter(
            f"{ns}_suppressed_views_total",
            "camera views skipped because all their tracked objects "
            "project into already-processed overlap regions",
            tmp.get("suppressed_views_total", 0),
        )
        yield counter(
            f"{ns}_partial_tiles_total",
            "ROI tiles actually re-detected (selected) vs the full "
            "tile-grid size of those frames (possible)",
            0,
            labels=["kind"],
            samples=[
                (["selected"], tmp.get("partial_tiles_total", 0)),
                (["possible"], tmp.get("partial_tiles_possible_total", 0)),
            ],
        )

        # kernel-attribution plane (ISSUE 14): per-op device time over
        # the sampler's last capture window, sampler counters, and each
        # model's roofline placement from the measured launch cost
        op = snap.get("op_sample") or {}
        samp = snap.get("sampler") or {}
        yield gauge(
            f"{ns}_op_device_seconds",
            "device time per XLA op over the sampler's last capture "
            "window (top-K by time; model attributed via HLO module / "
            "launch annotations)",
            0,
            labels=["model", "op", "kind"],
            samples=[
                (
                    [
                        str(r.get("model") or "unattributed"),
                        str(r.get("op", "?")),
                        str(r.get("kind", "other")),
                    ],
                    float(r.get("time_us", 0.0)) / 1e6,
                )
                for r in (op.get("rows") or [])
            ],
        )
        yield gauge(
            f"{ns}_op_sample_window_seconds",
            "length of the sampler's last profiler capture window",
            op.get("window_s", 0.0),
        )
        yield counter(
            f"{ns}_op_samples_total",
            "profiler capture windows delivered by the continuous "
            "sampler",
            op.get("samples", 0),
        )
        yield counter(
            f"{ns}_op_sample_skips_total",
            "sampler windows skipped because /profile held the capture "
            "guard",
            samp.get("skipped_busy", 0),
        )
        roofline_rows = [
            (m, m["roofline"]) for m in models if m.get("roofline")
        ]
        yield gauge(
            f"{ns}_model_roofline_info",
            "roofline bound class per model from XLA-measured "
            "flops/bytes (info gauge: compute/bandwidth)",
            0,
            labels=["model", "version", "bound"],
            samples=[
                ([m["model"], m["version"], r["bound"]], 1)
                for m, r in roofline_rows
            ],
        )
        yield gauge(
            f"{ns}_model_arithmetic_intensity",
            "measured flops per HBM byte of one launch "
            "(XLA cost model at the serving batch)",
            0,
            labels=["model", "version"],
            samples=[
                ([m["model"], m["version"]], r["intensity"])
                for m, r in roofline_rows
                if r["intensity"] == r["intensity"]
                and r["intensity"] not in (float("inf"),)
            ],
        )
        yield gauge(
            f"{ns}_model_attainable_fps",
            "roofline-ceiling frames/s at the measured batch (the "
            "honest headroom next to the served rate)",
            0,
            labels=["model", "version"],
            samples=[
                ([m["model"], m["version"]], r["attainable_fps"])
                for m, r in roofline_rows
            ],
        )
        hist_stats = snap.get("history") or {}
        yield gauge(
            f"{ns}_history_buffered",
            "metric-history snapshots buffered in the ring",
            hist_stats.get("buffered", 0),
        )

        # continuous quality plane (ISSUE 17): per model x served
        # variant rolling-window accuracy vs the f32 shadow reference,
        # the shadow sidecar's lag/drop accounting, and the canary
        # lifecycle. Own tpu_quality namespace (not ns-prefixed): the
        # accuracy column next to every capacity family.
        q = snap.get("quality") or {}
        q_pairs = q.get("pairs") or {}

        def pair_window_samples(field):
            out = []
            for key in sorted(q_pairs):
                last = q_pairs[key].get("last")
                if last is not None and field in last:
                    out.append((key.split("|", 1), last[field]))
            return out

        for field, doc in (
            ("map50", "rolling-window online mAP@0.5 of the served "
                      "variant scored against the shadow f32 reference "
                      "as pseudo-GT (0.995 = parity ceiling)"),
            ("map", "rolling-window online mAP@[.5:.95] vs the shadow "
                    "reference"),
            ("velocity_mae", "mean |velocity| error of matched "
                             "detections vs the shadow reference "
                             "(CenterPoint velocity head; 0 on 2D)"),
            ("id_switch_rate", "excess track births per frame of the "
                               "primary tracking stream vs the shadow "
                               "reference stream (ops/tracking "
                               "reference stepping)"),
        ):
            yield gauge(
                f"tpu_quality_{field}", doc, 0,
                labels=["model", "variant"],
                samples=pair_window_samples(field),
            )
        yield counter(
            "tpu_quality_scored_frames_total",
            "sampled frames scored against the shadow reference",
            0,
            labels=["model", "variant"],
            samples=[
                (key.split("|", 1), q_pairs[key].get("scored_frames", 0))
                for key in sorted(q_pairs)
            ],
        )
        yield gauge(
            "tpu_quality_shadow_lag_seconds",
            "lag between a sampled request being served and its shadow "
            "score landing (last scored frame)",
            0,
            labels=["model", "variant"],
            samples=[
                (key.split("|", 1), q_pairs[key].get("last_lag_s", 0.0))
                for key in sorted(q_pairs)
            ],
        )
        mirror = q.get("mirror") or {}
        yield counter(
            "tpu_quality_shadow_dropped_total",
            "sampled frames dropped because the shadow queue was full "
            "(the sidecar sheds itself, never the serving path)",
            mirror.get("dropped", 0),
        )
        canary = q.get("canary") or {}
        canary_models = canary.get("models") or {}
        yield gauge(
            "tpu_quality_canary_fraction",
            "fraction of the primary's traffic hash-sliced to the "
            "canary variant (1.0 = promoted, 0.0 = rolled back)",
            0,
            labels=["model", "variant"],
            samples=[
                ([m, c["variant"]], c["fraction"])
                for m, c in sorted(canary_models.items())
            ],
        )
        yield gauge(
            "tpu_quality_canary_info",
            "canary lifecycle state per model (info gauge: "
            "canary/promoted/rolled_back)",
            0,
            labels=["model", "variant", "state"],
            samples=[
                ([m, c["variant"], c["state"]], 1)
                for m, c in sorted(canary_models.items())
            ],
        )
        yield counter(
            "tpu_quality_promotions_total",
            "canary variants promoted to full traffic after N clean "
            "quality windows",
            canary.get("promotions", 0),
        )
        yield counter(
            "tpu_quality_rollbacks_total",
            "canary variants auto-rolled-back on a quality-budget "
            "violation (f32 re-pinned; exemplar trace ids in the log)",
            canary.get("rollbacks", 0),
        )

        # host-transport plane: negotiated transport per request, the
        # wire-vs-shm payload byte split, and the multi-frame stream
        # group-size distribution
        tp = snap.get("transport") or {}
        tp_requests = tp.get("requests") or {}
        yield gauge(
            f"{ns}_transport_info",
            "transports observed carrying inference requests "
            "(grpc/uds/shm/uds+shm; info gauge, 1 per observed label)",
            0,
            labels=["transport"],
            samples=[([t], 1) for t in sorted(tp_requests)],
        )
        yield counter(
            f"{ns}_transport_requests_total",
            "inference requests per negotiated transport",
            0,
            labels=["transport"],
            samples=[([t], n) for t, n in sorted(tp_requests.items())],
        )
        yield counter(
            f"{ns}_wire_bytes_total",
            "input payload bytes that travelled as gRPC raw content",
            tp.get("wire_bytes", 0),
        )
        yield counter(
            f"{ns}_shm_bytes_total",
            "input payload bytes that travelled through shared memory",
            tp.get("shm_bytes", 0),
        )
        groups = {
            int(k): v for k, v in (tp.get("stream_groups") or {}).items()
        }
        group_hist = HistogramMetricFamily(
            f"{ns}_stream_group_size",
            "frames per packed multi-frame stream message",
            labels=[],
        )
        cum, cum_buckets = 0, []
        for bound in (1, 2, 4, 8, 16, 32, 64):
            cum += sum(v for k, v in groups.items() if bound / 2 < k <= bound)
            cum_buckets.append((repr(float(bound)), cum))
        cum_buckets.append(("+Inf", sum(groups.values())))
        group_hist.add_metric(
            [], cum_buckets, float(sum(k * v for k, v in groups.items()))
        )
        yield group_hist

        # device HBM (absent on backends without memory_stats)
        if snap["memory"]:
            fam = GaugeMetricFamily(
                f"{ns}_device_hbm_bytes",
                "per-device memory_stats() bytes",
                labels=["device", "kind"],
            )
            for dev, stats in snap["memory"].items():
                for kind in _HBM_KINDS:
                    if kind in stats:
                        fam.add_metric([dev, kind], stats[kind])
            yield fam
            # per-device occupancy: the mesh-serving balance check — on
            # a healthy data-parallel channel every device sits at the
            # same ratio (params replicated + 1/N of the batch)
            occ = GaugeMetricFamily(
                f"{ns}_device_hbm_occupancy_ratio",
                "per-device bytes_in_use / bytes_limit",
                labels=["device"],
            )
            for dev, stats in snap["memory"].items():
                if stats.get("bytes_limit"):
                    occ.add_metric(
                        [dev],
                        stats.get("bytes_in_use", 0) / stats["bytes_limit"],
                    )
            yield occ

    def close(self) -> None:
        if self._registry is not None:
            try:
                self._registry.unregister(self)
            except KeyError:
                pass
            self._registry = None
