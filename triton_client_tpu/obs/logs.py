"""Structured log correlation: trace/request tags on log lines.

A request that crosses client -> router -> replica leaves log lines in
three processes. Grepping a tail exemplar out of the SLO ring is only
possible when those lines share a key — the distributed ``trace_id``
the traceparent context carries (obs/trace.py), falling back to the
process-local ring id for purely local traces. This module is the one
place that formats the correlation tag, so server, router, and batcher
lines agree on its shape:

    dispatch failed ... [trace=4f2a... req=frame-17]

``log_tag`` is pure string work (no locks, no syncs) and returns ""
when there is nothing to correlate, so call sites can append it
unconditionally.
"""

from __future__ import annotations

import logging


def log_tag(trace=None, request_id: str = "") -> str:
    """Correlation suffix ``" [trace=... req=...]"`` for a log line.

    ``trace`` is a RequestTrace (or None). A distributed context wins
    (its hex trace_id greps across processes); a purely local trace
    falls back to ``local:<ring id>``. Empty string when neither a
    trace nor a request id is at hand."""
    parts = []
    rid = request_id
    if trace is not None:
        ctx = getattr(trace, "context", None)
        if ctx is not None:
            parts.append(f"trace={ctx.trace_id}")
        else:
            tid = getattr(trace, "trace_id", None)
            if tid is not None:
                parts.append(f"trace=local:{tid}")
        rid = rid or getattr(trace, "request_id", "")
    if rid:
        parts.append(f"req={rid}")
    return (" [" + " ".join(parts) + "]") if parts else ""


class TraceLogAdapter(logging.LoggerAdapter):
    """LoggerAdapter that appends one request's correlation tag to
    every message — for code paths that emit several lines for the
    same request and don't want to thread the tag by hand."""

    def __init__(self, logger, trace=None, request_id: str = "") -> None:
        super().__init__(logger, {})
        self._tag = log_tag(trace, request_id)

    def process(self, msg, kwargs):
        return f"{msg}{self._tag}", kwargs
