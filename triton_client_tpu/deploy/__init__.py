"""Deployment tooling (SURVEY.md §2 #23 parity).

- fetch: model provisioning from S3/MinIO behind Keycloak OIDC
  (docker/server/utils/download_model_s3_keycloak.py), no boto3 —
  urllib + hand-rolled AWS SigV4.
- push: deploy.sh parity — convert a checkpoint, materialize a model
  repository entry, sync it to a (remote) model repo.
"""
