"""Model provisioning: Keycloak OIDC -> MinIO STS -> SigV4 S3 download.

Parity with the reference's server-image fetch tool
(docker/server/utils/download_model_s3_keycloak.py): authenticate a
user against Keycloak (OIDC password grant), trade the access token for
temporary S3 credentials via MinIO's STS AssumeRoleWithWebIdentity, and
download the model object. The reference uses boto3 + python-keycloak;
neither is in this image, so the wire protocols are implemented
directly (urllib + hmac SigV4) — which also drops ~100 MB of
dependency from the server image.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import hmac
import json
import pathlib
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _post_form(url: str, fields: dict[str, str], timeout: float = 30.0) -> bytes:
    data = urllib.parse.urlencode(fields).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/x-www-form-urlencoded"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def keycloak_token(
    server_url: str,
    realm: str,
    username: str,
    password: str,
    client_id: str = "account",
    client_secret: str | None = None,
    timeout: float = 30.0,
) -> dict[str, str]:
    """OIDC password grant -> {'access_token', 'refresh_token', ...}.

    ``server_url`` may be the legacy '/auth/' base the reference
    defaults to (download_model_s3_keycloak.py:41) or a modern root.
    """
    base = server_url.rstrip("/")
    url = f"{base}/realms/{realm}/protocol/openid-connect/token"
    fields = {
        "grant_type": "password",
        "client_id": client_id,
        "username": username,
        "password": password,
    }
    if client_secret:
        fields["client_secret"] = client_secret
    return json.loads(_post_form(url, fields, timeout))


@dataclasses.dataclass(frozen=True)
class S3Credentials:
    access_key: str
    secret_key: str
    session_token: str = ""


def sts_assume_role_web_identity(
    endpoint_url: str,
    web_identity_token: str,
    role_arn: str = "arn:aws:iam::123456789",
    session_name: str = "minios3",
    duration_s: int = 3600,
    timeout: float = 30.0,
) -> S3Credentials:
    """MinIO STS AssumeRoleWithWebIdentity -> temporary S3 credentials
    (the reference's boto3 sts.assume_role_with_web_identity,
    download_model_s3_keycloak.py:128-142)."""
    body = _post_form(
        endpoint_url,
        {
            "Action": "AssumeRoleWithWebIdentity",
            "Version": "2011-06-15",
            "WebIdentityToken": web_identity_token,
            "RoleArn": role_arn,
            "RoleSessionName": session_name,
            "DurationSeconds": str(duration_s),
        },
        timeout,
    )
    root = ET.fromstring(body)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]
    creds = root.find(f".//{ns}Credentials")
    if creds is None:
        raise ValueError(f"STS response has no Credentials element: {body[:200]!r}")

    def field(name: str) -> str:
        el = creds.find(f"{ns}{name}")
        return el.text if el is not None and el.text else ""

    return S3Credentials(
        access_key=field("AccessKeyId"),
        secret_key=field("SecretAccessKey"),
        session_token=field("SessionToken"),
    )


def sigv4_headers(
    method: str,
    url: str,
    creds: S3Credentials,
    region: str = "us-east-1",
    service: str = "s3",
    payload_hash: str = _EMPTY_SHA256,
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """AWS Signature Version 4 headers for one request (the part boto3
    did for the reference; Config(signature_version='s3v4'))."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    if creds.session_token:
        headers["x-amz-security-token"] = creds.session_token

    signed_names = sorted(headers)
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
    )
    # The path arrives already percent-encoded (it is what goes on the
    # wire); re-quoting here would double-encode (%20 -> %2520) and
    # break the signature for keys with spaces etc. S3-style SigV4
    # signs the path as sent.
    canonical_request = "\n".join(
        [
            method,
            parsed.path or "/",
            canonical_query,
            canonical_headers,
            signed_headers,
            payload_hash,
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = _hmac(b"AWS4" + creds.secret_key.encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(
        k_signing, string_to_sign.encode(), hashlib.sha256
    ).hexdigest()

    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return {k: v for k, v in headers.items() if k != "host"}


def s3_download(
    endpoint_url: str,
    bucket: str,
    key: str,
    creds: S3Credentials,
    output_path: str | pathlib.Path,
    region: str = "us-east-1",
    timeout: float = 300.0,
    chunk_bytes: int = 1 << 20,
) -> pathlib.Path:
    """SigV4-signed GET (path-style addressing, as MinIO expects)."""
    url = f"{endpoint_url.rstrip('/')}/{bucket}/{urllib.parse.quote(key)}"
    req = urllib.request.Request(
        url, headers=sigv4_headers("GET", url, creds, region=region)
    )
    output_path = pathlib.Path(output_path)
    with urllib.request.urlopen(req, timeout=timeout) as resp, open(
        output_path, "wb"
    ) as out:
        while True:
            chunk = resp.read(chunk_bytes)
            if not chunk:
                break
            out.write(chunk)
    return output_path


def fetch_model(
    username: str,
    password: str,
    object_path: str,
    output_path: str,
    minio_endpoint_url: str,
    keycloak_endpoint_url: str = "http://localhost:8080/auth/",
    keycloak_client_id: str = "account",
    keycloak_realm_name: str = "Agri-Gaia",
) -> pathlib.Path:
    """End-to-end fetch, argument-for-argument with the reference CLI
    (download_model_s3_keycloak.py:10-62). ``object_path`` is
    '<bucket>/<object_key>'."""
    bucket, _, key = object_path.partition("/")
    if not key:  # validate before any authenticated round-trip
        raise ValueError(
            f"object path {object_path!r} must be '<bucket>/<object_key>'"
        )
    tokens = keycloak_token(
        keycloak_endpoint_url, keycloak_realm_name, username, password,
        client_id=keycloak_client_id,
    )
    creds = sts_assume_role_web_identity(
        minio_endpoint_url, tokens["access_token"]
    )
    return s3_download(minio_endpoint_url, bucket, key, creds, output_path)


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="fetch a model artifact from MinIO/S3 behind Keycloak OIDC"
    )
    p.add_argument("--username", required=True)
    p.add_argument("--password", required=True)
    p.add_argument("--object-path", required=True, help="<bucket>/<object_key>")
    p.add_argument("--output-path", required=True)
    p.add_argument("--minio-endpoint-url", required=True)
    p.add_argument("--keycloak-endpoint-url", default="http://localhost:8080/auth/")
    p.add_argument("--keycloak-client-id", default="account")
    p.add_argument("--keycloak-realm-name", default="Agri-Gaia")
    args = p.parse_args(argv)
    out = fetch_model(
        args.username, args.password, args.object_path, args.output_path,
        args.minio_endpoint_url, args.keycloak_endpoint_url,
        args.keycloak_client_id, args.keycloak_realm_name,
    )
    print(f"downloaded {args.object_path} -> {out}")


if __name__ == "__main__":
    main()
