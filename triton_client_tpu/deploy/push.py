"""deploy.sh parity: convert a checkpoint -> model repo entry -> push.

The reference's deploy.sh (deploy.sh:1-65) hardcodes one flow: run the
upstream pth->ONNX exporter, scp the artifact into a remote Triton
model repository, and template a config.pbtxt over ssh. Here the
conversion target is the flax tree (runtime.importers), the repository
entry is the disk layout (runtime.disk_repository.export_model), and
the push is rsync/scp of the finished entry — with a local-path mode so
the whole flow is testable without a remote.
"""

from __future__ import annotations

import pathlib
import subprocess
import tempfile
from typing import Any, Mapping


def convert_checkpoint(
    family: str,
    checkpoint: str,
    model_kwargs: Mapping[str, Any] | None = None,
) -> tuple[dict, Mapping]:
    """Upstream checkpoint (.pt/.pth/.onnx) -> (config_doc, variables).

    Builds the family's pipeline to get the template tree, imports the
    checkpoint onto it, and returns the repo-entry config + weights.
    """
    from triton_client_tpu.runtime import disk_repository

    doc: dict = {"family": family}
    if model_kwargs:
        doc["model"] = dict(model_kwargs)
    template = disk_repository.conversion_template(doc=doc)
    variables = disk_repository.load_weights(checkpoint, family, template)
    return doc, variables


def push_entry(
    entry_dir: str | pathlib.Path,
    destination: str,
    dry_run: bool = False,
) -> list[str]:
    """Sync a finished model-repo entry to ``destination``.

    destination forms (always the model-repo ROOT; the entry's own
    directory level is preserved by every transport):
      * local path            -> copy tree (shutil)
      * user@host:/path       -> scp -r (deploy.sh:56-65's transport)
      * rsync://host/module   -> rsync -a
    Returns the command(s) executed (for logging/dry-run).
    """
    entry_dir = pathlib.Path(entry_dir)
    if ":" in destination and "@" in destination.split(":", 1)[0]:
        cmd = ["scp", "-r", str(entry_dir), destination]
        if not dry_run:
            subprocess.run(cmd, check=True)
        return [" ".join(cmd)]
    if destination.startswith("rsync://"):
        target = f"{destination.rstrip('/')}/{entry_dir.name}/"
        cmd = ["rsync", "-a", f"{entry_dir}/", target]
        if not dry_run:
            subprocess.run(cmd, check=True)
        return [" ".join(cmd)]
    # local path
    if not dry_run:
        import shutil

        dest = pathlib.Path(destination) / entry_dir.name
        if dest.exists():
            shutil.rmtree(dest)
        shutil.copytree(entry_dir, dest)
    return [f"copytree {entry_dir} -> {destination}/{entry_dir.name}"]


def deploy(
    family: str,
    checkpoint: str,
    model_name: str,
    destination: str,
    version: str = "1",
    model_kwargs: Mapping[str, Any] | None = None,
    config_extra: Mapping[str, Any] | None = None,
    dry_run: bool = False,
) -> list[str]:
    """Full deploy.sh flow: convert -> materialize entry -> push."""
    from triton_client_tpu.runtime.disk_repository import export_model

    doc, variables = convert_checkpoint(family, checkpoint, model_kwargs)
    doc.update(dict(config_extra or {}))
    with tempfile.TemporaryDirectory() as tmp:
        entry_dir = export_model(
            tmp, model_name, doc, variables=variables, version=version
        )
        return push_entry(entry_dir, destination, dry_run=dry_run)


def main(argv=None) -> None:
    import argparse

    import yaml

    p = argparse.ArgumentParser(
        description="convert a checkpoint and push a model-repository entry"
    )
    p.add_argument("-f", "--family", required=True,
                   help="model family (yolov5, pointpillars, ...)")
    p.add_argument("-c", "--checkpoint", required=True,
                   help=".pt/.pth/.onnx/.msgpack artifact to convert")
    p.add_argument("-m", "--model-name", required=True,
                   help="repository entry name")
    p.add_argument("-d", "--destination", required=True,
                   help="model repo root: local path, user@host:/path, rsync://")
    p.add_argument("--version", default="1")
    p.add_argument("--model-arg", action="append", default=[],
                   help="model kwarg as key=value (e.g. num_classes=2); "
                        "values parse as YAML")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    model_kwargs = {}
    for kv in args.model_arg:
        key, _, value = kv.partition("=")
        model_kwargs[key] = yaml.safe_load(value)

    for cmd in deploy(
        args.family, args.checkpoint, args.model_name, args.destination,
        version=args.version, model_kwargs=model_kwargs, dry_run=args.dry_run,
    ):
        print(cmd)


if __name__ == "__main__":
    main()
