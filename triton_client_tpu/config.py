"""Model specs: the in-tree equivalent of Triton's config.pbtxt.

The reference declares each served model's tensor contract in a
config.pbtxt (examples/YOLOv5/config.pbtxt, examples/pointpillar_kitti/
config.pbtxt:27-73) and the client re-parses it over gRPC at startup
(communicator/channel/grpc_channel.py:39-54, clients/base_client.py:32-104).
Here the contract is a typed Python dataclass registered alongside the
model function — metadata queries become dict lookups, and validation
happens once at registration, not per client process.

Specs are JSON-serializable for the model-repository-on-disk layout and
for serving them over the KServe v2 facade.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

# KServe v2 dtype strings <-> numpy, per the wire contract the reference
# asserts against (communicator/ros_inference3d.py:141-144).
_DTYPES = {
    "FP32": np.float32,
    "FP16": np.float16,
    "BF16": None,  # no numpy bf16; handled at the jax boundary
    "INT32": np.int32,
    "INT64": np.int64,
    "UINT8": np.uint8,
    "INT8": np.int8,
    "BOOL": np.bool_,
}


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """One input/output tensor contract. -1 dims are dynamic (bucketed
    at dispatch time — XLA itself only sees static shapes)."""

    name: str
    shape: tuple[int, ...]
    dtype: str = "FP32"
    layout: str = ""  # e.g. "NHWC" / "NCHW" for image inputs

    def np_dtype(self) -> np.dtype:
        if self.dtype not in _DTYPES or _DTYPES[self.dtype] is None:
            raise ValueError(f"no numpy dtype for {self.dtype}")
        return np.dtype(_DTYPES[self.dtype])

    def validate(self, arr: np.ndarray) -> None:
        if len(arr.shape) != len(self.shape):
            raise ValueError(
                f"tensor '{self.name}': rank {len(arr.shape)} != spec rank "
                f"{len(self.shape)}"
            )
        for got, want in zip(arr.shape, self.shape):
            if want != -1 and got != want:
                raise ValueError(
                    f"tensor '{self.name}': shape {arr.shape} incompatible "
                    f"with spec {self.shape}"
                )


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """A model's full serving contract (name, version, tensors, limits)."""

    name: str
    version: str = "1"
    platform: str = "jax"
    inputs: tuple[TensorSpec, ...] = ()
    outputs: tuple[TensorSpec, ...] = ()
    max_batch_size: int = 1
    # Free-form model config (class names file, thresholds, anchor sets,
    # voxel grid params, ...) — the analogue of the reference's
    # data/*.yaml hyperparameter files.
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    def input_by_name(self, name: str) -> TensorSpec:
        for t in self.inputs:
            if t.name == name:
                return t
        raise KeyError(f"model '{self.name}' has no input '{name}'")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_json(text: str) -> "ModelSpec":
        raw = json.loads(text)
        raw["inputs"] = tuple(TensorSpec(**t) for t in raw.get("inputs", ()))
        raw["outputs"] = tuple(TensorSpec(**t) for t in raw.get("outputs", ()))
        return ModelSpec(**raw)
