"""Model specs: the in-tree equivalent of Triton's config.pbtxt.

The reference declares each served model's tensor contract in a
config.pbtxt (examples/YOLOv5/config.pbtxt, examples/pointpillar_kitti/
config.pbtxt:27-73) and the client re-parses it over gRPC at startup
(communicator/channel/grpc_channel.py:39-54, clients/base_client.py:32-104).
Here the contract is a typed Python dataclass registered alongside the
model function — metadata queries become dict lookups, and validation
happens once at registration, not per client process.

Specs are JSON-serializable for the model-repository-on-disk layout and
for serving them over the KServe v2 facade.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

# KServe v2 dtype strings <-> numpy, per the wire contract the reference
# asserts against (communicator/ros_inference3d.py:141-144).
_DTYPES = {
    "FP64": np.float64,
    "FP32": np.float32,
    "FP16": np.float16,
    "BF16": None,  # no numpy bf16; handled at the jax boundary
    "INT64": np.int64,
    "INT32": np.int32,
    "INT16": np.int16,
    "INT8": np.int8,
    "UINT64": np.uint64,
    "UINT32": np.uint32,
    "UINT16": np.uint16,
    "UINT8": np.uint8,
    "BOOL": np.bool_,
}

# Wire width in bytes per dtype string (BF16 travels as 16-bit words).
_ITEMSIZE = {k: (2 if v is None else np.dtype(v).itemsize) for k, v in _DTYPES.items()}

# Headroom for protobuf framing + tensor name/shape metadata on top of
# raw payloads when sizing gRPC message caps from wire_bytes().
FRAMING_BYTES = 1 << 20


def parse_compute_dtype(name: str):
    """Model compute-dtype string ('fp32'/'bf16' + long aliases) ->
    jnp dtype. Single source for the CLI --dtype flag and repository
    config.yaml 'model: {dtype: ...}' entries (raises ValueError; the
    CLI wraps it into SystemExit)."""
    import jax.numpy as jnp

    table = {"fp32": jnp.float32, "float32": jnp.float32,
             "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16}
    if name not in table:
        raise ValueError(f"unknown model dtype {name!r} (fp32|bf16)")
    return table[name]


def config_dtypes() -> dict:
    """The canonical KServe dtype table (BF16 maps to None — resolved
    to ml_dtypes.bfloat16 at the codec layer). Single source for spec
    validation, wire sizing, and the gRPC codec."""
    return dict(_DTYPES)


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """One input/output tensor contract. -1 dims are dynamic (bucketed
    at dispatch time — XLA itself only sees static shapes)."""

    name: str
    shape: tuple[int, ...]
    dtype: str = "FP32"
    layout: str = ""  # e.g. "NHWC" / "NCHW" for image inputs
    # Input-only: the serving channel may donate this tensor's staged
    # device buffer to the launch (jax donate_argnums), letting XLA
    # reuse the HBM across consecutive batches. Only safe to declare
    # when no consumer re-reads the staged buffer after launch — the
    # channel stages a fresh copy per request, so in-tree pipelines
    # qualify; the request's host arrays are never donated.
    donatable: bool = False

    def np_dtype(self) -> np.dtype:
        if self.dtype not in _DTYPES or _DTYPES[self.dtype] is None:
            raise ValueError(f"no numpy dtype for {self.dtype}")
        return np.dtype(_DTYPES[self.dtype])

    def validate(self, arr: np.ndarray) -> None:
        if len(arr.shape) != len(self.shape):
            raise ValueError(
                f"tensor '{self.name}': rank {len(arr.shape)} != spec rank "
                f"{len(self.shape)}"
            )
        for got, want in zip(arr.shape, self.shape):
            if want != -1 and got != want:
                raise ValueError(
                    f"tensor '{self.name}': shape {arr.shape} incompatible "
                    f"with spec {self.shape}"
                )


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """A model's full serving contract (name, version, tensors, limits)."""

    name: str
    version: str = "1"
    platform: str = "jax"
    inputs: tuple[TensorSpec, ...] = ()
    outputs: tuple[TensorSpec, ...] = ()
    max_batch_size: int = 1
    # Free-form model config (class names file, thresholds, anchor sets,
    # voxel grid params, ...) — the analogue of the reference's
    # data/*.yaml hyperparameter files.
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    def input_by_name(self, name: str) -> TensorSpec:
        for t in self.inputs:
            if t.name == name:
                return t
        raise KeyError(f"model '{self.name}' has no input '{name}'")

    def donatable_inputs(self) -> tuple[str, ...]:
        """Input names whose staged device buffers the serving channel
        may donate to the launch (channel/tpu_channel.py)."""
        return tuple(t.name for t in self.inputs if t.donatable)

    def wire_bytes(self) -> int:
        """Max raw-tensor payload of one full-batch request/response, or
        0 if any dim is dynamic (callers fall back to a floor). This is
        the dynamic replacement for the reference's hardcoded
        ``batch_size * 8568044`` message budget (grpc_channel.py:26-29,
        README.md:118 'make dynamic' TODO)."""
        total = 0
        for t in tuple(self.inputs) + tuple(self.outputs):
            if any(d < 0 for d in t.shape):
                return 0
            total += int(np.prod(t.shape, dtype=np.int64)) * _ITEMSIZE.get(
                t.dtype, 8
            )
        return total * max(1, self.max_batch_size)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_json(text: str) -> "ModelSpec":
        raw = json.loads(text)
        raw["inputs"] = tuple(TensorSpec(**t) for t in raw.get("inputs", ()))
        raw["outputs"] = tuple(TensorSpec(**t) for t in raw.get("outputs", ()))
        return ModelSpec(**raw)
