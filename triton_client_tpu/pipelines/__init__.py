"""L2 per-model pipelines (the reference's clients/ layer, re-designed).

A reference "client" is a strategy bundle of parse_model + preprocess +
postprocess objects that run on host around a remote RPC
(clients/yolov5_client.py, clients/base_client.py). A pipeline here is
the same bundle compiled into ONE jitted device function:
resize/normalize -> forward -> decode -> NMS -> box rescale, so a frame
crosses host<->device exactly once each way per inference.
"""

from triton_client_tpu.pipelines.detect2d import (
    Detect2DConfig,
    Detect2DPipeline,
    build_yolov5_pipeline,
)
