"""3D detection pipeline: raw point cloud in, packed 3D boxes out.

The reference's 3D path spans three processes (client voxelizes on CPU
via OpenPCDet, ships dynamic-shaped tensors over gRPC, the server runs
the network; SURVEY.md section 3.2/3.3). Here voxelize -> VFE -> scatter
-> backbone -> head -> rotated NMS is ONE jitted program on static
budgets: the host only pads the raw cloud to the point budget
(pad_points) and reads back (max_det, 9) rows.

Bucketed padding: ``point_buckets`` trades recompiles for wasted
compute — clouds are padded up to the smallest bucket that fits, so
the jit caches one executable per bucket instead of one per frame
(the reference instead rewrites request shapes every frame,
communicator/ros_inference3d.py:131-139).
"""

from __future__ import annotations

import bisect
import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from triton_client_tpu.config import ModelSpec, TensorSpec
from triton_client_tpu.models.pointpillars import (
    PointPillars,
    PointPillarsConfig,
    init_pointpillars,
)
from triton_client_tpu.ops.detect3d_postprocess import (
    extract_boxes_3d,
    nms_pack_3d,
)
from triton_client_tpu.ops.fused import fused_interpret, resolve_fused_stages
from triton_client_tpu.ops.pallas_decode import fused_residual_decode
from triton_client_tpu.ops.pallas_voxel import fused_mean_volume
from triton_client_tpu.ops.voxelize import pad_points, voxelize
from triton_client_tpu.runtime.precision import (
    KEEP_F32_3D,
    PrecisionPolicy,
    realize,
    resolve_policy,
)


@dataclasses.dataclass(frozen=True)
class Detect3DConfig:
    model_name: str = "pointpillars"
    score_thresh: float = 0.1
    iou_thresh: float = 0.01
    max_det: int = 128
    # NMS candidate width (top-k on raw logits before box decode).
    # 256 measured mAP-identical to 512 on the trained closed-loop
    # model while saving ~1.7 ms/scan — the rotated-IoU matrix is
    # quadratic in this (BASELINE.md round-3 floor campaign); raise it
    # for scenes with hundreds of above-threshold objects
    pre_max: int = 256
    point_buckets: tuple[int, ...] = (32768, 65536, 131072)
    # Sensor-height z correction added to incoming points before
    # voxelization (reference driver parity: ros_inference3d.py:126-128
    # adds 1.5 m for its lidar mount)
    z_offset: float = 0.0
    class_names: tuple[str, ...] = ("Car", "Pedestrian", "Cyclist")
    # Sweeps aggregated per inference by the stream layer (ops/sweeps
    # .py sweep_source): 1 = single scan (KITTI), 10 = the reference's
    # nuScenes CenterPoint config. The pipeline itself always consumes
    # ONE aggregated cloud; this field carries the dataset default to
    # the CLI/driver layer.
    nsweeps: int = 1
    # VFE routing: "auto" uses the model's sort-free from_points path
    # when it has one — pillar models on nz == 1 grids, plus models
    # that declare scatter_any_nz (SECOND's mean VFE keys on the full
    # 3D cell, so tall grids route scatter too). "grouped" forces the
    # (V, K) voxelizer contract (exact OpenPCDet budget semantics —
    # caps at max_voxels/max_points_per_voxel; the scatter path keeps
    # all points, which can only add information).
    vfe: str = "auto"
    # Fused Pallas hot-path routing (ops/fused): "auto" fuses the
    # eligible stages on a real TPU backend (subject to the
    # TPU_FUSED_KERNELS env allowlist), "on" forces them everywhere
    # (interpret mode off-TPU — the parity matrix), "off" is the
    # spec-level opt-out. Resolved per stage at build time and
    # published as spec.extra["fused_stages"].
    fused: str = "auto"


class Detect3DPipeline:
    def __init__(
        self,
        config: Detect3DConfig,
        model: PointPillars,
        variables,
        precision: PrecisionPolicy | str | None = None,
    ) -> None:
        self.config = config
        self.model = model
        self.variables = variables
        # KEEP_F32_3D contract: the raw cloud stays f32 on the wire no
        # matter the policy — voxelize derives integer cell coords from
        # point xyz, and a bf16/int8 coordinate flips cells. int8
        # activation quantization is therefore a no-op for 3D (weights
        # still quantize); bf16 narrows the model, not the points.
        policy = PrecisionPolicy.parse(precision)
        if "points" not in policy.keep_f32_inputs:
            policy = dataclasses.replace(
                policy, keep_f32_inputs=policy.keep_f32_inputs + ("points",)
            )
        self.precision = policy
        if config.vfe not in ("auto", "grouped"):
            raise ValueError(f"unknown vfe mode {config.vfe!r} (auto|grouped)")
        # pillar scatter VFE is nz == 1 only (a taller grid's z cells
        # would merge silently), so auto falls back to grouped there;
        # models whose scatter path keys on the full 3D cell (SECOND's
        # mean VFE) declare scatter_any_nz
        self.use_scatter = (
            config.vfe == "auto"
            and hasattr(model, "from_points")
            and (
                model.cfg.voxel.grid_size[2] == 1
                or getattr(model, "scatter_any_nz", False)
            )
        )
        if self.use_scatter:
            logger.info(
                "vfe=auto routes %s to the sort-free scatter VFE: all points "
                "and pillars are kept, so outputs differ from the OpenPCDet "
                "budget contract (max_voxels/max_points_per_voxel caps) "
                "whenever budgets would have been exceeded; use vfe='grouped' "
                "for exact reference budget semantics",
                config.model_name,
            )
        # fused-stage eligibility is structural (which model surfaces
        # exist), the routing decision layers env + config + backend on
        # top (ops/fused). voxelize_scatter needs the dense-middle
        # scatter VFE (fused_mean_volume is _scatter_mean_volume's
        # twin); decode_nms applies to every 3D tail.
        candidates = ("decode_nms",)
        if (
            self.use_scatter
            and getattr(model, "scatter_any_nz", False)
            and getattr(model.cfg, "middle", None) == "dense"
            and hasattr(model, "from_volume")
        ):
            candidates = ("voxelize_scatter",) + candidates
        self.fused_stages = resolve_fused_stages(config.fused, candidates)
        if "voxelize_scatter" in self.fused_stages:
            logger.info(
                "fused voxelize->scatter caps occupied cells at max_voxels "
                "(%d) — the grouped/OpenPCDet budget contract; the XLA "
                "scatter path it replaces keeps every occupied cell, so "
                "outputs differ once a scan exceeds the budget",
                model.cfg.voxel.max_voxels,
            )
        self._jit = jax.jit(self._pipeline)

    def _pipeline(self, points: jnp.ndarray, count: jnp.ndarray):
        cfg = self.config
        use_scatter = self.use_scatter
        # int8 kernels dequantize inside the trace (runtime/precision.py
        # realize — HBM reads stay int8); voxelize below always sees the
        # f32 cloud (KEEP_F32_3D: cell coords are precision-sensitive)
        variables = realize(self.variables)
        interpret = fused_interpret()
        if "voxelize_scatter" in self.fused_stages:
            # fused Pallas voxelize->scatter: sorted-segment mean via
            # MXU one-hot matmuls + unique-index set-scatter epilogue,
            # replacing the XLA scatter-add that dominates the dense
            # SECOND front (ops/pallas_voxel module docstring)
            volume = fused_mean_volume(
                points, count, self.model.cfg.voxel, interpret=interpret
            )
            heads = self.model.apply(
                variables, volume, train=False, method=self.model.from_volume
            )
        elif use_scatter:
            # sort-free path: pillar mean/max as dense-grid scatters,
            # no (V, K) grouping (see PointPillars.from_points)
            heads = self.model.apply(
                variables, points, count, train=False,
                method=self.model.from_points,
            )
        else:
            vox = voxelize(points, count, self.model.cfg.voxel)
            heads = self.model.apply(
                variables,
                vox["voxels"][None],
                vox["num_points_per_voxel"][None],
                vox["coords"][None],
                train=False,
            )
        # keep-list boundary: box decode and NMS scoring below run in
        # f32 regardless of the model compute dtype
        heads = self.precision.boundary(heads)
        fuse_tail = "decode_nms" in self.fused_stages
        if hasattr(self.model, "decode_topk"):
            # Fast path: gate + top-k on raw logits BEFORE box decode —
            # only pre_max boxes are ever decoded (see decode_topk).
            if fuse_tail and hasattr(self.model, "topk_candidates"):
                # fused tail: residual decode + rectify as ONE
                # elementwise launch, then suppression + packing as
                # another (ops/pallas_decode) — detections never leave
                # the device between stages
                tc = self.model.topk_candidates(
                    heads, pre_max=cfg.pre_max, score_thresh=cfg.score_thresh
                )
                mc = self.model.cfg
                boxes = jax.vmap(
                    lambda d, a, db: fused_residual_decode(
                        d, a, db,
                        num_dir_bins=mc.num_dir_bins,
                        dir_offset=mc.dir_offset,
                        interpret=interpret,
                    )
                )(tc["deltas"], tc["anchors"], tc["dir_bin"])
                cand = {
                    "boxes": boxes,
                    "scores": tc["scores"],
                    "labels": tc["labels"],
                }
            else:
                cand = self.model.decode_topk(
                    heads, pre_max=cfg.pre_max, score_thresh=cfg.score_thresh
                )
            dets, valid = nms_pack_3d(
                cand["boxes"],
                cand["scores"],
                cand["labels"],
                iou_thresh=cfg.iou_thresh,
                max_det=cfg.max_det,
                fused=fuse_tail,
                interpret=interpret,
            )
        else:
            pred = self.model.decode(heads)
            boxes = pred["boxes"]
            if "velocity" in pred:
                # ride-along columns: velocity survives NMS packing and
                # surfaces as pred_velocities (the det3d wire carries
                # vx/vy the same way for CenterPoint)
                boxes = jnp.concatenate([boxes, pred["velocity"]], axis=-1)
            dets, valid = extract_boxes_3d(
                boxes,
                pred["scores"],
                score_thresh=cfg.score_thresh,
                iou_thresh=cfg.iou_thresh,
                max_det=cfg.max_det,
                pre_max=cfg.pre_max,
                fused=fuse_tail,
                interpret=interpret,
            )
        return dets[0], valid[0]

    def infer(self, points: np.ndarray) -> dict[str, np.ndarray]:
        """points: (M, 4+) raw cloud [x, y, z, intensity, ...]. Returns
        the reference 3D client contract: pred_boxes (n, 7), pred_scores
        (n,), pred_labels (n,) — n = live detections."""
        return self.infer_dispatch(points).result()

    def infer_dispatch(self, points: np.ndarray):
        """Async half of infer (the driver's --async path): host prep +
        jit enqueue happen here; the returned future's result() performs
        the only blocking step (device->host read + packing), so callers
        can overlap the next scan's prep with this scan's compute."""
        from triton_client_tpu.channel.base import InferFuture

        buckets = self.config.point_buckets
        i = bisect.bisect_left(buckets, points.shape[0])
        budget = buckets[min(i, len(buckets) - 1)]
        if points.shape[0] > budget:
            logger.warning(
                "point cloud (%d pts) exceeds largest bucket (%d); tail "
                "points dropped — raise Detect3DConfig.point_buckets",
                points.shape[0],
                budget,
            )
        # astype(copy=True default) always returns a fresh array, so the
        # in-place z shift below never aliases caller memory.
        pf = self.model.cfg.voxel.point_features
        points = points[:, :pf].astype(np.float32)
        if points.shape[1] < pf:
            # narrower cloud than the model's VFE contract: zero-fill
            # the missing trailing channels — a single sweep's Δt=0,
            # exactly the reference's zero-padded time column
            # (clients/preprocess/voxelize.py:38-40)
            points = np.pad(points, ((0, 0), (0, pf - points.shape[1])))
        if self.config.z_offset:
            points[:, 2] += self.config.z_offset
        padded, m = pad_points(points, budget)
        dets, valid = self._jit(jnp.asarray(padded), jnp.asarray(m))

        def resolve() -> dict[str, np.ndarray]:
            d, v = np.asarray(dets), np.asarray(valid)
            live = d[v]
            # rows are [box7, extras..., score, label]; whether the
            # extras are CenterPoint's (vx, vy) is a model-config fact,
            # not a row-width guess
            w = live.shape[1]
            out = {
                "pred_boxes": live[:, :7],
                "pred_scores": live[:, w - 2],
                "pred_labels": live[:, w - 1].astype(np.int32),
            }
            if getattr(self.model.cfg, "with_velocity", False):
                out["pred_velocities"] = live[:, 7:9]
            return out

        return InferFuture(resolve)

    def infer_fn(self):
        """Repository-facing adapter over the padded static contract.
        CenterPoint's velocity head additionally surfaces as a NAMED
        ``velocities`` output — the packed-row slice stays a device
        view, so remote clients (and the session tracker's motion seed)
        address it without knowing the row layout."""
        wv = getattr(self.model.cfg, "with_velocity", False)

        def fn(inputs):
            dets, valid = self._jit(inputs["points"], inputs["num_points"])
            out = {"detections": dets, "valid": valid}
            if wv:
                out["velocities"] = dets[:, 7:9]
            return out

        return fn

    def device_fn(self):
        """Jit-traceable form (runtime/ensemble.py fused DAGs): same
        padded static contract as infer_fn, composed via the unjitted
        pipeline so a parent ensemble's single XLA program inlines it —
        e.g. an aggregation/compensation step chained into a 3D
        detector keeps the padded cloud in HBM between members."""

        wv = getattr(self.model.cfg, "with_velocity", False)

        def fn(inputs):
            dets, valid = self._pipeline(
                inputs["points"], inputs["num_points"]
            )
            out = {"detections": dets, "valid": valid}
            if wv:
                out["velocities"] = dets[:, 7:9]
            return out

        return fn


def _detect3d_spec(
    cfg: Detect3DConfig, model_cfg, extra: dict | None = None
) -> ModelSpec:
    """Serving spec shared by every 3D pipeline (the analogue of
    examples/pointpillar_kitti/config.pbtxt + examples/second_iou).
    Detection rows are [box7, extras..., score, label]; CenterPoint's
    velocity rides as 2 extra columns."""
    n_extra = 2 if (extra or {}).get("with_velocity") else 0
    pf = model_cfg.voxel.point_features
    return ModelSpec(
        name=cfg.model_name,
        version="1",
        platform="jax",
        inputs=(
            # donatable: the voxelizer consumes the staged scan exactly
            # once, so the serving channel may recycle the HBM buffer
            # across consecutive scans (channel/tpu_channel.py).
            TensorSpec("points", (-1, pf), "FP32", donatable=True),
            TensorSpec("num_points", (), "INT32"),
        ),
        outputs=(
            TensorSpec("detections", (cfg.max_det, 9 + n_extra), "FP32"),
            TensorSpec("valid", (cfg.max_det,), "BOOL"),
        )
        + (
            # the velocity head's named surface (a view of detection
            # columns 7:9) — present exactly when with_velocity, so the
            # spec and the infer_fn output set never disagree
            (TensorSpec("velocities", (cfg.max_det, 2), "FP32"),)
            if n_extra
            else ()
        ),
        extra={
            "score_thresh": cfg.score_thresh,
            "iou_thresh": cfg.iou_thresh,
            # every in-repo 3D spec states velocity presence explicitly
            # so remote clients never have to sniff the row width
            "with_velocity": n_extra > 0,
            "class_names": list(cfg.class_names),
            "max_voxels": model_cfg.voxel.max_voxels,
            # Remote clients self-configure host-side prep from the
            # served metadata (the reference's parse_model pattern,
            # clients/detector_3d_client.py:28-91): pad buckets + the
            # sensor z correction applied before the padded contract.
            "point_buckets": list(cfg.point_buckets),
            "z_offset": cfg.z_offset,
            **(extra or {}),
        },
    )


def build_pointpillars_pipeline(
    rng: jax.Array | None = None,
    model_cfg: PointPillarsConfig | None = None,
    config: Detect3DConfig | None = None,
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect3DPipeline, ModelSpec, dict]:
    policy, dtype = resolve_policy(precision, dtype)
    model_cfg = model_cfg or PointPillarsConfig()
    if variables is None:
        model, variables = init_pointpillars(
            rng if rng is not None else jax.random.PRNGKey(0), model_cfg, dtype
        )
    else:
        model = PointPillars(model_cfg, dtype=dtype)
    # pipeline serves the cast tree; the UNCAST tree returns as the
    # weight-loading template (disk_repository)
    cast_vars = policy.cast_params(variables)
    cfg = config or Detect3DConfig()
    pipeline = Detect3DPipeline(cfg, model, cast_vars, precision=policy)
    spec = _detect3d_spec(cfg, model_cfg)
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(
        pipeline.precision.spec_extra(cast_vars, KEEP_F32_3D)
    )
    return pipeline, spec, variables


def build_second_pipeline(
    rng: jax.Array | None = None,
    model_cfg=None,
    config: Detect3DConfig | None = None,
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect3DPipeline, ModelSpec, dict]:
    """SECOND-IoU over the same seam as PointPillars (the reference
    serves both from the same Triton python backend shape,
    examples/second_iou/*). Duck-typed into Detect3DPipeline: identical
    apply/decode surfaces."""
    from triton_client_tpu.models.second import SECONDConfig, SECONDIoU, init_second

    policy, dtype = resolve_policy(precision, dtype)
    model_cfg = model_cfg or SECONDConfig()
    if variables is None:
        model, variables = init_second(
            rng if rng is not None else jax.random.PRNGKey(0), model_cfg, dtype
        )
    else:
        model = SECONDIoU(model_cfg, dtype=dtype)
    cast_vars = policy.cast_params(variables)
    cfg = config or Detect3DConfig(model_name="second_iou")
    pipeline = Detect3DPipeline(cfg, model, cast_vars, precision=policy)
    spec = _detect3d_spec(cfg, model_cfg, {"iou_alpha": model_cfg.iou_alpha})
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(
        pipeline.precision.spec_extra(cast_vars, KEEP_F32_3D)
    )
    return pipeline, spec, variables


def build_centerpoint_pipeline(
    rng: jax.Array | None = None,
    model_cfg=None,
    config: Detect3DConfig | None = None,
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect3DPipeline, ModelSpec, dict]:
    """CenterPoint-pillar, nuScenes config (the reference's det3d path,
    clients/preprocess/voxelize.py + data/nusc_centerpoint_pp...py).
    decode emits one-hot class scores so the shared rotated-NMS
    postprocess applies unchanged; with_velocity rides through the
    packed rows as 2 extra columns and surfaces as pred_velocities
    (the reference's base 3D wire carries boxes/scores/labels only,
    clients/detector_3d_client.py:29-34 — velocity is the det3d
    extension this config exists for)."""
    from triton_client_tpu.models.centerpoint import (
        CenterPointConfig,
        CenterPoint,
        init_centerpoint,
    )

    policy, dtype = resolve_policy(precision, dtype)
    model_cfg = model_cfg or CenterPointConfig()
    if variables is None:
        model, variables = init_centerpoint(
            rng if rng is not None else jax.random.PRNGKey(0), model_cfg, dtype
        )
    else:
        model = CenterPoint(model_cfg, dtype=dtype)
    cfg = config if config is not None else default_detect3d_config("centerpoint")
    # class_names derive from the MODEL config — reconcile so a caller
    # config built with the KITTI defaults can't mislabel nuScenes
    # predictions (pred_labels range over model_cfg.class_names).
    if tuple(cfg.class_names) != tuple(model_cfg.class_names):
        cfg = dataclasses.replace(cfg, class_names=tuple(model_cfg.class_names))
    cast_vars = policy.cast_params(variables)
    pipeline = Detect3DPipeline(cfg, model, cast_vars, precision=policy)
    spec = _detect3d_spec(cfg, model_cfg, {"with_velocity": model_cfg.with_velocity})
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(
        pipeline.precision.spec_extra(cast_vars, KEEP_F32_3D)
    )
    return pipeline, spec, variables


def default_detect3d_config(model_name: str) -> Detect3DConfig:
    """Single source of per-family pipeline defaults. Center-heatmap
    models pre-NMS via local peaks, so box NMS only needs to kill
    duplicate peaks (higher IoU gate)."""
    if model_name == "centerpoint":
        return Detect3DConfig(model_name=model_name, iou_thresh=0.2)
    return Detect3DConfig(model_name=model_name)


# family name -> builder; the single dispatch table shared by the CLI
# entry points and the disk model repository.
BUILDERS_3D = {
    "pointpillars": build_pointpillars_pipeline,
    "second_iou": build_second_pipeline,
    "centerpoint": build_centerpoint_pipeline,
}
