"""Standalone image-preprocess model: the classic first ensemble step.

Triton deployments routinely front a detector with a preprocess model
(DALI/python backend) chained via an ensemble — resize + dtype on the
server so clients ship raw camera bytes. The reference does this work
client-side instead (utils/preprocess.py image_adjust: resize + /255
before the wire). This family moves it server-side as a repository
entry, which is also the canonical IMAGE-SIZED-intermediate producer
for device-fused ensembles: preprocess -> detector chained host-side
round-trips a full float frame through host memory per step, fused it
stays in HBM (runtime/ensemble.py; A/B in perf/profile_ensemble.py).

Repository entry::

    <root>/preprocess/config.yaml
        family: preprocess
        model: {input_hw: [512, 512]}   # output resolution

No weights: the entry registers without version dirs. Contract:
``images`` (B, H, W, 3) uint8/float RGB in, ``preprocessed``
(B, out_h, out_w, 3) float32 out — raw pixel scale (detectors
normalize internally, so chaining never double-normalizes).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from triton_client_tpu.config import ModelSpec, TensorSpec


@dataclasses.dataclass(frozen=True)
class Preprocess2DConfig:
    model_name: str = "preprocess"
    # named input_hw (not out_hw) so the disk repository's shared 2D
    # plumbing (config.yaml model.input_hw override, warmup shape)
    # applies unchanged; semantically it is the OUTPUT resolution
    input_hw: tuple[int, int] = (512, 512)
    class_names: tuple[str, ...] = ()


class Preprocess2DPipeline:
    """Resize-to-target as a servable model (no parameters)."""

    def __init__(self, config: Preprocess2DConfig) -> None:
        self.config = config
        self._jit = jax.jit(self._fn)

    def _fn(self, frames: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        x = frames.astype(jnp.float32)
        if (x.shape[1], x.shape[2]) != cfg.input_hw:
            x = jax.image.resize(
                x,
                (x.shape[0], cfg.input_hw[0], cfg.input_hw[1], 3),
                method="bilinear",
            )
        return x

    def infer(self, frames) -> np.ndarray:
        if not hasattr(frames, "ndim"):
            frames = np.asarray(frames)
        if frames.ndim == 3:
            frames = frames[None]
        return np.asarray(self._jit(jnp.asarray(frames)))

    def infer_fn(self) -> Callable:
        def fn(inputs):
            # device arrays flow through uncoerced (no host bounce)
            return {"preprocessed": self.infer(inputs["images"])}

        return fn

    def device_fn(self) -> Callable:
        def fn(inputs):
            return {"preprocessed": self._fn(inputs["images"])}

        return fn


def build_preprocess_pipeline(
    rng=None,
    variables=None,
    config: Preprocess2DConfig | None = None,
    input_hw: tuple[int, int] = (512, 512),
):
    """Builder with the BUILDERS_2D signature; ``variables`` is
    accepted (and ignored — no parameters) so the disk repository's
    probe/registered flow applies unchanged."""
    cfg = config or Preprocess2DConfig(input_hw=tuple(input_hw))
    pipeline = Preprocess2DPipeline(cfg)
    spec = ModelSpec(
        name=cfg.model_name,
        version="1",
        platform="jax",
        inputs=(TensorSpec("images", (-1, -1, -1, 3), "FP32", "NHWC"),),
        outputs=(
            TensorSpec(
                "preprocessed", (-1, cfg.input_hw[0], cfg.input_hw[1], 3),
                "FP32", "NHWC",
            ),
        ),
        max_batch_size=8,
        extra={"out_hw": list(cfg.input_hw)},
    )
    return pipeline, spec, {}
