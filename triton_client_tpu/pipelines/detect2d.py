"""2D detection pipeline: raw frame(s) in, packed detections out.

Fuses the reference's five host/device hops (cv2.resize -> numpy
normalize -> gRPC -> torch NMS -> numpy rescale; SURVEY.md section 3.1)
into one XLA program per input resolution. Re-traces once per distinct
camera resolution (static shapes), then every frame is a single
dispatch.

Output contract per image: (max_det, 6) rows [x1, y1, x2, y2, conf,
class] in ORIGINAL image pixels + validity mask — the fixed-shape
analogue of the reference's variable-length list
(yolov5_postprocess.py:34 + ros_inference.py:100-115 rescale).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from triton_client_tpu.config import ModelSpec, TensorSpec
from triton_client_tpu.models.yolov5 import YoloV5, num_predictions
from triton_client_tpu.ops.boxes import scale_boxes
from triton_client_tpu.ops.detect_postprocess import (
    extract_boxes,
    extract_boxes_scored,
)
from triton_client_tpu.ops.fused import fused_interpret, resolve_fused_stages
from triton_client_tpu.ops.preprocess import normalize_image
from triton_client_tpu.runtime.precision import (
    KEEP_F32_2D,
    PrecisionPolicy,
    realize,
    resolve_policy,
)


@dataclasses.dataclass(frozen=True)
class Detect2DConfig:
    """Pipeline hyperparameters (reference: argparse FLAGS main.py:51-113
    + per-model thresholds ros_inference.py:148)."""

    model_name: str = "yolov5"
    input_hw: tuple[int, int] = (512, 512)
    num_classes: int = 80
    conf_thresh: float = 0.3
    iou_thresh: float = 0.45
    max_det: int = 300
    max_nms: int = 1024
    scaling: str = "yolo"
    multi_label: bool = False
    class_names: tuple[str, ...] = ()
    # "yolo": forward returns (B, N, 5+nc) obj/cls predictions.
    # "scored": forward returns ((B, N, 4) boxes, (B, N, nc) scores) —
    # the detectron family, where decode happens in the model.
    head_style: str = "yolo"
    # Fused Pallas decode+NMS routing (ops/fused): "auto" fuses on a
    # real TPU backend (subject to TPU_FUSED_KERNELS), "on" forces the
    # kernel everywhere (interpret mode off-TPU — the parity matrix),
    # "off" is the spec-level opt-out. Published as
    # spec.extra["fused_stages"].
    fused: str = "auto"


class Detect2DPipeline:
    """Wraps a detector apply-fn into the fused frame->detections jit."""

    def __init__(
        self,
        config: Detect2DConfig,
        forward: Callable[[jnp.ndarray], jnp.ndarray],
        precision: PrecisionPolicy | str | None = None,
    ) -> None:
        """``forward``: (B, H, W, 3) float input -> (B, N, 5+nc) decoded
        predictions in input-pixel units. ``precision``: the serving
        PrecisionPolicy (runtime/precision.py) — ingress frames cast to
        its compute dtype, model outputs return to f32 at ``boundary()``
        before the keep-list ops (box decode / NMS / rescale)."""
        self.config = config
        self._forward = forward
        self.precision = PrecisionPolicy.parse(precision)
        self.fused_stages = resolve_fused_stages(config.fused, ("decode_nms",))
        self._jit = jax.jit(self._pipeline, static_argnames=("orig_hw",))

    def _pipeline(
        self, frames: jnp.ndarray, orig_hw: tuple[int, int]
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        # policy compute dtype (f32 legacy; bf16 halves the resize/
        # normalize/forward HBM traffic). Wire inputs may arrive already
        # narrowed (uint8 frames, bf16 words, dequantized int8) — the
        # cast fuses into the first op either way.
        x = self.precision.cast_in(frames)
        if orig_hw != cfg.input_hw:
            b = x.shape[0]
            x = jax.image.resize(
                x, (b, cfg.input_hw[0], cfg.input_hw[1], 3), method="bilinear"
            )
        x = normalize_image(x, cfg.scaling)
        # keep-list boundary (KEEP_F32_2D, declared in the spec): box
        # decode, NMS scoring and pixel rescale below run in f32
        # regardless of policy
        pred = self.precision.boundary(self._forward(x))
        fuse_tail = "decode_nms" in self.fused_stages
        interpret = fused_interpret()
        if cfg.head_style == "scored":
            boxes_scores = pred
            dets, valid = extract_boxes_scored(
                *boxes_scores,
                conf_thresh=cfg.conf_thresh,
                iou_thresh=cfg.iou_thresh,
                max_det=cfg.max_det,
                max_nms=cfg.max_nms,
                multi_label=cfg.multi_label,
                fused=fuse_tail,
                interpret=interpret,
            )
        else:
            dets, valid = extract_boxes(
                pred,
                conf_thresh=cfg.conf_thresh,
                iou_thresh=cfg.iou_thresh,
                max_det=cfg.max_det,
                max_nms=cfg.max_nms,
                multi_label=cfg.multi_label,
                fused=fuse_tail,
                interpret=interpret,
            )
        boxes = scale_boxes(dets[..., :4], cfg.input_hw, orig_hw)
        dets = jnp.concatenate([boxes, dets[..., 4:]], axis=-1)
        dets = jnp.where(valid[..., None], dets, 0.0)
        return dets, valid

    def infer(self, frames) -> tuple[np.ndarray, np.ndarray]:
        """frames: (B, H, W, 3) or (H, W, 3) uint8/float RGB — numpy OR
        an already-device jax array (TPUChannel stages inputs on the
        mesh; jnp.asarray below is then a no-op, so the serving path
        pays ONE upload, not a device->host->device bounce). Returns
        ((B, max_det, 6), (B, max_det)) numpy; batch dim added if
        absent."""
        if not hasattr(frames, "ndim"):  # lists from host callers
            frames = np.asarray(frames)
        squeeze = frames.ndim == 3
        if squeeze:
            frames = frames[None]
        orig_hw = (frames.shape[1], frames.shape[2])
        dets, valid = self._jit(jnp.asarray(frames), orig_hw)
        dets, valid = np.asarray(dets), np.asarray(valid)
        return (dets[0], valid[0]) if squeeze else (dets, valid)

    def infer_fn(self):
        """Repository-facing dict->dict adapter. Emits the wire contract
        of the spec its builder registers: packed detections/valid for
        the YOLO family, the reference's detectron 4-output contract
        (boxes/scores/classes/dims, RetinaNet_detectron/config.pbtxt)
        for scored heads."""
        if self.config.head_style == "scored":

            def fn(inputs):
                # no np.asarray on the input: a device array from
                # TPUChannel must flow through without the
                # device->host->device bounce (see infer)
                dets, valid = self.infer(inputs["images"])
                return {
                    "boxes": dets[..., :4],
                    "scores": dets[..., 4],
                    "classes": dets[..., 5].astype(np.int64),
                    "dims": valid.sum(axis=-1).astype(np.int32),
                }

        else:

            def fn(inputs):
                dets, valid = self.infer(inputs["images"])
                return {"detections": dets, "valid": valid}

        return fn

    def device_fn(self):
        """Jit-traceable form of infer_fn: same tensor names, device
        arrays end to end, no host boundary — the member contract
        device-fused ensembles compose through (runtime/ensemble.py;
        intermediates stay in HBM instead of round-tripping host
        memory between steps). orig_hw comes off the traced shape, so
        per-resolution retracing matches the wire path's behavior."""
        if self.config.head_style == "scored":

            def fn(inputs):
                frames = inputs["images"]
                dets, valid = self._pipeline(
                    frames, (frames.shape[1], frames.shape[2])
                )
                return {
                    "boxes": dets[..., :4],
                    "scores": dets[..., 4],
                    "classes": dets[..., 5].astype(jnp.int32),
                    "dims": valid.sum(axis=-1).astype(jnp.int32),
                }

        else:

            def fn(inputs):
                frames = inputs["images"]
                dets, valid = self._pipeline(
                    frames, (frames.shape[1], frames.shape[2])
                )
                return {"detections": dets, "valid": valid}

        return fn


def load_class_names(path: str) -> tuple[str, ...]:
    """data/*.names loader (one class per line; reference
    yolov5_postprocess.py:19-26)."""
    with open(path) as f:
        return tuple(line.strip() for line in f if line.strip())


def build_yolov5_pipeline(
    rng: jax.Array | None = None,
    variant: str = "n",
    num_classes: int = 80,
    input_hw: tuple[int, int] = (512, 512),
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    config: Detect2DConfig | None = None,
    s2d: bool = False,
    ch_floor: int = 0,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect2DPipeline, ModelSpec, dict]:
    """Construct model + pipeline + serving spec in one call.

    The spec mirrors the reference's served contract
    (examples/YOLOv5/config.pbtxt: images in, [1, N, 5+nc] out) plus the
    packed-detections outputs unique to the fused pipeline.
    ``s2d``/``ch_floor`` are the MXU-shape options (models/yolov5.py) —
    identical detection function, faster chip layout. ``precision``
    selects the serving precision policy (runtime/precision.py): params
    are cast/quantized HERE, once, before registration.
    """
    policy, dtype = _resolve_precision(precision, dtype)
    model = YoloV5(
        num_classes=num_classes, variant=variant, dtype=dtype,
        s2d=s2d, ch_floor=ch_floor,
    )
    if variables is None:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
        variables = model.init(rng, dummy, train=False)
    # cast/quantize ONCE here; the UNCAST tree is still returned as the
    # weight-loading template (disk_repository restores checkpoints onto
    # the f32 structure, then rebuilds through this path)
    cast_vars = policy.cast_params(variables)

    def forward(x: jnp.ndarray) -> jnp.ndarray:
        # realize: int8 kernels dequantize inside the trace (HBM reads
        # stay int8); boundary: raw heads re-enter f32 BEFORE decode —
        # the KEEP_F32_2D contract
        raw = model.apply(realize(cast_vars), x, train=False)
        return model.decode(policy.boundary(raw))

    cfg = config or Detect2DConfig(
        model_name=f"yolov5{variant}", input_hw=input_hw, num_classes=num_classes
    )
    pipeline = Detect2DPipeline(cfg, forward, precision=policy)
    spec = _detect2d_spec(cfg, num_predictions(cfg.input_hw))
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(policy.spec_extra(cast_vars, KEEP_F32_2D))
    return pipeline, spec, variables


# builder-shared policy/compute-dtype resolution (runtime/precision.py)
_resolve_precision = resolve_policy


def build_yolov4_pipeline(
    rng: jax.Array | None = None,
    num_classes: int = 80,
    width: float = 1.0,
    input_hw: tuple[int, int] = (512, 512),
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    config: Detect2DConfig | None = None,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect2DPipeline, ModelSpec, dict]:
    """YOLOv4 variant of the fused pipeline (reference contract:
    examples/YOLOv4/config.pbtxt confs+boxes; decode parity with
    tools/yolo_layer.py). The flat pixel-unit decode drops into the same
    Detect2DPipeline as YOLOv5."""
    from triton_client_tpu.models.yolov4 import YoloV4
    from triton_client_tpu.models.yolov4 import num_predictions as v4_num_predictions

    policy, dtype = _resolve_precision(precision, dtype)
    model = YoloV4(num_classes=num_classes, width=width, dtype=dtype)
    if variables is None:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
        variables = model.init(rng, dummy, train=False)
    cast_vars = policy.cast_params(variables)

    def forward(x: jnp.ndarray) -> jnp.ndarray:
        raw = model.apply(realize(cast_vars), x, train=False)
        return model.decode_flat(policy.boundary(raw))

    cfg = config or Detect2DConfig(
        model_name="yolov4",
        input_hw=input_hw,
        num_classes=num_classes,
        conf_thresh=0.4,
        iou_thresh=0.6,
    )
    pipeline = Detect2DPipeline(cfg, forward, precision=policy)
    spec = _detect2d_spec(cfg, v4_num_predictions(cfg.input_hw))
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(policy.spec_extra(cast_vars, KEEP_F32_2D))
    return pipeline, spec, variables


def _detect2d_spec(cfg: Detect2DConfig, n_predictions: int) -> ModelSpec:
    """Serving spec shared by the 2D detector pipelines (the analogue of
    examples/YOLOv5/config.pbtxt + examples/YOLOv4/config.pbtxt)."""
    return ModelSpec(
        name=cfg.model_name,
        version="1",
        platform="jax",
        # Any camera resolution is accepted; the jitted graph re-traces
        # once per distinct resolution and resizes to input_hw on-device.
        # donatable: the pipeline consumes the staged frames exactly
        # once, so the serving channel may recycle the HBM input buffer
        # across consecutive batches (channel/tpu_channel.py).
        inputs=(
            TensorSpec("images", (-1, -1, -1, 3), "FP32", "NHWC", donatable=True),
        ),
        outputs=(
            TensorSpec("detections", (-1, cfg.max_det, 6), "FP32"),
            TensorSpec("valid", (-1, cfg.max_det), "BOOL"),
        ),
        # the 2D pipelines are genuinely batched (leading dim of every
        # tensor is the frame batch) — declaring it is what lets the
        # mesh-sharded serving channel split requests over the data
        # axis (channel/sharded_channel.py; Triton's own batchable
        # convention, examples/YOLOv5/config.pbtxt max_batch_size).
        # 8 matches the examples/ repository configs.
        max_batch_size=8,
        extra={
            "conf_thresh": cfg.conf_thresh,
            "iou_thresh": cfg.iou_thresh,
            "model_input_hw": list(cfg.input_hw),
            "num_predictions": n_predictions,
            "num_classes": cfg.num_classes,
            # Remote clients label/draw from served metadata
            # (parse_model role, base_client.py:32-104).
            "class_names": list(cfg.class_names),
        },
    )


def build_retinanet_pipeline(
    rng: jax.Array | None = None,
    num_classes: int = 80,
    depth: str = "resnet50",
    input_hw: tuple[int, int] = (480, 640),
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    config: Detect2DConfig | None = None,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect2DPipeline, ModelSpec, dict]:
    """RetinaNet (detectron family) fused pipeline.

    Contract parity: examples/RetinaNet_detectron/config.pbtxt (3x640x480
    input; boxes/classes/scores/dims outputs — served via
    detectron_infer_fn). Unlike the YOLO paths there is no /255 scaling
    (clients/preprocess/detectron_preprocess.py:12-24 feeds raw pixels).
    """
    from triton_client_tpu.models.retinanet import RetinaNet

    policy, dtype = _resolve_precision(precision, dtype)
    model = RetinaNet(
        num_classes=num_classes, depth=depth, input_hw=input_hw, dtype=dtype
    )
    if variables is None:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        dummy = jnp.zeros((1, *input_hw, 3), jnp.float32)
        variables = model.init(rng, dummy, train=False)
    cast_vars = policy.cast_params(variables)

    def forward(x: jnp.ndarray):
        # decode runs inside model.decode here (anchors -> boxes): feed
        # it f32 heads per the keep-list
        raw = model.apply(realize(cast_vars), x, train=False)
        return model.decode(policy.boundary(raw))

    cfg = config or Detect2DConfig(
        model_name="retinanet",
        input_hw=input_hw,
        num_classes=num_classes,
        conf_thresh=0.05,
        iou_thresh=0.5,
        max_det=100,
        scaling="none",
        multi_label=True,
        head_style="scored",
    )
    pipeline = Detect2DPipeline(cfg, forward, precision=policy)
    spec = _detectron_spec(cfg)
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(policy.spec_extra(cast_vars, KEEP_F32_2D))
    return pipeline, spec, variables


def build_fcos_pipeline(
    rng: jax.Array | None = None,
    num_classes: int = 80,
    depth: str = "resnet50",
    input_hw: tuple[int, int] = (480, 640),
    variables=None,
    dtype: jnp.dtype = jnp.float32,
    config: Detect2DConfig | None = None,
    precision: PrecisionPolicy | str | None = None,
) -> tuple[Detect2DPipeline, ModelSpec, dict]:
    """FCOS (anchor-free detectron family; the reference's FCOS_client)."""
    from triton_client_tpu.models.retinanet import FCOS

    policy, dtype = _resolve_precision(precision, dtype)
    model = FCOS(
        num_classes=num_classes, depth=depth, input_hw=input_hw, dtype=dtype
    )
    if variables is None:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        dummy = jnp.zeros((1, *input_hw, 3), jnp.float32)
        variables = model.init(rng, dummy, train=False)
    cast_vars = policy.cast_params(variables)

    def forward(x: jnp.ndarray):
        raw = model.apply(realize(cast_vars), x, train=False)
        return model.decode(policy.boundary(raw))

    cfg = config or Detect2DConfig(
        model_name="fcos",
        input_hw=input_hw,
        num_classes=num_classes,
        conf_thresh=0.05,
        iou_thresh=0.6,
        max_det=100,
        scaling="none",
        multi_label=True,
        head_style="scored",
    )
    pipeline = Detect2DPipeline(cfg, forward, precision=policy)
    spec = _detectron_spec(cfg)
    spec.extra["fused_stages"] = list(pipeline.fused_stages)
    spec.extra.update(policy.spec_extra(cast_vars, KEEP_F32_2D))
    return pipeline, spec, variables


def detectron_infer_fn(pipeline: Detect2DPipeline):
    """Back-compat alias: scored pipelines' infer_fn() already emits the
    detectron contract (boxes/scores/classes/dims)."""
    return pipeline.infer_fn()


def _detectron_spec(cfg: Detect2DConfig) -> ModelSpec:
    return ModelSpec(
        name=cfg.model_name,
        version="1",
        platform="jax",
        inputs=(TensorSpec("images", (-1, -1, -1, 3), "FP32", "NHWC"),),
        outputs=(
            TensorSpec("boxes", (-1, cfg.max_det, 4), "FP32"),
            TensorSpec("scores", (-1, cfg.max_det), "FP32"),
            TensorSpec("classes", (-1, cfg.max_det), "INT64"),
            TensorSpec("dims", (-1,), "INT32"),
        ),
        max_batch_size=8,
        extra={
            "conf_thresh": cfg.conf_thresh,
            "iou_thresh": cfg.iou_thresh,
            "scaling": cfg.scaling,
            "class_names": list(cfg.class_names),
        },
    )


def _build_preprocess(**kwargs):
    # lazy import: preprocess2d imports nothing heavy, but keeping the
    # table entries uniform (callable indirection) avoids import cycles
    from triton_client_tpu.pipelines.preprocess2d import (
        build_preprocess_pipeline,
    )

    return build_preprocess_pipeline(**kwargs)


# family name -> builder; the single dispatch table shared by the CLI
# entry points and the disk model repository.
BUILDERS_2D = {
    "yolov5": build_yolov5_pipeline,
    "yolov4": build_yolov4_pipeline,
    "retinanet": build_retinanet_pipeline,
    "fcos": build_fcos_pipeline,
    "preprocess": _build_preprocess,
}
