"""RetinaNet / FCOS (the reference's "detectron" family) in flax, NHWC.

The reference serves these models as Detectron2 exports behind Triton
(examples/RetinaNet_detectron/config.pbtxt: libtorch backend, 640x480
input, 4 outputs boxes/classes/scores/dims) and its client does no
decoding at all (clients/detectron_client.py:4-21,
clients/postprocess/detectron_postprocess.py:26-38). Here the whole
model lives in-tree, TPU-first:

  * ResNet backbone: NHWC convs so XLA tiles the MXU, bf16-capable,
    basic blocks (resnet18-style) or bottlenecks (resnet50-style);
  * FPN P3-P7 with the RetinaNet extra P6/P7 convs;
  * two heads over the shared pyramid:
      - RetinaNetHead: anchor-based, A=9, class subnet + box subnet,
        prior-prob bias init so training starts stable;
      - FCOSHead: anchor-free, ltrb + centerness (the reference's
        FCOS_client model);
  * decode folds the anchor table (trace-time constant) into the jit;
    NMS comes from ops.nms downstream.

Heads emit (B, N, ...) flattened over levels in pyramid order, matching
ops.anchor_decode's tables.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.ops.anchor_decode import (
    RETINA_OCTAVES,
    RETINA_RATIOS,
    RETINA_STRIDES,
    decode_deltas,
    fcos_decode,
    fcos_locations,
    pyramid_anchors,
)


class _ConvBnRelu(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    act: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.kernel // 2
        x = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding=((p, p), (p, p)),
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, dtype=self.dtype, name="bn"
        )(x)
        return nn.relu(x) if self.act else x


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        y = _ConvBnRelu(self.features, 3, self.stride, dtype=self.dtype, name="c1")(
            x, train
        )
        y = _ConvBnRelu(self.features, 3, 1, act=False, dtype=self.dtype, name="c2")(
            y, train
        )
        if identity.shape != y.shape:
            identity = _ConvBnRelu(
                self.features, 1, self.stride, act=False, dtype=self.dtype, name="down"
            )(x, train)
        return nn.relu(identity + y)


class Bottleneck(nn.Module):
    features: int  # output width (4x the inner width)
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        inner = self.features // 4
        identity = x
        y = _ConvBnRelu(inner, 1, 1, dtype=self.dtype, name="c1")(x, train)
        y = _ConvBnRelu(inner, 3, self.stride, dtype=self.dtype, name="c2")(y, train)
        y = _ConvBnRelu(self.features, 1, 1, act=False, dtype=self.dtype, name="c3")(
            y, train
        )
        if identity.shape != y.shape:
            identity = _ConvBnRelu(
                self.features, 1, self.stride, act=False, dtype=self.dtype, name="down"
            )(x, train)
        return nn.relu(identity + y)


# depth preset -> (block, blocks-per-stage, stage widths)
_RESNETS = {
    # "tiny" keeps unit tests fast: one block per stage, narrow.
    "tiny": (BasicBlock, (1, 1, 1, 1), (16, 32, 64, 128)),
    "resnet18": (BasicBlock, (2, 2, 2, 2), (64, 128, 256, 512)),
    "resnet34": (BasicBlock, (3, 4, 6, 3), (64, 128, 256, 512)),
    "resnet50": (Bottleneck, (3, 4, 6, 3), (256, 512, 1024, 2048)),
}
RESNET_DEPTHS = tuple(_RESNETS)


class ResNetFPN(nn.Module):
    """ResNet C2-C5 -> FPN P3-P7 feature pyramid."""

    depth: str = "resnet50"
    fpn_width: int = 256
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> list[jnp.ndarray]:
        block, stages, widths = _RESNETS[self.depth]
        stem = widths[0] // 4 if block is Bottleneck else widths[0]
        x = _ConvBnRelu(stem, 7, 2, dtype=self.dtype, name="stem")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        feats = []
        for si, (n, w) in enumerate(zip(stages, widths)):
            for bi in range(n):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = block(w, stride, dtype=self.dtype, name=f"s{si}b{bi}")(x, train)
            feats.append(x)
        _, c3, c4, c5 = feats

        # FPN lateral + top-down (P3-P5), plus RetinaNet's P6/P7.
        fw = self.fpn_width
        p5 = nn.Conv(fw, (1, 1), dtype=self.dtype, name="lat5")(c5)
        p4 = nn.Conv(fw, (1, 1), dtype=self.dtype, name="lat4")(c4) + _upsample2(p5, c4)
        p3 = nn.Conv(fw, (1, 1), dtype=self.dtype, name="lat3")(c3) + _upsample2(p4, c3)
        p3 = nn.Conv(fw, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="out3")(p3)
        p4 = nn.Conv(fw, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="out4")(p4)
        p5 = nn.Conv(fw, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="out5")(p5)
        p6 = nn.Conv(
            fw, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)), dtype=self.dtype, name="p6"
        )(c5)
        p7 = nn.Conv(
            fw, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)), dtype=self.dtype, name="p7"
        )(nn.relu(p6))
        return [p3, p4, p5, p6, p7]


def _upsample2(x: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Nearest 2x upsample to `like`'s spatial shape (handles odd sizes)."""
    b, h, w, c = like.shape
    return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), method="nearest")


def _prior_bias(prior: float = 0.01) -> float:
    """Focal-loss prior bias for classification convs."""
    return -math.log((1 - prior) / prior)


class RetinaNetHead(nn.Module):
    """Shared class/box subnets applied to every pyramid level."""

    num_classes: int
    num_anchors: int = len(RETINA_RATIOS) * len(RETINA_OCTAVES)
    width: int = 256
    depth: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pyramid: Sequence[jnp.ndarray]) -> tuple[jnp.ndarray, jnp.ndarray]:
        """-> (B, N, num_classes) logits, (B, N, 4) deltas; N flattened
        over levels in pyramid order (matches pyramid_anchors)."""
        cls_convs = [
            nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name=f"cls{i}")
            for i in range(self.depth)
        ]
        box_convs = [
            nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name=f"box{i}")
            for i in range(self.depth)
        ]
        cls_out = nn.Conv(
            self.num_anchors * self.num_classes,
            (3, 3),
            padding=((1, 1), (1, 1)),
            bias_init=nn.initializers.constant(_prior_bias()),
            dtype=jnp.float32,
            name="cls_out",
        )
        box_out = nn.Conv(
            self.num_anchors * 4,
            (3, 3),
            padding=((1, 1), (1, 1)),
            dtype=jnp.float32,
            name="box_out",
        )

        logits, deltas = [], []
        for feat in pyramid:
            c = feat
            for conv in cls_convs:
                c = nn.relu(conv(c))
            c = cls_out(c.astype(jnp.float32))
            b, h, w, _ = c.shape
            logits.append(c.reshape(b, h * w * self.num_anchors, self.num_classes))

            d = feat
            for conv in box_convs:
                d = nn.relu(conv(d))
            d = box_out(d.astype(jnp.float32))
            deltas.append(d.reshape(b, h * w * self.num_anchors, 4))
        return jnp.concatenate(logits, axis=1), jnp.concatenate(deltas, axis=1)


class FCOSHead(nn.Module):
    """Anchor-free head: class logits + ltrb distances + centerness."""

    num_classes: int
    width: int = 256
    depth: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, pyramid: Sequence[jnp.ndarray]
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """-> (B, N, nc) logits, (B, N, 4) ltrb >= 0, (B, N) centerness."""
        cls_convs = [
            nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name=f"cls{i}")
            for i in range(self.depth)
        ]
        reg_convs = [
            nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name=f"reg{i}")
            for i in range(self.depth)
        ]
        cls_out = nn.Conv(
            self.num_classes,
            (3, 3),
            padding=((1, 1), (1, 1)),
            bias_init=nn.initializers.constant(_prior_bias()),
            dtype=jnp.float32,
            name="cls_out",
        )
        reg_out = nn.Conv(4, (3, 3), padding=((1, 1), (1, 1)), dtype=jnp.float32,
                          name="reg_out")
        ctr_out = nn.Conv(1, (3, 3), padding=((1, 1), (1, 1)), dtype=jnp.float32,
                          name="ctr_out")

        logits, ltrb, ctr = [], [], []
        for li, feat in enumerate(pyramid):
            # Per-level learnable scale on the distance regression
            # (FCOS's trainable scalar per level).
            scale = self.param(f"scale{li}", nn.initializers.ones, (1,), jnp.float32)
            c = feat
            for conv in cls_convs:
                c = nn.relu(conv(c))
            r = feat
            for conv in reg_convs:
                r = nn.relu(conv(r))
            cl = cls_out(c.astype(jnp.float32))
            b, h, w, _ = cl.shape
            logits.append(cl.reshape(b, h * w, self.num_classes))
            dist = nn.relu(reg_out(r.astype(jnp.float32)) * scale) * RETINA_STRIDES[li]
            ltrb.append(dist.reshape(b, h * w, 4))
            ctr.append(ctr_out(r.astype(jnp.float32)).reshape(b, h * w))
        return (
            jnp.concatenate(logits, axis=1),
            jnp.concatenate(ltrb, axis=1),
            jnp.concatenate(ctr, axis=1),
        )


class RetinaNet(nn.Module):
    """Backbone + FPN + RetinaNet head, with in-jit decode."""

    num_classes: int = 80
    depth: str = "resnet50"
    input_hw: tuple[int, int] = (480, 640)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pyramid = ResNetFPN(self.depth, dtype=self.dtype, name="backbone")(x, train)
        return RetinaNetHead(self.num_classes, dtype=self.dtype, name="head")(pyramid)

    def decode(self, outputs) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(logits, deltas) -> ((B, N, 4) xyxy boxes, (B, N, nc) scores)."""
        logits, deltas = outputs
        anchors = jnp.asarray(pyramid_anchors(self.input_hw))
        return decode_deltas(anchors, deltas), jax.nn.sigmoid(logits)


class FCOS(nn.Module):
    """Backbone + FPN + FCOS head, with in-jit decode."""

    num_classes: int = 80
    depth: str = "resnet50"
    input_hw: tuple[int, int] = (480, 640)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pyramid = ResNetFPN(self.depth, dtype=self.dtype, name="backbone")(x, train)
        return FCOSHead(self.num_classes, dtype=self.dtype, name="head")(pyramid)

    def decode(self, outputs) -> tuple[jnp.ndarray, jnp.ndarray]:
        """-> ((B, N, 4) boxes, (B, N, nc) scores); scores are
        sqrt(cls * centerness), FCOS's test-time scoring."""
        logits, ltrb, ctr = outputs
        locations = jnp.asarray(fcos_locations(self.input_hw))
        boxes = fcos_decode(locations, ltrb)
        scores = jnp.sqrt(
            jax.nn.sigmoid(logits) * jax.nn.sigmoid(ctr)[..., None]
        )
        return boxes, scores


def num_locations(input_hw: tuple[int, int], per_cell: int = 1) -> int:
    return sum(
        (-(-input_hw[0] // s)) * (-(-input_hw[1] // s)) * per_cell
        for s in RETINA_STRIDES
    )


def init_retinanet(
    rng: Any,
    num_classes: int = 80,
    depth: str = "resnet50",
    input_hw: tuple[int, int] = (480, 640),
    dtype: jnp.dtype = jnp.float32,
):
    model = RetinaNet(num_classes=num_classes, depth=depth, input_hw=input_hw,
                      dtype=dtype)
    dummy = jnp.zeros((1, *input_hw, 3), jnp.float32)
    return model, model.init(rng, dummy, train=False)


def init_fcos(
    rng: Any,
    num_classes: int = 80,
    depth: str = "resnet50",
    input_hw: tuple[int, int] = (480, 640),
    dtype: jnp.dtype = jnp.float32,
):
    model = FCOS(num_classes=num_classes, depth=depth, input_hw=input_hw, dtype=dtype)
    dummy = jnp.zeros((1, *input_hw, 3), jnp.float32)
    return model, model.init(rng, dummy, train=False)
