"""SECOND-IoU voxel detector, TPU re-design (dense 3D middle encoder).

The reference serves SECOND-IoU via OpenPCDet + spconv CUDA sparse
convolutions (examples/second_iou/1/model.py:96-157; spconv build at
docker/server_3d/Dockerfile:41-55). TPUs have no sparse-conv story —
XLA wants dense, static-shaped convs on the MXU — so this is an
explicit re-design, not a port (SURVEY.md §7 "hard parts" (c)):

  * MeanVFE: per-voxel mean of points (OpenPCDet's MeanVFE);
  * dense middle encoder: voxel features scatter into a dense
    (nz, ny, nx, C) volume; stride-2 3D convs replace the sparse
    conv stages. Densifying at the reference's 0.05 m voxels would
    need a ~1408x1600x40 volume, so the default grid is coarser
    (0.2 x 0.2 x 0.4 m -> 352x400x10) — the accuracy/memory trade
    the dense emulation buys its MXU throughput with;
  * z collapses into channels -> the same BEVBackbone + anchor head
    as PointPillars (shared via duck-typed config fields);
  * the SECOND-IoU part: an extra per-anchor IoU-quality head whose
    prediction rectifies the classification score at decode time
    (score = cls^(1-a) * iou_q^a, the cascade's score calibration).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.models.pointpillars import (
    KITTI_ANCHORS,
    ROTATIONS,
    AnchorClassConfig,
    BEVBackbone,
    decode_boxes,
    decode_candidates,
    generate_anchors,
    rectify_direction,
    validate_bev_divisible,
)
from triton_client_tpu.ops.voxelize import VoxelConfig


@dataclasses.dataclass(frozen=True)
class SECONDConfig:
    # Coarser-than-reference grid: dense 3D volume must fit in HBM.
    voxel: VoxelConfig = VoxelConfig(
        point_cloud_range=(0.0, -40.0, -3.0, 70.4, 40.0, 1.0),
        voxel_size=(0.2, 0.2, 0.4),
        max_voxels=40000,  # kitti_dataset.yaml:66-70 test budget
        max_points_per_voxel=5,
    )
    middle_filters: tuple[int, ...] = (16, 32, 64)
    # 'dense' (stride-2 3D convs over the densified volume — needs a
    # coarse grid) or 'sparse' (submanifold gather convs over a fixed
    # occupancy budget, ops/sparse_conv.py — runs the reference's
    # 0.05 m grid where the dense volume would be 5.4 GB).
    middle: str = "dense"
    # sparse path: max occupied voxels at level 0 (0 -> voxel.max_voxels);
    # deeper levels auto-halve (floor 8192) — occupancy shrinks with
    # every stride and neighbor lookups are priced per budget ROW
    sparse_budget: int = 0
    # sparse path: densify from this stage index onward and run real
    # MXU convs — pick the first stage whose INPUT grid volume is
    # affordable (stage i reads level i-1: e.g. the 0.05 m config's
    # stage 3 reads 352x400x10x64 = 0.36 GB, while stage 2 would read
    # a 1.4 GB level-1 volume). 0 disables the dense tail.
    sparse_dense_tail_from: int = 0
    # strided-conv kernel: 2 (2^3 offsets, Minkowski downsample — the
    # perf default: a third of the 3^3 kernel's gather work) or 3
    # (spconv's exact kernel shape)
    sparse_stride_kernel: int = 2
    # BEVBackbone duck-typed fields (shared with PointPillarsConfig).
    backbone_layers: tuple[int, ...] = (5, 5)
    backbone_strides: tuple[int, ...] = (1, 2)
    backbone_filters: tuple[int, ...] = (128, 256)
    upsample_strides: tuple[int, ...] = (1, 2)
    upsample_filters: tuple[int, ...] = (256, 256)
    anchor_classes: tuple[AnchorClassConfig, ...] = KITTI_ANCHORS
    num_dir_bins: int = 2
    dir_offset: float = 0.78539
    # Score rectification exponent (OpenPCDet second_iou's
    # IOU_RECTIFIER alpha): score = cls^(1-a) * iou_q^a.
    iou_alpha: float = 0.71

    @property
    def num_classes(self) -> int:
        return len(self.anchor_classes)

    @property
    def anchors_per_loc(self) -> int:
        return len(self.anchor_classes) * len(ROTATIONS)

    @property
    def middle_stride(self) -> int:
        """BEV downsample factor of the middle encoder (2 per stage
        after the first)."""
        return 2 ** max(0, len(self.middle_filters) - 1)

    @property
    def head_stride(self) -> int:
        return self.middle_stride * (
            self.backbone_strides[0] // self.upsample_strides[0]
        )

    @property
    def head_hw(self) -> tuple[int, int]:
        nx, ny, _ = self.voxel.grid_size
        s = self.head_stride
        return ny // s, nx // s

    def validate(self) -> None:
        validate_bev_divisible(
            self.voxel, self.middle_stride * int(np.prod(self.backbone_strides))
        )


def _scatter_mean_volume(points: jnp.ndarray, count: jnp.ndarray, voxel) -> jnp.ndarray:
    """(N, F) padded cloud -> dense (nz, ny, nx, F) per-cell mean
    volume. ONE fused scatter-add carries feature sums AND counts (last
    column is the per-point weight) — a 131k-row TPU scatter costs
    ~5 ms, so halving the passes is directly measurable. Shared by the
    serving (from_points) and training (from_points_batch) paths so
    their VFE numerics can never diverge."""
    from triton_client_tpu.ops.voxelize import assign_cells, linearize_zyx

    nx, ny, nz = voxel.grid_size
    ijk, valid = assign_cells(points, count, voxel)
    vid, n_cells = linearize_zyx(ijk, valid, voxel)
    w = valid.astype(points.dtype)[:, None]
    f = points.shape[-1]
    acc = jnp.zeros((n_cells + 1, f + 1), points.dtype)
    acc = acc.at[vid].add(
        jnp.concatenate([points, jnp.ones_like(w)], axis=1) * w
    )
    volume = acc[:n_cells, :f] / jnp.maximum(acc[:n_cells, f:], 1.0)
    return volume.reshape(nz, ny, nx, f)


def scatter_to_volume(
    voxel_feats: jnp.ndarray,  # (V, C)
    coords: jnp.ndarray,       # (V, 3) [z, y, x], -1 invalid
    grid_dhw: tuple[int, int, int],
) -> jnp.ndarray:
    """Dense (nz, ny, nx, C) volume; invalid voxels land in a dump slot
    (the densify step replacing spconv's sparse tensor)."""
    d, h, w = grid_dhw
    c = voxel_feats.shape[-1]
    zz, yy, xx = coords[:, 0], coords[:, 1], coords[:, 2]
    valid = (zz >= 0) & (yy >= 0) & (xx >= 0)
    flat = jnp.where(valid, (zz * h + yy) * w + xx, d * h * w)
    canvas = jnp.zeros((d * h * w + 1, c), voxel_feats.dtype)
    canvas = canvas.at[flat].set(voxel_feats)
    return canvas[: d * h * w].reshape(d, h, w, c)


class MeanVFE(nn.Module):
    """Per-voxel mean of raw point features (OpenPCDet MeanVFE)."""

    @nn.compact
    def __call__(self, voxels: jnp.ndarray, num_points: jnp.ndarray) -> jnp.ndarray:
        k = voxels.shape[1]
        mask = (jnp.arange(k)[None, :] < num_points[:, None])[..., None]
        cnt = jnp.maximum(num_points, 1)[:, None]
        return (voxels * mask).sum(axis=1) / cnt


class DenseMiddleEncoder(nn.Module):
    """Stride-2 3D conv stages over the dense volume, then z folds into
    channels for the BEV stack."""

    filters: tuple[int, ...]
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, volume: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = volume.astype(self.dtype)
        for si, f in enumerate(self.filters):
            stride = (2, 2, 2) if si > 0 else (1, 1, 1)
            x = nn.Conv(
                f, (3, 3, 3), strides=stride, padding=1, use_bias=False,
                dtype=self.dtype, name=f"conv{si}",
            )(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.99, epsilon=1e-3,
                dtype=self.dtype, name=f"bn{si}",
            )(x)
            x = nn.relu(x)
        if x.ndim == 5:  # batched (training path): (B, d, h, w, c)
            bsz, d, h, w, c = x.shape
            return jnp.transpose(x, (0, 2, 3, 1, 4)).reshape(bsz, h, w, d * c)
        d, h, w, c = x.shape
        return jnp.transpose(x, (1, 2, 0, 3)).reshape(h, w, d * c)


class SparseMiddleEncoder(nn.Module):
    """The sparse sibling of DenseMiddleEncoder — same stage/filter
    structure (stage 0 submanifold, stride-2 sparse conv per later
    stage), spconv-like semantics over a fixed occupancy budget
    (ops/sparse_conv.py), ending in the same (h, w, nz' * C) BEV
    fold. Value-parity with the dense encoder holds per layer at
    occupied sites (unoccupied neighbors contribute zeros either way);
    across layers the dense path additionally grows a halo of
    activations at unoccupied cells that submanifold convs — like the
    reference's spconv stack — deliberately do not compute.

    Perf structure (measured on a v5e chip, perf/profile_sparse_second
    probes: neighbor lookups ~30 ms per 27x65k rows against the level-0
    table, feature gathers ~0.4 ms per 65k x 64ch pass): deeper levels
    halve the voxel budget (occupancy shrinks with every stride, and
    lookups are priced per budget row), strided convs default to the
    2^3 kernel, and from ``dense_tail_from`` on the level is densified
    and convolved with real MXU 3D convs."""

    filters: tuple[int, ...]
    grid: tuple[int, int, int]  # (nz, ny, nx)
    budget: int
    dense_tail_from: int = 2
    stride_kernel: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        ijk: jnp.ndarray,    # (V, 3) [z, y, x]
        feats: jnp.ndarray,  # (V, Cin)
        valid: jnp.ndarray,  # (V,)
        train: bool = False,
    ) -> jnp.ndarray:
        from triton_client_tpu.ops import sparse_conv as sp

        def bn_act(x, si, mask=None):
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.99, epsilon=1e-3,
                dtype=self.dtype, name=f"bn{si}",
            )(x)
            x = nn.relu(x)
            return x if mask is None else jnp.where(mask[:, None], x, 0.0)

        vs = sp.VoxelSet(ijk, feats.astype(self.dtype), valid, self.grid)
        budget = self.budget
        volume = None  # set once the dense tail starts
        for si, f in enumerate(self.filters):
            if volume is not None:  # dense tail stage
                volume = nn.Conv(
                    f, (3, 3, 3), strides=(2, 2, 2), padding=1,
                    use_bias=False, dtype=self.dtype, name=f"conv{si}",
                )(volume)
                volume = bn_act(volume, si)
                continue
            cin = vs.feats.shape[-1]
            if si == 0:
                w = self.param(
                    f"conv{si}", nn.initializers.he_normal(),
                    (27, cin, f), self.dtype,
                )
                x = sp.subm_conv(vs, sp.slot_table(vs), w)
                vs = sp.VoxelSet(vs.ijk, x, vs.valid, vs.grid)
            else:
                k3 = self.stride_kernel ** 3
                w = self.param(
                    f"conv{si}", nn.initializers.he_normal(),
                    (k3, cin, f), self.dtype,
                )
                budget = max(budget // 2, 8192)
                vs = sp.sparse_strided_conv(vs, sp.slot_table(vs), w, budget)
            x = bn_act(vs.feats, si, vs.valid)
            vs = sp.VoxelSet(vs.ijk, x, vs.valid, vs.grid)
            if (
                self.dense_tail_from
                and si + 1 >= self.dense_tail_from
                and si + 1 < len(self.filters)
            ):
                volume = sp.densify(vs)
        if volume is not None:
            d, h, w_, c = volume.shape
            return jnp.transpose(volume, (1, 2, 0, 3)).reshape(h, w_, d * c)
        return sp.scatter_bev(vs)


class SECONDIoU(nn.Module):
    """MeanVFE -> densify -> 3D encoder -> BEV backbone -> anchor +
    IoU-quality heads. ``from_points`` is the sort-free single-scan
    path: MeanVFE is parameter-free, so the mean volume is computed
    directly with dense-grid scatter-add (no (V, K) grouping, no point
    sort) — works for ANY nz since the full 3D cell id is used."""

    cfg: SECONDConfig = SECONDConfig()
    dtype: jnp.dtype = jnp.float32

    # mean VFE keys on the full 3D cell id, so the scatter path is valid
    # for tall (nz > 1) grids too — the pillar models' is not
    scatter_any_nz = True

    def setup(self) -> None:
        cfg, dt = self.cfg, self.dtype
        cfg.validate()
        self.vfe = MeanVFE()
        if cfg.middle == "sparse":
            nx, ny, nz = cfg.voxel.grid_size
            self.middle = SparseMiddleEncoder(
                cfg.middle_filters,
                grid=(nz, ny, nx),
                budget=cfg.sparse_budget or cfg.voxel.max_voxels,
                dense_tail_from=cfg.sparse_dense_tail_from,
                stride_kernel=cfg.sparse_stride_kernel,
                dtype=dt,
            )
        elif cfg.middle == "dense":
            self.middle = DenseMiddleEncoder(cfg.middle_filters, dtype=dt)
        else:
            raise ValueError(
                f"SECONDConfig.middle must be 'dense' or 'sparse', "
                f"got {cfg.middle!r}"
            )
        self.backbone = BEVBackbone(cfg, dtype=dt)
        a = cfg.anchors_per_loc
        self.cls_head = nn.Conv(a * cfg.num_classes, (1, 1), dtype=jnp.float32)
        self.box_head = nn.Conv(a * 7, (1, 1), dtype=jnp.float32)
        self.dir_head = nn.Conv(a * cfg.num_dir_bins, (1, 1), dtype=jnp.float32)
        self.iou_head = nn.Conv(a, (1, 1), dtype=jnp.float32)

    def __call__(
        self,
        voxels: jnp.ndarray,      # (B, V, K, F)
        num_points: jnp.ndarray,  # (B, V)
        coords: jnp.ndarray,      # (B, V, 3) [z, y, x]
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        nx, ny, nz = self.cfg.voxel.grid_size
        b, v, k, f = voxels.shape
        # flat (B*V) mean-VFE (module calls under jax.vmap trip flax's
        # transform check; the per-voxel mean is batch-independent)
        feats = self.vfe(
            voxels.reshape(b * v, k, f), num_points.reshape(b * v)
        ).reshape(b, v, -1)  # (B, V, F)
        if self.cfg.middle == "sparse":
            valid = coords[:, :, 0] >= 0
            # unrolled per-sample loop instead of vmap for the same
            # flax constraint; serving batches are B=1 scans
            bev = jnp.stack(
                [
                    self.middle(coords[i], feats[i], valid[i], train)
                    for i in range(b)
                ]
            )
            return self._heads_from_bev(bev, train)
        volume = jax.vmap(lambda f, c: scatter_to_volume(f, c, (nz, ny, nx)))(
            feats, coords
        )  # (B, nz, ny, nx, F)
        return self._heads(volume, train)

    def from_points(
        self,
        points: jnp.ndarray,  # (N, F>=4) padded cloud
        count: jnp.ndarray,   # () real rows
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Sort-free scatter path: per-cell mean via scatter-add (batch
        1). Bit-exact vs the grouped path (up to fp addition order)
        while the voxel budgets are not hit."""
        if self.cfg.middle == "sparse":
            from triton_client_tpu.ops.sparse_conv import points_to_voxelset

            vs = points_to_voxelset(
                points, count, self.cfg.voxel,
                self.cfg.sparse_budget or self.cfg.voxel.max_voxels,
            )
            bev = self.middle(vs.ijk, vs.feats, vs.valid, train)
            return self._heads_from_bev(bev[None], train)
        volume = _scatter_mean_volume(points, count, self.cfg.voxel)
        return self._heads(volume[None], train)

    def from_volume(
        self, volume: jnp.ndarray, train: bool = False
    ) -> dict[str, jnp.ndarray]:
        """Dense-middle entry for an externally-built (nz, ny, nx, F)
        mean volume — how the fused voxelize->scatter kernel
        (ops/pallas_voxel.fused_mean_volume) feeds the model without
        re-threading the point cloud through _scatter_mean_volume."""
        if self.cfg.middle == "sparse":
            raise ValueError("from_volume requires the dense middle encoder")
        return self._heads(volume[None], train)

    def from_points_batch(
        self,
        points: jnp.ndarray,  # (B, P, F>=4) padded clouds
        counts: jnp.ndarray,  # (B,) real rows
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Batched TRAINING path (dense middle only): per-sample mean
        volume via pure scatter (vmap-safe — MeanVFE has no params),
        then the middle encoder runs on the rank-5 batch directly so
        its BatchNorm sees the whole batch (a vmapped BN would trip
        flax's broadcast-state mutation, the same constraint as
        PointPillars.from_points_batch)."""
        if self.cfg.middle == "sparse":
            raise NotImplementedError(
                "training runs the dense middle encoder; train at a "
                "dense-capable grid (e.g. the 0.2 m default) and serve "
                "sparse after import"
            )
        volume = jax.vmap(
            lambda p, c: _scatter_mean_volume(p, c, self.cfg.voxel)
        )(points, counts)  # (B, nz, ny, nx, F)
        bev = self.middle(volume, train)  # rank-5 aware
        return self._heads_from_bev(bev, train)

    def _heads(self, volume: jnp.ndarray, train: bool) -> dict[str, jnp.ndarray]:
        # the middle encoder is rank-5 aware (see from_points_batch), so
        # the batch runs directly — no module call under jax.vmap
        bev = self.middle(volume, train)  # (B, h, w, C)
        return self._heads_from_bev(bev, train)

    def _heads_from_bev(
        self, bev: jnp.ndarray, train: bool
    ) -> dict[str, jnp.ndarray]:
        cfg = self.cfg
        spatial = self.backbone(bev, train).astype(jnp.float32)
        cls = self.cls_head(spatial)
        box = self.box_head(spatial)
        direction = self.dir_head(spatial)
        iou = self.iou_head(spatial)
        a = cfg.anchors_per_loc
        b, h, w, _ = cls.shape
        return {
            "cls": cls.reshape(b, h, w, a, cfg.num_classes),
            "box": box.reshape(b, h, w, a, 7),
            "dir": direction.reshape(b, h, w, a, cfg.num_dir_bins),
            "iou": iou.reshape(b, h, w, a),
        }

    def topk_candidates(
        self,
        heads: dict[str, jnp.ndarray],
        pre_max: int = 512,
        score_thresh: float = 0.1,
    ) -> dict[str, jnp.ndarray]:
        """Gate + top-k on the IoU-RECTIFIED score, BEFORE box decode
        (the PointPillars.topk_candidates counterpart).

        Unlike the plain anchor head, the ranking metric here is
        cls^(1-a) * q^a — not monotonic in the class logit alone — so
        the rectified score is computed densely (cheap elementwise over
        the anchor grid) and only the residual BOX decode is deferred to
        the K gathered candidates. Ordering matches decode() +
        extract_boxes_3d exactly."""
        cfg = self.cfg
        b, h, w, a_, nc = heads["cls"].shape
        n = h * w * a_
        cls_score = jax.nn.sigmoid(heads["cls"].reshape(b, n, nc))
        q = jnp.clip(
            (jnp.clip(heads["iou"].reshape(b, n), -1.0, 1.0) + 1.0) / 2.0,
            1e-6, 1.0,
        )
        al = cfg.iou_alpha
        score = cls_score ** (1.0 - al) * (q[..., None] ** al)

        best = score.max(axis=-1)
        labels = score.argmax(axis=-1) + 1
        k = min(pre_max, n)
        top_scores, top_idx = jax.lax.top_k(best, k)

        box = heads["box"].reshape(b, n, 7)
        dirs = heads["dir"].reshape(b, n, cfg.num_dir_bins)
        anchors = generate_anchors(cfg).reshape(n, 7)
        box_k = jnp.take_along_axis(box, top_idx[..., None], axis=1)
        dir_k = jnp.take_along_axis(dirs, top_idx[..., None], axis=1)
        labels_k = jnp.take_along_axis(labels, top_idx, axis=1)
        anchors_k = anchors[top_idx]

        scores = jnp.where(top_scores > score_thresh, top_scores, -jnp.inf)
        return {
            "deltas": box_k,
            "anchors": anchors_k,
            "dir_bin": jnp.argmax(dir_k, axis=-1),
            "scores": scores,
            "labels": labels_k,
        }

    def decode_topk(
        self,
        heads: dict[str, jnp.ndarray],
        pre_max: int = 512,
        score_thresh: float = 0.1,
    ) -> dict[str, jnp.ndarray]:
        """topk_candidates + the XLA residual-decode tail: boxes
        (B, K, 7), scores (B, K) with -inf on gated-out slots, labels
        (B, K) 1-indexed."""
        cfg = self.cfg
        cand = self.topk_candidates(heads, pre_max, score_thresh)
        return decode_candidates(cand, cfg.num_dir_bins, cfg.dir_offset)

    def decode(self, heads: dict[str, jnp.ndarray]) -> dict[str, jnp.ndarray]:
        """Heads -> flat boxes (B, N, 7) + IoU-rectified scores
        (B, N, nc). The IoU head predicts in [-1, 1] (tanh-free raw
        output clipped); quality q = (iou + 1) / 2, final score =
        cls^(1-a) * q^a (the SECOND-IoU cascade rectification)."""
        cfg = self.cfg
        anchors = generate_anchors(cfg)[None]
        boxes = decode_boxes(heads["box"], anchors)
        dir_bin = jnp.argmax(heads["dir"], axis=-1)
        rot = rectify_direction(
            boxes[..., 6], dir_bin, cfg.num_dir_bins, cfg.dir_offset
        )
        boxes = jnp.concatenate([boxes[..., :6], rot[..., None]], axis=-1)

        cls_score = jax.nn.sigmoid(heads["cls"])
        q = jnp.clip((jnp.clip(heads["iou"], -1.0, 1.0) + 1.0) / 2.0, 1e-6, 1.0)
        a = cfg.iou_alpha
        score = cls_score ** (1.0 - a) * (q[..., None] ** a)
        b = boxes.shape[0]
        return {
            "boxes": boxes.reshape(b, -1, 7),
            "scores": score.reshape(b, -1, cfg.num_classes),
        }


def init_second(rng, cfg: SECONDConfig | None = None, dtype=jnp.float32):
    cfg = cfg or SECONDConfig()
    model = SECONDIoU(cfg, dtype=dtype)
    v, k = cfg.voxel.max_voxels, cfg.voxel.max_points_per_voxel
    variables = model.init(
        rng,
        jnp.zeros((1, v, k, cfg.voxel.point_features)),
        jnp.zeros((1, v), jnp.int32),
        jnp.full((1, v, 3), -1, jnp.int32),
        train=False,
    )
    return model, variables
