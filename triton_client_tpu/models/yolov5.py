"""YOLOv5 in flax (NHWC, TPU-first).

The reference serves YOLOv5 as a server-side ONNX artifact
(examples/YOLOv5/config.pbtxt: 3x512x512 FP32 in -> [1, 16128, 7] out)
and never owns the network. Here the network is first-party so the
whole pre->forward->decode->NMS path compiles into one XLA program.

Architecture: v6.0-style CSP backbone + SPPF + PANet neck + anchor
Detect head at strides 8/16/32. Variant scaling via
(depth_multiple, width_multiple) as in upstream YOLOv5 (n/s/m/l/x).
With nc=2 and 512x512 input the decoded output is (1, 16128, 7) —
matching the reference's served tensor contract.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.models.layers import (
    C3,
    SPPF,
    ConvBnAct,
    make_divisible,
    scale_depth,
    upsample2x,
)
from triton_client_tpu.ops.yolo_decode import decode_yolo_grid

# (depth_multiple, width_multiple), upstream YOLOv5 scaling table.
YOLOV5_VARIANTS: dict[str, tuple[float, float]] = {
    "n": (0.33, 0.25),
    "s": (0.33, 0.50),
    "m": (0.67, 0.75),
    "l": (1.0, 1.0),
    "x": (1.33, 1.25),
}

# COCO-default anchor grid per stride (P3/8, P4/16, P5/32), pixels.
DEFAULT_ANCHORS: tuple[tuple[tuple[int, int], ...], ...] = (
    ((10, 13), (16, 30), (33, 23)),
    ((30, 61), (62, 45), (59, 119)),
    ((116, 90), (156, 198), (373, 326)),
)
STRIDES = (8, 16, 32)


class YoloV5(nn.Module):
    """YOLOv5 detector. ``__call__`` returns raw per-scale head tensors
    (for the training loss); ``decode`` maps them to (B, N, 5+nc)."""

    num_classes: int = 80
    variant: str = "n"
    anchors: Sequence[Sequence[tuple[int, int]]] = DEFAULT_ANCHORS
    dtype: jnp.dtype = jnp.float32
    # MXU-shape options (measured +16% together at b8 on a v5e chip,
    # perf/profile_mfu2d.py). Both are LOSSLESSLY importable from
    # upstream ultralytics weights (runtime/importers.load_yolov5):
    #   s2d: space-to-depth the input to (H/2, W/2, 12) and run the
    #     stem as the equivalent 3x3 stride-1 conv (the 6x6 s2 conv
    #     over 3 channels occupies 3 of the MXU's 128 lanes; its
    #     weights reshape exactly onto the blocked layout);
    #   ch_floor: pad every stage width up to this many channels
    #     (zero kernel columns + neutral BN rows keep padded channels
    #     exactly zero through SiLU).
    s2d: bool = False
    ch_floor: int = 0

    def _c(self, ch: int) -> int:
        base = make_divisible(ch * YOLOV5_VARIANTS[self.variant][1])
        return max(base, self.ch_floor) if self.ch_floor else base

    def _d(self, n: int) -> int:
        return scale_depth(n, YOLOV5_VARIANTS[self.variant][0])

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> list[jnp.ndarray]:
        """x: (B, H, W, 3) float in [0, 1]. Returns raw head outputs
        [(B, H/8, W/8, a, 5+nc), (B, H/16, ...), (B, H/32, ...)]."""
        c, d, dt = self._c, self._d, self.dtype
        na = len(self.anchors[0])
        no = 5 + self.num_classes

        x = x.astype(dt)
        # Backbone
        if self.s2d:
            b, h, w, ch = x.shape
            x = x.reshape(b, h // 2, 2, w // 2, 2, ch)
            x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
                b, h // 2, w // 2, 4 * ch
            )
            x = ConvBnAct(c(64), 3, 1, dtype=dt, name="stem")(x, train)
        else:
            x = ConvBnAct(c(64), 6, 2, padding=2, dtype=dt, name="stem")(x, train)
        x = ConvBnAct(c(128), 3, 2, dtype=dt, name="down2")(x, train)
        x = C3(c(128), d(3), dtype=dt, name="c3_2")(x, train)
        x = ConvBnAct(c(256), 3, 2, dtype=dt, name="down3")(x, train)
        p3 = C3(c(256), d(6), dtype=dt, name="c3_3")(x, train)
        x = ConvBnAct(c(512), 3, 2, dtype=dt, name="down4")(p3, train)
        p4 = C3(c(512), d(9), dtype=dt, name="c3_4")(x, train)
        x = ConvBnAct(c(1024), 3, 2, dtype=dt, name="down5")(p4, train)
        x = C3(c(1024), d(3), dtype=dt, name="c3_5")(x, train)
        p5 = SPPF(c(1024), 5, dtype=dt, name="sppf")(x, train)

        # PANet neck: top-down then bottom-up.
        t5 = ConvBnAct(c(512), 1, dtype=dt, name="lat5")(p5, train)
        x = jnp.concatenate([upsample2x(t5), p4], axis=-1)
        n4 = C3(c(512), d(3), shortcut=False, dtype=dt, name="c3_up4")(x, train)
        t4 = ConvBnAct(c(256), 1, dtype=dt, name="lat4")(n4, train)
        x = jnp.concatenate([upsample2x(t4), p3], axis=-1)
        out3 = C3(c(256), d(3), shortcut=False, dtype=dt, name="c3_up3")(x, train)
        x = ConvBnAct(c(256), 3, 2, dtype=dt, name="pan3")(out3, train)
        x = jnp.concatenate([x, t4], axis=-1)
        out4 = C3(c(512), d(3), shortcut=False, dtype=dt, name="c3_pan4")(x, train)
        x = ConvBnAct(c(512), 3, 2, dtype=dt, name="pan4")(out4, train)
        x = jnp.concatenate([x, t5], axis=-1)
        out5 = C3(c(1024), d(3), shortcut=False, dtype=dt, name="c3_pan5")(x, train)

        # Detect head: 1x1 conv per scale -> (B, h, w, a, no). Kept in
        # f32 regardless of compute dtype: box regression is
        # precision-sensitive at the output.
        heads = []
        for i, feat in enumerate((out3, out4, out5)):
            h = nn.Conv(na * no, (1, 1), dtype=jnp.float32, name=f"detect{i}")(
                feat.astype(jnp.float32)
            )
            b, hh, ww, _ = h.shape
            heads.append(h.reshape(b, hh, ww, na, no))
        return heads

    def decode(self, heads: list[jnp.ndarray]) -> jnp.ndarray:
        """Raw head outputs -> (B, sum(h*w*a), 5+nc) decoded predictions
        in input-pixel units (the reference's served [1, 16128, 7]
        contract for 512x512 / nc=2)."""
        decoded = [
            decode_yolo_grid(
                head, np.asarray(self.anchors[i], np.float32), STRIDES[i], "v5"
            )
            for i, head in enumerate(heads)
        ]
        return jnp.concatenate(decoded, axis=1)


def num_predictions(input_hw: tuple[int, int], num_anchors: int = 3) -> int:
    """Total prediction slots for an input size (e.g. 512 -> 16128)."""
    h, w = input_hw
    return sum((h // s) * (w // s) * num_anchors for s in STRIDES)


def init_yolov5(
    rng: Any,
    num_classes: int = 80,
    variant: str = "n",
    input_hw: tuple[int, int] = (512, 512),
    dtype: jnp.dtype = jnp.float32,
    s2d: bool = False,
    ch_floor: int = 0,
):
    """Build module + init variables. Returns (module, variables)."""
    model = YoloV5(
        num_classes=num_classes, variant=variant, dtype=dtype,
        s2d=s2d, ch_floor=ch_floor,
    )
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return model, variables
