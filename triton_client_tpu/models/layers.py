"""Shared conv building blocks (flax.linen, NHWC, bf16-friendly).

All convs are NHWC with explicit SAME-style padding so XLA tiles them
onto the MXU; channel counts are kept multiples of 8 by the width
scaler in yolov5.py. BatchNorm runs in inference mode by default
(use_running_average) and can be trained with mutable batch_stats for
the fine-tuning/training path.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


def autopad(kernel: int, padding: int | None = None) -> int:
    """'same' padding for odd kernels (the YOLO convention)."""
    return kernel // 2 if padding is None else padding


def mish(x: jnp.ndarray) -> jnp.ndarray:
    """Mish activation (YOLOv4 backbone)."""
    return x * jnp.tanh(nn.softplus(x))


# Activation registry: ConvBnAct.act accepts True (silu, the YOLOv5
# default), False (linear), or a name. YOLOv4 uses mish in the backbone
# and leaky(0.1) in the neck/head.
_ACTS = {
    "silu": nn.silu,
    "mish": mish,
    "leaky": lambda x: nn.leaky_relu(x, 0.1),
}


class ConvBnAct(nn.Module):
    """Conv2D + BatchNorm + activation — the universal YOLO block.

    ``eps`` follows the source framework so imported running stats
    reproduce the upstream forward exactly: ultralytics YOLOv5 uses
    BatchNorm2d(eps=1e-3) (the default here); pytorch-YOLOv4 keeps
    torch's 1e-5 default (yolov4.py overrides per-model).
    """

    features: int
    kernel: int = 1
    stride: int = 1
    padding: int | None = None
    groups: int = 1
    act: bool | str = True
    eps: float = 1e-3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        p = autopad(self.kernel, self.padding)
        x = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding=((p, p), (p, p)),
            feature_group_count=self.groups,
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.97,
            epsilon=self.eps,
            dtype=self.dtype,
            name="bn",
        )(x)
        if self.act:
            x = _ACTS["silu" if self.act is True else self.act](x)
        return x


class Bottleneck(nn.Module):
    """Two convs with optional residual add."""

    features: int
    shortcut: bool = True
    expansion: float = 0.5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        hidden = int(self.features * self.expansion)
        y = ConvBnAct(hidden, 1, dtype=self.dtype, name="cv1")(x, train)
        y = ConvBnAct(self.features, 3, dtype=self.dtype, name="cv2")(y, train)
        if self.shortcut and x.shape[-1] == self.features:
            y = x + y
        return y


class C3(nn.Module):
    """CSP bottleneck with 3 convs: split, stack bottlenecks, merge."""

    features: int
    depth: int = 1
    shortcut: bool = True
    expansion: float = 0.5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        hidden = int(self.features * self.expansion)
        a = ConvBnAct(hidden, 1, dtype=self.dtype, name="cv1")(x, train)
        b = ConvBnAct(hidden, 1, dtype=self.dtype, name="cv2")(x, train)
        for i in range(self.depth):
            a = Bottleneck(
                hidden, self.shortcut, expansion=1.0, dtype=self.dtype, name=f"m{i}"
            )(a, train)
        return ConvBnAct(self.features, 1, dtype=self.dtype, name="cv3")(
            jnp.concatenate([a, b], axis=-1), train
        )


class SPPF(nn.Module):
    """Spatial pyramid pooling (fast): 3 chained stride-1 maxpools."""

    features: int
    pool: int = 5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        hidden = x.shape[-1] // 2
        x = ConvBnAct(hidden, 1, dtype=self.dtype, name="cv1")(x, train)
        p = self.pool // 2
        pools = [x]
        for _ in range(3):
            pools.append(
                nn.max_pool(
                    pools[-1],
                    (self.pool, self.pool),
                    strides=(1, 1),
                    padding=((p, p), (p, p)),
                )
            )
        return ConvBnAct(self.features, 1, dtype=self.dtype, name="cv2")(
            jnp.concatenate(pools, axis=-1), train
        )


def upsample2x(x: jnp.ndarray) -> jnp.ndarray:
    """Nearest-neighbor 2x upsample (NHWC)."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, h * 2, w * 2, c)


def make_divisible(v: float, divisor: int = 8) -> int:
    """Round channel counts to a hardware-friendly multiple."""
    return max(divisor, int(round(v / divisor) * divisor))


def scale_depth(n: int, depth_multiple: float) -> int:
    return max(1, round(n * depth_multiple))


Shape = Sequence[int]
