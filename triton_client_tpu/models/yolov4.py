"""YOLOv4 in flax (NHWC, TPU-first).

The reference serves YOLOv4 as a server-side ONNX artifact with a
two-output contract — ``confs [1, N, nc]`` (obj*cls) and ``boxes
[1, N, 1, 4]`` normalized corner boxes (examples/YOLOv4/config.pbtxt) —
and decodes raw feature maps client-side when the served model emits
them (tools/yolo_layer.py:148-288). Here the network is first-party:
CSPDarknet53 (mish) + SPP + PANet (leaky) + anchor heads at strides
8/16/32, with the decode fused into the jit.

``decode_wire`` reproduces the reference wire contract exactly
(normalized x1y1x2y2 + obj*cls confs, tools/yolo_layer.py:259-288);
``decode_flat`` emits the framework-uniform (B, N, 5+nc) pixel-unit
tensor so YOLOv4 drops into the same Detect2DPipeline as YOLOv5.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.models.layers import make_divisible
from triton_client_tpu.models.layers import ConvBnAct as _ConvBnAct

# pytorch-YOLOv4 (the checkpoint lineage the reference's ONNX artifact
# exports from, examples/YOLOv4/config.pbtxt:2) keeps torch's BN
# default eps=1e-5 — every block in this file must match it or imported
# running stats reproduce a slightly different function per layer.
ConvBnAct = functools.partial(_ConvBnAct, eps=1e-5)
from triton_client_tpu.ops.yolo_decode import decode_yolo_grid

# Upstream YOLOv4 anchors (pixels at 512 input), masks [0:3, 3:6, 6:9]
# per stride 8/16/32 (reference tools/utils.py:168-171 comment block).
YOLOV4_ANCHORS: tuple[tuple[tuple[int, int], ...], ...] = (
    ((12, 16), (19, 36), (40, 28)),
    ((36, 75), (76, 55), (72, 146)),
    ((142, 110), (192, 243), (459, 401)),
)
STRIDES = (8, 16, 32)


class CSPStage(nn.Module):
    """Darknet CSP downsample stage: stride-2 conv, then a split-residual
    stack merged by 1x1 convs. ``first`` keeps full-width hidden channels
    (the darknet53 first-stage quirk)."""

    features: int
    depth: int
    first: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        dt = self.dtype
        hidden = self.features if self.first else self.features // 2
        x = ConvBnAct(self.features, 3, 2, act="mish", dtype=dt, name="down")(x, train)
        main = ConvBnAct(hidden, 1, act="mish", dtype=dt, name="split_main")(x, train)
        short = ConvBnAct(hidden, 1, act="mish", dtype=dt, name="split_short")(x, train)
        for i in range(self.depth):
            y = ConvBnAct(
                self.features // 2, 1, act="mish", dtype=dt, name=f"res{i}_cv1"
            )(main, train)
            y = ConvBnAct(hidden, 3, act="mish", dtype=dt, name=f"res{i}_cv2")(y, train)
            main = main + y
        main = ConvBnAct(hidden, 1, act="mish", dtype=dt, name="post")(main, train)
        merged = jnp.concatenate([main, short], axis=-1)
        return ConvBnAct(self.features, 1, act="mish", dtype=dt, name="merge")(
            merged, train
        )


class SPP(nn.Module):
    """YOLOv4 spatial pyramid pooling: parallel 5/9/13 maxpools."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        pools = [x]
        for k in (5, 9, 13):
            p = k // 2
            pools.append(
                nn.max_pool(x, (k, k), strides=(1, 1), padding=((p, p), (p, p)))
            )
        return ConvBnAct(
            self.features, 1, act="leaky", dtype=self.dtype, name="merge"
        )(jnp.concatenate(pools, axis=-1), train)


def _conv5(x, features: int, dtype, name: str, train: bool) -> jnp.ndarray:
    """The neck's 1-3-1-3-1 conv block (leaky)."""
    for i, (k, f) in enumerate(
        ((1, features), (3, features * 2), (1, features), (3, features * 2), (1, features))
    ):
        x = ConvBnAct(f, k, act="leaky", dtype=dtype, name=f"{name}_cv{i}")(x, train)
    return x


def _upsample2x(x: jnp.ndarray) -> jnp.ndarray:
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, h * 2, w * 2, c)


class YoloV4(nn.Module):
    """YOLOv4 detector. ``__call__`` returns raw per-scale head tensors;
    ``decode_wire``/``decode_flat`` map them to served outputs.

    ``width`` scales channel counts (1.0 = full CSPDarknet53); tests use
    small widths to keep CPU compile time sane.
    """

    num_classes: int = 80
    anchors: Sequence[Sequence[tuple[int, int]]] = YOLOV4_ANCHORS
    width: float = 1.0
    dtype: jnp.dtype = jnp.float32

    def _c(self, ch: int) -> int:
        return make_divisible(ch * self.width)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> list[jnp.ndarray]:
        """x: (B, H, W, 3) float in [0, 1]. Returns raw head outputs
        [(B, H/8, W/8, a, 5+nc), (B, H/16, ...), (B, H/32, ...)]."""
        c, dt = self._c, self.dtype
        na = len(self.anchors[0])
        no = 5 + self.num_classes

        x = x.astype(dt)
        # CSPDarknet53 backbone (depths 1,2,8,8,4).
        x = ConvBnAct(c(32), 3, act="mish", dtype=dt, name="stem")(x, train)
        x = CSPStage(c(64), 1, first=True, dtype=dt, name="stage1")(x, train)
        x = CSPStage(c(128), 2, dtype=dt, name="stage2")(x, train)
        p3 = CSPStage(c(256), 8, dtype=dt, name="stage3")(x, train)
        p4 = CSPStage(c(512), 8, dtype=dt, name="stage4")(p3, train)
        p5 = CSPStage(c(1024), 4, dtype=dt, name="stage5")(p4, train)

        # SPP block between two 1-3-1 conv groups.
        x = ConvBnAct(c(512), 1, act="leaky", dtype=dt, name="pre_spp0")(p5, train)
        x = ConvBnAct(c(1024), 3, act="leaky", dtype=dt, name="pre_spp1")(x, train)
        x = ConvBnAct(c(512), 1, act="leaky", dtype=dt, name="pre_spp2")(x, train)
        x = SPP(c(512), dtype=dt, name="spp")(x, train)
        x = ConvBnAct(c(1024), 3, act="leaky", dtype=dt, name="post_spp0")(x, train)
        n5 = ConvBnAct(c(512), 1, act="leaky", dtype=dt, name="post_spp1")(x, train)

        # PANet: top-down (with lateral 1x1s), then bottom-up.
        t4 = ConvBnAct(c(256), 1, act="leaky", dtype=dt, name="td4_lat")(p4, train)
        u5 = ConvBnAct(c(256), 1, act="leaky", dtype=dt, name="td4_up")(n5, train)
        n4 = _conv5(
            jnp.concatenate([t4, _upsample2x(u5)], axis=-1), c(256), dt, "td4", train
        )
        t3 = ConvBnAct(c(128), 1, act="leaky", dtype=dt, name="td3_lat")(p3, train)
        u4 = ConvBnAct(c(128), 1, act="leaky", dtype=dt, name="td3_up")(n4, train)
        n3 = _conv5(
            jnp.concatenate([t3, _upsample2x(u4)], axis=-1), c(128), dt, "td3", train
        )
        d3 = ConvBnAct(c(256), 3, 2, act="leaky", dtype=dt, name="bu4_down")(n3, train)
        n4 = _conv5(jnp.concatenate([d3, n4], axis=-1), c(256), dt, "bu4", train)
        d4 = ConvBnAct(c(512), 3, 2, act="leaky", dtype=dt, name="bu5_down")(n4, train)
        n5 = _conv5(jnp.concatenate([d4, n5], axis=-1), c(512), dt, "bu5", train)

        # Heads: 3x3 leaky conv then linear 1x1 (f32 outputs).
        heads = []
        for i, (feat, ch) in enumerate(((n3, c(256)), (n4, c(512)), (n5, c(1024)))):
            h = ConvBnAct(ch, 3, act="leaky", dtype=dt, name=f"head{i}_cv")(feat, train)
            h = nn.Conv(na * no, (1, 1), dtype=jnp.float32, name=f"detect{i}")(
                h.astype(jnp.float32)
            )
            b, hh, ww, _ = h.shape
            heads.append(h.reshape(b, hh, ww, na, no))
        return heads

    def decode_flat(
        self, heads: list[jnp.ndarray], normalize_hw: tuple[int, int] | None = None
    ) -> jnp.ndarray:
        """Raw heads -> (B, sum(h*w*a), 5+nc) [cx, cy, w, h, obj, cls...]
        in input pixels (or [0, 1] when normalize_hw is given)."""
        decoded = [
            decode_yolo_grid(
                head,
                np.asarray(self.anchors[i], np.float32),
                STRIDES[i],
                "v4",
                normalize_hw=normalize_hw,
            )
            for i, head in enumerate(heads)
        ]
        return jnp.concatenate(decoded, axis=1)

    def decode_wire(
        self, heads: list[jnp.ndarray], input_hw: tuple[int, int]
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Raw heads -> the reference served contract
        (examples/YOLOv4/config.pbtxt): ``boxes (B, N, 1, 4)`` normalized
        [x1, y1, x2, y2] and ``confs (B, N, nc)`` = obj * cls."""
        flat = self.decode_flat(heads, normalize_hw=input_hw)
        xy, wh = flat[..., :2], flat[..., 2:4]
        x1y1 = xy - wh * 0.5
        boxes = jnp.concatenate([x1y1, x1y1 + wh], axis=-1)[:, :, None, :]
        confs = flat[..., 5:] * flat[..., 4:5]
        return boxes, confs


def num_predictions(input_hw: tuple[int, int], num_anchors: int = 3) -> int:
    """Total prediction slots for an input size (512 -> 16128, the
    reference's served N)."""
    h, w = input_hw
    return sum((h // s) * (w // s) * num_anchors for s in STRIDES)


def init_yolov4(
    rng: Any,
    num_classes: int = 80,
    width: float = 1.0,
    input_hw: tuple[int, int] = (512, 512),
    dtype: jnp.dtype = jnp.float32,
):
    """Build module + init variables. Returns (module, variables)."""
    model = YoloV4(num_classes=num_classes, width=width, dtype=dtype)
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return model, variables
