"""BEV self-attention neck — the framework's long-context consumer.

The reference's 3D models are pure CNNs (OpenPCDet PointPillars /
SECOND, examples/pointpillar_kitti/1/model.py:163); their receptive
field over the BEV canvas is local. This neck adds global context over
the BEV token grid — and, more importantly for the framework, it is
the component that exercises sequence/context parallelism end to end:
a full-resolution KITTI canvas is 432x496 ≈ 214k tokens, far past what
one chip's VMEM-friendly attention wants, so the token axis shards
over the ``seq`` mesh axis and attention runs as ring attention
(parallel/sequence.py) with K/V blocks rotating over ICI.

Design:
  * tokens = strided patches of the BEV canvas (patch conv), so the
    sequence length is (H/p)*(W/p) and attention cost is controllable;
  * attention implementation is injected: dense (single chip) or
    ring/ulysses (sp>1) — the module's parameters are identical either
    way, so a checkpoint trained single-chip serves sharded;
  * pre-norm residual block, then the tokens are scattered back and
    fused with the input canvas (1x1 conv), preserving the CNN
    contract of the downstream detection heads.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from triton_client_tpu.parallel.sequence import full_attention

AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Single-device full attention (the sp=1 implementation)."""
    return full_attention(q, k, v, causal=False)


class BEVAttentionNeck(nn.Module):
    """Global-context neck over a BEV canvas (B, H, W, C).

    attention: injected implementation — ``dense_attention`` or a
    ``lambda q,k,v: ring_attention(q,k,v,mesh)`` closure. Parameters do
    not depend on the choice.
    """

    heads: int = 4
    head_dim: int = 32
    patch: int = 4
    attention: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        b, h, w, c = x.shape
        p = self.patch
        if h % p or w % p:
            raise ValueError(f"canvas {h}x{w} not divisible by patch {p}")
        attn = self.attention or dense_attention
        inner = self.heads * self.head_dim

        # patchify: (B, H/p, W/p, p*p*C) -> token embed
        tok = x.reshape(b, h // p, p, w // p, p, c)
        tok = tok.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, (h // p) * (w // p), p * p * c
        )
        tok = nn.Dense(inner, name="embed")(tok)

        y = nn.LayerNorm(name="ln")(tok)
        qkv = nn.Dense(3 * inner, name="qkv")(y)
        s = tok.shape[1]
        qkv = qkv.reshape(b, s, 3, self.heads, self.head_dim)
        out = attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        out = out.reshape(b, s, inner)
        tok = tok + nn.Dense(inner, name="proj")(out)

        # un-patchify to (B, H, W, c_out) and fuse with the input canvas
        back = nn.Dense(p * p * c, name="unembed")(tok)
        back = back.reshape(b, h // p, w // p, p, p, c)
        back = back.transpose(0, 1, 3, 2, 4, 5).reshape(b, h, w, c)
        return nn.Conv(c, (1, 1), use_bias=True, name="fuse")(
            jnp.concatenate([x, back], axis=-1)
        )
