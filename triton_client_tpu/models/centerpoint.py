"""CenterPoint (pillar variant) — center-heatmap 3D detector, nuScenes.

The reference's CenterPoint path is the det3d/nuScenes branch of its 3D
client (clients/preprocess/voxelize.py:11-47 feeds a served CenterPoint
with the nusc_centerpoint_pp_02voxel_two_pfn_10sweep config). Here the
whole detector is in-tree and TPU-shaped:

  * reuses the PointPillars VFE + scatter + BEV backbone (the pillar
    variant of CenterPoint shares that trunk);
  * CenterHead: class heatmap + regression maps (offset, height, size,
    sin/cos rotation, velocity);
  * decode is fixed-shape: 3x3 max-pool peak NMS on the sigmoid
    heatmap (the center-NMS trick replacing box NMS) + top-K gather —
    no data-dependent shapes anywhere, so the whole thing jits.

Anchor-free means no anchor table and no direction bins; headings come
from atan2(sin, cos) directly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.models.pointpillars import (
    BEVBackbone,
    PillarVFE,
    augment_points,
    require_pillar_grid,
    scatter_max_canvas,
    scatter_to_bev,
    validate_bev_divisible,
)
from triton_client_tpu.ops.voxelize import VoxelConfig

# nuScenes detection classes (data/nuscenes.names, nusc_centerpoint
# config class_names).
NUSC_CLASSES = (
    "car",
    "truck",
    "construction_vehicle",
    "bus",
    "trailer",
    "barrier",
    "motorcycle",
    "bicycle",
    "pedestrian",
    "traffic_cone",
)


@dataclasses.dataclass(frozen=True)
class CenterPointConfig:
    # nuScenes grid (nusc_centerpoint_pp_02voxel...: 0.2 m pillars over
    # a +/-51.2 m square -> 512x512 canvas).
    voxel: VoxelConfig = VoxelConfig(
        point_cloud_range=(-51.2, -51.2, -5.0, 51.2, 51.2, 3.0),
        voxel_size=(0.2, 0.2, 8.0),
        max_voxels=30000,
        max_points_per_voxel=20,
    )
    vfe_filters: int = 64
    backbone_layers: tuple[int, ...] = (3, 5, 5)
    backbone_strides: tuple[int, ...] = (2, 2, 2)
    backbone_filters: tuple[int, ...] = (64, 128, 256)
    upsample_strides: tuple[int, ...] = (1, 2, 4)
    upsample_filters: tuple[int, ...] = (128, 128, 128)
    class_names: tuple[str, ...] = NUSC_CLASSES
    head_width: int = 64
    max_objects: int = 128  # top-K centers kept per frame
    with_velocity: bool = True

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    @property
    def head_stride(self) -> int:
        return self.backbone_strides[0] // self.upsample_strides[0]

    @property
    def head_hw(self) -> tuple[int, int]:
        nx, ny, _ = self.voxel.grid_size
        s = self.head_stride
        return ny // s, nx // s

    def validate(self) -> None:
        validate_bev_divisible(self.voxel, int(np.prod(self.backbone_strides)))


class CenterHead(nn.Module):
    """Shared 3x3 conv + per-branch 1x1 heads over the BEV features."""

    cfg: CenterPointConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        cfg = self.cfg
        shared = nn.Conv(
            cfg.head_width, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
            name="shared",
        )(x)
        shared = nn.BatchNorm(
            use_running_average=not train, momentum=0.99, epsilon=1e-3,
            dtype=self.dtype, name="shared_bn",
        )(shared)
        shared = nn.relu(shared).astype(jnp.float32)

        def branch(features: int, name: str, bias_init=0.0):
            return nn.Conv(
                features,
                (1, 1),
                dtype=jnp.float32,
                bias_init=nn.initializers.constant(bias_init),
                name=name,
            )(shared)

        out = {
            # -2.19 = -log((1-0.1)/0.1), CenterNet's heatmap prior.
            "heatmap": branch(cfg.num_classes, "heatmap", bias_init=-2.19),
            "offset": branch(2, "offset"),
            "height": branch(1, "height"),
            "size": branch(3, "size"),
            "rot": branch(2, "rot"),  # (sin, cos)
        }
        if cfg.with_velocity:
            out["vel"] = branch(2, "vel")
        return out


class CenterPoint(nn.Module):
    cfg: CenterPointConfig = CenterPointConfig()
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg, dt = self.cfg, self.dtype
        cfg.validate()
        self.vfe = PillarVFE(cfg.vfe_filters, cfg.voxel, dtype=dt)
        self.backbone = BEVBackbone(cfg, dtype=dt)
        self.head = CenterHead(cfg, dtype=dt)

    def __call__(
        self,
        voxels: jnp.ndarray,      # (B, V, K, F)
        num_points: jnp.ndarray,  # (B, V)
        coords: jnp.ndarray,      # (B, V, 3) [z, y, x]
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        nx, ny, _ = self.cfg.voxel.grid_size
        b, v, k, f = voxels.shape
        # ONE flat VFE call over all B*V pillars (see
        # PointPillars.__call__): a parameterized module call under
        # jax.vmap trips flax's transform check.
        feats = self.vfe(
            voxels.reshape(b * v, k, f),
            num_points.reshape(b * v),
            coords.reshape(b * v, 3),
            train,
        ).reshape(b, v, -1)
        canvas = jax.vmap(lambda f, c: scatter_to_bev(f, c, (ny, nx)))(feats, coords)
        return self.head(self.backbone(canvas, train), train)

    def from_points(
        self,
        points: jnp.ndarray,  # (N, F>=4) padded cloud
        count: jnp.ndarray,   # () real rows
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Sort-free scatter path (see PointPillars.from_points): same
        parameters, no (V, K) grouping, batch 1. Pillar grids only."""
        require_pillar_grid(self.cfg.voxel.grid_size)
        nx, ny, _ = self.cfg.voxel.grid_size
        feats, vid, valid, cnt = augment_points(points, count, self.cfg.voxel)
        x = self.vfe.encode(feats, train)
        canvas = scatter_max_canvas(x, vid, valid, (ny, nx))
        return self.head(self.backbone(canvas[None], train), train)

    def from_points_batch(
        self,
        points: jnp.ndarray,  # (B, P, F>=4) padded clouds
        counts: jnp.ndarray,  # (B,) real rows per cloud
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Batched sort-free path for TRAINING (round 5 — makes the
        velocity head trainable end-to-end): per-sample pillar
        assignment (pure vmap), ONE flat VFE encode over all B*P rows
        so BatchNorm sees the whole batch's point population (a
        per-sample vmap would trip flax's broadcast-state mutation —
        the same constraint as PointPillars.from_points_batch), then
        per-sample canvas scatter. Multi-sweep training clouds carry
        the Δt channel as feature 5 exactly like serving."""
        require_pillar_grid(self.cfg.voxel.grid_size)
        nx, ny, _ = self.cfg.voxel.grid_size
        feats, vid, valid, _cnt = jax.vmap(
            lambda p, c: augment_points(p, c, self.cfg.voxel)
        )(points, counts)
        b, n, f = feats.shape
        x = self.vfe.encode(feats.reshape(b * n, f), train).reshape(b, n, -1)
        canvas = jax.vmap(
            lambda xx, vv, va: scatter_max_canvas(xx, vv, va, (ny, nx))
        )(x, vid, valid)
        return self.head(self.backbone(canvas, train), train)

    def decode(self, heads: dict[str, jnp.ndarray]) -> dict[str, jnp.ndarray]:
        """Center decode -> flat predictions shaped like the anchor
        models' contract so extract_boxes_3d / nms_bev apply unchanged:
        boxes (B, K, 7[+2 vel folded out]), scores (B, K, nc) one-hot at
        the peak's class.

        Peak picking: sigmoid heatmap, 3x3 max-pool equality mask
        (CenterNet's local-maximum NMS), flat top-K over (class, y, x).
        """
        cfg = self.cfg
        # Peak test runs in LOGIT space: sigmoid saturates (neighbors of
        # a confident peak become float-equal to it after sigmoid, which
        # would pass the whole 3x3 patch as peaks); logits don't.
        logits = heads["heatmap"]  # (B, H, W, nc)
        pooled = nn.max_pool(
            logits, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1))
        )
        heat = jnp.where(logits >= pooled, jax.nn.sigmoid(logits), 0.0)

        b, h, w, nc = heat.shape
        k = cfg.max_objects
        flat = heat.reshape(b, -1)  # (B, H*W*nc)
        scores, idx = jax.lax.top_k(flat, k)  # (B, K)
        cls = idx % nc
        cell = idx // nc
        ys = (cell // w).astype(jnp.float32)
        xs = (cell % w).astype(jnp.float32)

        def gather(name: str, feats: int):
            m = heads[name].reshape(b, h * w, feats)
            return jnp.take_along_axis(m, cell[..., None], axis=1)

        offset = gather("offset", 2)
        height = gather("height", 1)[..., 0]
        size = gather("size", 3)
        rot = gather("rot", 2)

        stride = cfg.head_stride
        vs = cfg.voxel.voxel_size
        r = cfg.voxel.point_cloud_range
        x_world = (xs + offset[..., 0]) * stride * vs[0] + r[0]
        y_world = (ys + offset[..., 1]) * stride * vs[1] + r[1]
        dims = jnp.exp(jnp.clip(size, -10, 10))
        heading = jnp.arctan2(rot[..., 0], rot[..., 1])

        boxes = jnp.stack(
            [x_world, y_world, height, dims[..., 0], dims[..., 1], dims[..., 2],
             heading],
            axis=-1,
        )  # (B, K, 7)
        # One-hot class scores so downstream max/argmax recovers
        # (score, label) — the anchor-family contract.
        score_map = jax.nn.one_hot(cls, nc) * scores[..., None]
        out = {"boxes": boxes, "scores": score_map}
        if cfg.with_velocity:
            out["velocity"] = gather("vel", 2)
        return out


def init_centerpoint(rng, cfg: CenterPointConfig | None = None, dtype=jnp.float32):
    cfg = cfg or CenterPointConfig()
    model = CenterPoint(cfg, dtype=dtype)
    v, k = cfg.voxel.max_voxels, cfg.voxel.max_points_per_voxel
    variables = model.init(
        rng,
        jnp.zeros((1, v, k, cfg.voxel.point_features)),
        jnp.zeros((1, v), jnp.int32),
        jnp.full((1, v, 3), -1, jnp.int32),
        train=False,
    )
    return model, variables
