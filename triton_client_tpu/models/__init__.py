"""L2 model zoo: flax modules jit-compiled for TPU.

Where the reference executes networks server-side via onnxruntime /
libtorch / OpenPCDet-CUDA behind Triton (examples/*/config.pbtxt), the
models here are first-party JAX: NHWC layouts, bfloat16-friendly,
static shapes, fused pre/post-processing.
"""

from triton_client_tpu.models.yolov5 import YoloV5, YOLOV5_VARIANTS
