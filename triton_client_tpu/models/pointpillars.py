"""PointPillars in flax (NHWC, TPU-first).

The reference serves PointPillars through Triton's python backend
wrapping OpenPCDet CUDA (examples/pointpillar_kitti/1/model.py:42-186):
voxels/coords/num_points in, (pred_boxes, pred_scores, pred_labels) out.
Here the network is first-party JAX with the same I/O contract, built
from the hyperparameters the reference ships in data/pointpillar.yaml:
PillarVFE(64) -> dense BEV scatter -> 3-block CNN backbone with FPN-style
deconv concat -> single-stage anchor head (3 classes x 2 rotations),
residual box coding, direction bins.

The scatter-to-BEV is an XLA scatter over the static max_voxels budget
(invalid pillars write to a dump row) — the dense analogue of
OpenPCDet's PointPillarScatter, with no dynamic shapes anywhere.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from triton_client_tpu.ops.voxelize import VoxelConfig, assign_cells


@dataclasses.dataclass(frozen=True)
class AnchorClassConfig:
    """Per-class anchor setup (data/pointpillar.yaml:118-142)."""

    name: str
    size: tuple[float, float, float]  # dx, dy, dz
    bottom_z: float
    matched_thresh: float = 0.6
    unmatched_thresh: float = 0.45


KITTI_ANCHORS = (
    AnchorClassConfig("Car", (3.9, 1.6, 1.56), -1.78, 0.6, 0.45),
    AnchorClassConfig("Pedestrian", (0.8, 0.6, 1.73), -0.6, 0.5, 0.35),
    AnchorClassConfig("Cyclist", (1.76, 0.6, 1.73), -0.6, 0.5, 0.35),
)
ROTATIONS = (0.0, math.pi / 2)


@dataclasses.dataclass(frozen=True)
class PointPillarsConfig:
    voxel: VoxelConfig = VoxelConfig()
    vfe_filters: int = 64
    backbone_layers: tuple[int, ...] = (3, 5, 5)
    backbone_strides: tuple[int, ...] = (2, 2, 2)
    backbone_filters: tuple[int, ...] = (64, 128, 256)
    upsample_strides: tuple[int, ...] = (1, 2, 4)
    upsample_filters: tuple[int, ...] = (128, 128, 128)
    anchor_classes: tuple[AnchorClassConfig, ...] = KITTI_ANCHORS
    num_dir_bins: int = 2
    dir_offset: float = 0.78539  # pi/4, OpenPCDet convention

    @property
    def num_classes(self) -> int:
        return len(self.anchor_classes)

    @property
    def anchors_per_loc(self) -> int:
        return len(self.anchor_classes) * len(ROTATIONS)

    @property
    def head_stride(self) -> int:
        return self.backbone_strides[0] // self.upsample_strides[0]

    @property
    def head_hw(self) -> tuple[int, int]:
        nx, ny, _ = self.voxel.grid_size
        s = self.head_stride
        return ny // s, nx // s

    def validate(self) -> None:
        validate_bev_divisible(self.voxel, int(np.prod(self.backbone_strides)))


def validate_bev_divisible(voxel: VoxelConfig, stride: int) -> None:
    """BEV dims must divide the deepest composed downsample exactly:
    with odd sizes the strided conv (ceil) and the floor-based head
    grid disagree, and parallel upsample branches of different strides
    cannot even concatenate — fail loudly at model build instead of a
    cryptic reshape error mid-trace (seen at 0.15 m voxels: 469x533
    grid, perf/profile_second_grid.py). Each branch downsamples by
    prod(strides[:i+1]) before its deconv restores the common scale,
    so divisibility by the product covers every stage. Shared by the
    PointPillars/SECOND/CenterPoint configs."""
    nx, ny, _ = voxel.grid_size
    if nx % stride or ny % stride:
        raise ValueError(
            f"BEV grid {nx}x{ny} (from voxel_size {voxel.voxel_size}) "
            f"must be divisible by the deepest composed downsample "
            f"{stride}; pick a voxel size whose grid divides it"
        )


def generate_anchors(cfg: PointPillarsConfig) -> jnp.ndarray:
    """Dense anchor grid (H, W, A, 7) [x, y, z, dx, dy, dz, rot] in
    world coordinates, matching OpenPCDet's AnchorGenerator semantics
    (anchors centered on head cells, z at class center height)."""
    h, w = cfg.head_hw
    r = cfg.voxel.point_cloud_range
    xs = np.linspace(r[0], r[3], w, endpoint=False) + (r[3] - r[0]) / w / 2
    ys = np.linspace(r[1], r[4], h, endpoint=False) + (r[4] - r[1]) / h / 2
    gx, gy = np.meshgrid(xs, ys)  # (h, w)
    anchors = []
    for cls_cfg in cfg.anchor_classes:
        cz = cls_cfg.bottom_z + cls_cfg.size[2] / 2
        for rot in ROTATIONS:
            a = np.zeros((h, w, 7), np.float32)
            a[..., 0], a[..., 1], a[..., 2] = gx, gy, cz
            a[..., 3:6] = cls_cfg.size
            a[..., 6] = rot
            anchors.append(a)
    return jnp.asarray(np.stack(anchors, axis=2))  # (h, w, A, 7)


def decode_boxes(deltas: jnp.ndarray, anchors: jnp.ndarray) -> jnp.ndarray:
    """Residual box decode (OpenPCDet ResidualCoder semantics):
    x = xt * diag + xa; z = zt * dza + za; d = exp(dt) * da; r = rt + ra."""
    xa, ya, za = anchors[..., 0], anchors[..., 1], anchors[..., 2]
    dxa, dya, dza = anchors[..., 3], anchors[..., 4], anchors[..., 5]
    ra = anchors[..., 6]
    diag = jnp.sqrt(dxa**2 + dya**2)
    x = deltas[..., 0] * diag + xa
    y = deltas[..., 1] * diag + ya
    z = deltas[..., 2] * dza + za
    dx = jnp.exp(jnp.clip(deltas[..., 3], -10, 10)) * dxa
    dy = jnp.exp(jnp.clip(deltas[..., 4], -10, 10)) * dya
    dz = jnp.exp(jnp.clip(deltas[..., 5], -10, 10)) * dza
    r = deltas[..., 6] + ra
    return jnp.stack([x, y, z, dx, dy, dz, r], axis=-1)


def rectify_direction(
    rot: jnp.ndarray,
    dir_bin: jnp.ndarray,
    num_dir_bins: int,
    dir_offset: float,
) -> jnp.ndarray:
    """OpenPCDet direction-bin heading rectification (shared by every
    anchor-head decode): fold the regressed angle into one period,
    then add the classified bin."""
    period = 2 * jnp.pi / num_dir_bins
    out = rot - dir_offset
    out = out - jnp.floor(out / period) * period + dir_offset
    return out + period * dir_bin.astype(jnp.float32)


def decode_candidates(
    cand: dict[str, jnp.ndarray], num_dir_bins: int, dir_offset: float
) -> dict[str, jnp.ndarray]:
    """The XLA residual-decode tail over a ``topk_candidates`` set —
    the reference twin of ops/pallas_decode.fused_residual_decode.
    Shared by every anchor-head model (PointPillars, SECOND)."""
    decoded = decode_boxes(cand["deltas"], cand["anchors"])
    rot = rectify_direction(
        decoded[..., 6], cand["dir_bin"], num_dir_bins, dir_offset
    )
    decoded = jnp.concatenate([decoded[..., :6], rot[..., None]], axis=-1)
    return {"boxes": decoded, "scores": cand["scores"], "labels": cand["labels"]}


def encode_boxes(boxes: jnp.ndarray, anchors: jnp.ndarray) -> jnp.ndarray:
    """Inverse of decode_boxes, for the training target assignment."""
    diag = jnp.sqrt(anchors[..., 3] ** 2 + anchors[..., 4] ** 2)
    eps = 1e-6
    return jnp.stack(
        [
            (boxes[..., 0] - anchors[..., 0]) / diag,
            (boxes[..., 1] - anchors[..., 1]) / diag,
            (boxes[..., 2] - anchors[..., 2]) / anchors[..., 5],
            jnp.log(jnp.maximum(boxes[..., 3], eps) / anchors[..., 3]),
            jnp.log(jnp.maximum(boxes[..., 4], eps) / anchors[..., 4]),
            jnp.log(jnp.maximum(boxes[..., 5], eps) / anchors[..., 5]),
            boxes[..., 6] - anchors[..., 6],
        ],
        axis=-1,
    )


class PillarVFE(nn.Module):
    """Pillar feature encoder: augment -> linear+BN+ReLU -> masked max.

    Feature augmentation per data/pointpillar.yaml (USE_ABSLOTE_XYZ):
    [x, y, z, i, x-xmean, y-ymean, z-zmean, x-xc, y-yc, z-zc] (10).

    Two entry points over the SAME parameters: ``__call__`` consumes
    the grouped (V, K, F) voxel contract (the reference's OpenPCDet
    wire shape); ``encode`` is the per-point MLP alone, used by the
    sort-free scatter path (``from_points``) where the segment
    mean/max are dense grid scatters instead of a K-axis reduction."""

    filters: int = 64
    voxel: VoxelConfig = VoxelConfig()
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        self.linear = nn.Dense(self.filters, use_bias=False, dtype=self.dtype)
        self.bn = nn.BatchNorm(momentum=0.99, epsilon=1e-3, dtype=self.dtype)

    def encode(self, feats: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        """(..., 10) augmented point features -> (..., filters)."""
        x = self.linear(feats.astype(self.dtype))
        x = self.bn(x, use_running_average=not train)
        return nn.relu(x)

    def __call__(
        self,
        voxels: jnp.ndarray,       # (V, K, F>=4)
        num_points: jnp.ndarray,   # (V,)
        coords: jnp.ndarray,       # (V, 3) [z, y, x]
        train: bool = False,
    ) -> jnp.ndarray:
        v, k, _ = voxels.shape
        mask = (jnp.arange(k)[None, :] < num_points[:, None])[..., None]
        xyz = voxels[..., :3]
        cnt = jnp.maximum(num_points, 1)[:, None, None]
        mean = (xyz * mask).sum(axis=1, keepdims=True) / cnt
        vs = jnp.asarray(self.voxel.voxel_size)
        r0 = jnp.asarray(self.voxel.point_cloud_range[:3])
        centers = (coords[:, ::-1].astype(jnp.float32) + 0.5) * vs + r0  # (V, 3) xyz
        feats = jnp.concatenate(
            [
                voxels[..., : self.voxel.point_features],
                xyz - mean,
                xyz - centers[:, None, :],
            ],
            axis=-1,
        )
        feats = jnp.where(mask, feats, 0.0)
        x = self.encode(feats, train)
        x = jnp.where(mask, x, -jnp.inf).max(axis=1)  # (V, filters)
        return jnp.where(num_points[:, None] > 0, x, 0.0)


def require_pillar_grid(grid_size: tuple[int, int, int]) -> None:
    """Shared nz == 1 guard for the pillar scatter paths (PointPillars
    and CenterPoint from_points): a taller grid's z cells would merge
    silently. The pipeline router falls back to the grouped voxelizer
    instead of tripping this (pipelines/detect3d.py)."""
    nz = grid_size[2]
    if nz != 1:
        raise ValueError(
            f"from_points is a pillar (nz == 1) path; this grid has "
            f"nz={nz} — use the grouped voxelizer (vfe='grouped')"
        )


def augment_points(
    points: jnp.ndarray,   # (N, F>=4) padded cloud [x, y, z, i, ...]
    count: jnp.ndarray,    # () real rows
    voxel: VoxelConfig,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-point pillar assignment + the 10-feature VFE augmentation,
    with the pillar mean computed as a dense-grid scatter instead of a
    (V, K) grouping. This is the sort-free half of the scatter VFE path:
    the voxelizer's 131k-point lax.sort (ops/voxelize.py) is the single
    most expensive stage of the fused 3D pipeline on a v5e chip; pillar
    mean/max are segment reductions, so they scatter straight into the
    (ny*nx) grid the BEV canvas needs anyway. Pillar-grid specific
    (collapses z: the pillar center z is the cell-0 center, identical
    to the grouped path where coords z is always 0).

    Returns (feats (N, 10), vid (N,) flat y*nx+x pillar id with
    ny*nx as the invalid dump slot, valid (N,), cnt (ny*nx+1,) points
    per pillar)."""
    nx, ny, _ = voxel.grid_size
    r = jnp.asarray(voxel.point_cloud_range)
    vs = jnp.asarray(voxel.voxel_size)
    xyz = points[:, :3]
    ijk, valid = assign_cells(points, count, voxel)
    dump = nx * ny
    vid = jnp.where(valid, ijk[:, 1] * nx + ijk[:, 0], dump)
    w = valid.astype(points.dtype)[:, None]
    # one fused scatter-add for xyz sums AND the count (column 3 is the
    # per-point weight), halving the scatter passes over the grid
    acc = jnp.zeros((dump + 1, 4), points.dtype)
    acc = acc.at[vid].add(jnp.concatenate([xyz, jnp.ones_like(w)], axis=1) * w)
    per_point = acc[vid]  # (N, 4) gather once
    mean = per_point[:, :3] / jnp.maximum(per_point[:, 3:], 1.0)
    cnt = acc[:, 3]
    centers = (ijk.astype(jnp.float32) + 0.5) * vs + r[:3]
    feats = jnp.concatenate(
        [points[:, : voxel.point_features], xyz - mean, xyz - centers], axis=1
    )
    return jnp.where(valid[:, None], feats, 0.0), vid, valid, cnt


def scatter_max_canvas(
    x: jnp.ndarray,      # (N, C) per-point features, NON-NEGATIVE
    vid: jnp.ndarray,    # (N,) flat y*nx+x pillar id (ny*nx = dump)
    valid: jnp.ndarray,  # (N,)
    grid_hw: tuple[int, int],
) -> jnp.ndarray:
    """Pillar-max scatter to the (H, W, C) canvas — the segment-max half
    of the sort-free VFE, shared by every pillar model's from_points so
    the grouped/scatter bit-exactness fix lives in ONE place.

    ``x`` must be non-negative (every caller feeds the VFE's post-ReLU
    features): scatter-max onto a ZERO canvas then equals the -inf-fill
    + where(count > 0) formulation bit-for-bit, while skipping two full
    (H*W, C) canvas passes — measured ~0.5 ms/scan on a v5e chip for
    the KITTI grid. Invalid rows route to the dump row (sliced off), so
    the scatter can promise in-bounds indices."""
    h, w = grid_hw
    vid = jnp.where(valid, vid, h * w)
    canvas = jnp.zeros((h * w + 1, x.shape[-1]), x.dtype)
    canvas = canvas.at[vid].max(
        x, mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS
    )[: h * w]
    return canvas.reshape(h, w, -1)


def scatter_to_bev(
    pillar_feats: jnp.ndarray,  # (V, C)
    coords: jnp.ndarray,        # (V, 3) [z, y, x], -1 invalid
    grid_hw: tuple[int, int],
) -> jnp.ndarray:
    """Dense BEV canvas (H=ny, W=nx, C); invalid pillars land in a dump
    row that is sliced off (PointPillarScatter equivalent)."""
    h, w = grid_hw
    c = pillar_feats.shape[-1]
    yy, xx = coords[:, 1], coords[:, 2]
    valid = (yy >= 0) & (xx >= 0)
    flat = jnp.where(valid, yy * w + xx, h * w)  # dump slot at the end
    canvas = jnp.zeros((h * w + 1, c), pillar_feats.dtype)
    canvas = canvas.at[flat].set(pillar_feats)  # last-writer-wins is fine
    return canvas[: h * w].reshape(h, w, c)


class BEVBackbone(nn.Module):
    """Multi-scale 2D CNN over the pillar canvas + FPN-style deconv concat."""

    cfg: PointPillarsConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        cfg, dt = self.cfg, self.dtype
        ups = []
        for bi, (n_layers, stride, filters, up_stride, up_filters) in enumerate(
            zip(
                cfg.backbone_layers,
                cfg.backbone_strides,
                cfg.backbone_filters,
                cfg.upsample_strides,
                cfg.upsample_filters,
            )
        ):
            x = nn.Conv(
                filters, (3, 3), strides=(stride, stride), padding=1,
                use_bias=False, dtype=dt, name=f"block{bi}_down",
            )(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.99, epsilon=1e-3,
                dtype=dt, name=f"block{bi}_down_bn",
            )(x)
            x = nn.relu(x)
            for li in range(n_layers):
                x = nn.Conv(
                    filters, (3, 3), padding=1, use_bias=False, dtype=dt,
                    name=f"block{bi}_conv{li}",
                )(x)
                x = nn.BatchNorm(
                    use_running_average=not train, momentum=0.99, epsilon=1e-3,
                    dtype=dt, name=f"block{bi}_bn{li}",
                )(x)
                x = nn.relu(x)
            u = nn.ConvTranspose(
                up_filters, (up_stride, up_stride),
                strides=(up_stride, up_stride), use_bias=False, dtype=dt,
                name=f"up{bi}",
            )(x)
            u = nn.BatchNorm(
                use_running_average=not train, momentum=0.99, epsilon=1e-3,
                dtype=dt, name=f"up{bi}_bn",
            )(u)
            ups.append(nn.relu(u))
        return jnp.concatenate(ups, axis=-1)


class PointPillars(nn.Module):
    """Full detector: VFE -> scatter -> backbone -> anchor head.

    __call__ consumes the voxelizer's output dict (batched) and returns
    raw head maps; ``from_points`` is the sort-free single-scan path
    (same parameters, no (V, K) grouping); ``decode`` produces
    per-anchor boxes/scores."""

    cfg: PointPillarsConfig = PointPillarsConfig()
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg, dt = self.cfg, self.dtype
        cfg.validate()
        self.vfe = PillarVFE(cfg.vfe_filters, cfg.voxel, dtype=dt)
        self.backbone = BEVBackbone(cfg, dtype=dt)
        a = cfg.anchors_per_loc
        self.cls_head = nn.Conv(a * cfg.num_classes, (1, 1), dtype=jnp.float32)
        self.box_head = nn.Conv(a * 7, (1, 1), dtype=jnp.float32)
        self.dir_head = nn.Conv(a * cfg.num_dir_bins, (1, 1), dtype=jnp.float32)

    def __call__(
        self,
        voxels: jnp.ndarray,      # (B, V, K, F)
        num_points: jnp.ndarray,  # (B, V)
        coords: jnp.ndarray,      # (B, V, 3)
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        nx, ny, _ = self.cfg.voxel.grid_size
        b, v, k, f = voxels.shape
        # ONE flat VFE call over all B*V pillars: the per-pillar math is
        # batch-independent, and a parameterized module call under
        # jax.vmap trips flax's transform check (the from_points_batch
        # constraint); flat BN also sees the whole batch's pillars.
        feats = self.vfe(
            voxels.reshape(b * v, k, f),
            num_points.reshape(b * v),
            coords.reshape(b * v, 3),
            train,
        ).reshape(b, v, -1)  # (B, V, C)
        canvas = jax.vmap(lambda f, c: scatter_to_bev(f, c, (ny, nx)))(
            feats, coords
        )  # (B, ny, nx, C)
        return self._heads(canvas, train)

    def from_points(
        self,
        points: jnp.ndarray,  # (N, F>=4) padded cloud
        count: jnp.ndarray,   # () real rows
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Sort-free scatter path: points -> canvas -> heads (batch 1).

        Equivalent to ``voxelize() + __call__`` whenever the voxelizer's
        budgets (max_voxels, max_points_per_voxel) are not hit; beyond
        them this path keeps ALL points and pillars (the budgets exist
        only to give the grouped wire contract a static shape). Skips
        the (N log N) point sort entirely — pillar mean and max are
        dense-grid scatters. Pillar grids only (require_pillar_grid)."""
        require_pillar_grid(self.cfg.voxel.grid_size)
        nx, ny, _ = self.cfg.voxel.grid_size
        feats, vid, valid, cnt = augment_points(points, count, self.cfg.voxel)
        x = self.vfe.encode(feats, train)  # (N, C)
        canvas = scatter_max_canvas(x, vid, valid, (ny, nx))
        return self._heads(canvas[None], train)

    def from_points_batch(
        self,
        points: jnp.ndarray,  # (B, P, F>=4) padded clouds
        counts: jnp.ndarray,  # (B,) real rows per cloud
        train: bool = False,
    ) -> dict[str, jnp.ndarray]:
        """Batched sort-free path for TRAINING: per-sample pillar
        assignment (pure vmap), one flat VFE encode over all B*P rows
        (so BatchNorm sees the whole batch's point population — a
        per-sample vmap would trip flax's broadcast-state mutation),
        then per-sample canvas scatter."""
        require_pillar_grid(self.cfg.voxel.grid_size)
        nx, ny, _ = self.cfg.voxel.grid_size
        feats, vid, valid, _cnt = jax.vmap(
            lambda p, c: augment_points(p, c, self.cfg.voxel)
        )(points, counts)
        b, n, f = feats.shape
        x = self.vfe.encode(feats.reshape(b * n, f), train).reshape(b, n, -1)
        canvas = jax.vmap(
            lambda xx, vv, va: scatter_max_canvas(xx, vv, va, (ny, nx))
        )(x, vid, valid)
        return self._heads(canvas, train)

    def _heads(self, canvas: jnp.ndarray, train: bool) -> dict[str, jnp.ndarray]:
        cfg = self.cfg
        spatial = self.backbone(canvas, train)
        spatial = spatial.astype(jnp.float32)
        cls = self.cls_head(spatial)
        box = self.box_head(spatial)
        direction = self.dir_head(spatial)
        a = cfg.anchors_per_loc
        b, h, w, _ = cls.shape
        return {
            "cls": cls.reshape(b, h, w, a, cfg.num_classes),
            "box": box.reshape(b, h, w, a, 7),
            "dir": direction.reshape(b, h, w, a, cfg.num_dir_bins),
        }

    def topk_candidates(
        self,
        heads: dict[str, jnp.ndarray],
        pre_max: int = 512,
        score_thresh: float = 0.1,
    ) -> dict[str, jnp.ndarray]:
        """Gate + top-k on RAW class logits, BEFORE any box decode:
        deltas/anchors (B, K, 7), dir_bin (B, K), scores (B, K) with
        -inf on gated-out slots, labels (B, K) 1-indexed.

        The pre-decode half of decode_topk, split out so pipelines can
        route the residual decode either through XLA
        (:func:`decode_candidates`) or the fused Pallas kernel
        (ops/pallas_decode.fused_residual_decode) — both consume
        exactly this candidate set."""
        cfg = self.cfg
        b, h, w, a, nc = heads["cls"].shape
        n = h * w * a
        cls = heads["cls"].reshape(b, n, nc)
        box = heads["box"].reshape(b, n, 7)
        dirs = heads["dir"].reshape(b, n, cfg.num_dir_bins)
        anchors = generate_anchors(cfg).reshape(n, 7)

        logit_max = cls.max(axis=-1)
        labels = cls.argmax(axis=-1) + 1
        k = min(pre_max, n)
        top_logits, top_idx = jax.lax.top_k(logit_max, k)  # (B, K)

        box_k = jnp.take_along_axis(box, top_idx[..., None], axis=1)
        dir_k = jnp.take_along_axis(dirs, top_idx[..., None], axis=1)
        labels_k = jnp.take_along_axis(labels, top_idx, axis=1)
        anchors_k = anchors[top_idx]  # (B, K, 7)

        scores = jax.nn.sigmoid(top_logits)
        scores = jnp.where(scores > score_thresh, scores, -jnp.inf)
        return {
            "deltas": box_k,
            "anchors": anchors_k,
            "dir_bin": jnp.argmax(dir_k, axis=-1),
            "scores": scores,
            "labels": labels_k,
        }

    def decode_topk(
        self,
        heads: dict[str, jnp.ndarray],
        pre_max: int = 512,
        score_thresh: float = 0.1,
    ) -> dict[str, jnp.ndarray]:
        """Gate + top-k on RAW class logits, then decode only the
        survivors: boxes (B, K, 7), scores (B, K) with -inf on gated-out
        slots, labels (B, K) 1-indexed.

        Equivalent to decode() + extract_boxes_3d's prefilter (sigmoid
        is monotonic, so top-k on max logits = top-k on max sigmoid
        scores), but the full anchor grid (321k anchors for the KITTI
        head) never goes through box decode — only K do. On a v5e chip
        this removes the dominant decode cost from the fused pipeline."""
        cfg = self.cfg
        cand = self.topk_candidates(heads, pre_max, score_thresh)
        return decode_candidates(cand, cfg.num_dir_bins, cfg.dir_offset)

    def decode(self, heads: dict[str, jnp.ndarray]) -> dict[str, jnp.ndarray]:
        """Raw head maps -> flat per-anchor predictions:
        boxes (B, N, 7), scores (B, N, num_classes) sigmoid, with
        direction-bin-corrected headings (OpenPCDet dir_offset scheme)."""
        cfg = self.cfg
        anchors = generate_anchors(cfg)[None]  # (1, h, w, A, 7)
        boxes = decode_boxes(heads["box"], anchors)
        dir_bin = jnp.argmax(heads["dir"], axis=-1)  # (B, h, w, A)
        rot = rectify_direction(
            boxes[..., 6], dir_bin, cfg.num_dir_bins, cfg.dir_offset
        )
        boxes = jnp.concatenate([boxes[..., :6], rot[..., None]], axis=-1)
        scores = jax.nn.sigmoid(heads["cls"])
        b = boxes.shape[0]
        return {
            "boxes": boxes.reshape(b, -1, 7),
            "scores": scores.reshape(b, -1, cfg.num_classes),
        }


def init_pointpillars(rng, cfg: PointPillarsConfig | None = None, dtype=jnp.float32):
    cfg = cfg or PointPillarsConfig()
    model = PointPillars(cfg, dtype=dtype)
    v, k = cfg.voxel.max_voxels, cfg.voxel.max_points_per_voxel
    variables = model.init(
        rng,
        jnp.zeros((1, v, k, cfg.voxel.point_features)),
        jnp.zeros((1, v), jnp.int32),
        jnp.full((1, v, 3), -1, jnp.int32),
        train=False,
    )
    return model, variables
