"""3D scene visualization: point clouds + oriented boxes, dependency-free.

Capability parity with the reference's Open3D / Mayavi scene renderers
(clients/postprocess/visualize_open3d.py:38-117,
clients/postprocess/visualize_mayavi.py:44-215): convert (N, 7)
[x, y, z, dx, dy, dz, heading] boxes to 8 corners, and render the scene
— here to plain numpy RGB images (a rotated-rectangle BEV raster and a
pinhole-projected 3D wireframe view) instead of an interactive GL
window, so visualization works headless on a TPU host with no GL stack.
Corner ordering matches the reference template
(visualize_mayavi.py:44-71) so downstream consumers interchange.

All functions are host-side numpy: viz runs on frames already pulled
off device, never inside the jitted path.
"""

from __future__ import annotations

import numpy as np

# Same palette role as the reference's box_colormap (visualize_mayavi.py:5-10),
# indexed by label id; RGB 0-255.
BOX_COLORMAP = np.array(
    [
        [255, 255, 255],
        [0, 255, 0],
        [0, 255, 255],
        [255, 255, 0],
        [255, 128, 0],
        [255, 0, 255],
        [64, 128, 255],
        [255, 64, 64],
        [128, 255, 128],
        [200, 200, 100],
    ],
    dtype=np.uint8,
)

# Unit-cube corner template, OpenPCDet ordering (visualize_mayavi.py:49-63):
# corners 0-3 are the bottom face (z = -dz/2), 4-7 the top, with corner k+4
# vertically above corner k.
_CORNER_TEMPLATE = (
    np.array(
        [
            [1, 1, -1],
            [1, -1, -1],
            [-1, -1, -1],
            [-1, 1, -1],
            [1, 1, 1],
            [1, -1, 1],
            [-1, -1, 1],
            [-1, 1, 1],
        ],
        dtype=np.float32,
    )
    / 2.0
)

# Wireframe edges over that ordering: bottom ring, top ring, verticals,
# plus the two heading-face diagonals the reference draws to mark +x
# (visualize_open3d.py:90-96 adds lines [0,5] and [4,1]).
_EDGES = np.array(
    [
        [0, 1], [1, 2], [2, 3], [3, 0],
        [4, 5], [5, 6], [6, 7], [7, 4],
        [0, 4], [1, 5], [2, 6], [3, 7],
        [0, 5], [4, 1],
    ],
    dtype=np.int32,
)


def corners_3d(boxes7: np.ndarray) -> np.ndarray:
    """(N, 7) [x, y, z, dx, dy, dz, yaw] -> (N, 8, 3) world-frame corners.

    Yaw rotates about +z, x toward y (visualize_mayavi.py:19-41).
    """
    boxes7 = np.asarray(boxes7, dtype=np.float32).reshape(-1, 7)
    n = boxes7.shape[0]
    corners = boxes7[:, None, 3:6] * _CORNER_TEMPLATE[None, :, :]  # (N,8,3)
    c, s = np.cos(boxes7[:, 6]), np.sin(boxes7[:, 6])
    zeros, ones = np.zeros(n, np.float32), np.ones(n, np.float32)
    rot = np.stack(
        [c, s, zeros, -s, c, zeros, zeros, zeros, ones], axis=1
    ).reshape(n, 3, 3)
    corners = corners @ rot
    return corners + boxes7[:, None, 0:3]


def _draw_line(img: np.ndarray, p0, p1, color) -> None:
    """Integer Bresenham-ish line via dense interpolation (host viz only)."""
    h, w = img.shape[:2]
    x0, y0 = float(p0[0]), float(p0[1])
    x1, y1 = float(p1[0]), float(p1[1])
    n = int(max(abs(x1 - x0), abs(y1 - y0))) + 1
    t = np.linspace(0.0, 1.0, n)
    xs = np.round(x0 + (x1 - x0) * t).astype(np.int64)
    ys = np.round(y0 + (y1 - y0) * t).astype(np.int64)
    keep = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    img[ys[keep], xs[keep]] = color


class BEVCanvas:
    """Rasterizes a metric top-down view into an RGB image.

    World x is forward (image up), world y is left (image left) — the
    usual LiDAR BEV convention for KITTI-range scenes
    (data/kitti_dataset.yaml POINT_CLOUD_RANGE semantics).
    """

    def __init__(
        self,
        xlim: tuple[float, float] = (0.0, 70.4),
        ylim: tuple[float, float] = (-40.0, 40.0),
        px_per_m: float = 10.0,
        background: int = 0,
    ) -> None:
        self.xlim, self.ylim, self.px_per_m = xlim, ylim, px_per_m
        self.width = int(round((ylim[1] - ylim[0]) * px_per_m))
        self.height = int(round((xlim[1] - xlim[0]) * px_per_m))
        self.img = np.full((self.height, self.width, 3), background, np.uint8)

    def world_to_px(self, xy: np.ndarray) -> np.ndarray:
        """(..., 2) world x,y -> (..., 2) pixel col,row."""
        xy = np.asarray(xy, dtype=np.float32)
        col = (self.ylim[1] - xy[..., 1]) * self.px_per_m
        row = (self.xlim[1] - xy[..., 0]) * self.px_per_m
        return np.stack([col, row], axis=-1)

    def add_points(self, points: np.ndarray, intensity: np.ndarray | None = None):
        """Splat (N, >=2) world points; brightness from intensity if given
        (parity with show_intensity, visualize_mayavi.py:79-83)."""
        points = np.asarray(points)
        px = self.world_to_px(points[:, :2])
        cols = np.round(px[:, 0]).astype(np.int64)
        rows = np.round(px[:, 1]).astype(np.int64)
        keep = (cols >= 0) & (cols < self.width) & (rows >= 0) & (rows < self.height)
        cols, rows = cols[keep], rows[keep]
        if intensity is None:
            shade = np.full(cols.shape, 180, np.uint8)
        else:
            inten = np.clip(np.asarray(intensity, np.float32)[keep], 0.0, 1.0)
            shade = (80 + 175 * inten).astype(np.uint8)
        self.img[rows, cols] = shade[:, None]
        return self

    def add_boxes(
        self,
        boxes7: np.ndarray,
        labels: np.ndarray | None = None,
        scores: np.ndarray | None = None,
        color: tuple[int, int, int] | None = None,
    ):
        """Draw rotated rectangles with a heading tick from center to the
        front-face midpoint (so yaw is visually checkable, like the
        reference's oriented LineSets, visualize_open3d.py:76-103)."""
        boxes7 = np.asarray(boxes7, dtype=np.float32).reshape(-1, 7)
        corners = corners_3d(boxes7)[:, :4, :2]  # bottom ring in world xy
        for i, quad in enumerate(corners):
            if color is not None:
                col = color
            elif labels is not None:
                col = BOX_COLORMAP[int(labels[i]) % len(BOX_COLORMAP)]
            else:
                col = (0, 255, 0)
            px = self.world_to_px(quad)
            for a, b in ((0, 1), (1, 2), (2, 3), (3, 0)):
                _draw_line(self.img, px[a], px[b], col)
            # heading tick: center -> midpoint of the +x face (corners 0,1)
            center = self.world_to_px(boxes7[i, :2])
            front = self.world_to_px(quad[:2].mean(axis=0))
            _draw_line(self.img, center, front, col)
            if scores is not None:
                # brightness-coded score dot at the box center
                r, c = int(round(center[1])), int(round(center[0]))
                if 0 <= r < self.height and 0 <= c < self.width:
                    shade = int(55 + 200 * float(np.clip(scores[i], 0, 1)))
                    self.img[r, c] = (shade, shade, shade)
        return self


def draw_scene_bev(
    points: np.ndarray | None,
    boxes7: np.ndarray | None = None,
    labels: np.ndarray | None = None,
    scores: np.ndarray | None = None,
    gt_boxes7: np.ndarray | None = None,
    xlim: tuple[float, float] = (0.0, 70.4),
    ylim: tuple[float, float] = (-40.0, 40.0),
    px_per_m: float = 10.0,
) -> np.ndarray:
    """One-call scene render, the draw_scenes equivalent
    (visualize_mayavi.py:142-171: points + ref boxes(green) + gt(blue)).

    Returns an (H, W, 3) uint8 RGB image.
    """
    canvas = BEVCanvas(xlim=xlim, ylim=ylim, px_per_m=px_per_m)
    if points is not None and len(points):
        inten = points[:, 3] if points.shape[1] > 3 else None
        canvas.add_points(points, inten)
    if gt_boxes7 is not None and len(gt_boxes7):
        canvas.add_boxes(gt_boxes7, color=(64, 128, 255))
    if boxes7 is not None and len(boxes7):
        canvas.add_boxes(boxes7, labels=labels, scores=scores)
    return canvas.img


def project_pinhole(
    pts_world: np.ndarray,
    eye: np.ndarray,
    look_at: np.ndarray,
    up: np.ndarray = np.array([0.0, 0.0, 1.0]),
    focal_px: float = 500.0,
    size: tuple[int, int] = (600, 600),
) -> tuple[np.ndarray, np.ndarray]:
    """Project world points through a simple pinhole camera.

    Returns (pixels (N, 2) col,row, depth (N,)); points behind the camera
    get depth <= 0 and should be masked by the caller.
    """
    eye = np.asarray(eye, np.float32)
    fwd = np.asarray(look_at, np.float32) - eye
    fwd = fwd / (np.linalg.norm(fwd) + 1e-9)
    right = np.cross(fwd, np.asarray(up, np.float32))
    right = right / (np.linalg.norm(right) + 1e-9)
    cam_up = np.cross(right, fwd)
    rel = np.asarray(pts_world, np.float32) - eye
    x = rel @ right
    y = rel @ cam_up
    z = rel @ fwd
    w, h = size
    zc = np.where(np.abs(z) < 1e-6, 1e-6, z)
    cols = w / 2.0 + focal_px * x / zc
    rows = h / 2.0 - focal_px * y / zc
    return np.stack([cols, rows], axis=-1), z


def draw_scene_3d(
    points: np.ndarray | None,
    boxes7: np.ndarray | None = None,
    labels: np.ndarray | None = None,
    eye: tuple[float, float, float] = (-25.0, -25.0, 20.0),
    look_at: tuple[float, float, float] = (20.0, 0.0, 0.0),
    size: tuple[int, int] = (600, 600),
) -> np.ndarray:
    """Perspective wireframe render — the headless stand-in for the
    reference's interactive GL viewers (same default 600x600 viewport as
    visualize_mayavi.py:77)."""
    w, h = size
    img = np.zeros((h, w, 3), np.uint8)
    if points is not None and len(points):
        px, depth = project_pinhole(points[:, :3], eye, look_at, size=size)
        order = np.argsort(-depth)  # painter's order: far first
        px, depth = px[order], depth[order]
        keep = depth > 0.1
        cols = np.round(px[keep, 0]).astype(np.int64)
        rows = np.round(px[keep, 1]).astype(np.int64)
        ok = (cols >= 0) & (cols < w) & (rows >= 0) & (rows < h)
        shade = np.clip(255.0 * 20.0 / depth[keep][ok], 40, 220).astype(np.uint8)
        img[rows[ok], cols[ok]] = shade[:, None]
    if boxes7 is not None and len(boxes7):
        corners = corners_3d(boxes7)
        for i, corn in enumerate(corners):
            color = (
                BOX_COLORMAP[int(labels[i]) % len(BOX_COLORMAP)]
                if labels is not None
                else (0, 255, 0)
            )
            px, depth = project_pinhole(corn, eye, look_at, size=size)
            if np.any(depth <= 0.1):
                continue
            for a, b in _EDGES:
                _draw_line(img, px[a], px[b], color)
    return img
