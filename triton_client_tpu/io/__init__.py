"""Input sources and output sinks for the inference drivers."""

from triton_client_tpu.io.sources import (
    Frame,
    FrameSource,
    ImageDirSource,
    NpyPointCloudSource,
    SyntheticImageSource,
    SyntheticPointCloudSource,
    VideoSource,
    open_source,
)
from triton_client_tpu.io.sinks import (
    DetectionLogSink,
    ImageFileSink,
    NullSink,
    Sink,
)

__all__ = [
    "Frame",
    "FrameSource",
    "ImageDirSource",
    "NpyPointCloudSource",
    "SyntheticImageSource",
    "SyntheticPointCloudSource",
    "VideoSource",
    "open_source",
    "DetectionLogSink",
    "ImageFileSink",
    "NullSink",
    "Sink",
]
