"""Synthetic labeled datasets: the in-environment accuracy oracle.

The reference proves accuracy by serving *trained* weights and scoring
them online against a ground-truth topic (communicator/
evaluate_inference.py:400-446); its weights arrive from outside the
repo. With no artifact access, the equivalent proof is a closed loop:
generate labeled scenes with known ground truth, train with the `train`
CLI, then run the FULL detect pipeline (decode + NMS included) and
assert nonzero mAP through eval/detection_map.py.

Two generators, matching the reference's two domains:

* 2D (`write_detection_dataset`): crop-field-like images — textured
  ground, shape-classed objects (ellipse / rotated box / triangle for
  the crop/weed-style classes of data/crop.names), line+speckle
  distractors — with tight [x1, y1, x2, y2, cls] ground truth in the
  gt-JSONL schema `cli/common.load_gt_lookup` reads.
* 3D (`write_scene_dataset`): KITTI-like lidar scenes — ground clutter
  + surface-sampled, yaw-rotated objects with 1/r^2 return density
  (grown from perf/profile_second_grid.py's scene model) — as .npy
  clouds plus [cx, cy, cz, dx, dy, dz, yaw, cls] ground truth.

Determinism: everything derives from the seed, so train/holdout splits
are reproducible by seed alone.
"""

from __future__ import annotations

import json
import os

import numpy as np

# KITTI anchor geometry (data/pointpillar.yaml anchor_sizes), reused by
# the 3D scene generator so synthetic objects match the anchor priors.
KITTI_CLASS_GEOMETRY = {
    # name: ((dx, dy, dz), bottom_z)
    "Car": ((3.9, 1.6, 1.56), -1.78),
    "Pedestrian": ((0.8, 0.6, 1.73), -0.6),
    "Cyclist": ((1.76, 0.6, 1.73), -0.6),
}


# --------------------------------------------------------------------------
# 2D: shape-classed field scenes
# --------------------------------------------------------------------------

def _background(rng: np.random.Generator, hw: tuple[int, int]) -> np.ndarray:
    """Low-frequency field texture + speckle, uint8 RGB."""
    h, w = hw
    # coarse noise upsampled -> smooth patches (soil/foliage blobs)
    coarse = rng.uniform(0.0, 1.0, (max(h // 32, 2), max(w // 32, 2), 3))
    idx_y = np.linspace(0, coarse.shape[0] - 1, h)
    idx_x = np.linspace(0, coarse.shape[1] - 1, w)
    smooth = coarse[idx_y.astype(int)][:, idx_x.astype(int)]
    base = np.array([90.0, 70.0, 50.0]) + smooth * np.array([60.0, 50.0, 30.0])
    img = base + rng.normal(0, 12.0, (h, w, 3))
    return np.clip(img, 0, 255).astype(np.uint8)


_SHAPE_COLORS = (
    (60, 180, 60),   # vivid green
    (200, 60, 60),   # red
    (60, 80, 210),   # blue
    (220, 200, 40),  # yellow
    (180, 60, 200),  # magenta
)


def _draw_object(img, rng, cls: int, box: tuple[int, int, int, int]) -> None:
    """Draw one class-`cls` shape tightly inside `box` (x1, y1, x2, y2)."""
    import cv2

    x1, y1, x2, y2 = box
    color = tuple(
        int(np.clip(c + rng.normal(0, 20), 0, 255))
        for c in _SHAPE_COLORS[rng.integers(0, len(_SHAPE_COLORS))]
    )
    cx, cy = (x1 + x2) // 2, (y1 + y2) // 2
    if cls == 0:  # ellipse
        cv2.ellipse(
            img, (cx, cy), ((x2 - x1) // 2, (y2 - y1) // 2), 0, 0, 360,
            color, -1, cv2.LINE_AA,
        )
    elif cls == 1:  # filled box with an inner notch (distinct from ellipse)
        cv2.rectangle(img, (x1, y1), (x2, y2), color, -1)
        nw, nh = max((x2 - x1) // 4, 1), max((y2 - y1) // 4, 1)
        dark = tuple(int(c * 0.35) for c in color)
        cv2.rectangle(img, (cx - nw // 2, cy - nh // 2),
                      (cx + nw // 2, cy + nh // 2), dark, -1)
    else:  # triangle touching the box edges
        pts = np.array(
            [[cx, y1], [x1, y2], [x2, y2]], np.int32
        )
        cv2.fillPoly(img, [pts], color, cv2.LINE_AA)


def _iou_xyxy(a: np.ndarray, b: np.ndarray) -> float:
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / max(ua, 1e-9)


def synth_detection_frame(
    rng: np.random.Generator,
    hw: tuple[int, int] = (512, 512),
    num_classes: int = 2,
    max_objects: int = 6,
    distractors: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """One labeled frame: (img uint8 (H, W, 3), boxes (n, 5)
    [x1, y1, x2, y2, cls] float32). Objects are shape-classed, sized
    8-30% of the short side, rejection-sampled to pairwise IoU < 0.2."""
    import cv2

    h, w = hw
    img = _background(rng, hw)
    if distractors:
        for _ in range(int(rng.integers(4, 10))):
            p1 = (int(rng.integers(0, w)), int(rng.integers(0, h)))
            p2 = (int(rng.integers(0, w)), int(rng.integers(0, h)))
            shade = int(rng.integers(30, 90))
            cv2.line(img, p1, p2, (shade, shade, shade), 1, cv2.LINE_AA)
        for _ in range(int(rng.integers(8, 20))):
            c = (int(rng.integers(0, w)), int(rng.integers(0, h)))
            shade = tuple(int(v) for v in rng.integers(40, 140, 3))
            cv2.circle(img, c, int(rng.integers(1, 3)), shade, -1)

    short = min(h, w)
    boxes: list[np.ndarray] = []
    n_obj = int(rng.integers(1, max_objects + 1))
    for _ in range(n_obj):
        for _attempt in range(20):
            bw = int(rng.uniform(0.08, 0.30) * short)
            bh = int(bw * rng.uniform(0.7, 1.4))
            x1 = int(rng.uniform(2, w - bw - 2))
            y1 = int(rng.uniform(2, h - bh - 2))
            cand = np.array([x1, y1, x1 + bw, y1 + bh], np.float32)
            if all(_iou_xyxy(cand, b[:4]) < 0.2 for b in boxes):
                cls = int(rng.integers(0, num_classes))
                _draw_object(img, rng, cls, (x1, y1, x1 + bw, y1 + bh))
                boxes.append(np.append(cand, np.float32(cls)))
                break
    return img, np.stack(boxes).astype(np.float32)


def write_detection_dataset(
    out_dir: str,
    n_images: int,
    hw: tuple[int, int] = (512, 512),
    num_classes: int = 2,
    seed: int = 0,
    max_objects: int = 6,
) -> tuple[str, str]:
    """Write `<out_dir>/images/%06d.png` + `<out_dir>/gt.jsonl`
    (frame_id = sorted-filename index, the ImageDirSource contract).
    Returns (images_dir, gt_path)."""
    import cv2

    rng = np.random.default_rng(seed)
    images_dir = os.path.join(out_dir, "images")
    os.makedirs(images_dir, exist_ok=True)
    gt_path = os.path.join(out_dir, "gt.jsonl")
    with open(gt_path, "w") as f:
        for i in range(n_images):
            img, boxes = synth_detection_frame(
                rng, hw, num_classes, max_objects
            )
            cv2.imwrite(
                os.path.join(images_dir, f"{i:06d}.png"), img[..., ::-1]
            )
            f.write(
                json.dumps(
                    {"frame_id": i, "boxes": [list(map(float, b)) for b in boxes]}
                )
                + "\n"
            )
    return images_dir, gt_path


# --------------------------------------------------------------------------
# 3D: KITTI-like lidar scenes with yaw-rotated ground truth
# --------------------------------------------------------------------------

def synth_scene_frame(
    rng: np.random.Generator,
    pc_range: tuple = (0.0, -39.68, -3.0, 69.12, 39.68, 1.0),
    n_objects: int = 8,
    n_clutter: int = 16_000,
    class_names: tuple[str, ...] = ("Car", "Pedestrian", "Cyclist"),
    yaw: bool = True,
    yaw_mode: str = "uniform",
    min_points: int = 20,
    n_sweeps: int = 0,
    sweep_dt: float = 0.05,
    velocity_max: float = 0.0,
    front_bias: float = 0.0,
) -> tuple[np.ndarray, np.ndarray]:
    """One labeled scan: (points (N, 4) [x, y, z, intensity] float32,
    boxes (n, 8) [cx, cy, cz, dx, dy, dz, yaw, cls] float32).

    Ground plane clutter + surface-sampled objects whose return density
    falls ~1/r^2 with range (perf/profile_second_grid.py's scene model,
    plus per-object yaw so the rotated-IoU eval path is exercised);
    objects closer than `min_points` returns are rejected so every GT
    box is actually observable.

    ``yaw_mode``: 'uniform' draws headings uniformly (the hard,
    rotation-agnostic case); 'road' draws 80% near an axis (N(axis,
    0.15), axis in {0, pi/2, pi, -pi/2}) + 20% uniform — KITTI-like
    traffic, the distribution the reference's axis-aligned anchor
    config (data/pointpillar.yaml:118-142 rotations [0, 1.57]) is
    designed for.

    ``n_sweeps > 0`` switches to the nuScenes multi-sweep contract the
    served CenterPoint consumes (reference clients/preprocess/
    voxelize.py:13-24 feeds 10-sweep clouds): points gain a Δt channel
    (-> (N, 5)), each object gets a ground-plane velocity drawn from
    [-velocity_max, velocity_max]² whose MOTION IS IN THE DATA — sweep
    k's returns sample the object displaced to c - v·k·dt — and boxes
    gain [vx, vy] (-> (n, 10)). Velocity is thereby observable from a
    single stacked cloud (the motion streak), which is exactly what the
    CenterPoint velocity head trains on.

    ``front_bias > 0`` skews each object's surface returns toward its
    +x (heading) half: a fraction ``front_bias`` of returns land on the
    front half, the rest on the rear. A perfect cuboid with symmetric
    sampling is EXACTLY π-rotation-invariant, which makes full-circle
    yaw unlearnable on principle — component-wise L1 over the
    {(sinθ, cosθ), (−sinθ, −cosθ)} mixture medians to (0, 0), the
    failure CenterPoint's det3d (sin, cos) regression hits on such
    data (anchor heads dodge it via the mod-π sin-difference loss).
    Real lidar returns are front/back asymmetric (bumpers, windshield
    rake, mirrors), which is the asymmetry this models."""
    x0, y0, _z0, x1, y1, _z1 = pc_range
    sweeps = max(1, n_sweeps)
    cols = 5 if n_sweeps > 0 else 4
    ground = np.stack(
        [
            rng.uniform(x0, x1, n_clutter),
            rng.uniform(y0, y1, n_clutter),
            rng.normal(-1.9, 0.05, n_clutter),
            rng.uniform(0, 1, n_clutter),
        ],
        axis=1,
    ).astype(np.float32)
    if cols == 5:
        # static clutter appears in every sweep at the same place;
        # spread its Δt uniformly over the sweep window
        ts = rng.integers(0, sweeps, n_clutter) * sweep_dt
        ground = np.concatenate(
            [ground, ts[:, None].astype(np.float32)], axis=1
        )
    parts = [ground]
    boxes: list[list[float]] = []
    for _ in range(n_objects):
        for _attempt in range(20):
            cls = int(rng.integers(0, len(class_names)))
            (dx, dy, dz), bz = KITTI_CLASS_GEOMETRY[class_names[cls]]
            cx = float(rng.uniform(x0 + 5, min(x1 - 3, 60)))
            cy = float(rng.uniform(y0 + 5, y1 - 5))
            cz = bz + dz / 2
            if not yaw:
                ry = 0.0
            elif yaw_mode == "road" and rng.uniform() < 0.8:
                axis = rng.choice([0.0, np.pi / 2, np.pi, -np.pi / 2])
                ry = float(axis + rng.normal(0.0, 0.15))
            else:
                ry = float(rng.uniform(-np.pi, np.pi))
            vx = vy = 0.0
            if velocity_max > 0:
                vx = float(rng.uniform(-velocity_max, velocity_max))
                vy = float(rng.uniform(-velocity_max, velocity_max))
            r = float(np.hypot(cx, cy))
            n_pts = int(60_000 / max(r, 5) ** 2)
            if n_pts < min_points:
                continue
            # keep objects separated (no overlapping GT): centre
            # distance vs the larger footprint diagonal
            too_close = any(
                np.hypot(cx - b[0], cy - b[1])
                < 0.7 * (np.hypot(dx, dy) + np.hypot(b[3], b[4]))
                for b in boxes
            )
            if too_close:
                continue
            obj_parts = []
            for k in range(sweeps):
                nk = max(n_pts // sweeps, 4)
                face = rng.integers(0, 3, nk)
                u = rng.uniform(-0.5, 0.5, (nk, 3))
                if front_bias > 0:
                    to_front = rng.uniform(size=nk) < front_bias
                    u[:, 0] = np.where(
                        to_front, np.abs(u[:, 0]), -np.abs(u[:, 0])
                    )
                u[face == 0, 0] = np.sign(u[face == 0, 0]) * 0.5
                u[face == 1, 1] = np.sign(u[face == 1, 1]) * 0.5
                u[face == 2, 2] = 0.5  # top surface
                lx, ly, lz = u[:, 0] * dx, u[:, 1] * dy, u[:, 2] * dz
                c, s = np.cos(ry), np.sin(ry)
                # sweep k observed the object k·dt in the past: its
                # center was displaced by -v·k·dt (the motion streak
                # the velocity head reads)
                t = k * sweep_dt
                sweep_cols = [
                    cx - vx * t + lx * c - ly * s,
                    cy - vy * t + lx * s + ly * c,
                    cz + lz,
                    rng.uniform(0, 1, nk),
                ]
                if cols == 5:
                    sweep_cols.append(np.full(nk, t))
                obj_parts.append(
                    np.stack(sweep_cols, axis=1).astype(np.float32)
                )
            parts.extend(obj_parts)
            row = [cx, cy, cz, dx, dy, dz, ry, float(cls)]
            if cols == 5:
                row += [vx, vy]
            boxes.append(row)
            break
    points = np.concatenate(parts)
    return points, np.asarray(boxes, np.float32).reshape(-1, 10 if cols == 5 else 8)


def write_scene_dataset(
    out_dir: str,
    n_scenes: int,
    seed: int = 0,
    **scene_kwargs,
) -> tuple[str, str]:
    """Write `<out_dir>/clouds/%06d.npy` + `<out_dir>/gt3d.jsonl`
    ({"frame_id", "boxes": [[cx, cy, cz, dx, dy, dz, yaw, cls]]}).
    Returns (clouds_dir, gt_path)."""
    rng = np.random.default_rng(seed)
    clouds_dir = os.path.join(out_dir, "clouds")
    os.makedirs(clouds_dir, exist_ok=True)
    gt_path = os.path.join(out_dir, "gt3d.jsonl")
    with open(gt_path, "w") as f:
        for i in range(n_scenes):
            points, boxes = synth_scene_frame(rng, **scene_kwargs)
            np.save(os.path.join(clouds_dir, f"{i:06d}.npy"), points)
            f.write(
                json.dumps(
                    {"frame_id": i, "boxes": [list(map(float, b)) for b in boxes]}
                )
                + "\n"
            )
    return clouds_dir, gt_path


def load_gt3d_lookup(path: str):
    """gt3d JSONL -> frame lookup of (n, 8|10) [cx, cy, cz, dx, dy, dz,
    yaw, cls(, vx, vy)] arrays (the 3D sibling of
    cli/common.load_gt_lookup); 10-column rows carry the multi-sweep
    velocity labels."""
    table: dict[int, np.ndarray] = {}
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            arr = np.asarray(row["boxes"], np.float64)
            arr = arr.reshape(len(row["boxes"]), -1) if len(row["boxes"]) else arr.reshape(0, 8)
            if arr.shape[1] not in (8, 10):
                raise ValueError(
                    f"gt3d rows must have 8 or 10 columns, got {arr.shape[1]}"
                )
            table[int(row["frame_id"])] = arr

    def lookup(frame):
        return table.get(frame.frame_id)

    return lookup
