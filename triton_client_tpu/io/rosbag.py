"""Dependency-free rosbag v2.0 reader/writer + ROS1 message codec.

The reference replays recorded sensor data with the ``rosbag`` package
(communicator/bag_inference2d.py:92, bag_inference3d.py:61-63,116) and
writes detections into an output bag (bag_inference3d.py:182-183) —
all of which requires a full ROS installation. TPU serving hosts have
none, so this module implements the open rosbag V2.0 container format
and the ROS1 message serialization rules directly:

- ``BagReader``: sequential chunk walk (none/bz2 compression; lz4 is
  import-gated), yielding ``(topic, message, t)`` like
  ``rosbag.Bag.read_messages``.
- ``BagWriter``: writes indexed V2.0 bags (chunks + index data +
  connection + chunk-info records) that standard ROS tooling can read.
- A message-spec codec with the standard md5 computation, covering the
  message types the reference touches: sensor_msgs Image /
  CompressedImage / PointCloud2, vision_msgs Detection2DArray (the
  evaluator's GT topic, communicator/evaluate_inference.py:115), and
  jsk_recognition_msgs BoundingBoxArray (the 3D output topic,
  bag_inference3d.py:64).

Everything here is host-side I/O; nothing touches JAX.
"""

from __future__ import annotations

import bz2
import dataclasses
import hashlib
import struct
from types import SimpleNamespace
from typing import Any, Iterator

import numpy as np

# ---------------------------------------------------------------------------
# Message specs
# ---------------------------------------------------------------------------

_BUILTIN_FMT = {
    "bool": "B",
    "int8": "b",
    "uint8": "B",
    "byte": "b",
    "char": "B",
    "int16": "h",
    "uint16": "H",
    "int32": "i",
    "uint32": "I",
    "int64": "q",
    "uint64": "Q",
    "float32": "f",
    "float64": "d",
}
_BUILTIN_NP = {
    "bool": np.uint8,
    "int8": np.int8,
    "uint8": np.uint8,
    "byte": np.int8,
    "char": np.uint8,
    "int16": np.int16,
    "uint16": np.uint16,
    "int32": np.int32,
    "uint32": np.uint32,
    "int64": np.int64,
    "uint64": np.uint64,
    "float32": np.float32,
    "float64": np.float64,
}
_BUILTINS = set(_BUILTIN_FMT) | {"string", "time", "duration"}


@dataclasses.dataclass(frozen=True)
class Field:
    type: str  # resolved full type name (or builtin)
    name: str
    is_array: bool = False
    array_len: int | None = None  # None = variable length


@dataclasses.dataclass(frozen=True)
class Constant:
    type: str
    name: str
    value: str


class MsgSpec:
    def __init__(self, full_name: str, text: str) -> None:
        self.full_name = full_name
        self.package = full_name.split("/")[0]
        self.text = text.strip()
        self.fields: list[Field] = []
        self.constants: list[Constant] = []
        for raw in self.text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            type_tok, rest = line.split(None, 1)
            if "=" in rest:
                cname, value = rest.split("=", 1)
                self.constants.append(
                    Constant(type_tok, cname.strip(), value.strip())
                )
                continue
            is_array, array_len = False, None
            if "[" in type_tok:
                base, dims = type_tok.split("[", 1)
                dims = dims.rstrip("]")
                is_array = True
                array_len = int(dims) if dims else None
                type_tok = base
            self.fields.append(
                Field(self._resolve(type_tok), rest.strip(), is_array, array_len)
            )

    def _resolve(self, t: str) -> str:
        if t in _BUILTINS:
            return t
        if t == "Header":  # special-cased by the ROS msg language
            return "std_msgs/Header"
        if "/" in t:
            return t
        return f"{self.package}/{t}"


REGISTRY: dict[str, MsgSpec] = {}


def register(full_name: str, text: str) -> MsgSpec:
    spec = MsgSpec(full_name, text)
    REGISTRY[full_name] = spec
    return spec


def compute_md5(type_name: str) -> str:
    """Standard ROS md5: constants first, builtin field lines verbatim,
    complex fields replaced by '<nested md5> <name>' (array spec dropped)."""
    spec = REGISTRY[type_name]
    lines = [f"{c.type} {c.name}={c.value}" for c in spec.constants]
    for f in spec.fields:
        if f.type in _BUILTINS:
            if f.is_array:
                dims = "" if f.array_len is None else str(f.array_len)
                lines.append(f"{f.type}[{dims}] {f.name}")
            else:
                lines.append(f"{f.type} {f.name}")
        else:
            lines.append(f"{compute_md5(f.type)} {f.name}")
    return hashlib.md5("\n".join(lines).encode()).hexdigest()


def full_definition(type_name: str) -> str:
    """gendeps --cat style concatenated definition for connection headers."""
    seen: list[str] = []

    def deps(name: str) -> None:
        for f in REGISTRY[name].fields:
            if f.type not in _BUILTINS:
                if f.type not in seen:
                    seen.append(f.type)
                deps(f.type)

    deps(type_name)
    parts = [REGISTRY[type_name].text]
    sep = "=" * 80
    for dep in seen:
        parts.append(f"{sep}\nMSG: {dep}\n{REGISTRY[dep].text}")
    return "\n".join(parts) + "\n"


# --- the message vocabulary the reference's pipelines touch ---------------

register("std_msgs/Header", "uint32 seq\ntime stamp\nstring frame_id")
register("geometry_msgs/Point", "float64 x\nfloat64 y\nfloat64 z")
register("geometry_msgs/Quaternion", "float64 x\nfloat64 y\nfloat64 z\nfloat64 w")
register("geometry_msgs/Vector3", "float64 x\nfloat64 y\nfloat64 z")
register(
    "geometry_msgs/Pose",
    "geometry_msgs/Point position\ngeometry_msgs/Quaternion orientation",
)
register("geometry_msgs/Pose2D", "float64 x\nfloat64 y\nfloat64 theta")
register(
    "geometry_msgs/PoseWithCovariance",
    "geometry_msgs/Pose pose\nfloat64[36] covariance",
)
register(
    "geometry_msgs/Twist",
    "geometry_msgs/Vector3 linear\ngeometry_msgs/Vector3 angular",
)
register(
    "geometry_msgs/TwistWithCovariance",
    "geometry_msgs/Twist twist\nfloat64[36] covariance",
)
register(
    "nav_msgs/Odometry",
    "Header header\nstring child_frame_id\n"
    "geometry_msgs/PoseWithCovariance pose\n"
    "geometry_msgs/TwistWithCovariance twist",
)
register(
    "sensor_msgs/PointField",
    "uint8 INT8=1\nuint8 UINT8=2\nuint8 INT16=3\nuint8 UINT16=4\n"
    "uint8 INT32=5\nuint8 UINT32=6\nuint8 FLOAT32=7\nuint8 FLOAT64=8\n"
    "string name\nuint32 offset\nuint8 datatype\nuint32 count",
)
register(
    "sensor_msgs/PointCloud2",
    "Header header\nuint32 height\nuint32 width\n"
    "sensor_msgs/PointField[] fields\nbool is_bigendian\nuint32 point_step\n"
    "uint32 row_step\nuint8[] data\nbool is_dense",
)
register(
    "sensor_msgs/Image",
    "Header header\nuint32 height\nuint32 width\nstring encoding\n"
    "uint8 is_bigendian\nuint32 step\nuint8[] data",
)
register(
    "sensor_msgs/CompressedImage",
    "Header header\nstring format\nuint8[] data",
)
register(
    "jsk_recognition_msgs/BoundingBox",
    "Header header\ngeometry_msgs/Pose pose\ngeometry_msgs/Vector3 dimensions\n"
    "float32 value\nuint32 label",
)
register(
    "jsk_recognition_msgs/BoundingBoxArray",
    "Header header\njsk_recognition_msgs/BoundingBox[] boxes",
)
register(
    "vision_msgs/ObjectHypothesisWithPose",
    "int64 id\nfloat64 score\ngeometry_msgs/PoseWithCovariance pose",
)
register(
    "vision_msgs/BoundingBox2D",
    "geometry_msgs/Pose2D center\nfloat64 size_x\nfloat64 size_y",
)
register(
    "vision_msgs/Detection2D",
    "Header header\nvision_msgs/ObjectHypothesisWithPose[] results\n"
    "vision_msgs/BoundingBox2D bbox\nsensor_msgs/Image source_img",
)
register(
    "vision_msgs/Detection2DArray",
    "Header header\nvision_msgs/Detection2D[] detections",
)


# ---------------------------------------------------------------------------
# Serialization (little-endian ROS1 wire rules)
# ---------------------------------------------------------------------------


def make(type_name: str, **kwargs: Any) -> SimpleNamespace:
    """Default-initialized message instance (recursively), then kwargs."""
    spec = REGISTRY[type_name]
    msg = SimpleNamespace(_type=type_name)
    for f in spec.fields:
        if f.is_array:
            if f.type in _BUILTIN_NP:
                val: Any = np.zeros(f.array_len or 0, _BUILTIN_NP[f.type])
            else:
                val = []
        elif f.type in _BUILTIN_FMT:
            val = 0
        elif f.type == "string":
            val = ""
        elif f.type in ("time", "duration"):
            val = (0, 0)
        else:
            val = make(f.type)
        setattr(msg, f.name, val)
    for k, v in kwargs.items():
        setattr(msg, k, v)
    return msg


def _ser_value(out: bytearray, ftype: str, value: Any) -> None:
    if ftype in _BUILTIN_FMT:
        out += struct.pack("<" + _BUILTIN_FMT[ftype], value)
    elif ftype == "string":
        data = value.encode() if isinstance(value, str) else bytes(value)
        out += struct.pack("<I", len(data)) + data
    elif ftype in ("time", "duration"):
        secs, nsecs = _as_time(value)
        out += struct.pack("<II", secs, nsecs)
    else:
        _serialize_into(out, ftype, value)


def _serialize_into(out: bytearray, type_name: str, msg: Any) -> None:
    for f in REGISTRY[type_name].fields:
        value = getattr(msg, f.name)
        if not f.is_array:
            _ser_value(out, f.type, value)
            continue
        if f.type in _BUILTIN_NP:
            arr = np.ascontiguousarray(value, dtype=_BUILTIN_NP[f.type])
            if f.array_len is None:
                out += struct.pack("<I", arr.size)
            elif arr.size != f.array_len:
                raise ValueError(
                    f"{type_name}.{f.name}: fixed array wants {f.array_len}, "
                    f"got {arr.size}"
                )
            out += arr.tobytes()
        else:
            seq = list(value)
            if f.array_len is None:
                out += struct.pack("<I", len(seq))
            for item in seq:
                _ser_value(out, f.type, item)


def serialize(type_name: str, msg: Any) -> bytes:
    out = bytearray()
    _serialize_into(out, type_name, msg)
    return bytes(out)


def _as_time(value: Any) -> tuple[int, int]:
    if isinstance(value, (tuple, list)):
        return int(value[0]), int(value[1])
    if isinstance(value, (int, float)):
        secs = int(value)
        return secs, int(round((float(value) - secs) * 1e9))
    return int(value.secs), int(value.nsecs)  # rospy.Time-like


def _des_value(buf: memoryview, pos: int, ftype: str) -> tuple[Any, int]:
    if ftype in _BUILTIN_FMT:
        fmt = "<" + _BUILTIN_FMT[ftype]
        size = struct.calcsize(fmt)
        return struct.unpack_from(fmt, buf, pos)[0], pos + size
    if ftype == "string":
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        return bytes(buf[pos : pos + n]).decode(errors="replace"), pos + n
    if ftype in ("time", "duration"):
        secs, nsecs = struct.unpack_from("<II", buf, pos)
        return (secs, nsecs), pos + 8
    return _deserialize_from(buf, pos, ftype)


def _deserialize_from(
    buf: memoryview, pos: int, type_name: str
) -> tuple[SimpleNamespace, int]:
    msg = SimpleNamespace(_type=type_name)
    for f in REGISTRY[type_name].fields:
        if not f.is_array:
            value, pos = _des_value(buf, pos, f.type)
        elif f.type in _BUILTIN_NP:
            if f.array_len is None:
                (count,) = struct.unpack_from("<I", buf, pos)
                pos += 4
            else:
                count = f.array_len
            dt = np.dtype(_BUILTIN_NP[f.type])
            nbytes = count * dt.itemsize
            value = np.frombuffer(buf, dt, count, pos).copy()
            pos += nbytes
        else:
            if f.array_len is None:
                (count,) = struct.unpack_from("<I", buf, pos)
                pos += 4
            else:
                count = f.array_len
            items = []
            for _ in range(count):
                item, pos = _des_value(buf, pos, f.type)
                items.append(item)
            value = items
        setattr(msg, f.name, value)
    return msg, pos


def deserialize(type_name: str, data: bytes | memoryview) -> SimpleNamespace:
    msg, pos = _deserialize_from(memoryview(data), 0, type_name)
    if pos != len(data):
        raise ValueError(
            f"{type_name}: {len(data) - pos} trailing bytes after deserialize"
        )
    return msg


# ---------------------------------------------------------------------------
# Bag container format (V2.0)
# ---------------------------------------------------------------------------

MAGIC = b"#ROSBAG V2.0\n"
_OP_MSG = 0x02
_OP_BAG_HEADER = 0x03
_OP_INDEX = 0x04
_OP_CHUNK = 0x05
_OP_CHUNK_INFO = 0x06
_OP_CONNECTION = 0x07
_BAG_HEADER_LEN = 4096  # standard padded bag-header record size


def _pack_header(fields: dict[str, bytes]) -> bytes:
    out = bytearray()
    for name, value in fields.items():
        entry = name.encode() + b"=" + value
        out += struct.pack("<I", len(entry)) + entry
    return bytes(out)


def _parse_header(data: bytes | memoryview) -> dict[str, bytes]:
    fields: dict[str, bytes] = {}
    pos, n = 0, len(data)
    while pos < n:
        (flen,) = struct.unpack_from("<I", data, pos)
        pos += 4
        entry = bytes(data[pos : pos + flen])
        pos += flen
        name, _, value = entry.partition(b"=")
        fields[name.decode()] = value
    return fields


def _u32(v: int) -> bytes:
    return struct.pack("<I", v)


def _u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def _time_bytes(t: float) -> bytes:
    secs = int(t)
    return struct.pack("<II", secs, int(round((t - secs) * 1e9)))


def _time_from(b: bytes) -> float:
    secs, nsecs = struct.unpack("<II", b)
    return secs + nsecs * 1e-9


@dataclasses.dataclass
class Connection:
    conn_id: int
    topic: str
    datatype: str
    md5sum: str
    definition: str


@dataclasses.dataclass
class BagMessage:
    """Lazily-decoded message: ``.msg`` deserializes on first access."""

    connection: Connection
    raw: bytes
    time: float

    _decoded: Any = dataclasses.field(default=None, repr=False)

    @property
    def msg(self) -> Any:
        if self._decoded is None:
            if self.connection.datatype not in REGISTRY:
                raise KeyError(
                    f"no spec registered for {self.connection.datatype}; "
                    "use .raw or register() the type"
                )
            self._decoded = deserialize(self.connection.datatype, self.raw)
        return self._decoded


def _decompress(compression: str, data: bytes) -> bytes:
    if compression in ("none", ""):
        return data
    if compression == "bz2":
        return bz2.decompress(data)
    if compression == "lz4":
        try:
            import lz4.frame  # noqa: F401 - optional, absent on TPU hosts
        except ImportError as e:
            raise NotImplementedError(
                "lz4-compressed bag and no lz4 module; re-record with "
                "bz2/none compression"
            ) from e
        return lz4.frame.decompress(data)
    raise NotImplementedError(f"unknown chunk compression {compression!r}")


class BagReader:
    """Sequential rosbag V2.0 reader.

    Walks the file record by record (no index needed — robust to
    unindexed/truncated bags), expanding chunks inline. Messages come
    out in file order, which for rosbag-recorded files is time order
    per chunk.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self.connections: dict[int, Connection] = {}
        self._f = open(path, "rb")
        magic = self._f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(
                f"{path}: not a rosbag V2.0 file (magic {magic!r}); "
                "V1.2 bags must be migrated with `rosbag fix`"
            )

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "BagReader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _read_record_from_file(self) -> tuple[dict[str, bytes], bytes] | None:
        hdr_len_b = self._f.read(4)
        if len(hdr_len_b) < 4:
            return None
        (hdr_len,) = struct.unpack("<I", hdr_len_b)
        header = self._f.read(hdr_len)
        (data_len,) = struct.unpack("<I", self._f.read(4))
        data = self._f.read(data_len)
        if len(header) < hdr_len or len(data) < data_len:
            return None  # truncated tail
        return _parse_header(header), data

    def _register_connection(self, fields: dict[str, bytes], data: bytes) -> None:
        (conn_id,) = struct.unpack("<I", fields["conn"])
        if conn_id in self.connections:
            return
        info = _parse_header(data)
        self.connections[conn_id] = Connection(
            conn_id=conn_id,
            topic=fields.get("topic", info.get("topic", b"")).decode(),
            datatype=info.get("type", b"").decode(),
            md5sum=info.get("md5sum", b"").decode(),
            definition=info.get("message_definition", b"").decode(
                errors="replace"
            ),
        )

    def _iter_chunk(self, data: bytes) -> Iterator[BagMessage]:
        buf = memoryview(data)
        pos, n = 0, len(buf)
        while pos < n:
            (hdr_len,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            fields = _parse_header(buf[pos : pos + hdr_len])
            pos += hdr_len
            (data_len,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            payload = bytes(buf[pos : pos + data_len])
            pos += data_len
            op = fields["op"][0]
            if op == _OP_CONNECTION:
                self._register_connection(fields, payload)
            elif op == _OP_MSG:
                (conn_id,) = struct.unpack("<I", fields["conn"])
                yield BagMessage(
                    connection=self.connections[conn_id],
                    raw=payload,
                    time=_time_from(fields["time"]),
                )

    def read_messages(
        self, topics: list[str] | None = None, raw: bool = False
    ) -> Iterator[tuple[str, Any, float]]:
        """Yield ``(topic, msg, t)`` — rosbag.Bag.read_messages parity
        (bag_inference2d.py:92): a falsy ``topics`` ([] or None) means
        every topic, matching rosbag's truthiness check. ``raw=True``
        yields the BagMessage (undecoded) instead of the deserialized
        message."""
        yield from self._scan(set(topics) if topics else None, raw)

    def _scan(
        self, want: set[str] | None, raw: bool = False
    ) -> Iterator[tuple[str, Any, float]]:
        """Record walk; ``want`` is the exact topic filter (empty set =
        yield nothing, i.e. a connection-metadata-only scan)."""
        self._f.seek(len(MAGIC))
        while True:
            rec = self._read_record_from_file()
            if rec is None:
                return
            fields, data = rec
            op = fields["op"][0]
            if op == _OP_CONNECTION:
                self._register_connection(fields, data)
            elif op == _OP_CHUNK:
                compression = fields.get("compression", b"none").decode()
                for bm in self._iter_chunk(_decompress(compression, data)):
                    if want is None or bm.connection.topic in want:
                        yield (
                            bm.connection.topic,
                            bm if raw else bm.msg,
                            bm.time,
                        )
            elif op == _OP_MSG:  # unchunked bag (not produced by rosbag,
                # but legal) — treat like an in-chunk record
                (conn_id,) = struct.unpack("<I", fields["conn"])
                bm = BagMessage(
                    connection=self.connections[conn_id],
                    raw=data,
                    time=_time_from(fields["time"]),
                )
                if want is None or bm.connection.topic in want:
                    yield bm.connection.topic, bm if raw else bm.msg, bm.time
            # _OP_INDEX / _OP_CHUNK_INFO / _OP_BAG_HEADER: skip

    def topics(self) -> dict[str, str]:
        """topic -> datatype map (raw scan — never decodes payloads, so
        unregistered message types in the bag are fine)."""
        for _ in self._scan(set()):
            pass
        return {c.topic: c.datatype for c in self.connections.values()}


class BagWriter:
    """Indexed rosbag V2.0 writer (chunked; none or bz2 compression)."""

    def __init__(
        self,
        path: str,
        compression: str = "none",
        chunk_threshold: int = 768 * 1024,
    ) -> None:
        if compression not in ("none", "bz2"):
            raise ValueError("compression must be 'none' or 'bz2'")
        self.path = path
        self.compression = compression
        self.chunk_threshold = chunk_threshold
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._write_bag_header(0, 0, 0)  # placeholder, rewritten on close
        self._conns: dict[str, Connection] = {}  # topic -> Connection
        self._chunk = bytearray()
        self._chunk_index: dict[int, list[tuple[float, int]]] = {}
        self._chunk_conns_written: set[int] = set()
        self._chunk_infos: list[tuple[int, float, float, dict[int, int]]] = []
        self._closed = False

    # -- record plumbing --

    def _write_record(self, fields: dict[str, bytes], data: bytes) -> None:
        header = _pack_header(fields)
        self._f.write(_u32(len(header)) + header + _u32(len(data)) + data)

    def _write_bag_header(self, index_pos: int, conns: int, chunks: int) -> None:
        header = _pack_header(
            {
                "op": bytes([_OP_BAG_HEADER]),
                "index_pos": _u64(index_pos),
                "conn_count": _u32(conns),
                "chunk_count": _u32(chunks),
            }
        )
        pad = _BAG_HEADER_LEN - 8 - len(header)
        self._f.write(_u32(len(header)) + header + _u32(pad) + b" " * pad)

    def _connection_data(self, c: Connection) -> bytes:
        return _pack_header(
            {
                "topic": c.topic.encode(),
                "type": c.datatype.encode(),
                "md5sum": c.md5sum.encode(),
                "message_definition": c.definition.encode(),
            }
        )

    def _conn_record(self, c: Connection) -> bytes:
        fields = {
            "op": bytes([_OP_CONNECTION]),
            "conn": _u32(c.conn_id),
            "topic": c.topic.encode(),
        }
        header = _pack_header(fields)
        data = self._connection_data(c)
        return _u32(len(header)) + header + _u32(len(data)) + data

    # -- public API --

    def register(
        self,
        topic: str,
        datatype: str,
        md5sum: str | None = None,
        definition: str | None = None,
    ) -> Connection:
        if topic in self._conns:
            return self._conns[topic]
        if datatype in REGISTRY:
            if md5sum is None:
                md5sum = compute_md5(datatype)
            if definition is None:
                definition = full_definition(datatype)
        else:
            # Raw passthrough of a type we have no spec for: '*' is the
            # ROS wildcard md5 (subscribers that don't type-check accept it).
            md5sum = md5sum or "*"
            definition = definition or ""
        conn = Connection(len(self._conns), topic, datatype, md5sum, definition)
        self._conns[topic] = conn
        return conn

    def write(
        self,
        topic: str,
        msg: Any,
        t: float | None = None,
        datatype: str | None = None,
    ) -> None:
        """Write a message. ``msg`` is a SimpleNamespace from make()/
        deserialize() (datatype from ``._type`` unless given), a
        BagMessage (re-written raw), or raw bytes (datatype required)."""
        if isinstance(msg, BagMessage):
            raw = msg.raw
            datatype = datatype or msg.connection.datatype
            if t is None:
                t = msg.time
            conn = (
                self._conns[topic]
                if topic in self._conns
                else self.register(
                    topic,
                    datatype,
                    msg.connection.md5sum,
                    msg.connection.definition,
                )
            )
        elif isinstance(msg, (bytes, bytearray, memoryview)):
            if datatype is None:
                raise ValueError("raw bytes need an explicit datatype")
            raw = bytes(msg)
            conn = self.register(topic, datatype)
        else:
            datatype = datatype or getattr(msg, "_type")
            raw = serialize(datatype, msg)
            conn = self.register(topic, datatype)
        if t is None:
            stamp = getattr(msg, "header", None)
            t = 0.0
            if stamp is not None:
                secs, nsecs = _as_time(stamp.stamp)
                t = secs + nsecs * 1e-9

        if conn.conn_id not in self._chunk_conns_written:
            self._chunk += self._conn_record(conn)
            self._chunk_conns_written.add(conn.conn_id)
        offset = len(self._chunk)
        header = _pack_header(
            {
                "op": bytes([_OP_MSG]),
                "conn": _u32(conn.conn_id),
                "time": _time_bytes(t),
            }
        )
        self._chunk += _u32(len(header)) + header + _u32(len(raw)) + raw
        self._chunk_index.setdefault(conn.conn_id, []).append((t, offset))
        if len(self._chunk) >= self.chunk_threshold:
            self._flush_chunk()

    def _flush_chunk(self) -> None:
        if not self._chunk:
            return
        chunk_pos = self._f.tell()
        payload = bytes(self._chunk)
        data = bz2.compress(payload) if self.compression == "bz2" else payload
        all_times = [t for idx in self._chunk_index.values() for t, _ in idx]
        self._write_record(
            {
                "op": bytes([_OP_CHUNK]),
                "compression": self.compression.encode(),
                "size": _u32(len(payload)),
            },
            data,
        )
        for conn_id, entries in sorted(self._chunk_index.items()):
            data = b"".join(
                _time_bytes(t) + _u32(off) for t, off in entries
            )
            self._write_record(
                {
                    "op": bytes([_OP_INDEX]),
                    "ver": _u32(1),
                    "conn": _u32(conn_id),
                    "count": _u32(len(entries)),
                },
                data,
            )
        self._chunk_infos.append(
            (
                chunk_pos,
                min(all_times) if all_times else 0.0,
                max(all_times) if all_times else 0.0,
                {cid: len(e) for cid, e in self._chunk_index.items()},
            )
        )
        self._chunk = bytearray()
        self._chunk_index = {}
        self._chunk_conns_written = set()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush_chunk()
        index_pos = self._f.tell()
        for conn in self._conns.values():
            self._write_record(
                {
                    "op": bytes([_OP_CONNECTION]),
                    "conn": _u32(conn.conn_id),
                    "topic": conn.topic.encode(),
                },
                self._connection_data(conn),
            )
        for chunk_pos, t0, t1, counts in self._chunk_infos:
            data = b"".join(
                _u32(cid) + _u32(cnt) for cid, cnt in sorted(counts.items())
            )
            self._write_record(
                {
                    "op": bytes([_OP_CHUNK_INFO]),
                    "ver": _u32(1),
                    "chunk_pos": _u64(chunk_pos),
                    "start_time": _time_bytes(t0),
                    "end_time": _time_bytes(t1),
                    "count": _u32(len(counts)),
                },
                data,
            )
        self._f.seek(len(MAGIC))
        self._write_bag_header(index_pos, len(self._conns), len(self._chunk_infos))
        self._f.close()

    def __enter__(self) -> "BagWriter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Sensor message <-> numpy helpers
# ---------------------------------------------------------------------------

_PF_DTYPE = {
    1: np.int8,
    2: np.uint8,
    3: np.int16,
    4: np.uint16,
    5: np.int32,
    6: np.uint32,
    7: np.float32,
    8: np.float64,
}


def pointcloud2_to_xyzi(msg: Any) -> np.ndarray:
    """(N, 4) float32 x/y/z/intensity — parity with the driver's
    ``point_cloud2.read_points(msg, ('x','y','z','intensity'))``
    (communicator/ros_inference3d.py:125). Missing intensity -> zeros."""
    n = int(msg.width) * int(msg.height)
    step = int(msg.point_step)
    buf = np.ascontiguousarray(msg.data, np.uint8)
    # Structured dtype with explicit offsets + itemsize handles arbitrary
    # point layouts (padding, extra fields, steps not divisible by 4 —
    # e.g. velodyne's 22-byte x/y/z/intensity/ring points).
    present = {
        f.name: (f.offset, np.dtype(_PF_DTYPE[int(f.datatype)]))
        for f in msg.fields
        if f.name in ("x", "y", "z", "intensity")
    }
    rec = np.frombuffer(
        buf,  # zero-copy view; the .astype below does the only copy
        dtype=np.dtype(
            {
                "names": list(present),
                "formats": [dt for _, dt in present.values()],
                "offsets": [off for off, _ in present.values()],
                "itemsize": step,
            }
        ),
        count=n,
    )
    cols = [
        rec[name].astype(np.float32)
        if name in present
        else np.zeros(n, np.float32)
        for name in ("x", "y", "z", "intensity")
    ]
    return np.stack(cols, axis=1)


def xyzi_to_pointcloud2(
    points: np.ndarray,
    frame_id: str = "lidar",
    stamp: float = 0.0,
    seq: int = 0,
) -> SimpleNamespace:
    """(N, 4) float32 -> dense PointCloud2 with x/y/z/intensity fields."""
    pts = np.ascontiguousarray(points, np.float32)
    n = pts.shape[0]
    fields = [
        make(
            "sensor_msgs/PointField",
            name=name,
            offset=4 * i,
            datatype=7,  # FLOAT32
            count=1,
        )
        for i, name in enumerate(("x", "y", "z", "intensity"))
    ]
    header = make(
        "std_msgs/Header", seq=seq, stamp=_split_time(stamp), frame_id=frame_id
    )
    return make(
        "sensor_msgs/PointCloud2",
        header=header,
        height=1,
        width=n,
        fields=fields,
        is_bigendian=0,
        point_step=16,
        row_step=16 * n,
        data=pts.reshape(-1).view(np.uint8),
        is_dense=1,
    )


def _split_time(t: float) -> tuple[int, int]:
    secs = int(t)
    return secs, int(round((t - secs) * 1e9))


def image_to_numpy(msg: Any) -> np.ndarray:
    """sensor_msgs/Image -> RGB uint8 (rgb8/bgr8/mono8/bgra8/rgba8)."""
    h, w = int(msg.height), int(msg.width)
    enc = msg.encoding.lower()
    data = np.asarray(msg.data, np.uint8)
    ch = {"mono8": 1, "rgb8": 3, "bgr8": 3, "rgba8": 4, "bgra8": 4}.get(enc)
    if ch is None:
        raise NotImplementedError(f"image encoding {msg.encoding!r}")
    step = int(msg.step) or w * ch
    img = data.reshape(h, step)[:, : w * ch].reshape(h, w, ch)
    if enc == "mono8":
        return np.repeat(img, 3, axis=2)
    if enc.startswith("bgr"):
        img = img[..., [2, 1, 0]]
    return np.ascontiguousarray(img[..., :3])


def compressed_image_to_numpy(msg: Any) -> np.ndarray:
    """sensor_msgs/CompressedImage -> RGB uint8 via cv2 (reference's
    cv2.imdecode path, ros_inference.py:119-131) or PIL fallback."""
    raw = np.asarray(msg.data, np.uint8)
    try:
        import cv2

        bgr = cv2.imdecode(raw, cv2.IMREAD_COLOR)
        if bgr is None:
            raise IOError("cv2.imdecode failed")
        return bgr[..., ::-1].copy()
    except ImportError:
        import io as _io

        from PIL import Image

        return np.asarray(Image.open(_io.BytesIO(raw.tobytes())).convert("RGB"))


def numpy_to_image(
    img: np.ndarray, frame_id: str = "camera", stamp: float = 0.0, seq: int = 0
) -> SimpleNamespace:
    """RGB uint8 (H, W, 3) -> sensor_msgs/Image rgb8."""
    img = np.ascontiguousarray(img, np.uint8)
    h, w = img.shape[:2]
    header = make(
        "std_msgs/Header", seq=seq, stamp=_split_time(stamp), frame_id=frame_id
    )
    return make(
        "sensor_msgs/Image",
        header=header,
        height=h,
        width=w,
        encoding="rgb8",
        is_bigendian=0,
        step=w * 3,
        data=img.reshape(-1),
    )


def numpy_to_compressed_image(
    img: np.ndarray, frame_id: str = "camera", stamp: float = 0.0, seq: int = 0
) -> SimpleNamespace:
    """RGB uint8 -> jpeg CompressedImage (cv2 required)."""
    import cv2

    ok, enc = cv2.imencode(".jpg", np.ascontiguousarray(img[..., ::-1]))
    if not ok:
        raise IOError("cv2.imencode failed")
    header = make(
        "std_msgs/Header", seq=seq, stamp=_split_time(stamp), frame_id=frame_id
    )
    return make(
        "sensor_msgs/CompressedImage",
        header=header,
        format="jpeg",
        data=np.asarray(enc, np.uint8).reshape(-1),
    )


def yaw_to_quaternion(yaw: float) -> SimpleNamespace:
    """Rotation about +z — the driver's yaw2quaternion
    (communicator/ros_inference3d.py:117-118)."""
    return make(
        "geometry_msgs/Quaternion",
        x=0.0,
        y=0.0,
        z=float(np.sin(yaw / 2.0)),
        w=float(np.cos(yaw / 2.0)),
    )


def boxes7_to_jsk_array(
    boxes7: np.ndarray,
    scores: np.ndarray,
    labels: np.ndarray,
    frame_id: str = "lidar",
    stamp: float = 0.0,
    seq: int = 0,
) -> SimpleNamespace:
    """(N, 7) [x,y,z,dx,dy,dz,yaw] -> jsk BoundingBoxArray, with the
    reference's dimension mapping (dimensions.x <- dy, dimensions.y <- dx
    swap per bag_inference3d.py:170-172 / ros_inference3d.py:177-186)."""
    header = make(
        "std_msgs/Header", seq=seq, stamp=_split_time(stamp), frame_id=frame_id
    )
    arr = make("jsk_recognition_msgs/BoundingBoxArray", header=header)
    for i in range(len(boxes7)):
        b = boxes7[i]
        box = make(
            "jsk_recognition_msgs/BoundingBox",
            header=header,
            pose=make(
                "geometry_msgs/Pose",
                position=make(
                    "geometry_msgs/Point",
                    x=float(b[0]),
                    y=float(b[1]),
                    z=float(b[2]),
                ),
                orientation=yaw_to_quaternion(float(b[6])),
            ),
            dimensions=make(
                "geometry_msgs/Vector3",
                x=float(b[4]),
                y=float(b[3]),
                z=float(b[5]),
            ),
            value=float(scores[i]),
            label=int(labels[i]),
        )
        arr.boxes.append(box)
    return arr
