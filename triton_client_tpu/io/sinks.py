"""Output sinks for the drivers.

The reference publishes annotated images to a ROS topic live
(communicator/ros_inference.py:158-175) and writes numbered PNGs in
replay mode (communicator/bag_inference2d.py:136, pattern
``./output_data/{:04d}.png``); 3D replay writes detections into an
output bag (bag_inference3d.py:182-183). Here sinks implement one
``write(frame, result)`` protocol; the ROS publisher lives behind the
same protocol in the gated ROS adapter.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Protocol

import numpy as np

from triton_client_tpu.io.draw import draw_boxes
from triton_client_tpu.io.sources import Frame


class Sink(Protocol):
    def write(self, frame: Frame, result: Mapping[str, Any]) -> None: ...

    def close(self) -> None: ...


class NullSink:
    """Discard results (benchmark mode)."""

    def write(self, frame: Frame, result: Mapping[str, Any]) -> None:
        pass

    def close(self) -> None:
        pass


class ImageFileSink:
    """Numbered annotated PNGs, parity with bag_inference2d.py:136."""

    def __init__(
        self, out_dir: str = "./output_data", class_names: tuple[str, ...] = ()
    ) -> None:
        self.out_dir = out_dir
        self.class_names = class_names
        os.makedirs(out_dir, exist_ok=True)

    def write(self, frame: Frame, result: Mapping[str, Any]) -> None:
        img = draw_boxes(
            frame.data,
            result["detections"],
            result.get("valid"),
            self.class_names,
        )
        path = os.path.join(self.out_dir, f"{frame.frame_id:04d}.png")
        try:
            import cv2

            cv2.imwrite(path, img[..., ::-1])
        except ImportError:  # pragma: no cover
            from PIL import Image

            Image.fromarray(img).save(path)

    def close(self) -> None:
        pass


class DetectionLogSink:
    """Detections as JSON lines — the machine-readable record (the
    replacement for the reference's output bag, bag_inference3d.py:63)."""

    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "w")

    def write(self, frame: Frame, result: Mapping[str, Any]) -> None:
        row: dict[str, Any] = {"frame_id": frame.frame_id, "ts": frame.timestamp}
        for key, val in result.items():
            if isinstance(val, np.ndarray):
                row[key] = val.tolist()
            elif isinstance(val, (int, float, str, list, bool)):
                row[key] = val
        self._f.write(json.dumps(row) + "\n")

    def close(self) -> None:
        self._f.close()
