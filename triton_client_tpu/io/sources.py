"""Frame sources: the pull-driven input seam of the drivers.

The reference has two input modes — live ROS topics
(communicator/ros_inference.py:91-96 subscriber push) and rosbag replay
(communicator/bag_inference2d.py:92 pull loop) — hard-wired into each
driver. Here the seam is one iterator protocol, so the same driver runs
a directory of images, a video file, recorded .npy point clouds, a
synthetic generator (benchmarks), or a live ROS adapter (drivers/ros.py,
import-gated) without knowing which.

cv2 is used when present (JPEG decode parity with the reference's
cv2.imdecode, ros_inference.py:119-131) and PIL is the fallback.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import time
from typing import Iterator, Protocol

import numpy as np

try:
    import cv2

    _HAVE_CV2 = True
except ImportError:  # pragma: no cover
    cv2 = None
    _HAVE_CV2 = False

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp")


@dataclasses.dataclass
class Frame:
    """One unit of input: an RGB image (H, W, 3) uint8 or a point cloud
    (N, >=4) float32, plus identity/timing for eval + sinks."""

    data: np.ndarray
    frame_id: int
    timestamp: float
    path: str = ""
    # carrier for source-specific context (e.g. the raw BagMessage so a
    # bag sink can copy the input message through unchanged, the way
    # bag_inference3d.py:182 re-writes the input cloud)
    meta: object = None


class FrameSource(Protocol):
    def __iter__(self) -> Iterator[Frame]: ...

    def __len__(self) -> int: ...


class ImageDirSource:
    """Sorted directory of images -> RGB frames (the reference's
    filesystem requestGenerator, utils/preprocess.py:185-263)."""

    def __init__(self, path: str, limit: int = 0) -> None:
        self.paths = sorted(
            p
            for p in glob.glob(os.path.join(path, "*"))
            if os.path.splitext(p)[1].lower() in IMAGE_EXTENSIONS
        )
        if limit:
            self.paths = self.paths[:limit]
        if not self.paths:
            raise FileNotFoundError(f"no images under {path}")

    def __len__(self) -> int:
        return len(self.paths)

    def __iter__(self) -> Iterator[Frame]:
        for i, p in enumerate(self.paths):
            yield Frame(_read_image_rgb(p), i, time.time(), p)


class VideoSource:
    """Video file -> RGB frames (the reference's local baseline input,
    yolo_onnx_test.py:154-198)."""

    def __init__(self, path: str, limit: int = 0) -> None:
        if not _HAVE_CV2:
            raise ImportError("VideoSource requires cv2")
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.limit = limit
        cap = cv2.VideoCapture(path)
        self._length = int(cap.get(cv2.CAP_PROP_FRAME_COUNT)) or 0
        cap.release()
        if limit:
            self._length = min(self._length, limit)

    def __len__(self) -> int:
        return self._length

    def __iter__(self) -> Iterator[Frame]:
        cap = cv2.VideoCapture(self.path)
        i = 0
        try:
            while True:
                if self.limit and i >= self.limit:
                    break
                ok, bgr = cap.read()
                if not ok:
                    break
                yield Frame(bgr[..., ::-1].copy(), i, time.time(), self.path)
                i += 1
        finally:
            cap.release()


class SyntheticImageSource:
    """Deterministic random frames — the benchmark input (no-IO mode)."""

    def __init__(self, n: int, hw: tuple[int, int] = (480, 640), seed: int = 0):
        self.n, self.hw, self.seed = n, hw, seed

    def __len__(self) -> int:
        return self.n

    def __iter__(self) -> Iterator[Frame]:
        rng = np.random.default_rng(self.seed)
        for i in range(self.n):
            img = rng.integers(0, 255, (*self.hw, 3), dtype=np.uint8)
            yield Frame(img, i, time.time())


class NpyPointCloudSource:
    """Directory of .npy point clouds (the reference extracts these from
    bags with tools/pc_extractor.py:17-45 for its 3D demo path)."""

    def __init__(self, path: str, limit: int = 0) -> None:
        self.paths = sorted(glob.glob(os.path.join(path, "*.npy")))
        if limit:
            self.paths = self.paths[:limit]
        if not self.paths:
            raise FileNotFoundError(f"no .npy point clouds under {path}")

    def __len__(self) -> int:
        return len(self.paths)

    def __iter__(self) -> Iterator[Frame]:
        for i, p in enumerate(self.paths):
            yield Frame(np.load(p).astype(np.float32), i, time.time(), p)


class SyntheticPointCloudSource:
    """Random KITTI-like point clouds for 3D benchmarks/tests."""

    def __init__(self, n: int, points: int = 20000, seed: int = 0) -> None:
        self.n, self.points, self.seed = n, points, seed

    def __len__(self) -> int:
        return self.n

    def __iter__(self) -> Iterator[Frame]:
        rng = np.random.default_rng(self.seed)
        for i in range(self.n):
            pc = np.stack(
                [
                    rng.uniform(0, 70, self.points),  # x forward
                    rng.uniform(-40, 40, self.points),  # y left
                    rng.uniform(-3, 1, self.points),  # z up
                    rng.uniform(0, 1, self.points),  # intensity
                ],
                axis=1,
            ).astype(np.float32)
            yield Frame(pc, i, time.time())


def open_source(spec: str, limit: int = 0, kind: str = "image") -> FrameSource:
    """CLI string -> source. ``synthetic[:N[:HxW]]``, a directory, or a
    video file (2D); ``synthetic`` or a .npy directory (3D)."""
    if spec.startswith("synthetic"):
        parts = spec.split(":")
        n = int(parts[1]) if len(parts) > 1 else (limit or 100)
        if kind == "pointcloud":
            return SyntheticPointCloudSource(n)
        hw = (480, 640)
        if len(parts) > 2:
            h, w = parts[2].split("x")
            hw = (int(h), int(w))
        return SyntheticImageSource(n, hw)
    if spec.endswith(".bag"):
        from triton_client_tpu.io.bag_io import BagImageSource, BagPointCloudSource

        if kind == "pointcloud":
            return BagPointCloudSource(spec, limit=limit)
        return BagImageSource(spec, limit=limit)
    if kind == "pointcloud":
        return NpyPointCloudSource(spec, limit)
    if os.path.isdir(spec):
        return ImageDirSource(spec, limit)
    return VideoSource(spec, limit)


def _read_image_rgb(path: str) -> np.ndarray:
    if _HAVE_CV2:
        bgr = cv2.imread(path, cv2.IMREAD_COLOR)
        if bgr is None:
            raise IOError(f"cannot decode {path}")
        return bgr[..., ::-1].copy()
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"))
