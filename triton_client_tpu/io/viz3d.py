"""Interactive 3D scene viewer (Open3D), reference parity for
clients/postprocess/visualize_open3d.py.

The reference renders point clouds + oriented boxes in an Open3D
window (draw_scenes, visualize_open3d.py:38-117; the Mayavi sibling
visualize_mayavi.py:142). This module is that capability over the
in-tree box geometry (io/draw3d.corners_3d), behind an optional
import — open3d is a visualization extra, never a core dependency
(the reference gates it the same way, clients/__init__.py:6-9).
Headless rendering (BEV / pinhole PNGs) lives in io/draw3d.py.
"""

from __future__ import annotations

import numpy as np

from triton_client_tpu.io.draw3d import corners_3d

# 12 box edges + the front-face cross the reference draws so heading
# is visible (visualize_open3d.py translate_boxes_to_open3d_instance)
_BOX_LINES = np.array(
    [
        [0, 1], [1, 2], [2, 3], [3, 0],  # bottom
        [4, 5], [5, 6], [6, 7], [7, 4],  # top
        [0, 4], [1, 5], [2, 6], [3, 7],  # verticals
        [0, 5], [1, 4],                  # front-face cross (heading)
    ],
    np.int64,
)

PRED_COLOR = (0.0, 1.0, 0.0)   # green, the reference's pred color
GT_COLOR = (0.0, 0.0, 1.0)     # blue, the reference's gt color


def _require_open3d():
    try:
        import open3d  # type: ignore

        return open3d
    except ImportError as e:
        raise ImportError(
            "interactive 3D display needs open3d (`pip install open3d`); "
            "headless rendering (io/draw3d.py BEV/pinhole PNGs) works "
            "without it"
        ) from e


def box_linesets(o3d, boxes7: np.ndarray, color) -> list:
    """(n, 7) boxes -> Open3D LineSets (12 edges + heading cross)."""
    out = []
    if len(boxes7) == 0:
        return out
    corners = corners_3d(np.asarray(boxes7, np.float64))  # (n, 8, 3)
    for c in corners:
        ls = o3d.geometry.LineSet()
        ls.points = o3d.utility.Vector3dVector(c)
        ls.lines = o3d.utility.Vector2iVector(_BOX_LINES)
        ls.colors = o3d.utility.Vector3dVector(
            np.tile(np.asarray(color, np.float64), (len(_BOX_LINES), 1))
        )
        out.append(ls)
    return out


def scene_geometries(
    points: np.ndarray,
    pred_boxes: np.ndarray | None = None,
    gt_boxes: np.ndarray | None = None,
):
    """Build the Open3D geometry list for one scene: gray cloud +
    origin frame + green predictions + blue ground truth."""
    o3d = _require_open3d()
    geoms = [
        o3d.geometry.TriangleMesh.create_coordinate_frame(size=1.0)
    ]
    pc = o3d.geometry.PointCloud()
    pc.points = o3d.utility.Vector3dVector(
        np.asarray(points, np.float64)[:, :3]
    )
    pc.paint_uniform_color((0.6, 0.6, 0.6))
    geoms.append(pc)
    if pred_boxes is not None:
        geoms.extend(box_linesets(o3d, pred_boxes, PRED_COLOR))
    if gt_boxes is not None:
        geoms.extend(box_linesets(o3d, gt_boxes, GT_COLOR))
    return geoms


def draw_detections_3d(
    points: np.ndarray,
    pred_boxes: np.ndarray | None = None,
    gt_boxes: np.ndarray | None = None,
    window_name: str = "tpu detections",
) -> None:
    """Blocking interactive render of one scene (the reference's
    draw_scenes call shape)."""
    o3d = _require_open3d()
    o3d.visualization.draw_geometries(
        scene_geometries(points, pred_boxes, gt_boxes),
        window_name=window_name,
    )


class ShowSink3D:
    """Driver sink that opens an interactive window per frame (close
    the window to advance the stream — the reference's per-scene
    blocking draw_scenes loop)."""

    def __init__(self, gt_lookup=None) -> None:
        _require_open3d()  # fail at construction, not mid-stream
        self._gt_lookup = gt_lookup

    def write(self, frame, result) -> None:
        gts = self._gt_lookup(frame) if self._gt_lookup is not None else None
        draw_detections_3d(
            np.asarray(frame.data),
            pred_boxes=np.asarray(result.get("pred_boxes", np.zeros((0, 7)))),
            gt_boxes=None if gts is None else np.asarray(gts)[:, :7],
            window_name=f"frame {frame.frame_id}",
        )

    def close(self) -> None:
        pass
