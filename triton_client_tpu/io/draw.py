"""Host-side box drawing (visualization parity).

The reference draws with cv2.rectangle/putText inline in the driver
(communicator/ros_inference.py:158-169) and in plot_boxes_cv2
(clients/postprocess/yolov5_postprocess.py:127-169), with a per-class
color hash. Same behavior here, as a pure function over the packed
(max_det, 6) detection rows; falls back to numpy rectangle strokes when
cv2 is absent so headless tests don't need OpenCV.
"""

from __future__ import annotations

import numpy as np

try:
    import cv2

    _HAVE_CV2 = True
except ImportError:  # pragma: no cover
    cv2 = None
    _HAVE_CV2 = False


def class_color(cls_id: int) -> tuple[int, int, int]:
    """Deterministic per-class RGB (the reference hashes class id into
    HSV offsets, yolov5_postprocess.py:131-141)."""
    rng = np.random.default_rng(cls_id + 12345)
    r, g, b = rng.integers(64, 256, 3)
    return int(r), int(g), int(b)


def draw_boxes(
    image: np.ndarray,
    detections: np.ndarray,
    valid: np.ndarray | None = None,
    class_names: tuple[str, ...] = (),
    thickness: int = 2,
) -> np.ndarray:
    """Return a copy of ``image`` (H, W, 3 uint8 RGB) with detection
    rows [x1, y1, x2, y2, conf, cls] drawn."""
    out = np.ascontiguousarray(image).copy()
    detections = np.asarray(detections).reshape(-1, 6)
    if valid is not None:
        detections = detections[np.asarray(valid, dtype=bool).reshape(-1)]
    h, w = out.shape[:2]
    for x1, y1, x2, y2, conf, cls in detections:
        c = int(cls)
        color = class_color(c)
        x1, y1 = max(0, int(x1)), max(0, int(y1))
        x2, y2 = min(w - 1, int(x2)), min(h - 1, int(y2))
        if x2 <= x1 or y2 <= y1:
            continue
        label = class_names[c] if c < len(class_names) else str(c)
        text = f"{label} {conf:.2f}"
        if _HAVE_CV2:
            cv2.rectangle(out, (x1, y1), (x2, y2), color, thickness)
            cv2.putText(
                out,
                text,
                (x1, max(0, y1 - 4)),
                cv2.FONT_HERSHEY_SIMPLEX,
                0.5,
                color,
                1,
                cv2.LINE_AA,
            )
        else:
            t = thickness
            out[y1 : y1 + t, x1:x2] = color
            out[max(0, y2 - t) : y2, x1:x2] = color
            out[y1:y2, x1 : x1 + t] = color
            out[y1:y2, max(0, x2 - t) : x2] = color
    return out
