"""Bag-backed frame sources and the output-bag detection sink.

These give the drivers the reference's bag replay mode without ROS:
``BagImageSource`` / ``BagPointCloudSource`` are the pull loops of
communicator/bag_inference2d.py:92 and bag_inference3d.py:116, and
``OutputBagSink`` reproduces bag_inference3d.py:182-183 — each input
cloud copied through plus a jsk BoundingBoxArray of the detections on
the publish topic, written to ``<bag>_output.bag``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Iterator, Mapping

import numpy as np

from triton_client_tpu.io import rosbag as rb
from triton_client_tpu.io.sources import Frame

_IMAGE_TYPES = ("sensor_msgs/CompressedImage", "sensor_msgs/Image")


def _msg_time(msg, record_t: float) -> float:
    """header.stamp (capture time) when set, else the bag record time —
    sweeps and pose interpolation align on capture time so per-topic
    transport latency doesn't skew the compensation."""
    try:
        secs, nsecs = msg.header.stamp
    except (AttributeError, TypeError, ValueError):
        return record_t
    stamp = float(secs) + float(nsecs) * 1e-9
    return stamp if stamp > 0 else record_t


def _pick_topic(path: str, wanted_types: tuple[str, ...]) -> str:
    with rb.BagReader(path) as r:
        topics = r.topics()
    matches = [t for t, dt in topics.items() if dt in wanted_types]
    if not matches:
        raise ValueError(
            f"{path}: no topic of type {wanted_types} (found {topics})"
        )
    return sorted(matches)[0]


class _BagSourceBase:
    def __init__(self, path: str, topic: str | None, limit: int) -> None:
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.topic = topic
        self.limit = limit
        self._length: int | None = None

    def _count(self, topic: str) -> int:
        n = 0
        with rb.BagReader(self.path) as r:
            for _ in r.read_messages(topics=[topic], raw=True):
                n += 1
        return n

    def __len__(self) -> int:
        if self._length is None:
            n = self._count(self.topic)
            self._length = min(n, self.limit) if self.limit else n
        return self._length


class BagImageSource(_BagSourceBase):
    """Image/CompressedImage topic -> RGB frames.

    ``topic=None`` auto-selects the first image-typed connection (the
    reference hardwires the topic in the YAML param file instead,
    data/client_parameter.yaml)."""

    def __init__(self, path: str, topic: str | None = None, limit: int = 0):
        super().__init__(path, topic, limit)
        if self.topic is None:
            self.topic = _pick_topic(path, _IMAGE_TYPES)

    def __iter__(self) -> Iterator[Frame]:
        with rb.BagReader(self.path) as r:
            for i, (_, bm, t) in enumerate(
                r.read_messages(topics=[self.topic], raw=True)
            ):
                if self.limit and i >= self.limit:
                    return
                msg = bm.msg
                if bm.connection.datatype == "sensor_msgs/CompressedImage":
                    img = rb.compressed_image_to_numpy(msg)
                else:
                    img = rb.image_to_numpy(msg)
                seq = int(msg.header.seq) if msg.header.seq else i
                yield Frame(img, seq, t, self.path, meta=bm)


class BagPointCloudSource(_BagSourceBase):
    """PointCloud2 topic -> (N, 4) float32 x/y/z/intensity frames.

    Raw sensor values — the reference's intensity normalization and
    z offset (ros_inference3d.py:126-128) belong to the pipeline's
    preprocess, not the source."""

    def __init__(self, path: str, topic: str | None = None, limit: int = 0):
        super().__init__(path, topic, limit)
        if self.topic is None:
            self.topic = _pick_topic(path, ("sensor_msgs/PointCloud2",))

    def __iter__(self) -> Iterator[Frame]:
        with rb.BagReader(self.path) as r:
            for i, (_, bm, t) in enumerate(
                r.read_messages(topics=[self.topic], raw=True)
            ):
                if self.limit and i >= self.limit:
                    return
                msg = bm.msg
                pts = rb.pointcloud2_to_xyzi(msg)
                seq = int(msg.header.seq) if msg.header.seq else i
                # prefer the sensor's own header.stamp over the bag
                # record time: sweep Δt and ego-pose interpolation must
                # use capture time, not transport/record latency
                yield Frame(pts, seq, _msg_time(msg, t), self.path, meta=bm)


def default_output_bag(in_bag: str) -> str:
    """'<basename>_output.bag' in the cwd (bag_inference3d.py:63)."""
    return f"{os.path.basename(in_bag)}_output.bag"


class OutputBagSink:
    """3D detections -> output bag: input cloud passthrough + jsk
    BoundingBoxArray per frame (bag_inference3d.py:156-183)."""

    def __init__(
        self,
        path: str,
        pub_topic: str = "/tpu_detections/boxes3d",
        input_topic: str | None = None,
        frame_id: str = "lidar",
        compression: str = "none",
    ) -> None:
        self.pub_topic = pub_topic
        self.input_topic = input_topic
        self.frame_id = frame_id
        self._w = rb.BagWriter(path, compression=compression)

    def write(self, frame: Frame, result: Mapping[str, Any]) -> None:
        t = frame.timestamp or time.time()
        stamp, frame_id = t, self.frame_id
        if isinstance(frame.meta, rb.BagMessage):
            bm = frame.meta
            topic = self.input_topic or bm.connection.topic
            self._w.write(topic, bm, t=t)
            stamp = t
            frame_id = bm.msg.header.frame_id or self.frame_id
        elif frame.data is not None and frame.data.ndim == 2:
            topic = self.input_topic or "/points"
            self._w.write(
                topic,
                rb.xyzi_to_pointcloud2(
                    frame.data, frame_id=frame_id, stamp=t, seq=frame.frame_id
                ),
                t=t,
            )
        boxes, scores, labels = _unpack_boxes(result)
        arr = rb.boxes7_to_jsk_array(
            boxes, scores, labels, frame_id=frame_id, stamp=stamp,
            seq=frame.frame_id,
        )
        self._w.write(self.pub_topic, arr, t=t)

    def close(self) -> None:
        self._w.close()


def _unpack_boxes(result: Mapping[str, Any]):
    """Accept either the 3D client dict contract (pred_boxes/pred_scores/
    pred_labels, clients/detector_3d_client.py:29-34) or the packed
    (dets (M, 9), valid) form the fused pipeline emits."""
    if "pred_boxes" in result:
        return (
            np.asarray(result["pred_boxes"], np.float32).reshape(-1, 7),
            np.asarray(result["pred_scores"], np.float32).reshape(-1),
            np.asarray(result["pred_labels"]).reshape(-1).astype(np.int64),
        )
    dets = np.asarray(result["detections"], np.float32)
    if dets.ndim == 3:  # batch of 1
        dets = dets[0]
    if dets.shape[-1] < 9:
        raise ValueError(
            "OutputBagSink needs 3D detections (M, 9) [x,y,z,dx,dy,dz,yaw,"
            f"score,label]; got shape {dets.shape} — 2D pipelines should "
            "use the images/jsonl sinks"
        )
    if "valid" in result:
        valid = np.asarray(result["valid"]).reshape(-1).astype(bool)
        dets = dets[: valid.size][valid[: dets.shape[0]]]
    return dets[:, :7], dets[:, 7], dets[:, 8].astype(np.int64)


# ---------------------------------------------------------------------------
# Ego-pose sources for multi-sweep aggregation (ops/sweeps.py)
# ---------------------------------------------------------------------------

def bag_pose_lookup(path: str, topic: str | None = None):
    """Odometry topic of a bag -> pose_lookup callback for
    ``sweep_source``: frame -> (4, 4) world_T_sensor interpolated at
    the frame's timestamp (linear translation + normalized-lerp
    rotation between the bracketing odometry samples; clamped at the
    ends). The reference compensates ego motion from dataset pose
    records (clients/preprocess/voxelize.py:13-24); a live/replay
    stream's equivalent pose source is its odometry topic."""
    from triton_client_tpu.ops.sweeps import pose_to_matrix

    if topic is None:
        topic = _pick_topic(path, ("nav_msgs/Odometry",))
    stamps: list[float] = []
    trans: list[list[float]] = []
    quats: list[list[float]] = []
    with rb.BagReader(path) as r:
        for _, bm, t in r.read_messages(topics=[topic], raw=True):
            p = bm.msg.pose.pose
            stamps.append(_msg_time(bm.msg, t))
            trans.append([p.position.x, p.position.y, p.position.z])
            quats.append(
                [p.orientation.x, p.orientation.y, p.orientation.z,
                 p.orientation.w]
            )
    if not stamps:
        raise ValueError(f"{path}: no messages on odometry topic {topic!r}")
    order = np.argsort(stamps)
    t_arr = np.asarray(stamps, np.float64)[order]
    tr_arr = np.asarray(trans, np.float64)[order]
    q_arr = np.asarray(quats, np.float64)[order]

    def lookup(frame) -> np.ndarray:
        t = float(frame.timestamp)
        i = int(np.searchsorted(t_arr, t))
        if i <= 0:
            return pose_to_matrix(tr_arr[0], q_arr[0])
        if i >= len(t_arr):
            return pose_to_matrix(tr_arr[-1], q_arr[-1])
        w = (t - t_arr[i - 1]) / max(t_arr[i] - t_arr[i - 1], 1e-12)
        tr = (1 - w) * tr_arr[i - 1] + w * tr_arr[i]
        qa, qb = q_arr[i - 1], q_arr[i]
        if np.dot(qa, qb) < 0:  # shorter arc
            qb = -qb
        q = (1 - w) * qa + w * qb
        return pose_to_matrix(tr, q)

    return lookup


def pose_lookup_from_jsonl(path: str):
    """Pose JSONL ({"frame_id": int, "pose": [x, y, z, qx, qy, qz,
    qw]}) -> pose_lookup callback keyed by frame_id — the file-based
    pose source for .npy replay streams."""
    import json

    from triton_client_tpu.ops.sweeps import pose_to_matrix

    table: dict[int, np.ndarray] = {}
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            v = row["pose"]
            if len(v) != 7:
                raise ValueError(
                    f"{path}: pose must be [x, y, z, qx, qy, qz, qw], "
                    f"got {len(v)} values"
                )
            table[int(row["frame_id"])] = pose_to_matrix(v[:3], v[3:])

    def lookup(frame):
        return table.get(frame.frame_id)

    return lookup
