"""Multi-host runtime: jax.distributed + host-aware mesh + data feeds.

The reference's only cross-machine transport is one blocking gRPC call
per frame (communicator/channel/grpc_channel.py:73-78, SURVEY.md §2.10:
"no NCCL/MPI/Gloo/UCX"). This module is the TPU-native distributed
backend that replaces that role at scale: processes join a
`jax.distributed` cluster (the coordination layer NCCL/MPI provide
elsewhere), computation is expressed once over a GLOBAL mesh spanning
every host's chips, and XLA inserts the collectives — riding ICI
within a slice and DCN between hosts.

Layout policy (the scaling-book recipe): the mesh's device array is
built host-major, and `MeshConfig.resolve` factors axes as
(data, model, seq, pipe) with `data` leading — so whenever
model*seq*pipe <= chips-per-host, those axes land INSIDE a host (ICI)
and only data-parallel gradient/batch traffic crosses DCN. A config
whose model axis would straddle hosts is accepted but warned, since
tensor-parallel collectives over DCN are the classic silent 10x.

Launch (one command per host — the reference's docker-compose role):

    COORDINATOR=<host0>:9876 NPROC=4 PROC_ID=<i> \
        python -m triton_client_tpu train --distributed env ...
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax
import numpy as np

from triton_client_tpu.parallel.mesh import (
    DATA_AXIS,
    MeshConfig,
    Mesh,
)

log = logging.getLogger(__name__)

_ENV_COORD = ("COORDINATOR", "JAX_COORDINATOR_ADDRESS")
_ENV_NPROC = ("NPROC", "JAX_NUM_PROCESSES")
_ENV_PROC = ("PROC_ID", "JAX_PROCESS_ID")


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Cluster coordinates. ``from_spec`` parses the CLI form:
    'env' (read COORDINATOR/NPROC/PROC_ID) or
    '<host:port>,<num_processes>,<process_id>'."""

    coordinator: str
    num_processes: int
    process_id: int

    @classmethod
    def from_spec(cls, spec: str) -> "DistributedConfig":
        if spec == "env":
            vals = []
            for names in (_ENV_COORD, _ENV_NPROC, _ENV_PROC):
                for name in names:
                    if name in os.environ:
                        vals.append(os.environ[name])
                        break
                else:
                    raise ValueError(
                        f"--distributed env: set one of {names} "
                        "(coordinator host:port, process count, process id)"
                    )
            coordinator, nproc, pid = vals
        else:
            parts = spec.split(",")
            if len(parts) != 3:
                raise ValueError(
                    "--distributed takes 'env' or "
                    "'<host:port>,<num_processes>,<process_id>', got "
                    f"{spec!r}"
                )
            coordinator, nproc, pid = parts
        cfg = cls(coordinator, int(nproc), int(pid))
        if not (0 <= cfg.process_id < cfg.num_processes):
            raise ValueError(
                f"process_id {cfg.process_id} outside "
                f"[0, {cfg.num_processes})"
            )
        return cfg


_initialized = False


def _client_already_up() -> bool:
    """Whether some caller already ran jax.distributed.initialize.
    Deliberately avoids jax.process_count()/jax.devices() here: those
    lazily initialize the XLA backend, and initialize() REFUSES to run
    after backend init — probing with them would break every real
    multi-host launch."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client is not None
    except Exception:  # private API moved: assume not initialized
        return False


def init_distributed(config: DistributedConfig) -> None:
    """Join the cluster (idempotent). After this, jax.devices() is the
    GLOBAL device list across every process and pjit/collectives span
    hosts — the single runtime switch between one machine and a pod.

    Must run before anything touches the XLA backend (jax.devices(),
    any jit call): jax.distributed.initialize raises otherwise."""
    global _initialized
    if config.num_processes <= 1:
        return
    if _initialized or _client_already_up():
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=config.coordinator,
        num_processes=config.num_processes,
        process_id=config.process_id,
    )
    _initialized = True
    log.info(
        "joined cluster: process %d/%d, %d local / %d global devices",
        config.process_id, config.num_processes,
        jax.local_device_count(), jax.device_count(),
    )


def is_coordinator() -> bool:
    """True on the process that should do singleton work (checkpoint
    writes, metric export, repository scans that print)."""
    return jax.process_index() == 0


def host_major_devices(devices=None) -> list:
    """Global devices ordered host-major (all of process 0's chips,
    then process 1's, ...). Feeding this to make_mesh puts trailing
    mesh axes (model/seq/pipe) on intra-host ICI whenever they fit."""
    devices = list(devices if devices is not None else jax.devices())
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def global_mesh(config: MeshConfig | None = None) -> Mesh:
    """Host-aware mesh over ALL processes' devices (host-major, data
    axis leading => data parallelism crosses DCN, everything else stays
    on ICI when it fits in one host). Warns when a non-data axis
    straddles hosts."""
    from triton_client_tpu.parallel.mesh import make_mesh

    devices = host_major_devices()
    multi_host = jax.process_count() > 1
    if multi_host and config is not None and config.data > 0:
        want = (
            config.data
            * max(1, config.model) * max(1, config.seq) * max(1, config.pipe)
        )
        if want != len(devices):
            # make_mesh's single-host convenience (claim a device
            # prefix) would silently drop whole HOSTS here, stranding
            # their processes outside the mesh (hangs/errors at the
            # first collective) — refuse instead.
            raise ValueError(
                f"multi-host mesh must use all {len(devices)} global "
                f"devices; config {config} names {want} — drop data= to "
                "auto-fill, or resize the cluster"
            )
    mesh = make_mesh(config, devices)
    per_host = max(
        1,
        len([d for d in devices if d.process_index == devices[0].process_index]),
    )
    trailing = int(np.prod([mesh.shape[a] for a in mesh.axis_names if a != DATA_AXIS]))
    if multi_host and per_host % trailing != 0:
        # covers both trailing > per_host and non-dividing trailing —
        # either way some model/seq/pipe group straddles a host boundary
        log.warning(
            "mesh axes %s (trailing %d) do not pack into the %d devices "
            "per host: tensor/seq/pipe collectives will cross DCN "
            "(slow); keep model*seq*pipe a divisor of %d and scale data "
            "across hosts",
            dict(mesh.shape), trailing, per_host, per_host,
        )
    return mesh


def shard_host_batch(global_batch, mesh: Mesh, spec=None):
    """Per-host input feed: each process holds ITS slice of the global
    batch (the reference streams every frame through one client
    process; here every host reads its own cameras/bags) and the pieces
    assemble into one global jax.Array without any host gathering.

    ``global_batch``: this process's local shard, a numpy array whose
    leading dim is global_batch_size / process_count.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, spec or PartitionSpec(DATA_AXIS))
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(global_batch)
    )
