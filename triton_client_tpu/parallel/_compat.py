"""jax API compatibility shims for the parallel kernels.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace (and renamed ``check_rep`` to
``check_vma``) across jax releases; the pinned toolchain may sit on
either side. The kernels import the modern spelling from here so both
jax generations collect and run.
"""

from __future__ import annotations

try:  # modern jax: top-level export with check_vma
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental API with check_rep

    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
