"""Ragged (packed) batch execution: segment tables + TPU segment kernels.

The padding tax the dense batcher pays is worst exactly where batching
helps most: variable-size inputs. A 3D scan's point count swings 2-10x
between frames (the reference's MAX_NUMBER_OF_VOXELS ceiling exists
because of it), so padding every member of a merged batch to the
widest member — or the whole merge to a power-of-two bucket — ships
mostly dead rows. *Ragged Paged Attention* (PAPERS.md) shows the TPU
answer: concatenate the real rows back to back and carry a row-offset /
segment-id table alongside, so one launched program processes every
request at its true size.

This module is that mechanism for the serving stack:

  * :class:`RaggedLayout` — the row-offset/segment-id table that rides
    with a packed batch (built once on the host by the scheduler,
    shipped to the device as one int32 vector);
  * :func:`pack_rows` — concatenate per-request row blocks into one
    packed array, padded to a bucketed row count so the compiled-shape
    set stays log-bounded (pad rows belong to a dead segment and are
    dropped by construction);
  * :func:`segment_reduce` — the segment-aware reduction every ragged
    model body leans on: a Pallas TPU kernel (one-hot x values matmul,
    the MXU-friendly formulation) with an XLA ``segment_sum`` fallback
    for hosts without the Pallas toolchain;
  * :func:`partition_segments` / :func:`shard_pack` — contiguous,
    row-balanced partition of a packed batch over a mesh data axis, so
    the sharded channel splits ragged work without a segment ever
    straddling two devices (no cross-device collectives in the body).

Bitwise/accuracy contract: packing never changes a row's values, and a
segment's rows stay contiguous and in request order — a ragged model
body that reduces per segment sees exactly the arrays a solo request
would (modulo the reduction's own reassociation, which `segment_reduce`
keeps in row order).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from triton_client_tpu.runtime.padding import bucket

_LANES = 128
_SUBLANES = 8


def _round_up(n: int, m: int) -> int:
    return ((max(1, n) + m - 1) // m) * m


def ragged_row_bucket(n: int) -> int:
    """Padded row count for a packed batch: 8 steps per power-of-two
    octave, sublane-aligned. The classic pow2 table wastes up to 50% on
    the big row counts ragged batching exists for (a 5000-point merge
    would pad to 8192); this table bounds the pad at 12.5% while the
    compiled-shape set stays log-bounded (<= 8 shapes per octave — jit
    retraces per packed shape, so the table IS the executable budget).
    Lane alignment is NOT needed here: the segment kernels pad to tile
    boundaries internally."""
    n = max(1, n)
    step = max(_SUBLANES, bucket(n) // 8)
    return _round_up(n, step)


def kernel_block_rows(n: int, block: int) -> int:
    """Padded row count for a fused Pallas kernel launch over a packed
    cloud/batch: the learned ragged bucket, rounded up to the kernel's
    point-block multiple.

    ``block`` must be a power of two >= ``_SUBLANES`` — that guarantee
    is what keeps the two tables compatible: ``ragged_row_bucket``'s
    step is ``max(8, bucket(n) // 8)``, itself a power of two, so for
    every bucket >= ``8 * block`` the step is already a ``block``
    multiple and the tables coincide exactly (asserted by
    :func:`assert_block_divides_buckets`); below that the round-up
    costs at most ``block - 1`` extra rows while the compiled-shape set
    stays a subset of the bucket table's."""
    if block < _SUBLANES or block & (block - 1):
        raise ValueError(
            f"kernel block must be a power of two >= {_SUBLANES}, got {block}"
        )
    return _round_up(ragged_row_bucket(n), block)


def assert_block_divides_buckets(block: int, max_rows: int = 1 << 22) -> None:
    """Assert the fused-kernel block size divides every learned bucket
    in its regime (bucket >= 8 * block) — the invariant that lets a
    channel reuse one packed array for BOTH the segment kernels (bucket
    shapes) and a fused kernel launch (block-multiple shapes) without a
    re-pad in between. Raises AssertionError naming the first violator."""
    if block < _SUBLANES or block & (block - 1):
        raise ValueError(
            f"kernel block must be a power of two >= {_SUBLANES}, got {block}"
        )
    floor = 8 * block
    n = floor
    while n <= max_rows:
        b = ragged_row_bucket(n)
        if b >= floor:
            assert b % block == 0, (
                f"ragged_row_bucket({n}) = {b} is not a multiple of the "
                f"fused kernel block {block}"
            )
        n += max(1, b // 16)  # sample densely enough to hit every step


@dataclasses.dataclass(frozen=True)
class RaggedLayout:
    """Row-offset/segment-id table for one packed ragged batch.

    ``sizes[i]`` is request *i*'s row count; ``offsets`` is the
    exclusive prefix sum (length ``n_segments + 1``); ``padded_rows``
    is the bucketed row count every packed array is padded to (pad rows
    carry segment id ``n_segments`` — one past the last real segment,
    so every reduction drops them); ``seg_bucket`` is the bucketed
    segment count the launched program is traced for — the ONLY part of
    the layout that keys the launcher cache, so the executable set is
    log-bounded in both rows (jit's own shape cache over ``padded_rows``
    buckets) and segments (our cache over ``seg_bucket``)."""

    sizes: tuple[int, ...]

    @property
    def n_segments(self) -> int:
        return len(self.sizes)

    @property
    def total(self) -> int:
        return int(sum(self.sizes))

    @functools.cached_property
    def offsets(self) -> np.ndarray:
        return np.concatenate(
            [[0], np.cumsum(np.asarray(self.sizes, np.int64))]
        ).astype(np.int32)

    @property
    def padded_rows(self) -> int:
        return ragged_row_bucket(self.total)

    @property
    def seg_bucket(self) -> int:
        """Static segment capacity the launched program is traced for."""
        return bucket(self.n_segments)

    @property
    def launch_segments(self) -> int:
        """The static ``num_segments`` the channel's ragged launcher is
        built (and cache-keyed) at — the uniform name both layout kinds
        expose to ``StagedChannel.launch``."""
        return self.seg_bucket

    @functools.cached_property
    def segment_ids(self) -> np.ndarray:
        """(padded_rows,) int32 — pad rows get id ``n_segments`` (out
        of range for a ``num_segments``-sized reduce, so they vanish)."""
        ids = np.full(self.padded_rows, self.n_segments, np.int32)
        ids[: self.total] = np.repeat(
            np.arange(self.n_segments, dtype=np.int32),
            np.asarray(self.sizes, np.int64),
        )
        return ids

    @property
    def pad_rows(self) -> int:
        return self.padded_rows - self.total


def pack_rows(parts: list[np.ndarray], layout: RaggedLayout) -> np.ndarray:
    """Concatenate per-request row blocks into one packed array padded
    to ``layout.padded_rows``. Pad rows replicate the last real row
    (never zeros: a copied row cannot steer a model down a numerically
    different path — the same rule as ``runtime/padding.pad_rows``) and
    belong to the dead segment, so their outputs are never read."""
    if [int(p.shape[0]) for p in parts] != list(layout.sizes):
        raise ValueError(
            f"pack_rows: part sizes {[p.shape[0] for p in parts]} != "
            f"layout sizes {list(layout.sizes)}"
        )
    packed = np.concatenate([np.asarray(p) for p in parts])
    pad = layout.padded_rows - packed.shape[0]
    if pad > 0:
        fill = (
            np.repeat(packed[-1:], pad, axis=0)
            if packed.shape[0]
            else np.zeros((pad, *packed.shape[1:]), packed.dtype)
        )
        packed = np.concatenate([packed, fill])
    return packed


# -- segment-aware reduction (the ragged model-body primitive) -----------------


def _segment_sum_kernel(values_ref, ids_ref, out_ref, *, num_segments):
    """One-hot x values matmul: ``out[s, f] = sum_r [ids[r]==s] * v[r, f]``.

    The MXU formulation of segment-sum — the gather/scatter-free shape
    *Ragged Paged Attention* uses for its row bookkeeping: build the
    (S, R) one-hot selector from a 2D iota compare (TPU has no 1D
    iota), then one ``jnp.dot`` keeps the whole reduction on the
    systolic array. Pad rows carry an out-of-range id, so their one-hot
    row is all zeros and they contribute nothing.
    """
    import jax
    import jax.numpy as jnp

    r = ids_ref.shape[1]
    seg = jax.lax.broadcasted_iota(jnp.int32, (num_segments, r), 0)
    onehot = (seg == ids_ref[0:1, :]).astype(jnp.float32)
    out_ref[:] = jnp.dot(
        onehot, values_ref[:], preferred_element_type=jnp.float32
    )


def segment_sum_pallas(values, segment_ids, num_segments: int, interpret: bool = False):
    """Pallas TPU segment-sum: ``values`` (R, F) f32, ``segment_ids``
    (R,) int32 -> (num_segments, F) f32. Out-of-range ids (the packing
    pad convention) are dropped. ``interpret=True`` runs the same
    kernel on CPU for tests."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, f = values.shape
    r_pad = _round_up(r, _LANES)
    f_pad = _round_up(f, _LANES)
    s_pad = _round_up(num_segments, _SUBLANES)

    v = jnp.zeros((r_pad, f_pad), jnp.float32)
    v = v.at[:r, :f].set(values.astype(jnp.float32))
    ids = jnp.full((1, r_pad), num_segments, jnp.int32)
    ids = ids.at[0, :r].set(segment_ids.astype(jnp.int32))

    out = pl.pallas_call(
        functools.partial(_segment_sum_kernel, num_segments=s_pad),
        out_shape=jax.ShapeDtypeStruct((s_pad, f_pad), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v, ids)
    return out[:num_segments, :f]


def segment_reduce(values, segment_ids, num_segments: int, op: str = "sum"):
    """Segment-aware reduction routed to the best backend: the Pallas
    kernel on TPU (sum/mean — the MXU shapes), XLA's ``segment_*`` ops
    elsewhere and for max/min. ``values`` (R, F) or (R,); out has
    leading dim ``num_segments``. The one primitive every in-tree
    ragged model body is written against, so the backend choice lives
    in exactly one place."""
    import jax
    import jax.numpy as jnp

    squeeze = values.ndim == 1
    v = values[:, None] if squeeze else values
    if op in ("sum", "mean") and _use_pallas(v):
        out = segment_sum_pallas(v, segment_ids, num_segments)
        if op == "mean":
            ones = jnp.ones((v.shape[0], 1), jnp.float32)
            counts = segment_sum_pallas(ones, segment_ids, num_segments)
            out = out / jnp.maximum(counts, 1.0)
    else:
        seg = jax.ops.segment_sum if op in ("sum", "mean") else (
            jax.ops.segment_max if op == "max" else jax.ops.segment_min
        )
        out = seg(v, segment_ids, num_segments=num_segments)
        if op == "mean":
            counts = jax.ops.segment_sum(
                jnp.ones((v.shape[0],), jnp.float32),
                segment_ids,
                num_segments=num_segments,
            )
            out = out / jnp.maximum(counts[:, None], 1.0)
        if op in ("max", "min"):
            # XLA fills empty segments with the dtype identity
            # (-inf/+inf for floats); zero them so dead pad segments
            # can't leak infinities into a downstream stack
            counts = jax.ops.segment_sum(
                jnp.ones((v.shape[0],), jnp.int32),
                segment_ids,
                num_segments=num_segments,
            )
            out = jnp.where(counts[:, None] > 0, out, 0.0)
    return out[:, 0] if squeeze else out


def _use_pallas(values) -> bool:
    """Pallas only on a real TPU backend with a VMEM-fitting working
    set; everywhere else the XLA segment ops are faster than interpret
    mode and numerically identical in row order."""
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    return segment_reduce_vmem_fits(values.shape[0], values.shape[1])


def segment_reduce_vmem_fits(
    rows: int, features: int, budget_bytes: int = 12 << 20
) -> bool:
    """Whether the one-hot matmul's VMEM working set fits comfortably
    (values + one-hot + out, f32)."""
    r = _round_up(rows, _LANES)
    f = _round_up(features, _LANES)
    s = _SUBLANES  # lower bound; the one-hot dominates via r anyway
    return (r * f + s * r + s * f) * 4 < budget_bytes


# -- data-axis sharding of a packed batch --------------------------------------


def partition_segments(sizes, n_shards: int) -> list[list[int]]:
    """Contiguous, row-balanced partition of segments over ``n_shards``.

    Greedy walk: each shard takes segments until it reaches the ideal
    rows-per-shard for the REMAINING work (re-computed per shard so one
    huge leading segment can't starve the tail). Contiguity is the
    point — a segment never straddles two shards, so the sharded body
    needs no cross-device collectives and per-request outputs reassemble
    by concatenation. Returns ``n_shards`` lists of segment indices
    (possibly empty on a narrow batch)."""
    sizes = [int(s) for s in sizes]
    groups: list[list[int]] = [[] for _ in range(max(1, int(n_shards)))]
    i = 0
    for w in range(len(groups)):
        left = len(groups) - w
        remaining_rows = sum(sizes[i:])
        target = remaining_rows / left if left else 0
        rows = 0
        # every shard after this one must still be able to take at
        # least one segment
        max_take = len(sizes) - i - (left - 1)
        while i < len(sizes) and (not groups[w] or len(groups[w]) < max_take):
            if groups[w] and rows + sizes[i] > target and rows > 0:
                break
            groups[w].append(i)
            rows += sizes[i]
            i += 1
    return groups


@dataclasses.dataclass(frozen=True)
class ShardedRaggedLayout:
    """Per-shard layout for one packed batch split over the data axis.

    Each shard holds ``rows_pad`` rows and ``seg_pad`` segment slots
    (both maxima over shards, bucketed) so every shard runs the SAME
    program shape; ``groups`` maps shard-local segments back to request
    order for reassembly."""

    base: RaggedLayout
    n_shards: int
    groups: tuple[tuple[int, ...], ...]
    rows_pad: int
    seg_pad: int

    @property
    def counts(self) -> tuple[int, ...]:
        """Real segments per shard."""
        return tuple(len(g) for g in self.groups)

    @property
    def launch_segments(self) -> int:
        """Per-SHARD static segment capacity (see
        :attr:`RaggedLayout.launch_segments`)."""
        return self.seg_pad

    @property
    def n_segments(self) -> int:
        return self.base.n_segments


def shard_layout(layout: RaggedLayout, n_shards: int) -> ShardedRaggedLayout:
    groups = partition_segments(layout.sizes, n_shards)
    rows = [sum(layout.sizes[i] for i in g) for g in groups]
    segs = [len(g) for g in groups]
    return ShardedRaggedLayout(
        base=layout,
        n_shards=max(1, int(n_shards)),
        groups=tuple(tuple(g) for g in groups),
        rows_pad=ragged_row_bucket(max(rows + [1])),
        seg_pad=bucket(max(segs + [1])),
    )


def shard_pack_rows(
    parts: list[np.ndarray], sl: ShardedRaggedLayout
) -> np.ndarray:
    """Pack per-request row blocks as ``(n_shards * rows_pad, ...)`` —
    shard-major, so a batch sharding over the leading dim gives each
    device its contiguous segment group. Pad rows replicate the shard's
    last real row (or zero-fill an empty shard) under dead segment
    ids."""
    sizes = sl.base.sizes
    if [int(p.shape[0]) for p in parts] != list(sizes):
        raise ValueError("shard_pack_rows: parts do not match layout sizes")
    trailing = parts[0].shape[1:]
    dtype = parts[0].dtype
    out = np.zeros((sl.n_shards, sl.rows_pad, *trailing), dtype)
    for w, g in enumerate(sl.groups):
        o = 0
        for i in g:
            p = np.asarray(parts[i])
            out[w, o : o + p.shape[0]] = p
            o += p.shape[0]
        if o and o < sl.rows_pad:
            out[w, o:] = out[w, o - 1]
    return out.reshape(sl.n_shards * sl.rows_pad, *trailing)


def shard_segment_ids(sl: ShardedRaggedLayout) -> np.ndarray:
    """Shard-LOCAL segment ids, ``(n_shards * rows_pad,)`` int32 —
    each shard's ids live in ``[0, seg_pad)`` with pad rows at the dead
    id ``seg_pad`` (out of range for the per-shard reduce)."""
    ids = np.full((sl.n_shards, sl.rows_pad), sl.seg_pad, np.int32)
    for w, g in enumerate(sl.groups):
        o = 0
        for local, i in enumerate(g):
            n = sl.base.sizes[i]
            ids[w, o : o + n] = local
            o += n
    return ids.reshape(-1)


def shard_stack_segments(
    parts: list[np.ndarray], sl: ShardedRaggedLayout
) -> np.ndarray:
    """Stack per-request (non-ragged) arrays as
    ``(n_shards * seg_pad, ...)`` shard-major, matching the output
    layout of a sharded ragged launch. Dead slots replicate the shard's
    last real entry."""
    trailing = np.asarray(parts[0]).shape
    out = np.zeros((sl.n_shards, sl.seg_pad, *trailing), np.asarray(parts[0]).dtype)
    for w, g in enumerate(sl.groups):
        for local, i in enumerate(g):
            out[w, local] = np.asarray(parts[i])
        if g and len(g) < sl.seg_pad:
            out[w, len(g):] = out[w, len(g) - 1]
    return out.reshape(sl.n_shards * sl.seg_pad, *trailing)


def unshard_segments(arr, sl: ShardedRaggedLayout):
    """Gather the real per-request rows back out of a
    ``(n_shards * seg_pad, ...)`` sharded ragged output, in request
    order. Lazy slices per shard — on device arrays the host copy pays
    only for real segments."""
    out = []
    for w, g in enumerate(sl.groups):
        if g:
            base = w * sl.seg_pad
            out.append(arr[base : base + len(g)])
    if not out:
        return arr[:0]
    return np.concatenate([np.asarray(a) for a in out])
