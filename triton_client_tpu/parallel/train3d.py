"""Sharded training step for the anchor-based 3D detectors.

The reference serves OpenPCDet-trained .pth weights
(examples/pointpillar_kitti/1/model.py:91-117) — training happens
outside its tree. This module closes the loop TPU-natively for the
pillar family, mirroring OpenPCDet's AxisAlignedTargetAssigner +
anchor-head loss semantics but written as fixed-shape JAX:

  * assignment: per-anchor best class-matched GT by NEAREST-BEV IoU
    (yaw rounded to the closer axis — the assigner's axis-aligned
    approximation), computed as a lax.scan over the padded GT rows so
    the (321k anchors x T GTs) IoU never materializes;
  * per-GT force match (every valid GT claims its best anchor);
  * losses: sigmoid focal class loss (alpha 0.25 / gamma 2), smooth-L1
    on encoded residuals with the sin(a-b) yaw decomposition, and the
    direction-bin cross-entropy — weights 1.0 / 2.0 / 0.2, normalized
    by the positive count (OpenPCDet's pointpillar.yaml LOSS_CONFIG).

Targets ride as (B, T, 8) rows [cx, cy, cz, dx, dy, dz, yaw, cls],
padded with cls = -1 — static shapes end to end.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_client_tpu.models.pointpillars import (
    PointPillars,
    encode_boxes,
    generate_anchors,
)
from triton_client_tpu.parallel.mesh import DATA_AXIS
from triton_client_tpu.parallel.train import TrainState, shard_variables


@dataclasses.dataclass(frozen=True)
class Loss3DConfig:
    cls_w: float = 1.0
    loc_w: float = 2.0
    dir_w: float = 0.2
    # IoU-quality head weight (SECOND-IoU): regression of the decoded
    # box's IoU with its matched GT, encoded 2*iou - 1 (the score
    # calibration signal decode rectifies with). 0 disables — models
    # without an 'iou' head (PointPillars) ignore it.
    iou_w: float = 1.0
    focal_alpha: float = 0.25
    focal_gamma: float = 2.0
    smooth_l1_beta: float = 1.0 / 9.0
    dir_offset: float = 0.78539
    num_dir_bins: int = 2


@dataclasses.dataclass(frozen=True)
class Augment3DConfig:
    """Global scene augmentation, the det3d/OpenPCDet train-time
    recipe (GlobalRotScaleTrans + RandomFlip in every shipped config,
    e.g. nusc_centerpoint_pp_02voxel_two_pfn_10sweep.py): per sample,
    one rotation about the z axis, an optional y-mirror, and an
    isotropic scale, applied identically to points, boxes, and
    ground-plane velocities. This is what makes single-cell yaw/
    velocity regression GENERALIZE — without it a center head binds
    heading to absolute scene context and memorizes the train split
    (round-5 closed-loop finding: train rot err 0.22 rad vs holdout
    1.0 rad)."""

    rot_max: float = 0.7854       # U(-pi/4, pi/4), OpenPCDet KITTI
    scale_min: float = 0.95
    scale_max: float = 1.05
    flip_y: bool = True           # mirror across y=0 with p=0.5
    seed: int = 17


def augment_scene_batch(
    key: jax.Array,
    points: jnp.ndarray,   # (B, P, F>=3) padded clouds
    targets: jnp.ndarray,  # (B, T, 8|10) [box7, cls(, vx, vy)]
    cfg: Augment3DConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Jittable global rot/flip/scale over a padded batch. Padded
    point rows (zeros) stay zeros under rotation+scale; padded target
    rows keep cls == -1 untouched. Boxes pushed out of the grid by the
    transform are dropped by the target assigners' in-range masks,
    matching the reference pipelines' post-augment filtering."""
    b = points.shape[0]
    k_rot, k_scale, k_flip = jax.random.split(key, 3)
    theta = jax.random.uniform(
        k_rot, (b,), minval=-cfg.rot_max, maxval=cfg.rot_max
    )
    scale = jax.random.uniform(
        k_scale, (b,), minval=cfg.scale_min, maxval=cfg.scale_max
    )
    flip = jax.random.bernoulli(k_flip, 0.5 if cfg.flip_y else 0.0, (b,))
    sign = jnp.where(flip, -1.0, 1.0)[:, None]  # y-mirror per sample
    c = jnp.cos(theta)[:, None]
    s = jnp.sin(theta)[:, None]

    def rot_xy(x, y):
        y = y * sign
        return c * x - s * y, s * x + c * y

    px, py = rot_xy(points[..., 0], points[..., 1])
    sc = scale[:, None]
    points = points.at[..., 0].set(px * sc)
    points = points.at[..., 1].set(py * sc)
    points = points.at[..., 2].set(points[..., 2] * sc)

    cx, cy = rot_xy(targets[..., 0], targets[..., 1])
    # mirror then rotate: yaw -> -yaw under the y-flip, then + theta
    yaw = targets[..., 6] * sign + theta[:, None]
    out = targets
    out = out.at[..., 0].set(cx * sc)
    out = out.at[..., 1].set(cy * sc)
    out = out.at[..., 2].set(targets[..., 2] * sc)
    out = out.at[..., 3:6].set(targets[..., 3:6] * sc[..., None])
    out = out.at[..., 6].set(yaw)
    if targets.shape[-1] >= 10:
        vx, vy = rot_xy(targets[..., 8], targets[..., 9])
        out = out.at[..., 8].set(vx * sc)
        out = out.at[..., 9].set(vy * sc)
    return points, out


def nearest_bev_halfdims(dims_xy: jnp.ndarray, yaw: jnp.ndarray) -> jnp.ndarray:
    """(..., 2) BEV half-extents with yaw rounded to the nearest axis
    (OpenPCDet boxes3d_nearest_bev_iou): within pi/4 of the x axis the
    footprint is (dx, dy), else swapped."""
    quarter = jnp.abs(
        yaw - jnp.floor(yaw / jnp.pi + 0.5) * jnp.pi
    )  # distance to nearest multiple of pi
    swap = quarter > (jnp.pi / 4)
    dx, dy = dims_xy[..., 0], dims_xy[..., 1]
    hx = jnp.where(swap, dy, dx) / 2
    hy = jnp.where(swap, dx, dy) / 2
    return jnp.stack([hx, hy], axis=-1)


def nearest_bev_iou_rowwise(
    a: jnp.ndarray,  # (..., 7)
    b: jnp.ndarray,  # (..., 7)
) -> jnp.ndarray:
    """Elementwise nearest-axis BEV IoU between matched box rows (the
    IoU-quality head's regression target)."""
    ah = nearest_bev_halfdims(a[..., 3:5], a[..., 6])
    bh = nearest_bev_halfdims(b[..., 3:5], b[..., 6])
    lo = jnp.maximum(a[..., :2] - ah, b[..., :2] - bh)
    hi = jnp.minimum(a[..., :2] + ah, b[..., :2] + bh)
    wh = jnp.clip(hi - lo, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = 4 * ah[..., 0] * ah[..., 1]
    area_b = 4 * bh[..., 0] * bh[..., 1]
    return inter / jnp.maximum(area_a + area_b - inter, 1e-9)


def nearest_bev_iou_vs_gt(
    anchors: jnp.ndarray,  # (N, 7) — rot is 0 or pi/2 (axis-aligned)
    gt_box: jnp.ndarray,   # (7,)
) -> jnp.ndarray:
    """(N,) axis-aligned BEV IoU of every anchor against one GT with
    the GT's yaw rounded to the nearest axis."""
    ah = nearest_bev_halfdims(anchors[:, 3:5], anchors[:, 6])  # (N, 2)
    gh = nearest_bev_halfdims(gt_box[3:5], gt_box[6])  # (2,)
    lo = jnp.maximum(anchors[:, :2] - ah, gt_box[:2] - gh)
    hi = jnp.minimum(anchors[:, :2] + ah, gt_box[:2] + gh)
    wh = jnp.clip(hi - lo, 0.0)
    inter = wh[:, 0] * wh[:, 1]
    area_a = 4 * ah[:, 0] * ah[:, 1]
    area_g = 4 * gh[0] * gh[1]
    return inter / jnp.maximum(area_a + area_g - inter, 1e-9)


def assign_targets(
    anchors: jnp.ndarray,      # (N, 7) flat anchor grid
    anchor_cls: jnp.ndarray,   # (N,) int32 class of each anchor slot
    matched_t: jnp.ndarray,    # (N,) per-anchor matched threshold
    unmatched_t: jnp.ndarray,  # (N,) per-anchor unmatched threshold
    gt: jnp.ndarray,           # (T, 8) [box7, cls], cls == -1 padding
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One sample's assignment -> (matched_gt (N,) int32 index or -1,
    positive (N,) bool, negative (N,) bool). Anchors between the
    thresholds are neither (ignored by the class loss). Every valid GT
    force-claims its best anchor (threshold-free), matching OpenPCDet's
    assigner."""
    n = anchors.shape[0]
    gt_cls = gt[:, 7].astype(jnp.int32)
    gt_valid = gt_cls >= 0

    def body(carry, row):
        best_iou, best_gt, t = carry
        box, cls_v, valid_v = row[:7], row[7].astype(jnp.int32), row[8] > 0
        iou = nearest_bev_iou_vs_gt(anchors, box)
        iou = jnp.where(valid_v & (anchor_cls == cls_v), iou, 0.0)
        take = iou > best_iou
        best_iou = jnp.where(take, iou, best_iou)
        best_gt = jnp.where(take, t, best_gt)
        # the GT's own best anchor (argmax breaks ties to the first)
        gt_best_anchor = jnp.argmax(iou)
        gt_best_iou = iou[gt_best_anchor]
        return (best_iou, best_gt, t + 1), (gt_best_anchor, gt_best_iou)

    rows = jnp.concatenate(
        [gt[:, :8], gt_valid[:, None].astype(gt.dtype)], axis=1
    )
    (best_iou, best_gt, _), (gt_best_anchor, gt_best_iou) = jax.lax.scan(
        body, (jnp.zeros(n), jnp.full(n, -1, jnp.int32), jnp.int32(0)), rows
    )

    positive = best_iou >= matched_t
    negative = best_iou < unmatched_t
    # force match: each valid GT with any class-matched overlap claims
    # its best anchor, overriding thresholds (and the negative set).
    # A force-claimed anchor's best_gt is already >= 0 (the forcing GT
    # gave it nonzero IoU), so best_gt is the match for it too.
    force = gt_valid & (gt_best_iou > 1e-6)
    forced_pos = (
        jnp.zeros(n, jnp.int32).at[gt_best_anchor].max(force.astype(jnp.int32))
        > 0
    )
    positive = positive | forced_pos
    negative = negative & ~forced_pos
    matched_gt = jnp.where(positive, best_gt, -1)
    return matched_gt, positive, negative


def _smooth_l1(x: jnp.ndarray, beta: float) -> jnp.ndarray:
    ax = jnp.abs(x)
    return jnp.where(ax < beta, 0.5 * ax**2 / beta, ax - 0.5 * beta)


def _focal(logits, targets, alpha, gamma):
    """Elementwise sigmoid focal loss (RetinaNet form, OpenPCDet
    SigmoidFocalClassificationLoss)."""
    p = jax.nn.sigmoid(logits)
    bce = jnp.maximum(logits, 0) - logits * targets + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
    a_t = alpha * targets + (1 - alpha) * (1 - targets)
    p_t = p * targets + (1 - p) * (1 - targets)
    return a_t * (1 - p_t) ** gamma * bce


def detection3d_loss(
    heads: dict[str, jnp.ndarray],
    targets: jnp.ndarray,  # (B, T, 8)
    model_cfg,
    cfg: Loss3DConfig,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """Anchor-head loss over raw head maps (cls/box/dir)."""
    num_classes = model_cfg.num_classes
    b, h, w, a, _ = heads["cls"].shape
    n = h * w * a
    anchors = generate_anchors(model_cfg).reshape(n, 7)
    # anchor slot -> class: slots are [cls0 rot0, cls0 rot90, cls1 ...]
    per_cls = np.concatenate(
        [np.full(2, i, np.int32) for i in range(num_classes)]
    )
    anchor_cls = jnp.asarray(np.tile(per_cls, h * w))
    m_t = np.concatenate(
        [np.full(2, c.matched_thresh, np.float32) for c in model_cfg.anchor_classes]
    )
    u_t = np.concatenate(
        [np.full(2, c.unmatched_thresh, np.float32) for c in model_cfg.anchor_classes]
    )
    matched_t = jnp.asarray(np.tile(m_t, h * w))
    unmatched_t = jnp.asarray(np.tile(u_t, h * w))

    matched_gt, positive, negative = jax.vmap(
        lambda g: assign_targets(anchors, anchor_cls, matched_t, unmatched_t, g)
    )(targets)  # each (B, N)

    cls_logits = heads["cls"].reshape(b, n, num_classes)
    box_pred = heads["box"].reshape(b, n, 7)
    dir_logits = heads["dir"].reshape(b, n, cfg.num_dir_bins)

    safe_idx = jnp.maximum(matched_gt, 0)
    gt_boxes = jnp.take_along_axis(
        targets[:, :, :7], safe_idx[..., None], axis=1
    )  # (B, N, 7)
    gt_cls = jnp.take_along_axis(
        targets[:, :, 7].astype(jnp.int32), safe_idx, axis=1
    )  # (B, N)

    n_pos = jnp.maximum(positive.sum(), 1).astype(jnp.float32)

    # ---- class: focal over positives (one-hot of the matched GT's
    # class) + negatives (all-zero target); in-between anchors ignored
    cls_tgt = jax.nn.one_hot(
        jnp.where(positive, gt_cls, -1), num_classes
    )  # -1 -> all-zero row
    cls_weight = (positive | negative).astype(jnp.float32)
    cls_loss = (
        _focal(cls_logits, cls_tgt, cfg.focal_alpha, cfg.focal_gamma).sum(-1)
        * cls_weight
    ).sum() / n_pos

    # ---- box: smooth-L1 on encoded residuals at positives, with the
    # sin(a - b) decomposition for yaw (OpenPCDet add_sin_difference)
    enc_tgt = encode_boxes(gt_boxes, anchors[None])  # (B, N, 7)
    yaw_p, yaw_t = box_pred[..., 6], enc_tgt[..., 6]
    sin_p = jnp.sin(yaw_p) * jnp.cos(yaw_t)
    sin_t = jnp.cos(yaw_p) * jnp.sin(yaw_t)
    resid = jnp.concatenate(
        [
            box_pred[..., :6] - enc_tgt[..., :6],
            (sin_p - sin_t)[..., None],
        ],
        axis=-1,
    )
    pos_f = positive.astype(jnp.float32)
    box_loss = (
        _smooth_l1(resid, cfg.smooth_l1_beta).sum(-1) * pos_f
    ).sum() / n_pos

    # ---- direction bin at positives: bin of the GT heading relative
    # to the anchor's rotation (OpenPCDet get_direction_target)
    rot_gt = gt_boxes[..., 6] - anchors[None, :, 6]
    offset_rot = rot_gt - cfg.dir_offset
    dir_tgt = jnp.clip(
        jnp.floor(offset_rot / (2 * jnp.pi / cfg.num_dir_bins)).astype(jnp.int32),
        0,
        cfg.num_dir_bins - 1,
    )
    dir_ce = optax.softmax_cross_entropy_with_integer_labels(
        dir_logits, dir_tgt
    )
    dir_loss = (dir_ce * pos_f).sum() / n_pos

    loss = cfg.cls_w * cls_loss + cfg.loc_w * box_loss + cfg.dir_w * dir_loss
    metrics = {
        "cls": cls_loss,
        "box": box_loss,
        "dir": dir_loss,
        "n_pos": n_pos,
    }

    # ---- IoU-quality head (SECOND-IoU): smooth-L1 toward 2*iou - 1 of
    # the DECODED prediction vs its matched GT at positives
    if "iou" in heads and cfg.iou_w > 0:
        from triton_client_tpu.models.pointpillars import decode_boxes

        iou_pred = heads["iou"].reshape(b, n)
        decoded = decode_boxes(box_pred, anchors[None])  # (B, N, 7)
        t_iou = jax.lax.stop_gradient(
            nearest_bev_iou_rowwise(decoded, gt_boxes)
        )
        iou_loss = (
            _smooth_l1(iou_pred - (2.0 * t_iou - 1.0), cfg.smooth_l1_beta)
            * pos_f
        ).sum() / n_pos
        loss = loss + cfg.iou_w * iou_loss
        metrics["iou"] = iou_loss

    metrics["loss"] = loss
    return loss, metrics


# ---------------------------------------------------------------------------
# CenterPoint (anchor-free) training — center heatmap + offset/size/
# rot/velocity regression, the det3d CenterHead loss semantics as
# fixed-shape JAX (round 5: proves the velocity head end-to-end).
# Reference anchor: the served det3d CenterPoint lineage
# (clients/preprocess/voxelize.py:13-24,
# data/nusc_centerpoint_pp_02voxel_two_pfn_10sweep.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CenterLossConfig:
    hm_w: float = 1.0
    reg_w: float = 0.25       # det3d loc weight
    vel_code_w: float = 0.2   # nuScenes code_weights for vx, vy
    focal_alpha: float = 2.0  # CenterNet penalty-reduced focal
    focal_beta: float = 4.0
    min_radius: float = 2.0
    gaussian_overlap: float = 0.1


def gaussian_radius(dims_cells: jnp.ndarray, min_overlap: float) -> jnp.ndarray:
    """CenterNet's gaussian radius, EXACTLY as det3d/CenterPoint ship it
    (det3d core/utils/center_utils.py): all three quadratic roots use
    the upstream (b + sqrt(disc)) / 2 form — including the well-known
    quirk that r2/r3 skip the 1/(2a) divisor. Matching the shipped
    formula, not the textbook roots, is deliberate: the loss semantics
    being reproduced are det3d's (dims in feature cells, (..., 2))."""
    h, w = dims_cells[..., 0], dims_cells[..., 1]
    a1 = 1.0
    b1 = h + w
    c1 = w * h * (1 - min_overlap) / (1 + min_overlap)
    r1 = (b1 + jnp.sqrt(jnp.maximum(b1**2 - 4 * a1 * c1, 0.0))) / 2
    a2 = 4.0
    b2 = 2 * (h + w)
    c2 = (1 - min_overlap) * w * h
    r2 = (b2 + jnp.sqrt(jnp.maximum(b2**2 - 4 * a2 * c2, 0.0))) / 2
    a3 = 4 * min_overlap
    b3 = -2 * min_overlap * (h + w)
    c3 = (min_overlap - 1) * w * h
    r3 = (b3 + jnp.sqrt(jnp.maximum(b3**2 - 4 * a3 * c3, 0.0))) / 2
    return jnp.minimum(jnp.minimum(r1, r2), r3)


def centerpoint_targets(
    gt: jnp.ndarray,  # (T, 8|10) [box7, cls(, vx, vy)], cls == -1 pad
    model_cfg,
    cfg: CenterLossConfig,
):
    """One sample's center targets: heatmap (H, W, nc) with unit peaks
    at GT center cells under clamped-radius gaussians (rendered by a
    lax.scan elementwise-max, so the (T, H, W, nc) tensor never
    materializes), plus per-GT regression rows gathered at those
    cells."""
    h, w = model_cfg.head_hw
    nc = model_cfg.num_classes
    stride = model_cfg.head_stride
    vs = model_cfg.voxel.voxel_size
    r0 = model_cfg.voxel.point_cloud_range

    cls = gt[:, 7].astype(jnp.int32)
    cx = (gt[:, 0] - r0[0]) / (vs[0] * stride)
    cy = (gt[:, 1] - r0[1]) / (vs[1] * stride)
    ix = jnp.clip(jnp.floor(cx).astype(jnp.int32), 0, w - 1)
    iy = jnp.clip(jnp.floor(cy).astype(jnp.int32), 0, h - 1)
    inside = (cx >= 0) & (cx < w) & (cy >= 0) & (cy < h)
    valid = (cls >= 0) & inside

    dims_cells = jnp.stack(
        [gt[:, 4] / (vs[1] * stride), gt[:, 3] / (vs[0] * stride)], axis=-1
    )
    radius = jnp.maximum(
        gaussian_radius(dims_cells, cfg.gaussian_overlap), cfg.min_radius
    )
    sigma = (2 * radius + 1) / 6.0

    ys = jnp.arange(h, dtype=jnp.float32)[:, None]
    xs = jnp.arange(w, dtype=jnp.float32)[None, :]

    def render(heat, row):
        rix, riy, rsig, rcls, rvalid = row
        g = jnp.exp(
            -((xs - rix) ** 2 + (ys - riy) ** 2) / (2.0 * rsig**2)
        ) * rvalid
        return jnp.maximum(
            heat, g[:, :, None] * jax.nn.one_hot(rcls.astype(jnp.int32), nc)
        ), None

    heat, _ = jax.lax.scan(
        render,
        jnp.zeros((h, w, nc), jnp.float32),
        (
            ix.astype(jnp.float32),
            iy.astype(jnp.float32),
            sigma,
            cls,
            valid.astype(jnp.float32),
        ),
    )

    vel = gt[:, 8:10] if gt.shape[1] >= 10 else jnp.zeros((gt.shape[0], 2))
    reg = jnp.concatenate(
        [
            (cx - ix)[:, None], (cy - iy)[:, None],        # offset
            gt[:, 2:3],                                    # height
            jnp.log(jnp.maximum(gt[:, 3:6], 1e-3)),        # size
            jnp.sin(gt[:, 6:7]), jnp.cos(gt[:, 6:7]),      # rot
            vel,                                           # velocity
        ],
        axis=-1,
    )  # (T, 10)
    flat = iy * w + ix
    return heat, flat, reg, valid


def centerpoint_loss(
    heads: dict[str, jnp.ndarray],
    targets: jnp.ndarray,  # (B, T, 8|10)
    model_cfg,
    cfg: CenterLossConfig,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """Penalty-reduced focal on the class heatmap + masked L1 on the
    center-gathered regression bundle (velocity channels down-weighted
    by the nuScenes code weights). When targets carry no velocity
    columns the vel loss is exactly zero (head still differentiable)."""
    has_vel = targets.shape[-1] >= 10 and "vel" in heads
    heat_t, flat, reg_t, valid = jax.vmap(
        lambda g: centerpoint_targets(g, model_cfg, cfg)
    )(targets)

    logits = heads["heatmap"]
    p = jnp.clip(jax.nn.sigmoid(logits), 1e-6, 1 - 1e-6)
    pos = heat_t >= 0.9999
    pos_loss = -((1 - p) ** cfg.focal_alpha) * jnp.log(p) * pos
    neg_loss = (
        -((1 - heat_t) ** cfg.focal_beta)
        * (p**cfg.focal_alpha)
        * jnp.log(1 - p)
        * (~pos)
    )
    n_pos = jnp.maximum(valid.sum(), 1).astype(jnp.float32)
    hm_loss = (pos_loss.sum() + neg_loss.sum()) / n_pos

    b, hh, ww, _ = logits.shape
    parts = [heads["offset"], heads["height"], heads["size"], heads["rot"]]
    if has_vel:
        parts.append(heads["vel"])
    pred = jnp.concatenate(parts, axis=-1).reshape(b, hh * ww, -1)
    pred_at = jnp.take_along_axis(
        pred, flat[..., None], axis=1
    )  # (B, T, 8|10)
    ch = pred_at.shape[-1]
    code_w = jnp.concatenate(
        [jnp.ones(8), jnp.full(2, cfg.vel_code_w)]
    )[:ch]
    l1 = jnp.abs(pred_at - reg_t[..., :ch]) * code_w
    reg_loss = (l1.sum(-1) * valid).sum() / n_pos

    loss = cfg.hm_w * hm_loss + cfg.reg_w * reg_loss
    metrics = {"hm": hm_loss, "reg": reg_loss, "n_pos": n_pos, "loss": loss}
    if has_vel:
        vel_l1 = jnp.abs(pred_at[..., 8:10] - reg_t[..., 8:10])
        metrics["vel_l1"] = (
            vel_l1.mean(-1) * valid
        ).sum() / n_pos  # un-weighted, for monitoring
    return loss, metrics


def _maybe_augment(augment, state, points, targets):
    """Shared per-step augmentation: key folded from the step counter
    so a resumed run replays the same stream (and both step factories
    derive it identically)."""
    if augment is None:
        return points, targets
    key = jax.random.fold_in(jax.random.PRNGKey(augment.seed), state.step)
    return augment_scene_batch(key, points, targets, augment)


def make_center3d_step(
    model,
    optimizer: optax.GradientTransformation,
    loss_cfg: CenterLossConfig,
    mesh: Mesh,
    augment: Augment3DConfig | None = None,
):
    """CenterPoint training step: (state, points (B, P, F), counts (B,),
    targets (B, T, 8|10)) -> (state, metrics), batch sharded over the
    data axis — the anchor-free sibling of make_train3d_step. With
    ``augment``, the global rot/flip/scale transform is applied inside
    the jit (key folded from the step counter, so resume replays the
    same stream)."""

    def step_fn(state: TrainState, points, counts, targets):
        points, targets = _maybe_augment(augment, state, points, targets)

        def loss_fn(params):
            variables = {**state.variables, "params": params}
            heads, mutated = model.apply(
                variables,
                points,
                counts,
                train=True,
                mutable=["batch_stats"],
                method=type(model).from_points_batch,
            )
            loss, metrics = centerpoint_loss(
                heads, targets, model.cfg, loss_cfg
            )
            return loss, (metrics, mutated["batch_stats"])

        grads, (metrics, new_stats) = jax.grad(loss_fn, has_aux=True)(
            state.variables["params"]
        )
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.variables["params"]
        )
        new_params = optax.apply_updates(state.variables["params"], updates)
        return (
            TrainState(
                variables={"params": new_params, "batch_stats": new_stats},
                opt_state=new_opt,
                step=state.step + 1,
            ),
            metrics,
        )

    data = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step_fn,
        in_shardings=(None, data, data, data),
        donate_argnums=(0,),
    )


def make_train3d_step(
    model: PointPillars,
    optimizer: optax.GradientTransformation,
    loss_cfg: Loss3DConfig,
    mesh: Mesh,
    augment: Augment3DConfig | None = None,
):
    """(state, points (B, P, F), counts (B,), targets (B, T, 8)) ->
    (state, metrics), batch sharded over the data axis. ``augment``
    enables the global rot/flip/scale transform inside the jit."""

    def step_fn(state: TrainState, points, counts, targets):
        points, targets = _maybe_augment(augment, state, points, targets)

        def loss_fn(params):
            variables = {**state.variables, "params": params}
            heads, mutated = model.apply(
                variables,
                points,
                counts,
                train=True,
                mutable=["batch_stats"],
                method=type(model).from_points_batch,
            )
            loss, metrics = detection3d_loss(
                heads, targets, model.cfg, loss_cfg
            )
            return loss, (metrics, mutated["batch_stats"])

        grads, (metrics, new_stats) = jax.grad(loss_fn, has_aux=True)(
            state.variables["params"]
        )
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.variables["params"]
        )
        new_params = optax.apply_updates(state.variables["params"], updates)
        return (
            TrainState(
                variables={"params": new_params, "batch_stats": new_stats},
                opt_state=new_opt,
                step=state.step + 1,
            ),
            metrics,
        )

    data = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step_fn,
        in_shardings=(None, data, data, data),
        donate_argnums=(0,),
    )


def init_train3d_state(
    model: PointPillars,
    variables,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
) -> TrainState:
    sharded = shard_variables(variables, mesh)
    opt_state = optimizer.init(sharded["params"])
    return TrainState(
        variables=sharded, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
    )
