"""Sharded fine-tuning step for the detector zoo.

The reference is inference-only (weights arrive as server-side .pth/
ONNX artifacts, SURVEY.md section 5 "checkpoint/resume"); this module
adds the training capability TPU-natively so models can be fine-tuned
(e.g. the crop/weed classes) on the same mesh that serves them:

  * data parallelism over the `data` mesh axis (batch sharding),
  * tensor parallelism over `model` for wide conv kernels (output-
    channel sharding; XLA inserts the all-gathers/reduce-scatters),
  * loss: YOLOv5-style anchor-matched detection loss — wh-ratio anchor
    matching, CIoU box loss, BCE objectness (IoU-weighted targets), BCE
    class loss — written gather/scatter-style with static shapes
    (targets padded to max_boxes per image).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_client_tpu.models.yolov5 import STRIDES, YoloV5
from triton_client_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


# --------------------------------------------------------------------------
# Sharding policy
# --------------------------------------------------------------------------

def param_spec(path: tuple, leaf: jnp.ndarray, model_size: int) -> P:
    """Output-channel TP for wide conv kernels; everything else replicated.

    Conv kernels are (kh, kw, cin, cout); sharding cout over `model`
    splits both the matmul and the activations feeding the next layer.
    Only kernels whose cout divides evenly and is wide enough to keep
    per-device tiles MXU-friendly (>= 128 per shard) are sharded.
    """
    if leaf.ndim >= 2:
        cout = leaf.shape[-1]
        if cout % model_size == 0 and cout // model_size >= 128:
            return P(*([None] * (leaf.ndim - 1) + [MODEL_AXIS]))
    return P()


def shard_variables(variables: Mapping, mesh: Mesh):
    """device_put model variables per the TP policy."""
    model_size = mesh.shape[MODEL_AXIS]

    def place(path, leaf):
        spec = param_spec(path, leaf, model_size)
        # np.asarray forces a host copy first: device_put alone can alias
        # the caller's buffer (same-device zero-copy), and the train step
        # donates its state — donation must not delete the caller's arrays.
        return jax.device_put(np.asarray(leaf), NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, dict(variables))


# --------------------------------------------------------------------------
# Detection loss
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LossConfig:
    num_classes: int
    anchors: tuple  # ((a, 2) per scale), pixels
    box_w: float = 0.05
    obj_w: float = 1.0
    cls_w: float = 0.5
    anchor_t: float = 4.0  # wh-ratio match threshold (YOLOv5 default)


def _bce(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Elementwise binary cross-entropy on logits."""
    return jnp.maximum(logits, 0) - logits * targets + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def _ciou(box1: jnp.ndarray, box2: jnp.ndarray) -> jnp.ndarray:
    """Complete-IoU between (..., 4) cxcywh boxes."""
    b1x1, b1y1 = box1[..., 0] - box1[..., 2] / 2, box1[..., 1] - box1[..., 3] / 2
    b1x2, b1y2 = box1[..., 0] + box1[..., 2] / 2, box1[..., 1] + box1[..., 3] / 2
    b2x1, b2y1 = box2[..., 0] - box2[..., 2] / 2, box2[..., 1] - box2[..., 3] / 2
    b2x2, b2y2 = box2[..., 0] + box2[..., 2] / 2, box2[..., 1] + box2[..., 3] / 2
    inter = jnp.clip(jnp.minimum(b1x2, b2x2) - jnp.maximum(b1x1, b2x1), 0) * jnp.clip(
        jnp.minimum(b1y2, b2y2) - jnp.maximum(b1y1, b2y1), 0
    )
    w1, h1 = box1[..., 2], box1[..., 3]
    w2, h2 = box2[..., 2], box2[..., 3]
    union = w1 * h1 + w2 * h2 - inter
    iou = inter / jnp.maximum(union, 1e-9)
    # enclosing box diagonal
    cw = jnp.maximum(b1x2, b2x2) - jnp.minimum(b1x1, b2x1)
    ch = jnp.maximum(b1y2, b2y2) - jnp.minimum(b1y1, b2y1)
    c2 = cw**2 + ch**2 + 1e-9
    rho2 = (box2[..., 0] - box1[..., 0]) ** 2 + (box2[..., 1] - box1[..., 1]) ** 2
    v = (4 / jnp.pi**2) * (jnp.arctan(w2 / jnp.maximum(h2, 1e-9))
                           - jnp.arctan(w1 / jnp.maximum(h1, 1e-9))) ** 2
    alpha = v / jnp.maximum(1 - iou + v, 1e-9)
    return iou - rho2 / c2 - jax.lax.stop_gradient(alpha) * v


def detection_loss(
    heads: list[jnp.ndarray],
    targets: jnp.ndarray,
    cfg: LossConfig,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """YOLOv5-style loss over raw head outputs.

    targets: (B, T, 5) rows [cls, cx, cy, w, h] in input pixels, padded
    with w == 0 rows. Assignment: a target matches anchor `a` at its
    center cell when max(wh/anchor, anchor/wh) < anchor_t.
    """
    total_box = total_obj = total_cls = 0.0
    tw = targets[..., 3]
    t_valid = tw > 0  # (B, T)

    for si, raw in enumerate(heads):
        b, h, w, na, no = raw.shape
        stride = STRIDES[si]
        anchors = jnp.asarray(cfg.anchors[si], jnp.float32)  # (na, 2)

        # --- matching (static shapes: B x T x na candidate grid)
        t_wh = targets[..., 3:5]  # (B, T, 2)
        ratio = t_wh[:, :, None, :] / anchors[None, None]  # (B, T, na, 2)
        worst = jnp.maximum(ratio, 1.0 / jnp.maximum(ratio, 1e-9)).max(-1)
        matched = (worst < cfg.anchor_t) & t_valid[:, :, None]  # (B, T, na)

        gi = jnp.clip((targets[..., 1] / stride).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((targets[..., 2] / stride).astype(jnp.int32), 0, h - 1)

        # --- gather predictions at each (target, anchor) slot
        def per_image(raw_i, gi_i, gj_i):
            return raw_i[gj_i, gi_i]  # (T, na, no)

        pred_t = jax.vmap(per_image)(raw, gi, gj)  # (B, T, na, no)

        # decode boxes at matched cells (v5 parameterization)
        pxy = (jax.nn.sigmoid(pred_t[..., :2]) * 2.0 - 0.5
               + jnp.stack([gi, gj], -1)[:, :, None, :]) * stride
        pwh = (jax.nn.sigmoid(pred_t[..., 2:4]) * 2.0) ** 2 * anchors[None, None]
        pbox = jnp.concatenate([pxy, pwh], -1)
        tbox = jnp.broadcast_to(
            targets[:, :, None, 1:5], pbox.shape
        )
        ciou = _ciou(pbox, tbox)  # (B, T, na)
        n_matched = jnp.maximum(matched.sum(), 1)
        total_box += ((1.0 - ciou) * matched).sum() / n_matched

        # --- objectness: scatter IoU targets into the (B, h, w, na) grid
        obj_tgt = jnp.zeros((b, h, w, na), jnp.float32)
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None, None], matched.shape)
        aidx = jnp.broadcast_to(jnp.arange(na)[None, None, :], matched.shape)
        gjb = jnp.broadcast_to(gj[:, :, None], matched.shape)
        gib = jnp.broadcast_to(gi[:, :, None], matched.shape)
        iou_tgt = jnp.where(matched, jnp.clip(ciou, 0.0), 0.0)
        obj_tgt = obj_tgt.at[
            bidx.reshape(-1), gjb.reshape(-1), gib.reshape(-1), aidx.reshape(-1)
        ].max(iou_tgt.reshape(-1))
        total_obj += _bce(raw[..., 4], jax.lax.stop_gradient(obj_tgt)).mean()

        # --- classification at matched slots
        if cfg.num_classes > 1:
            t_cls = jax.nn.one_hot(targets[..., 0].astype(jnp.int32), cfg.num_classes)
            t_cls = jnp.broadcast_to(t_cls[:, :, None, :], pred_t[..., 5:].shape)
            cls_bce = _bce(pred_t[..., 5:], t_cls).sum(-1)
            total_cls += (cls_bce * matched).sum() / n_matched

    loss = (
        cfg.box_w * total_box + cfg.obj_w * total_obj + cfg.cls_w * total_cls
    )
    return loss, {
        "loss": loss,
        "box": total_box,
        "obj": total_obj,
        "cls": total_cls,
    }


# --------------------------------------------------------------------------
# Train step
# --------------------------------------------------------------------------

@dataclasses.dataclass
class TrainState:
    variables: Any  # {'params': ..., 'batch_stats': ...}
    opt_state: Any
    step: jnp.ndarray


def make_train_step(
    model: YoloV5,
    optimizer: optax.GradientTransformation,
    loss_cfg: LossConfig,
    mesh: Mesh,
):
    """Build the pjit-compiled train step: (state, images, targets) ->
    (state, metrics). Images are sharded over `data`; params follow the
    TP policy; optimizer state inherits param shardings."""

    def step_fn(state: TrainState, images: jnp.ndarray, targets: jnp.ndarray):
        def loss_fn(params):
            variables = {**state.variables, "params": params}
            heads, mutated = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            loss, metrics = detection_loss(heads, targets, loss_cfg)
            return loss, (metrics, mutated["batch_stats"])

        grads, (metrics, new_stats) = jax.grad(loss_fn, has_aux=True)(
            state.variables["params"]
        )
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.variables["params"]
        )
        new_params = optax.apply_updates(state.variables["params"], updates)
        new_state = TrainState(
            variables={"params": new_params, "batch_stats": new_stats},
            opt_state=new_opt,
            step=state.step + 1,
        )
        return new_state, metrics

    data_sharding = NamedSharding(mesh, P(DATA_AXIS))
    jitted = jax.jit(
        step_fn,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    return jitted


def init_train_state(
    model: YoloV5,
    variables: Mapping,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
) -> TrainState:
    sharded = shard_variables(variables, mesh)
    opt_state = optimizer.init(sharded["params"])
    return TrainState(
        variables=sharded, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
    )


jax.tree_util.register_dataclass(
    TrainState, data_fields=["variables", "opt_state", "step"], meta_fields=[]
)
