"""Mesh construction and canonical shardings.

Axes:
  data   — batch / camera-stream data parallelism (the BASELINE.json
           "ensemble multi-camera over v5e-8" config maps cameras here)
  model  — tensor parallelism for wide layers (conv channel sharding,
           voxel-axis sharding for the 3D stack)
  seq    — sequence/context parallelism: the point/pillar/BEV-token
           axis for long point clouds (the reference's scale axis is
           MAX_NUMBER_OF_VOXELS=40000, data/kitti_dataset.yaml:66-70;
           a full KITTI BEV canvas is 432x496 ≈ 214k tokens). Ring
           attention and the distributed pillar scatter in
           parallel/sequence.py ride this axis over ICI.

On a single host this is `jax.devices()` reshaped; on multi-host the
same code runs under `jax.distributed` with DCN-attached hosts, with
the data axis laid out across hosts (DCN) and model across the
intra-slice ICI ring, so heavy collectives stay on ICI.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = -1  # -1: all remaining devices
    model: int = 1
    seq: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        model = max(1, self.model)
        seq = max(1, self.seq)
        pipe = max(1, self.pipe)
        rest = model * seq * pipe
        data = self.data if self.data > 0 else n_devices // rest
        if data * rest != n_devices:
            raise ValueError(
                f"mesh {data}x{model}x{seq}x{pipe} != {n_devices} devices"
            )
        return data, model, seq, pipe


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    if config.data > 0:
        # Fully explicit mesh: claim only the devices it names, so e.g.
        # --mesh data=4 works on an 8-device host (first 4 devices) —
        # loudly, so a mis-sized training config can't silently run at
        # partial throughput.
        want = (
            config.data
            * max(1, config.model) * max(1, config.seq) * max(1, config.pipe)
        )
        if want < len(devices):
            import logging

            logging.getLogger(__name__).warning(
                "mesh %s uses %d of %d available devices",
                config, want, len(devices),
            )
            devices = devices[:want]
    data, model, seq, pipe = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(data, model, seq, pipe)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis, replicate rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_axis_size(mesh: Mesh) -> int:
    """Width of the data axis — the serving channel's batch multiple."""
    return int(mesh.shape[DATA_AXIS])


def serving_shardings(mesh: Mesh) -> tuple[NamedSharding, NamedSharding]:
    """The two shardings the serving path ever uses: ``(batch, params)``
    — batch-leading request arrays split over ``data``, everything else
    (params, scalars, non-batched inputs) replicated on every device.
    One helper so the channel and the jit ``in_shardings`` can't
    disagree about placement."""
    return batch_sharding(mesh), replicated(mesh)


def replicate_params(tree, mesh: Mesh):
    """Place a param pytree once onto the mesh, replicated on every
    device. Serving's replicate-params / shard-batch shape: params are
    uploaded a single time at model registration, then every sharded
    launch reads the local copy — no per-request weight movement."""
    return jax.device_put(tree, replicated(mesh))
