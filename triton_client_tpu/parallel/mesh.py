"""Mesh construction and canonical shardings.

Axes:
  data   — batch / camera-stream data parallelism (the BASELINE.json
           "ensemble multi-camera over v5e-8" config maps cameras here)
  model  — tensor parallelism for wide layers (conv channel sharding,
           voxel-axis sharding for the 3D stack)

On a single host this is `jax.devices()` reshaped; on multi-host the
same code runs under `jax.distributed` with DCN-attached hosts, with
the data axis laid out across hosts (DCN) and model across the
intra-slice ICI ring, so heavy collectives stay on ICI.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = -1  # -1: all remaining devices
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = max(1, self.model)
        data = self.data if self.data > 0 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != {n_devices} devices available"
            )
        return data, model


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    data, model = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis, replicate rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
