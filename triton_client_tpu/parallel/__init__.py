"""Device mesh + sharding policy.

The reference has no device parallelism at all (SURVEY.md section 2.10
— one blocking RPC per frame, NCCL/MPI absent). This package supplies
the TPU-native scale story: a named `jax.sharding.Mesh` (data / model /
seq / pipe axes) with XLA collectives over ICI/DCN, batch sharding for
multi-camera serving, ring + all-to-all sequence parallelism for long
point clouds and BEV token grids, GPipe microbatch pipelining for deep
stacks, and the sharded training step used for fine-tuning.
"""

from triton_client_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MeshConfig,
    batch_sharding,
    make_mesh,
    replicated,
)
from triton_client_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from triton_client_tpu.parallel.sequence import (
    full_attention,
    ring_attention,
    sequence_parallel_pillar_canvas,
    ulysses_attention,
)
