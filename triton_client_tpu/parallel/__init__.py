"""Device mesh + sharding policy.

The reference has no device parallelism at all (SURVEY.md section 2.10
— one blocking RPC per frame, NCCL/MPI absent). This package supplies
the TPU-native scale story: a named `jax.sharding.Mesh` with XLA
collectives over ICI/DCN, batch/data sharding for multi-camera serving,
and the sharded training step used for fine-tuning.
"""

from triton_client_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    batch_sharding,
    replicated,
)
