"""Sequence / context parallelism primitives (ring + all-to-all).

The reference has no attention and no sequence parallelism — its scale
axis is the per-frame point-cloud length (dynamic voxel counts,
communicator/ros_inference3d.py:131-139, capped at
MAX_NUMBER_OF_VOXELS=40000, data/kitti_dataset.yaml:66-70). On TPU the
equivalent first-class capability is sharding that long axis across a
``seq`` mesh axis and combining with XLA collectives over ICI:

  * ``ring_attention`` — blockwise self-attention over a
    sequence-sharded axis. K/V blocks rotate around the ICI ring via
    ``lax.ppermute`` while each device keeps a numerically-stable
    online-softmax accumulator (the Ring Attention construction:
    memory per device is O(S/sp), the full S x S score matrix is never
    materialized). Used by the BEV attention neck over ~214k-token
    KITTI canvases (432x496, data/pointpillar.yaml grid).
  * ``ulysses_attention`` — the all-to-all alternative (DeepSpeed
    Ulysses construction): all_to_all re-shards sequence -> heads, each
    device runs *full-sequence* attention for its head slice, then
    all_to_all back. One collective pair instead of sp ring steps;
    needs heads % sp == 0.
  * ``sequence_parallel_pillar_canvas`` — the point-axis analogue:
    points are sharded over ``seq``; each device bins its shard into a
    dense per-pillar accumulator, pillar statistics are combined with
    ``psum`` and the max-pooled pillar embedding with ``pmax``. No
    dynamic voxel lists cross devices — only fixed-shape dense grids,
    so the whole thing jits to one XLA program with ICI all-reduces.

All three are pure shard_map kernels over mesh axes from
parallel/mesh.py; they compile and run identically on a virtual CPU
mesh (tests) and a real TPU slice.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_client_tpu.parallel._compat import shard_map
from triton_client_tpu.parallel.mesh import SEQ_AXIS

_NEG = -1e30  # soft -inf: keeps exp() finite for fully-masked rows


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------


def _ring_attention_kernel(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
) -> jnp.ndarray:
    """Per-device body. q/k/v: (B, Sblk, H, D) local sequence blocks."""
    sp = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))

    q_pos = idx * s_blk + jnp.arange(s_blk)

    # Online softmax state: running max m, normalizer l, weighted sum acc.
    m = jnp.full((b, h, s_blk), _NEG, jnp.float32)
    l = jnp.zeros((b, h, s_blk), jnp.float32)
    acc = jnp.zeros((b, s_blk, h, d), jnp.float32)

    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def accumulate(i, m, l, acc, k_blk, v_blk):
        # Block currently held started at device (idx - i) mod sp.
        src = (idx - i) % sp
        k_pos = src * s_blk + jnp.arange(s_blk)

        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)

        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return m_new, l, acc

    def body(i, carry):
        # Rotate at the top so the final iteration's blocks are consumed,
        # not discarded — exactly sp-1 ppermute rounds in total.
        m, l, acc, k_blk, v_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        m, l, acc = accumulate(i, m, l, acc, k_blk, v_blk)
        return m, l, acc, k_blk, v_blk

    m, l, acc = accumulate(0, m, l, acc, k, v)  # local block, no transfer
    m, l, acc, _, _ = jax.lax.fori_loop(1, sp, body, (m, l, acc, k, v))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
) -> jnp.ndarray:
    """Sequence-parallel attention; q/k/v (B, S, H, D) sharded on S.

    The global sequence length S must divide evenly by the ``axis``
    mesh size. Memory per device is O(S/sp * D); the K/V blocks travel
    the ICI ring once (sp ppermute steps), overlapping with the local
    block matmuls under XLA's async collective scheduling.
    """
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_kernel, axis_name=axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) attention
# ---------------------------------------------------------------------------


def full_attention(q, k, v, causal):
    b, s, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    s_mat = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        s_mat = jnp.where(mask[None, None], s_mat, _NEG)
    p = jax.nn.softmax(s_mat, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _ulysses_kernel(q, k, v, *, axis_name: str, causal: bool):
    """Per-device body. q/k/v: (B, S/sp, H, D) -> all_to_all -> (B, S, H/sp, D)."""

    def seq_to_heads(x):
        # split the head axis (2) across devices, gather the seq axis (1)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = full_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal
    )
    return heads_to_seq(out)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (Ulysses): re-shard S -> H, run
    full attention per head slice, re-shard back. Requires
    num_heads % mesh.shape[axis] == 0."""
    sp = mesh.shape[axis]
    if q.shape[2] % sp:
        raise ValueError(f"heads {q.shape[2]} not divisible by seq axis {sp}")
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ulysses_kernel, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Sequence-parallel pillar canvas (distributed point-axis voxelization)
# ---------------------------------------------------------------------------


def _pillar_canvas_kernel(
    points: jnp.ndarray,
    valid: jnp.ndarray,
    w: jnp.ndarray,
    b_: jnp.ndarray,
    *,
    axis_name: str,
    grid: tuple[int, int],
    pc_range: Sequence[float],
    voxel_size: Sequence[float],
) -> jnp.ndarray:
    """Per-device body. points: (N/sp, 4) [x,y,z,r]; valid: (N/sp,).

    Two-pass distributed PillarVFE without voxel lists:
      pass 1: dense per-pillar xyz sums + counts, psum over the ring
              -> exact global pillar means (cross-shard points agree);
      pass 2: 9-feature augment (PointPillars PillarVFE layout), linear
              + relu embed, dense scatter-max, pmax over the ring.
    """
    nx, ny = grid
    ncells = nx * ny
    x, y, z = points[:, 0], points[:, 1], points[:, 2]

    ix = jnp.floor((x - pc_range[0]) / voxel_size[0]).astype(jnp.int32)
    iy = jnp.floor((y - pc_range[1]) / voxel_size[1]).astype(jnp.int32)
    inb = (
        valid.astype(bool)
        & (ix >= 0) & (ix < nx)
        & (iy >= 0) & (iy < ny)
        & (z >= pc_range[2]) & (z <= pc_range[5])
    )
    pid = jnp.where(inb, iy * nx + ix, ncells)  # out-of-range -> dump slot

    # pass 1: global pillar means via dense psum
    ones = inb.astype(jnp.float32)
    sums = jnp.zeros((ncells + 1, 3), jnp.float32).at[pid].add(
        points[:, :3] * ones[:, None]
    )
    counts = jnp.zeros((ncells + 1,), jnp.float32).at[pid].add(ones)
    sums = jax.lax.psum(sums, axis_name)
    counts = jax.lax.psum(counts, axis_name)
    mean = sums / jnp.maximum(counts, 1.0)[:, None]

    # pass 2: augmented features -> embed -> distributed max-pool
    pmean = mean[pid]  # (N/sp, 3)
    cx = pc_range[0] + (ix.astype(jnp.float32) + 0.5) * voxel_size[0]
    cy = pc_range[1] + (iy.astype(jnp.float32) + 0.5) * voxel_size[1]
    feat = jnp.concatenate(
        [
            points[:, :4],
            points[:, :3] - pmean,
            (x - cx)[:, None],
            (y - cy)[:, None],
        ],
        axis=-1,
    )  # (N/sp, 9)
    emb = jax.nn.relu(feat @ w + b_)  # (N/sp, C)
    emb = jnp.where(inb[:, None], emb, _NEG)
    canvas = jnp.full((ncells + 1, emb.shape[-1]), _NEG, jnp.float32)
    canvas = canvas.at[pid].max(emb)
    canvas = jax.lax.pmax(canvas, axis_name)
    canvas = jnp.where(counts[:, None] > 0, canvas, 0.0)[:ncells]
    return canvas.reshape(1, ny, nx, -1)  # leading axis: shard_map replica


def sequence_parallel_pillar_canvas(
    points: jnp.ndarray,
    valid: jnp.ndarray,
    w: jnp.ndarray,
    b: jnp.ndarray,
    mesh: Mesh,
    *,
    grid: tuple[int, int],
    pc_range: Sequence[float],
    voxel_size: Sequence[float],
    axis: str = SEQ_AXIS,
) -> jnp.ndarray:
    """Distributed points -> dense BEV pillar canvas (ny, nx, C).

    ``points`` (N, 4) and ``valid`` (N,) are sharded over ``axis``; the
    returned canvas is replicated. The combine is two dense ICI
    all-reduces (psum for stats, pmax for the pooled embedding) — the
    TPU-native replacement for the reference's dynamic voxel lists
    (clients/preprocess/preprocess_3d.py:30-52).
    """
    kernel = functools.partial(
        _pillar_canvas_kernel,
        axis_name=axis,
        grid=grid,
        pc_range=tuple(pc_range),
        voxel_size=tuple(voxel_size),
    )
    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P()),
        out_specs=P(axis),  # each shard returns identical (1, ny, nx, C)
        check_vma=False,
    )
    out = fn(points, valid, w, b)  # (sp, ny, nx, C) — identical slices
    return out[0]
