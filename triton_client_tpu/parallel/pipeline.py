"""Pipeline parallelism: microbatched GPipe schedule over a mesh axis.

The reference's only "pipeline" is implicit — frame N+1's preprocessing
waits on frame N's RPC (SURVEY.md section 2.10). This module provides real
pipeline parallelism for deep homogeneous stacks (the BEV backbone's
repeated conv blocks, the attention neck's layers): the stack is split
into S stages laid out along the ``pipe`` mesh axis, microbatches
stream through, and activations hop stage-to-stage with
``lax.ppermute`` over ICI — the idiomatic TPU pipelining construction
(stacked per-stage params + shard_map, as in praxis/t5x), not a
port of any GPU framework's scheduler.

Schedule: plain GPipe. For M microbatches and S stages the loop runs
M + S - 1 ticks; at tick t, stage s computes microbatch t - s (when in
range). Bubble fraction is (S-1)/(M+S-1) — callers pick M >= S.
Every device executes every tick (SPMD), with masked no-ops in the
bubble; XLA overlaps the ppermute with the next tick's compute.

Constraints (inherent to ring pipelining, documented not hidden):
  * stage_fn must map (params_slice, x) -> y with y.shape == x.shape
    (homogeneous stages — true for residual stacks);
  * stage params are stacked on a leading axis of size S and sharded
    over the ``pipe`` axis.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_client_tpu.parallel._compat import shard_map
from triton_client_tpu.parallel.mesh import PIPE_AXIS

StageFn = Callable[..., jnp.ndarray]


def stack_stage_params(param_trees) -> object:
    """Stack a list of per-stage param pytrees on a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def _pipeline_kernel(
    params,
    xs: jnp.ndarray,
    *,
    stage_fn: StageFn,
    axis_name: str,
) -> jnp.ndarray:
    """Per-device body. params: stage slice (leading axis 1); xs: all
    microbatches (M, mb, ...) replicated (only stage 0 reads them)."""
    params = jax.tree.map(lambda p: p[0], params)
    stage = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    n_micro = xs.shape[0]

    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    recv = jnp.zeros_like(xs[0])
    outputs = jnp.zeros_like(xs)

    def tick(t, carry):
        recv, outputs = carry
        # stage 0 feeds from the microbatch queue; others from the ring
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jnp.where(stage == 0, xs[mb_idx], recv)
        y = stage_fn(params, x_in)
        # last stage banks microbatch t - (S-1) once it's real
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_last = stage == n_stages - 1
        live = (t - (n_stages - 1) >= 0) & is_last
        outputs = jnp.where(
            live,
            outputs.at[out_idx].set(y),
            outputs,
        )
        recv = jax.lax.ppermute(y, axis_name, perm)
        return recv, outputs

    _, outputs = jax.lax.fori_loop(
        0, n_micro + n_stages - 1, tick, (recv, outputs)
    )
    return outputs[None]  # (1, M, mb, ...): stacked over pipe -> take [-1]


def pipeline_apply(
    stacked_params,
    microbatches: jnp.ndarray,
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    axis: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run microbatches (M, mb, ...) through S pipelined stages.

    ``stacked_params``: pytree with leading axis S == mesh.shape[axis]
    (see stack_stage_params). Returns (M, mb, ...) — the last stage's
    outputs in microbatch order.
    """
    n_stages = mesh.shape[axis]
    lead = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if lead != {n_stages}:
        raise ValueError(
            f"stacked params leading axes {lead} != pipe axis size {n_stages}"
        )
    if microbatches.shape[0] < n_stages:
        raise ValueError(
            f"{microbatches.shape[0]} microbatches < {n_stages} stages — "
            "the bubble would dominate; split the batch finer"
        )
    fn = shard_map(
        functools.partial(
            _pipeline_kernel, stage_fn=stage_fn, axis_name=axis
        ),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)[-1]
