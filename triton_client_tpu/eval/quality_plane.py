"""Continuous quality plane: online accuracy scored in rolling windows,
quality-gated canary routing, and auto-rollback (ISSUE 17).

The missing half of the reference's ``evaluate.py`` lineage: the repo
serves precision variants (PR 5), fused-kernel routes (PR 16), and
velocity/tracking heads (PR 15) that all trade accuracy for speed with
— until now — zero runtime check. This module closes the loop:

  sampled request ─(shadow.ShadowMirror)─> f32 reference outputs
        │                                        │
        └── primary (served variant) outputs ────┤
                                                 v
                    QualityScorer: rolling per-(model × variant) window
                      * online mAP       — eval/detection_map.py COCO
                        math, shadow outputs as the frame's pseudo-GT
                      * velocity MAE     — matched CenterPoint velocity
                        columns (ops/tracking TrackerConfig.velocity_cols)
                      * ID-switch delta  — two ops/tracking
                        ``reference_step`` streams (primary vs shadow),
                        excess track churn per frame
                                                 v
                    QualityGate: window verdict against the precision
                    policy's declared mAP budget (runtime/precision.py
                    ``_MAP_BUDGETS`` — the same numbers the offline
                    parity tests enforce)
                                                 v
                    CanaryController: ``serve --canary v=f`` routes the
                    hash-sliced fraction to the variant; N clean windows
                    promote it to full traffic; one violated window
                    rolls it back (fraction 0, f32 re-pinned,
                    ``TPU_FUSED_KERNELS=0`` when configured, counted +
                    logged with trace exemplars).

Hot-path contract (tpulint ``HOT_PATH_ROOTS`` pins it): ``route`` and
``observe`` are the only methods a serving thread touches — one keyed
hash and at most one ``put_nowait`` each; every numpy call lives on the
mirror's worker thread.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

import numpy as np

from triton_client_tpu.eval.detection_map import (
    Detection3DEvaluator,
    DetectionEvaluator,
)
from triton_client_tpu.eval.shadow import ShadowMirror, slice_decision, sample_decision
from triton_client_tpu.runtime.precision import MAP_BUDGETS

log = logging.getLogger(__name__)

#: compute_ap's 101-pt interpolated ceiling for a perfect detector (the
#: closing sentinel costs half the last 0.01 recall bin) — the score an
#: identical primary/shadow pair attains, and therefore the "no drop"
#: reference the budgets subtract from.
AP_CEILING = 0.995

#: minimum shadow confidence for a detection to count as pseudo-GT
PSEUDO_GT_CONF = 0.05


def precision_of_name(variant: str) -> str:
    """Default variant -> precision-policy resolver: sniff the policy
    name out of the variant's model name (``det_int8w``, ``pp-bf16``,
    ...). Serving stacks with a repository pass a spec-backed resolver
    instead; unknown names conservatively read as f32 (zero budget)."""
    low = variant.lower()
    for policy in ("int8w", "int8", "bf16"):
        if policy in low:
            return policy
    return "f32"


class _TrackStream:
    """One persistent ops/tracking reference stream (primary or shadow
    side of a pair): steps the NumPy mirror tracker and counts track
    births — the churn signal the ID-switch delta is built from."""

    def __init__(self) -> None:
        self._cfg = None
        self._state = None
        self._active: set = set()
        self.births = 0
        self.frames = 0
        self._dead = False

    def step(self, det: np.ndarray, valid: np.ndarray) -> None:
        if self._dead or det.size == 0:
            return
        from triton_client_tpu.ops import tracking

        try:
            if self._state is None:
                self._cfg = tracking.TrackerConfig(
                    max_tracks=64,
                    velocity_cols=(7, 9) if det.shape[1] >= 11 else None,
                )
                self._state = tracking.init_state(
                    self._cfg, det.shape[1], id_base=0
                )
            if det.shape[1] != self._state["tracks"].shape[1]:
                return  # det width changed mid-stream: skip the frame
            self._state, out = tracking.reference_step(
                self._cfg, self._state, det, valid
            )
            ids = np.asarray(out["track_ids"])
            alive = np.asarray(out["tracks_valid"], bool)
            active = set(int(i) for i in ids[alive])
            self.births += len(active - self._active)
            self._active = active
            self.frames += 1
        except Exception:
            # tracking is a best-effort signal: never let it take the
            # mAP/velocity scoring down with it
            self._dead = True
            log.debug("quality tracker stream disabled", exc_info=True)

    def reset_window(self) -> None:
        self.births = 0
        self.frames = 0


class _PairScore:
    """Rolling accumulation for one (model × variant) pair."""

    def __init__(self, window_frames: int, max_windows: int) -> None:
        self.window_frames = max(1, int(window_frames))
        self.evaluator = None  # built lazily: 2D or 3D per output kind
        self.kind = None
        self.vel_abs_err: list[float] = []
        self.track_primary = _TrackStream()
        self.track_shadow = _TrackStream()
        self.frames = 0
        self.scored_total = 0
        self.exemplars: deque = deque(maxlen=8)
        self.windows: deque = deque(maxlen=max(1, int(max_windows)))
        self.last_lag_s = 0.0


def _unbatch(arr: np.ndarray) -> np.ndarray:
    """Drop the unit batch axis serving responses carry: the batcher
    hands each request its own slice, so per-request detection outputs
    arrive as (1, n, k) / (1, n) — the offline shape without the lead."""
    if arr.ndim >= 2 and arr.shape[0] == 1:
        return arr[0]
    return arr


def _packed_2d(outputs) -> tuple[np.ndarray, np.ndarray]:
    """(det, valid) from the 2D packed contract (detections [+valid]),
    batched (1, n, 6+) or bare (n, 6+)."""
    det = _unbatch(np.asarray(outputs["detections"], np.float64))
    if det.ndim != 2 or det.shape[1] < 6:
        raise ValueError(f"packed detections must be (n, 6+): {det.shape}")
    if "valid" in outputs and outputs["valid"] is not None:
        valid = np.asarray(outputs["valid"], bool).reshape(-1)[: len(det)]
    else:
        valid = np.ones(len(det), bool)
    return det, valid


def _rows_3d(outputs) -> np.ndarray:
    """(n, k+2) tracker/score rows from the 3D contract: boxes columns,
    then score, then label — score at column -2 (the packed-row
    convention ops/tracking and the fused decode kernels share)."""
    boxes = _unbatch(np.asarray(outputs["pred_boxes"], np.float64))
    scores = np.asarray(outputs["pred_scores"], np.float64).reshape(-1)
    labels = np.asarray(outputs["pred_labels"], np.float64).reshape(-1)
    n = min(len(boxes), len(scores), len(labels))
    return np.concatenate(
        [boxes[:n], scores[:n, None], labels[:n, None]], axis=1
    )


def _match_velocity_mae(primary: np.ndarray, shadow: np.ndarray) -> list:
    """Per-detection |velocity| error between primary and shadow boxes
    (CenterPoint layout, velocity at columns 7:9), matched greedily by
    BEV center distance. Returns the matched absolute errors."""
    if primary.shape[1] < 9 or shadow.shape[1] < 9:
        return []
    if not len(primary) or not len(shadow):
        return []
    dist = np.linalg.norm(
        primary[:, None, :2] - shadow[None, :, :2], axis=-1
    )
    errs: list[float] = []
    used: set = set()
    for i in np.argsort(dist.min(axis=1)):
        order = np.argsort(dist[i])
        for j in order:
            if j in used:
                continue
            if dist[i, j] > 3.0:
                break
            used.add(int(j))
            errs.append(
                float(np.abs(primary[i, 7:9] - shadow[j, 7:9]).mean())
            )
            break
    return errs


class QualityScorer:
    """Rolling-window primary-vs-shadow scoring over live pairs.

    All methods run on the shadow mirror's worker thread; ``snapshot``
    and ``history_row`` are called from the collector's scrape thread
    under the scorer lock."""

    def __init__(
        self,
        window_frames: int = 32,
        max_windows: int = 64,
        on_window=None,
    ) -> None:
        self._window_frames = max(1, int(window_frames))
        self._max_windows = max(1, int(max_windows))
        self._on_window = on_window
        self._pairs: dict[tuple[str, str], _PairScore] = {}
        self._lock = threading.Lock()
        self._unscorable = 0

    def _pair(self, model: str, variant: str) -> _PairScore:
        key = (model, variant)
        pair = self._pairs.get(key)
        if pair is None:
            pair = _PairScore(self._window_frames, self._max_windows)
            self._pairs[key] = pair
        return pair

    def score_pair(
        self, model, variant, primary_outputs, shadow_outputs, lag_s,
        trace_id,
    ) -> None:
        """Score one sampled frame; roll the window when full."""
        finished = None
        with self._lock:
            pair = self._pair(model, variant)
            try:
                if "detections" in primary_outputs:
                    self._score_2d(pair, primary_outputs, shadow_outputs)
                elif "pred_boxes" in primary_outputs:
                    self._score_3d(pair, primary_outputs, shadow_outputs)
                else:
                    self._unscorable += 1
                    return
            except Exception:
                self._unscorable += 1
                log.debug("unscorable quality frame", exc_info=True)
                return
            pair.frames += 1
            pair.scored_total += 1
            pair.last_lag_s = float(lag_s)
            if trace_id:
                pair.exemplars.append(trace_id)
            if pair.frames >= pair.window_frames:
                finished = self._finalize_window(model, variant, pair)
        if finished is not None and self._on_window is not None:
            self._on_window(model, variant, finished)

    def _score_2d(self, pair, primary_outputs, shadow_outputs) -> None:
        if pair.evaluator is None:
            pair.evaluator, pair.kind = DetectionEvaluator(), "2d"
        pdet, pvalid = _packed_2d(primary_outputs)
        sdet, svalid = _packed_2d(shadow_outputs)
        keep = svalid & (sdet[:, 4] >= PSEUDO_GT_CONF)
        gts = sdet[keep][:, [0, 1, 2, 3, 5]]
        pair.evaluator.add_frame(pdet, pvalid, gts)
        pair.track_primary.step(
            pdet.astype(np.float32), pvalid.astype(bool)
        )
        pair.track_shadow.step(sdet.astype(np.float32), svalid.astype(bool))

    def _score_3d(self, pair, primary_outputs, shadow_outputs) -> None:
        if pair.evaluator is None:
            pair.evaluator, pair.kind = Detection3DEvaluator(), "3d"
        prows = _rows_3d(primary_outputs)
        srows = _rows_3d(shadow_outputs)
        keep = srows[:, -2] >= PSEUDO_GT_CONF
        sboxes = srows[keep]
        # 3D pseudo-GT rows: 7 box columns + class at column 7. The
        # add_frame3d contract takes 1-indexed pred labels (OpenPCDet)
        # but 0-indexed gt classes — shift the shadow labels down.
        gts = np.concatenate([sboxes[:, :7], sboxes[:, -1:] - 1.0], axis=1)
        pboxes = np.asarray(primary_outputs["pred_boxes"], np.float64)
        pair.evaluator.add_frame3d(
            pboxes[:, :7],
            np.asarray(primary_outputs["pred_scores"], np.float64),
            np.asarray(primary_outputs["pred_labels"]),
            gts,
        )
        pair.vel_abs_err.extend(_match_velocity_mae(prows, srows))
        pvalid = np.ones(len(prows), bool)
        svalid = np.ones(len(srows), bool)
        pair.track_primary.step(prows.astype(np.float32), pvalid)
        pair.track_shadow.step(srows.astype(np.float32), svalid)

    def _finalize_window(self, model, variant, pair) -> dict | None:
        summary = pair.evaluator.summary()
        frames = pair.frames
        births_p = pair.track_primary.births
        births_s = pair.track_shadow.births
        window = {
            "t": time.time(),
            "frames": frames,
            "map50": float(summary.get("map50", 0.0)),
            "map": float(summary.get("map", 0.0)),
            "precision": float(summary.get("precision", 0.0)),
            "recall": float(summary.get("recall", 0.0)),
            "f1": float(summary.get("f1", 0.0)),
            "velocity_mae": (
                float(np.mean(pair.vel_abs_err))
                if pair.vel_abs_err else 0.0
            ),
            # excess primary track churn vs the reference stream: a
            # flickering variant births/kills tracks the f32 stream
            # holds stable
            "id_switch_rate": max(0, births_p - births_s) / max(1, frames),
            "gateable": bool(pair.evaluator.frames)
            and any(
                f.conf.size or f.target_cls.size
                for f in pair.evaluator.frames
            ),
            "exemplars": list(pair.exemplars),
        }
        pair.windows.append(window)
        # window reset: evaluator + velocity restart, tracker streams
        # persist (track identity must survive the window boundary)
        pair.evaluator = (
            DetectionEvaluator() if pair.kind == "2d"
            else Detection3DEvaluator()
        )
        pair.vel_abs_err = []
        pair.frames = 0
        pair.track_primary.reset_window()
        pair.track_shadow.reset_window()
        return window

    def snapshot(self) -> dict:
        with self._lock:
            pairs = {}
            for (model, variant), pair in self._pairs.items():
                last = pair.windows[-1] if pair.windows else None
                pairs[f"{model}|{variant}"] = {
                    "kind": pair.kind,
                    "scored_frames": pair.scored_total,
                    "window_frames": pair.frames,
                    "last_lag_s": pair.last_lag_s,
                    "windows": len(pair.windows),
                    "last": (
                        {k: v for k, v in last.items() if k != "exemplars"}
                        if last else None
                    ),
                }
            return {"pairs": pairs, "unscorable": self._unscorable}

    def last_windows(self) -> dict[tuple[str, str], dict]:
        with self._lock:
            return {
                key: pair.windows[-1]
                for key, pair in self._pairs.items()
                if pair.windows
            }

    def scored_totals(self) -> dict[tuple[str, str], tuple[int, float]]:
        """(scored_frames_total, last_lag_s) per pair, for the export."""
        with self._lock:
            return {
                key: (pair.scored_total, pair.last_lag_s)
                for key, pair in self._pairs.items()
            }


class QualityGate:
    """Window verdicts against the precision policy's accuracy budget.

    A window is *clean* when its shadow-relative mAP@0.5 stays above
    ``AP_CEILING * (1 - budget)`` (budget = ``_MAP_BUDGETS`` for the
    variant's precision — the identical numbers the offline parity
    suite enforces), and, when configured, velocity MAE and ID-switch
    rate stay under their ceilings."""

    def __init__(
        self,
        precision_of=None,
        tolerance: float = 0.01,
        velocity_budget: float | None = None,
        id_switch_budget: float | None = None,
    ) -> None:
        self._precision_of = precision_of or precision_of_name
        self._tolerance = float(tolerance)
        self._velocity_budget = velocity_budget
        self._id_switch_budget = id_switch_budget

    def floor_for(self, variant: str) -> float:
        policy = self._precision_of(variant)
        budget = MAP_BUDGETS.get(policy, 0.0)
        return AP_CEILING * (1.0 - budget) - self._tolerance

    def evaluate(self, variant: str, window: dict) -> tuple[bool, str]:
        """(clean, reason). Ungateable windows (nothing detected on
        either side) are clean by definition — absence of evidence
        never trips a rollback."""
        if not window.get("gateable", True):
            return True, "empty window (not gated)"
        floor = self.floor_for(variant)
        if window["map50"] < floor:
            return False, (
                f"map50 {window['map50']:.3f} under budget floor "
                f"{floor:.3f} ({self._precision_of(variant)})"
            )
        if (
            self._velocity_budget is not None
            and window["velocity_mae"] > self._velocity_budget
        ):
            return False, (
                f"velocity_mae {window['velocity_mae']:.3f} over "
                f"{self._velocity_budget:.3f}"
            )
        if (
            self._id_switch_budget is not None
            and window["id_switch_rate"] > self._id_switch_budget
        ):
            return False, (
                f"id_switch_rate {window['id_switch_rate']:.3f} over "
                f"{self._id_switch_budget:.3f}"
            )
        return True, "clean"


class CanaryController:
    """Hash-sliced canary lifecycle, driven by gate verdicts.

    States: ``canary`` (fraction of traffic) -> ``promoted`` (all
    traffic, after ``promote_after`` consecutive clean windows) or
    ``rolled_back`` (zero traffic, first violated window; f32 re-pinned
    and — when ``pin_fused_off`` — the fused-kernel route disabled via
    ``TPU_FUSED_KERNELS=0``, the same env pin the kernel PR documents).
    """

    def __init__(
        self, promote_after: int = 3, pin_fused_off: bool = False
    ) -> None:
        self._promote_after = max(1, int(promote_after))
        self._pin_fused_off = bool(pin_fused_off)
        self._lock = threading.Lock()
        self._by_model: dict[str, dict] = {}
        self.promotions = 0
        self.rollbacks = 0

    def set_canary(self, model: str, variant: str, fraction: float) -> None:
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1]: {fraction}")
        with self._lock:
            self._by_model[model] = {
                "variant": variant,
                "fraction": float(fraction),
                "initial_fraction": float(fraction),
                "state": "canary",
                "clean_windows": 0,
                "served_variant": 0,
                "served_primary": 0,
                "since": time.time(),
                "reason": "",
                "exemplars": [],
            }
        log.info(
            "canary armed: %s -> %s at %.1f%% of traffic",
            model, variant, fraction * 100.0,
        )

    def clear(self, model: str) -> None:
        with self._lock:
            self._by_model.pop(model, None)

    # -- hot path (rooted in tpulint HOT_PATH_ROOTS) --------------------------

    def route(self, model: str, trace_id: str) -> str:
        """Serving decision for one request: the variant when the
        request's hash falls in the canary slice (or the canary is
        promoted), else the primary. One dict probe + one keyed hash."""
        c = self._by_model.get(model)
        if c is None:
            return model
        state = c["state"]
        if state == "promoted":
            c["served_variant"] += 1
            return c["variant"]
        if state != "canary":
            c["served_primary"] += 1
            return model
        if slice_decision(trace_id, c["fraction"]):
            c["served_variant"] += 1
            return c["variant"]
        c["served_primary"] += 1
        return model

    # -- gate feedback --------------------------------------------------------

    def on_window(
        self, model: str, variant: str, window: dict, clean: bool,
        reason: str,
    ) -> None:
        with self._lock:
            c = self._by_model.get(model)
            if c is None or c["variant"] != variant:
                return
            if c["state"] != "canary":
                return
            if clean:
                c["clean_windows"] += 1
                if c["clean_windows"] >= self._promote_after:
                    c["state"] = "promoted"
                    c["fraction"] = 1.0
                    c["reason"] = (
                        f"{c['clean_windows']} clean windows"
                    )
                    self.promotions += 1
                    log.info(
                        "canary PROMOTED: %s -> %s now takes full "
                        "traffic (%s)", model, variant, c["reason"],
                    )
                return
            c["state"] = "rolled_back"
            c["fraction"] = 0.0
            c["clean_windows"] = 0
            c["reason"] = reason
            c["exemplars"] = list(window.get("exemplars") or [])[-5:]
            self.rollbacks += 1
            if self._pin_fused_off:
                os.environ["TPU_FUSED_KERNELS"] = "0"
            log.warning(
                "canary ROLLED BACK: %s re-pinned to f32 primary, "
                "variant %s ejected (%s)%s; trace exemplars: %s",
                model, variant, reason,
                " + TPU_FUSED_KERNELS=0" if self._pin_fused_off else "",
                ",".join(c["exemplars"]) or "-",
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "models": {
                    model: dict(c) for model, c in self._by_model.items()
                },
            }


def parse_canary_spec(spec: str) -> tuple[str | None, str, float]:
    """``[primary:]variant=fraction`` -> (primary | None, variant,
    fraction). The one-argument ``serve --canary det_int8=0.05`` form
    infers the primary from the variant name (longest strict prefix up
    to a separator); the explicit ``det:det_int8=0.05`` form overrides.
    """
    body, eq, frac = spec.partition("=")
    if not eq:
        raise ValueError(
            f"canary spec must be [primary:]variant=fraction: {spec!r}"
        )
    fraction = float(frac)
    primary, colon, variant = body.partition(":")
    if colon:
        return primary, variant, fraction
    return None, body, fraction


def infer_primary(variant: str, model_names) -> str | None:
    """Longest registered model name that is a strict prefix of the
    variant at a separator (``det_int8`` -> ``det``)."""
    best = None
    for name in model_names:
        if variant != name and variant.startswith(name):
            sep = variant[len(name): len(name) + 1]
            if sep in ("_", "-", ".", "@"):
                if best is None or len(name) > len(best):
                    best = name
    return best


class QualityPlane:
    """Facade the server/router wire in: sampling + mirroring + scoring
    + gate + canary lifecycle, one object.

    Hot-path surface: :meth:`route` (canary decision) and
    :meth:`observe` (sample decision + queue hand-off). Everything else
    runs on the mirror worker or the scrape thread."""

    def __init__(
        self,
        channel=None,
        sample_rate: float = 0.05,
        window_frames: int = 32,
        promote_after: int = 3,
        reference_for=None,
        precision_of=None,
        queue_depth: int = 256,
        pin_fused_off: bool = False,
        velocity_budget: float | None = None,
        id_switch_budget: float | None = None,
        max_windows: int = 64,
    ) -> None:
        self._sample_rate = float(sample_rate)
        self.scorer = QualityScorer(
            window_frames=window_frames,
            max_windows=max_windows,
            on_window=self._on_window,
        )
        self.gate = QualityGate(
            precision_of=precision_of,
            velocity_budget=velocity_budget,
            id_switch_budget=id_switch_budget,
        )
        self.canary = CanaryController(
            promote_after=promote_after, pin_fused_off=pin_fused_off
        )
        self.mirror = ShadowMirror(
            channel=channel,
            score=self.scorer.score_pair,
            reference_for=reference_for,
            queue_depth=queue_depth,
        )
        self._observed = 0
        self._sampled = 0
        self.legacy_exporter = None  # optional EvalPrometheusExporter
        self.temporal = None  # optional runtime.temporal.TemporalReusePlane

    # -- wiring ---------------------------------------------------------------

    def attach_channel(self, channel) -> None:
        self.mirror.attach_channel(channel)

    def attach_temporal(self, temporal) -> None:
        """Quality-gate the temporal-reuse plane (ISSUE 19): a dirty
        rolling window on a model disables its frame-skipping shortcuts
        the same way a canary rolls back — the coast path can never
        silently spend tracking quality."""
        self.temporal = temporal

    def attach_legacy_exporter(self, exporter) -> None:
        """Satellite 1: the folded legacy eval Summaries (model_precision
        / model_recall / model_ap / model_f1) observe each finished
        window, so the reference's spelling and the ``tpu_quality_*``
        families read off one registry."""
        self.legacy_exporter = exporter

    def set_canary(
        self, model: str, variant: str, fraction: float
    ) -> None:
        self.canary.set_canary(model, variant, fraction)

    def set_sample_rate(self, rate: float) -> None:
        self._sample_rate = float(rate)

    @property
    def sample_rate(self) -> float:
        return self._sample_rate

    # -- hot path (rooted in tpulint HOT_PATH_ROOTS) --------------------------

    def route(self, model: str, trace_id: str) -> str:
        return self.canary.route(model, trace_id)

    def observe(
        self, model, served_model, trace_id, inputs, outputs
    ) -> bool:
        """Post-serve hook: one keyed hash; sampled requests hand their
        (already host-resident) inputs + outputs to the mirror queue."""
        self._observed += 1
        if not sample_decision(trace_id, self._sample_rate):
            return False
        self._sampled += 1
        return self.mirror.enqueue(
            model, served_model, inputs, outputs, trace_id
        )

    # -- gate plumbing --------------------------------------------------------

    def _on_window(self, model: str, variant: str, window: dict) -> None:
        clean, reason = self.gate.evaluate(variant, window)
        self.canary.on_window(model, variant, window, clean, reason)
        if not clean and self.temporal is not None and variant == model:
            # the PRIMARY path's own online quality regressed (not a
            # canary variant's): stop trading accuracy for throughput
            # on this model until an operator re-enables reuse
            try:
                self.temporal.note_quality_violation(model)
            except Exception:
                log.debug("temporal quality gate failed", exc_info=True)
        exporter = self.legacy_exporter
        if exporter is not None:
            try:
                exporter.observe_window(window)
            except Exception:
                log.debug("legacy eval export failed", exc_info=True)

    # -- export ---------------------------------------------------------------

    def snapshot(self) -> dict:
        snap = self.scorer.snapshot()
        snap["sample_rate"] = self._sample_rate
        snap["observed"] = self._observed
        snap["sampled"] = self._sampled
        snap["mirror"] = self.mirror.stats()
        snap["canary"] = self.canary.stats()
        return snap

    stats = snapshot

    def history_row(self) -> dict:
        """Compact per-pair last-window metrics for the obs/history
        ring — quality trends persist across drain/restart next to the
        rate/MFU rows."""
        row = {}
        for (model, variant), window in self.scorer.last_windows().items():
            row[f"{model}|{variant}"] = {
                "map50": window["map50"],
                "map": window["map"],
                "velocity_mae": window["velocity_mae"],
                "id_switch_rate": window["id_switch_rate"],
            }
        return row

    def drain(self, timeout_s: float = 5.0) -> bool:
        return self.mirror.drain(timeout_s)

    def close(self) -> None:
        self.mirror.close()
