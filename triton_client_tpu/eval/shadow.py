"""Shadow mirroring for the continuous quality plane (ISSUE 17).

Two jobs, deliberately split from the scoring math in
``quality_plane.py``:

  * **Deterministic sampling** — :func:`sample_decision` and
    :func:`slice_decision` reduce a PR-11 trace id to a uniform
    ``[0, 1)`` point with the same keyed ``blake2b`` construction the
    router's rendezvous affinity uses (``_rendezvous_score``), so a
    router, a replica, and an offline replayer all agree on which
    requests are sampled (and which ride the canary slice) with **no
    coordination** and no shared RNG state. The two decisions hash in
    different domains (a salt prefix), so the canary slice and the
    shadow sample are statistically independent.

  * **Shadow dispatch** — :class:`ShadowMirror` re-issues sampled
    requests against the f32 reference (or a named candidate variant)
    through any ``BaseChannel``-shaped handle — the ``FrontDoorRouter``
    in a fleet, the server's own channel stack in a single-process
    deployment — on a bounded background worker. The primary serving
    path pays exactly one hash + one ``put_nowait``; a full queue
    drops the sample (counted) rather than ever back-pressuring the
    request thread.

The ``quality_corrupt`` fault point (runtime/faults.py) is probed here,
on the worker: when armed for the served variant it perturbs the
primary detections deterministically (RNG seeded from the trace id)
before scoring, so CI can drive the auto-rollback path without a
genuinely broken quantization.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time

import numpy as np

from triton_client_tpu.runtime import faults

log = logging.getLogger(__name__)

_HASH_SPAN = float(2**64)


def _unit_hash(key: str) -> float:
    """Map ``key`` to a uniform point in ``[0, 1)`` — pure, stateless,
    process-independent (``hashlib``, never Python's salted ``hash``)."""
    h = hashlib.blake2b(key.encode("utf-8", "replace"), digest_size=8)
    return int.from_bytes(h.digest(), "big") / _HASH_SPAN


def sample_decision(trace_id: str, rate: float) -> bool:
    """Should this request be shadow-scored? Pure function of the trace
    id: every process holding the same id reaches the same verdict."""
    if rate <= 0.0 or not trace_id:
        return False
    if rate >= 1.0:
        return True
    return _unit_hash(f"shadow|{trace_id}") < rate


def slice_decision(trace_id: str, fraction: float) -> bool:
    """Does this request ride the canary slice? Hashes in a distinct
    domain from :func:`sample_decision` so the canary's traffic is
    sampled at the same rate as the primary's."""
    if fraction <= 0.0 or not trace_id:
        return False
    if fraction >= 1.0:
        return True
    return _unit_hash(f"canary|{trace_id}") < fraction


def corrupt_detections(outputs: dict, trace_id: str) -> dict:
    """The ``quality_corrupt`` payload: a deterministic, unmistakably
    out-of-budget perturbation of a detection output mapping (2D packed
    ``detections`` or 3D ``pred_boxes``), seeded from the trace id so
    identical drives corrupt identically."""
    seed = int(_unit_hash(f"corrupt|{trace_id}") * 2**31)
    rng = np.random.default_rng(seed)
    out = dict(outputs)
    if "detections" in out:
        det = np.array(out["detections"], np.float32, copy=True)
        if det.ndim == 3 and det.shape[0] == 1:
            det = det[0]  # serving responses carry a unit batch axis
        if det.ndim == 2 and det.shape[1] >= 6 and det.shape[0]:
            # shove every box far off its truth and scramble the class
            det[:, :4] += rng.uniform(50.0, 200.0, (det.shape[0], 4))
            det[:, 5] = (det[:, 5] + 1 + rng.integers(0, 3, det.shape[0])) % 7
        out["detections"] = det
    if "pred_boxes" in out:
        boxes = np.array(out["pred_boxes"], np.float32, copy=True)
        if boxes.ndim == 3 and boxes.shape[0] == 1:
            boxes = boxes[0]
        if boxes.ndim == 2 and boxes.shape[0]:
            boxes[:, :3] += rng.uniform(5.0, 20.0, (boxes.shape[0], 3))
            if boxes.shape[1] >= 9:
                boxes[:, 7:9] += rng.uniform(3.0, 9.0, (boxes.shape[0], 2))
        out["pred_boxes"] = boxes
    return out


class ShadowMirror:
    """Bounded-queue shadow dispatcher.

    ``channel``: anything quacking ``do_inference`` (FrontDoorRouter,
    a channel stack). ``score``: callback
    ``(model, variant, primary_outputs, shadow_outputs, lag_s,
    trace_id)`` — the quality plane's scorer. ``reference_for``: maps a
    primary model name to the shadow/reference model name (identity by
    default: the primary registration IS the f32 reference).
    """

    def __init__(
        self,
        channel=None,
        score=None,
        reference_for=None,
        queue_depth: int = 256,
    ) -> None:
        self._channel = channel
        self._score = score
        self._reference_for = reference_for or (lambda model: model)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._lock = threading.Lock()
        self._mirrored = 0
        self._dropped = 0
        self._scored = 0
        self._errors = 0
        self._corrupted = 0
        self._last_lag_s = 0.0
        self._lag_sum = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="quality-shadow"
        )
        self._started = False

    def attach_channel(self, channel) -> None:
        """Late-bind the shadow dispatch handle (the server builds the
        plane before its channel stack exists)."""
        self._channel = channel

    # -- hot-path seam (rooted in tpulint HOT_PATH_ROOTS) ---------------------

    def enqueue(self, model, variant, inputs, outputs, trace_id) -> bool:
        """Hand one sampled request to the worker. Never blocks, never
        raises, never touches the arrays: a full queue drops the sample
        and counts it."""
        if self._closed:
            return False
        if not self._started:
            self._start()
        try:
            self._q.put_nowait(
                (model, variant, inputs, outputs, trace_id,
                 time.perf_counter())
            )
        except queue.Full:
            with self._lock:
                self._dropped += 1
            return False
        with self._lock:
            self._mirrored += 1
        return True

    # -- worker ---------------------------------------------------------------

    def _start(self) -> None:
        with self._lock:
            if not self._started:
                self._thread.start()
                self._started = True

    def _run(self) -> None:
        from triton_client_tpu.channel.base import InferRequest

        while True:
            item = self._q.get()
            if item is None:
                return
            model, variant, inputs, outputs, trace_id, t0 = item
            try:
                reference = self._reference_for(model)
                if self._channel is not None and variant != reference:
                    resp = self._channel.do_inference(
                        InferRequest(model_name=reference, inputs=inputs)
                    )
                    shadow_outputs = resp.outputs
                else:
                    # primary == reference (no canary in flight): the
                    # served outputs ARE the reference — scoring them
                    # against themselves keeps the window machinery,
                    # lag accounting, and export live at zero extra
                    # device cost
                    shadow_outputs = outputs
                if faults.probe_flag("quality_corrupt", variant):
                    outputs = corrupt_detections(outputs, trace_id)
                    with self._lock:
                        self._corrupted += 1
                lag_s = time.perf_counter() - t0
                if self._score is not None:
                    self._score(
                        model, variant, outputs, shadow_outputs, lag_s,
                        trace_id,
                    )
                with self._lock:
                    self._scored += 1
                    self._last_lag_s = lag_s
                    self._lag_sum += lag_s
            except Exception:
                with self._lock:
                    self._errors += 1
                log.debug(
                    "shadow scoring failed for model %s variant %s",
                    model, variant, exc_info=True,
                )

    def stats(self) -> dict:
        with self._lock:
            scored = self._scored
            return {
                "mirrored": self._mirrored,
                "dropped": self._dropped,
                "scored": scored,
                "errors": self._errors,
                "corrupted": self._corrupted,
                "queue_depth": self._q.qsize(),
                "last_lag_s": self._last_lag_s,
                "mean_lag_s": (self._lag_sum / scored) if scored else 0.0,
            }

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Test/ops helper: wait for the queue to empty (the worker may
        still be scoring its in-hand item for one scheduling quantum)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty():
                return True
            time.sleep(0.005)
        return self._q.empty()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._started:
            self._q.put(None)
            self._thread.join(timeout=5.0)
