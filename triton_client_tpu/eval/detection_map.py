"""Detection mAP evaluation (COCO 101-point interpolated AP).

Behavioral parity with the reference's online evaluator
(communicator/evaluate_inference.py): ``compute_ap`` is the 101-pt
interpolated AP (:131-156), ``ap_per_class`` the per-class P/R/AP/F1
curves reported at the max-F1 operating point (:158-218), and
``match_predictions`` the greedy unique IoU matching at 10 thresholds
0.5:0.05:0.95 (:400-446). The reference runs this math through torch
tensors inside a ROS callback; here it is torch-free numpy driven by
the evaluation driver (host-side bookkeeping — the TPU does detection,
the host does the running score).

Matching subtlety kept bit-identical: candidate (gt, det) pairs are
sorted by IoU descending ONCE, then deduped by detection column, then
deduped by gt column WITHOUT re-sorting (the reference's second
argsort is commented out, evaluate_inference.py:422) — np.unique
returns first occurrences, which after the desc sort are the
highest-IoU pair per index.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# IoU thresholds 0.5:0.05:0.95 (evaluate_inference.py:411).
IOU_THRESHOLDS = np.linspace(0.5, 0.95, 10)


def box_iou_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Pairwise IoU of (N, 4) x (M, 4) xyxy boxes -> (N, M)."""
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * np.clip(b[:, 3] - b[:, 1], 0, None)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-16)


def compute_ap(recall: np.ndarray, precision: np.ndarray) -> float:
    """Average precision from raw recall/precision curves (COCO 101-pt
    interpolation, evaluate_inference.py:131-156)."""
    mrec = np.concatenate(([0.0], recall, [1.0]))
    mpre = np.concatenate(([1.0], precision, [0.0]))
    mpre = np.flip(np.maximum.accumulate(np.flip(mpre)))
    x = np.linspace(0, 1, 101)
    integrate = getattr(np, "trapezoid", np.trapz)
    return float(integrate(np.interp(x, mrec, mpre), x))


def ap_per_class(
    tp: np.ndarray,
    conf: np.ndarray,
    pred_cls: np.ndarray,
    target_cls: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-class precision/recall/AP/F1 (evaluate_inference.py:158-218).

    Args:
      tp: (n_pred, n_iou) bool true-positive matrix from
        ``match_predictions``.
      conf: (n_pred,) confidences.
      pred_cls: (n_pred,) predicted class ids.
      target_cls: (n_gt,) ground-truth class ids.

    Returns:
      (p, r, ap, f1, unique_classes): p/r/f1 are (nc,) at the max-F1
      operating point; ap is (nc, n_iou); unique_classes is (nc,) int32
      over classes present in the ground truth.
    """
    tp = np.atleast_2d(np.asarray(tp, dtype=np.float64))
    order = np.argsort(-conf)
    tp, conf, pred_cls = tp[order], conf[order], pred_cls[order]

    unique_classes = np.unique(target_cls)
    nc = unique_classes.shape[0]
    n_iou = tp.shape[1]

    px = np.linspace(0, 1, 1000)
    ap = np.zeros((nc, n_iou))
    p = np.zeros((nc, 1000))
    r = np.zeros((nc, 1000))
    for ci, c in enumerate(unique_classes):
        mask = pred_cls == c
        n_labels = int((target_cls == c).sum())
        if not mask.any() or n_labels == 0:
            continue
        fpc = (1.0 - tp[mask]).cumsum(0)
        tpc = tp[mask].cumsum(0)
        recall = tpc / (n_labels + 1e-16)
        precision = tpc / (tpc + fpc)
        # curves sampled on a fixed 1000-pt confidence grid (conf
        # decreases along the curve, hence the negated interp).
        r[ci] = np.interp(-px, -conf[mask], recall[:, 0], left=0)
        p[ci] = np.interp(-px, -conf[mask], precision[:, 0], left=1)
        for j in range(n_iou):
            ap[ci, j] = compute_ap(recall[:, j], precision[:, j])

    f1 = 2 * p * r / (p + r + 1e-16)
    best = int(f1.mean(0).argmax())
    return p[:, best], r[:, best], ap, f1[:, best], unique_classes.astype(np.int32)


def greedy_match(
    iou: np.ndarray,  # (n_gt, n_pred)
    gt_cls: np.ndarray,
    pred_cls: np.ndarray,
    iou_thresholds: np.ndarray,
) -> np.ndarray:
    """Greedy unique TP matrix from a precomputed IoU matrix — the
    matching core shared by the 2D (axis-aligned) and 3D (rotated BEV)
    evaluators. Candidates need IoU >= thresholds[0] and matching
    class; pairs assign best-IoU-first, one det per gt and one gt per
    det; a matched det is TP at every threshold its IoU clears."""
    n_pred, n_iou = iou.shape[1], len(iou_thresholds)
    correct = np.zeros((n_pred, n_iou), dtype=bool)
    candidate = (iou >= iou_thresholds[0]) & (
        np.asarray(gt_cls)[:, None] == np.asarray(pred_cls)[None, :]
    )
    gt_idx, det_idx = np.nonzero(candidate)
    if gt_idx.shape[0] == 0:
        return correct
    matches = np.stack([gt_idx, det_idx, iou[gt_idx, det_idx]], axis=1)
    if matches.shape[0] > 1:
        matches = matches[matches[:, 2].argsort()[::-1]]
        matches = matches[np.unique(matches[:, 1], return_index=True)[1]]
        matches = matches[np.unique(matches[:, 0], return_index=True)[1]]
    det = matches[:, 1].astype(int)
    correct[det] = matches[:, 2:3] >= iou_thresholds[None, :]
    return correct


def match_predictions(
    pred_boxes: np.ndarray,
    pred_cls: np.ndarray,
    gt_boxes: np.ndarray,
    gt_cls: np.ndarray,
    iou_thresholds: np.ndarray = IOU_THRESHOLDS,
) -> np.ndarray:
    """Greedy unique matching of one frame's predictions to GT.

    Parity with evaluate_inference.py:400-446 (see greedy_match).
    Returns: (n_pred, n_iou) bool TP matrix.
    """
    n_pred, n_iou = pred_boxes.shape[0], len(iou_thresholds)
    if n_pred == 0 or gt_boxes.shape[0] == 0:
        return np.zeros((n_pred, n_iou), dtype=bool)
    iou = box_iou_np(gt_boxes[:, :4], pred_boxes[:, :4])
    return greedy_match(iou, gt_cls, pred_cls, iou_thresholds)


@dataclasses.dataclass
class FrameStats:
    """One frame's matching result, the unit of accumulation."""

    correct: np.ndarray  # (n_pred, n_iou) bool
    conf: np.ndarray  # (n_pred,)
    pred_cls: np.ndarray  # (n_pred,)
    target_cls: np.ndarray  # (n_gt,)


class DetectionEvaluator:
    """Accumulating detection evaluator (the reference's
    EvaluateInference metric core, decoupled from ROS topics).

    Usage: ``add_frame(dets, valid, gts)`` per frame, then ``summary()``
    for aggregate P/R/mAP@0.5/mAP@0.5:0.95/F1. ``observe_prometheus``
    optionally pushes per-class Summaries, parity with the reference's
    port-7658 exporter (evaluate_inference.py:52-61,437-444).
    """

    def __init__(self, iou_thresholds: np.ndarray = IOU_THRESHOLDS) -> None:
        self.iou_thresholds = np.asarray(iou_thresholds)
        self.frames: list[FrameStats] = []

    def add_frame(
        self,
        detections: np.ndarray,
        valid: np.ndarray | None,
        ground_truths: np.ndarray,
    ) -> FrameStats:
        """detections: (max_det, 6) packed [x1, y1, x2, y2, conf, cls]
        rows (+ optional validity mask); ground_truths: (n_gt, 5)
        [x1, y1, x2, y2, cls]."""
        detections = np.asarray(detections)
        if valid is not None:
            detections = detections[np.asarray(valid, dtype=bool)]
        ground_truths = np.asarray(ground_truths).reshape(-1, 5)
        stats = FrameStats(
            correct=match_predictions(
                detections[:, :4],
                detections[:, 5],
                ground_truths[:, :4],
                ground_truths[:, 4],
                self.iou_thresholds,
            ),
            conf=detections[:, 4],
            pred_cls=detections[:, 5],
            target_cls=ground_truths[:, 4],
        )
        self.frames.append(stats)
        return stats

    def summary(self) -> dict[str, float | dict[int, float]]:
        """Aggregate over all frames (the standard eval protocol; the
        reference additionally re-runs ap_per_class per frame, which
        ``per_frame_summaries`` reproduces for the Prometheus path)."""
        if not self.frames:
            return {
                "frames": 0,
                "precision": 0.0,
                "recall": 0.0,
                "f1": 0.0,
                "map50": 0.0,
                "map": 0.0,
                "per_class_ap50": {},
            }
        correct = np.concatenate([f.correct for f in self.frames])
        conf = np.concatenate([f.conf for f in self.frames])
        pred_cls = np.concatenate([f.pred_cls for f in self.frames])
        target_cls = np.concatenate([f.target_cls for f in self.frames])
        p, r, ap, f1, classes = ap_per_class(correct, conf, pred_cls, target_cls)
        return {
            "frames": len(self.frames),
            "precision": float(p.mean()) if p.size else 0.0,
            "recall": float(r.mean()) if r.size else 0.0,
            "f1": float(f1.mean()) if f1.size else 0.0,
            "map50": float(ap[:, 0].mean()) if ap.size else 0.0,
            "map": float(ap.mean()) if ap.size else 0.0,
            "per_class_ap50": {
                int(c): float(ap[i, 0]) for i, c in enumerate(classes)
            },
        }

    def add_frame_from(self, outputs, ground_truths) -> FrameStats:
        """Driver-facing adapter: score one frame from the infer fn's
        output mapping (the 2D contract: packed detections + valid)."""
        return self.add_frame(
            np.asarray(outputs["detections"]),
            np.asarray(outputs["valid"]) if "valid" in outputs else None,
            ground_truths,
        )

    def per_frame_summaries(self):
        """Yield (p, r, ap, f1, classes) per frame — what the reference
        observes into its Prometheus Summaries frame by frame."""
        for f in self.frames:
            yield ap_per_class(f.correct, f.conf, f.pred_cls, f.target_cls)


# --------------------------------------------------------------------------
# 3D (BEV rotated-IoU) evaluation
# --------------------------------------------------------------------------

def _rect_corners_np(boxes: np.ndarray) -> np.ndarray:
    """(N, 5) [cx, cy, dx, dy, yaw] -> (N, 4, 2) CCW corners."""
    c, s = np.cos(boxes[:, 4]), np.sin(boxes[:, 4])
    hx, hy = boxes[:, 2] / 2, boxes[:, 3] / 2
    local = np.stack(
        [
            np.stack([hx, hy], -1),
            np.stack([-hx, hy], -1),
            np.stack([-hx, -hy], -1),
            np.stack([hx, -hy], -1),
        ],
        axis=1,
    )  # (N, 4, 2)
    rot = np.stack(
        [np.stack([c, -s], -1), np.stack([s, c], -1)], axis=1
    )  # (N, 2, 2)
    return np.einsum("nij,nkj->nki", rot, local) + boxes[:, None, :2]


def _clip_polygon_np(poly: np.ndarray, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sutherland-Hodgman: keep the half-plane left of edge a->b."""
    if len(poly) == 0:
        return poly
    edge = b - a
    rel = poly - a
    side = edge[0] * rel[:, 1] - edge[1] * rel[:, 0]
    out = []
    n = len(poly)
    for i in range(n):
        j = (i + 1) % n
        if side[i] >= 0:
            out.append(poly[i])
            if side[j] < 0:
                t = side[i] / (side[i] - side[j])
                out.append(poly[i] + t * (poly[j] - poly[i]))
        elif side[j] >= 0:
            t = side[i] / (side[i] - side[j])
            out.append(poly[i] + t * (poly[j] - poly[i]))
    return np.asarray(out) if out else np.zeros((0, 2))


def _polygon_area_np(poly: np.ndarray) -> float:
    if len(poly) < 3:
        return 0.0
    x, y = poly[:, 0], poly[:, 1]
    return 0.5 * abs(
        float(np.dot(x, np.roll(y, -1)) - np.dot(y, np.roll(x, -1)))
    )


def rotated_bev_iou_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Pairwise rotated BEV IoU of (N, 5) x (M, 5) [cx, cy, dx, dy,
    yaw] boxes -> (N, M). Host-side eval oracle, numpy-only — kept
    independent of the jax kernel (ops/boxes3d.rotated_iou_bev) so the
    evaluator can cross-check the compiled path."""
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    ca, cb = _rect_corners_np(a), _rect_corners_np(b)
    area_a = a[:, 2] * a[:, 3]
    area_b = b[:, 2] * b[:, 3]
    out = np.zeros((len(a), len(b)))
    for i in range(len(a)):
        for j in range(len(b)):
            # cheap reject: circumscribed circles disjoint
            if np.hypot(*(a[i, :2] - b[j, :2])) > (
                np.hypot(a[i, 2], a[i, 3]) + np.hypot(b[j, 2], b[j, 3])
            ) / 2:
                continue
            poly = ca[i]
            for k in range(4):
                poly = _clip_polygon_np(poly, cb[j][k], cb[j][(k + 1) % 4])
            inter = _polygon_area_np(poly)
            union = area_a[i] + area_b[j] - inter
            if union > 0:
                out[i, j] = inter / union
    return out


class Detection3DEvaluator(DetectionEvaluator):
    """mAP for 7-dof boxes matched by rotated BEV IoU — the 3D accuracy
    loop the reference runs only for 2D (its 3D path has no evaluator;
    this closes that gap with the same P/R/AP/F1 protocol). Ground
    truths are (n_gt, 8) [cx, cy, cz, dx, dy, dz, yaw, cls]."""

    def add_frame3d(
        self,
        pred_boxes: np.ndarray,   # (n, 7)
        pred_scores: np.ndarray,  # (n,)
        pred_labels: np.ndarray,  # (n,) 1-indexed (OpenPCDet contract)
        ground_truths: np.ndarray,  # (m, 8), cls 0-indexed
    ) -> FrameStats:
        pred_boxes = np.asarray(pred_boxes, np.float64).reshape(-1, 7)
        gts = np.asarray(ground_truths, np.float64)
        # 10-column rows carry [vx, vy] velocity labels (multi-sweep
        # gt3d, io/synthdata.py) — the box metric ignores them
        if gts.ndim != 2 or gts.size == 0:
            gts = gts.reshape(-1, 8)
        if gts.shape[1] not in (8, 10):
            raise ValueError(
                f"ground_truths must have 8 or 10 columns, got {gts.shape[1]}"
            )
        gts = gts[:, :8]
        pred_cls = np.asarray(pred_labels, np.int64) - 1
        if len(pred_boxes) and len(gts):
            iou = rotated_bev_iou_np(
                gts[:, [0, 1, 3, 4, 6]], pred_boxes[:, [0, 1, 3, 4, 6]]
            )
            correct = greedy_match(
                iou, gts[:, 7].astype(np.int64), pred_cls, self.iou_thresholds
            )
        else:
            correct = np.zeros(
                (len(pred_boxes), len(self.iou_thresholds)), dtype=bool
            )
        stats = FrameStats(
            correct=correct,
            conf=np.asarray(pred_scores, np.float64),
            pred_cls=pred_cls,
            target_cls=gts[:, 7].astype(np.int64),
        )
        self.frames.append(stats)
        return stats

    def add_frame_from(self, outputs, ground_truths) -> FrameStats:
        """Driver-facing adapter over the 3D infer contract
        (pred_boxes/pred_scores/pred_labels)."""
        return self.add_frame3d(
            outputs["pred_boxes"],
            outputs["pred_scores"],
            outputs["pred_labels"],
            ground_truths,
        )
