"""Evaluation: online detection mAP (COCO 101-pt), metric export, and
the continuous quality plane (shadow scoring + canary gating)."""

from triton_client_tpu.eval.detection_map import (
    Detection3DEvaluator,
    DetectionEvaluator,
    ap_per_class,
    compute_ap,
    match_predictions,
)
from triton_client_tpu.eval.quality_plane import (
    CanaryController,
    QualityGate,
    QualityPlane,
    QualityScorer,
)
from triton_client_tpu.eval.shadow import (
    ShadowMirror,
    sample_decision,
    slice_decision,
)

__all__ = [
    "CanaryController",
    "Detection3DEvaluator",
    "DetectionEvaluator",
    "QualityGate",
    "QualityPlane",
    "QualityScorer",
    "ShadowMirror",
    "ap_per_class",
    "compute_ap",
    "match_predictions",
    "sample_decision",
    "slice_decision",
]
