"""Evaluation: online detection mAP (COCO 101-pt) + metric export."""

from triton_client_tpu.eval.detection_map import (
    DetectionEvaluator,
    ap_per_class,
    compute_ap,
    match_predictions,
)

__all__ = [
    "DetectionEvaluator",
    "ap_per_class",
    "compute_ap",
    "match_predictions",
]
