"""Prometheus export for the online evaluator.

Parity with the reference's client-side exporter: five Summary metrics
served from an HTTP endpoint on port 7658
(communicator/evaluate_inference.py:52-61), observed per evaluated
frame (:437-444). Import of prometheus_client is gated the same way the
reference gates its optional deps (communicator/__init__.py:5-8):
constructing the exporter without the package raises, and
``available()`` lets drivers degrade gracefully.
"""

from __future__ import annotations

import numpy as np

try:
    import prometheus_client

    _HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover - environment without the dep
    prometheus_client = None
    _HAVE_PROMETHEUS = False

DEFAULT_PORT = 7658


def available() -> bool:
    return _HAVE_PROMETHEUS


class EvalPrometheusExporter:
    """Five Summaries (precision/recall/ap/f1/ap_class), one HTTP port."""

    def __init__(self, port: int = DEFAULT_PORT, start_server: bool = True) -> None:
        if not _HAVE_PROMETHEUS:
            raise ImportError("prometheus_client is not installed")
        registry = prometheus_client.CollectorRegistry()
        self.registry = registry
        s = prometheus_client.Summary
        self.p_summary = s("model_precision", "per-class precision", registry=registry)
        self.r_summary = s("model_recall", "per-class recall", registry=registry)
        self.ap_summary = s("model_ap", "per-class AP@0.5", registry=registry)
        self.f1_summary = s("model_f1", "per-class F1", registry=registry)
        self.ap_class_summary = s(
            "model_ap_class", "class ids contributing AP", registry=registry
        )
        if start_server:
            prometheus_client.start_http_server(port, registry=registry)

    def observe(self, p, r, ap, f1, classes) -> None:
        """Observe one ap_per_class result, value-by-value as the
        reference does (evaluate_inference.py:440-444)."""
        for v in np.atleast_1d(p):
            self.p_summary.observe(float(v))
        for v in np.atleast_1d(r):
            self.r_summary.observe(float(v))
        ap = np.atleast_2d(ap)
        for v in ap[:, 0] if ap.size else ():
            self.ap_summary.observe(float(v))
        for v in np.atleast_1d(f1):
            self.f1_summary.observe(float(v))
        for v in np.atleast_1d(classes):
            self.ap_class_summary.observe(float(v))
