"""Prometheus export for the online evaluator.

Parity with the reference's client-side exporter: five Summary metrics
(communicator/evaluate_inference.py:52-61), observed per evaluated
frame (:437-444). Import of prometheus_client is gated the same way the
reference gates its optional deps (communicator/__init__.py:5-8):
constructing the exporter without the package raises, and
``available()`` lets drivers degrade gracefully.

ISSUE 17 folds this exporter into the runtime scrape plane: pass
``registry=`` (the ``RuntimeCollector``'s registry) and the Summaries
register **there** — one scrape endpoint, the legacy spellings
(``model_precision`` / ``model_recall`` / ``model_ap`` / ``model_f1`` /
``model_ap_class``) served next to the ``tpu_quality_*`` families with
no dual-registry drift. The original standalone form (own registry, own
HTTP server on port 7658) still works as a deprecation shim for the
``evaluate`` CLI's ``--prometheus-port`` flag, but warns: new
deployments should scrape the telemetry port.
"""

from __future__ import annotations

import warnings

import numpy as np

try:
    import prometheus_client

    _HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover - environment without the dep
    prometheus_client = None
    _HAVE_PROMETHEUS = False

DEFAULT_PORT = 7658


def available() -> bool:
    return _HAVE_PROMETHEUS


class EvalPrometheusExporter:
    """Five Summaries (precision/recall/ap/f1/ap_class).

    ``registry=None`` (legacy): a private registry, optionally served
    from its own HTTP port — the reference's standalone exporter, kept
    as a deprecation shim. ``registry=<CollectorRegistry>``: register
    the same Summaries into the shared runtime registry instead (the
    folded, single-endpoint form; ``port``/``start_server`` are then
    ignored — the telemetry server already serves the registry)."""

    def __init__(
        self,
        port: int = DEFAULT_PORT,
        start_server: bool = True,
        registry=None,
    ) -> None:
        if not _HAVE_PROMETHEUS:
            raise ImportError("prometheus_client is not installed")
        folded = registry is not None
        if not folded:
            registry = prometheus_client.CollectorRegistry()
            if start_server:
                warnings.warn(
                    "the standalone port-7658 eval exporter is "
                    "deprecated: pass registry=<RuntimeCollector "
                    "registry> (or scrape the serving telemetry port) "
                    "instead",
                    DeprecationWarning,
                    stacklevel=2,
                )
        self.registry = registry
        self.folded = folded
        s = prometheus_client.Summary
        self.p_summary = s("model_precision", "per-class precision", registry=registry)
        self.r_summary = s("model_recall", "per-class recall", registry=registry)
        self.ap_summary = s("model_ap", "per-class AP@0.5", registry=registry)
        self.f1_summary = s("model_f1", "per-class F1", registry=registry)
        self.ap_class_summary = s(
            "model_ap_class", "class ids contributing AP", registry=registry
        )
        if not folded and start_server:
            prometheus_client.start_http_server(port, registry=registry)

    @classmethod
    def into(cls, registry) -> "EvalPrometheusExporter":
        """The folded spelling: Summaries on the shared registry."""
        return cls(registry=registry)

    def observe(self, p, r, ap, f1, classes) -> None:
        """Observe one ap_per_class result, value-by-value as the
        reference does (evaluate_inference.py:440-444)."""
        for v in np.atleast_1d(p):
            self.p_summary.observe(float(v))
        for v in np.atleast_1d(r):
            self.r_summary.observe(float(v))
        ap = np.atleast_2d(ap)
        for v in ap[:, 0] if ap.size else ():
            self.ap_summary.observe(float(v))
        for v in np.atleast_1d(f1):
            self.f1_summary.observe(float(v))
        for v in np.atleast_1d(classes):
            self.ap_class_summary.observe(float(v))

    def observe_window(self, window: dict) -> None:
        """Quality-plane bridge: one finished rolling window observed
        under the legacy spellings (aggregate precision/recall/AP@0.5/
        F1 — the window summary has no per-class split to fan out)."""
        self.p_summary.observe(float(window.get("precision", 0.0)))
        self.r_summary.observe(float(window.get("recall", 0.0)))
        self.ap_summary.observe(float(window.get("map50", 0.0)))
        self.f1_summary.observe(float(window.get("f1", 0.0)))
