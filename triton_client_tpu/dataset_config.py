"""YAML dataset/model hyperparameter files -> typed configs.

The reference drives its 3D stack from YAML/py config files —
data/kitti_dataset.yaml (voxelization + point range),
data/pointpillar.yaml:110-142 (anchors + heads), and
data/nusc_centerpoint_pp_02voxel_two_pfn_10sweep.py (nuScenes grid) —
parsed by OpenPCDet/det3d at client startup
(clients/preprocess/preprocess_3d.py:13-25, voxelize.py:13-24). Here the
same hyperparameters live in data/*.yaml files that map 1:1 onto the
frozen config dataclasses, so a deployment can retune grids/anchors
without touching code, and the in-code defaults remain the source of
truth for anything the file omits.

Also loads the client parameter file (endpoint + topic wiring,
data/client_parameter.yaml — main.py:119-121 parity).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import yaml

from triton_client_tpu.ops.voxelize import VoxelConfig


def load_yaml(path: str) -> dict:
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a YAML mapping at top level")
    return doc


def _tup(v: Any) -> tuple:
    return tuple(v) if isinstance(v, (list, tuple)) else (v,)


def _check_keys(d: Mapping[str, Any], cls, what: str) -> None:
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - known
    if unknown:
        raise KeyError(
            f"unknown {what} keys {sorted(unknown)}; known: {sorted(known)}"
        )


def voxel_from_dict(d: Mapping[str, Any], base: VoxelConfig | None = None) -> VoxelConfig:
    base = base or VoxelConfig()
    _check_keys(d, VoxelConfig, "voxel config")
    # coerce per the dataclass field's declared type so a future
    # float-valued scalar field is not silently truncated by int()
    types = {f.name: f.type for f in dataclasses.fields(VoxelConfig)}

    def _coerce(k: str, v: Any):
        if k in ("point_cloud_range", "voxel_size"):
            return _tup(v)
        t = str(types.get(k, "int"))
        return float(v) if "float" in t else int(v)

    return dataclasses.replace(base, **{k: _coerce(k, v) for k, v in d.items()})


def _anchor_classes(rows: list[Mapping[str, Any]]):
    from triton_client_tpu.models.pointpillars import AnchorClassConfig

    out = []
    for r in rows:
        _check_keys(r, AnchorClassConfig, f"anchor class {r.get('name', '?')!r}")
        out.append(
            AnchorClassConfig(
                name=r["name"],
                size=_tup(r["size"]),
                bottom_z=float(r["bottom_z"]),
                matched_thresh=float(r.get("matched_thresh", 0.6)),
                unmatched_thresh=float(r.get("unmatched_thresh", 0.45)),
            )
        )
    return tuple(out)


def _apply_overrides(cfg, d: Mapping[str, Any], tuple_keys: set[str]):
    """Overlay YAML keys onto a frozen dataclass; unknown keys error so
    typos fail loudly instead of silently keeping defaults."""
    known = {f.name for f in dataclasses.fields(cfg)}
    updates = {}
    for k, v in d.items():
        if k not in known:
            raise KeyError(
                f"unknown {type(cfg).__name__} key {k!r} (valid: {sorted(known)})"
            )
        updates[k] = _tup(v) if k in tuple_keys and isinstance(v, list) else v
    return dataclasses.replace(cfg, **updates)


_SEQ_KEYS = {
    "backbone_layers",
    "backbone_strides",
    "backbone_filters",
    "upsample_strides",
    "upsample_filters",
    "middle_filters",
    "class_names",
    "point_buckets",
}


def model_config_from_dict(model: str, d: Mapping[str, Any]):
    """'pointpillars' | 'second_iou' | 'centerpoint' + mapping -> config
    dataclass. Recognized sections: ``voxel`` (grid), ``anchors`` (list
    of per-class anchor rows), everything else = direct field override."""
    d = dict(d)
    voxel = d.pop("voxel", None)
    anchors = d.pop("anchors", None)
    if model == "pointpillars":
        from triton_client_tpu.models.pointpillars import PointPillarsConfig

        cfg = PointPillarsConfig()
    elif model == "second_iou":
        from triton_client_tpu.models.second import SECONDConfig

        cfg = SECONDConfig()
    elif model == "centerpoint":
        from triton_client_tpu.models.centerpoint import CenterPointConfig

        cfg = CenterPointConfig()
    else:
        raise ValueError(f"unknown 3D model {model!r}")
    if voxel is not None:
        cfg = dataclasses.replace(cfg, voxel=voxel_from_dict(voxel, cfg.voxel))
    if anchors is not None:
        if not hasattr(cfg, "anchor_classes"):
            raise ValueError(f"{model} is anchor-free; remove the anchors section")
        cfg = dataclasses.replace(cfg, anchor_classes=_anchor_classes(anchors))
    return _apply_overrides(cfg, d, _SEQ_KEYS)


def detect3d_from_yaml(path: str):
    """Full 3D stack config file -> (model_name, model_cfg,
    Detect3DConfig). Layout::

        model: pointpillars
        voxel: {point_cloud_range: [...], voxel_size: [...], ...}
        anchors: [{name: Car, size: [...], bottom_z: ...}, ...]
        pipeline: {score_thresh: ..., z_offset: ..., ...}
        <field>: <model-config override>
    """
    from triton_client_tpu.pipelines.detect3d import default_detect3d_config

    doc = load_yaml(path)
    model = doc.pop("model", "pointpillars")
    pipe_d = dict(doc.pop("pipeline", {}))
    model_cfg = model_config_from_dict(model, doc)
    pipe_cfg = _apply_overrides(default_detect3d_config(model), pipe_d, _SEQ_KEYS)
    # Keep label vocabulary consistent with the model's classes.
    names = getattr(model_cfg, "class_names", None)
    if names is None and hasattr(model_cfg, "anchor_classes"):
        names = tuple(a.name for a in model_cfg.anchor_classes)
    if names and tuple(pipe_cfg.class_names) != tuple(names):
        pipe_cfg = dataclasses.replace(pipe_cfg, class_names=tuple(names))
    return model, model_cfg, pipe_cfg


_CLIENT_PARAM_DEFAULTS = {
    "channel": "tpu",
    "grpc_channel": "localhost:8001",
    "sub_topic": "/camera/color/image_raw",
    "pub_topic": "/tpu_detections/image",
    "gt_topic": "/camera/color/Detection2DArray",
    "pointcloud_topic": "/os_cloud_node/points",
    "mesh": {"data": -1, "model": 1},
}


def client_params(path: str | None = None) -> dict:
    """Endpoint/topic wiring with defaults (client_parameter.yaml
    semantics, main.py:119-121)."""
    params = dict(_CLIENT_PARAM_DEFAULTS)
    if path:
        params.update(load_yaml(path))
    return params
