// tcr_runtime: native serving runtime core (C ABI for ctypes).
//
// The reference delegates its serving runtime — request queue,
// dynamic batcher, scheduler — to the Triton Inference Server C++
// binary (SURVEY.md §2.9 row 1; docker/server/Dockerfile:23-27). This
// is the in-tree TPU-native equivalent: C++ owns admission, batch
// formation and timing; tensor payloads never enter C++ (they stay as
// numpy arrays keyed by request id on the Python side), so the hot
// data path has zero extra copies while batching policy runs off the
// GIL.
//
//   * tcr_server: bounded two-priority MPMC queue + batcher thread.
//     Batches close when (a) max_batch requests are pending, or
//     (b) timeout_us elapsed since the oldest admitted request, or
//     (c) shutdown drains. Formed batches are handed to a registered
//     callback (Python: ctypes CFUNCTYPE — ctypes re-acquires the GIL
//     for the call, so the callback may run JAX directly).
//   * tcr_arena: fixed-slot aligned buffer pool for frame staging
//     (the allocator piece; 64-byte aligned for vectorized host ops).
//
// Build: g++ -O2 -fPIC -shared -pthread (driven by ../build.py).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

typedef void (*tcr_batch_cb)(void* user, const uint64_t* ids, int32_t count);

typedef struct {
  uint64_t enqueued;
  uint64_t rejected_full;
  uint64_t batches;
  uint64_t batched_requests;
  uint64_t timeout_closes;   // batches closed by deadline
  uint64_t size_closes;      // batches closed by reaching max_batch
  int32_t queue_depth;
  double mean_batch;
  double mean_queue_us;      // mean admission->dispatch latency
} tcr_stats;

}  // extern "C"

namespace {

using Clock = std::chrono::steady_clock;

struct Pending {
  uint64_t id;
  Clock::time_point admitted;
};

struct Server {
  int32_t max_batch;
  int64_t timeout_us;
  int32_t capacity;

  tcr_batch_cb cb = nullptr;
  void* user = nullptr;

  std::mutex mu;
  std::condition_variable cv;
  std::deque<Pending> high, normal;  // two-priority admission
  bool running = false;
  bool stopping = false;
  std::thread worker;

  // stats (written under mu except the atomics)
  std::atomic<uint64_t> enqueued{0}, rejected{0};
  uint64_t batches = 0, batched_requests = 0;
  uint64_t timeout_closes = 0, size_closes = 0;
  double queue_us_sum = 0.0;

  int32_t depth_locked() const {
    return static_cast<int32_t>(high.size() + normal.size());
  }

  // Pop up to max_batch ids, oldest-admitted deadline already expired
  // or batch full. Returns ids + whether the close was size-triggered.
  void run() {
    std::vector<uint64_t> ids;
    ids.reserve(max_batch);
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      cv.wait(lk, [&] { return stopping || depth_locked() > 0; });
      if (stopping && depth_locked() == 0) return;

      // Batch window: wait until max_batch ready or the oldest
      // request's deadline passes.
      Clock::time_point oldest;
      if (high.empty())
        oldest = normal.front().admitted;
      else if (normal.empty())
        oldest = high.front().admitted;
      else
        oldest = std::min(high.front().admitted, normal.front().admitted);
      const auto deadline = oldest + std::chrono::microseconds(timeout_us);
      bool full = cv.wait_until(lk, deadline, [&] {
        return stopping || depth_locked() >= max_batch;
      });

      ids.clear();
      const auto now = Clock::now();
      while (depth_locked() > 0 &&
             static_cast<int32_t>(ids.size()) < max_batch) {
        auto& q = high.empty() ? normal : high;
        queue_us_sum +=
            std::chrono::duration<double, std::micro>(now - q.front().admitted)
                .count();
        ids.push_back(q.front().id);
        q.pop_front();
      }
      if (ids.empty()) continue;
      batches++;
      batched_requests += ids.size();
      if (full && static_cast<int32_t>(ids.size()) >= max_batch)
        size_closes++;
      else
        timeout_closes++;

      // Dispatch outside the lock: the callback re-enters Python.
      lk.unlock();
      cb(user, ids.data(), static_cast<int32_t>(ids.size()));
      lk.lock();
    }
  }
};

}  // namespace

extern "C" {

Server* tcr_server_create(int32_t max_batch, int64_t timeout_us,
                          int32_t capacity) {
  if (max_batch < 1 || capacity < 1) return nullptr;
  auto* s = new Server();
  s->max_batch = max_batch;
  s->timeout_us = timeout_us;
  s->capacity = capacity;
  return s;
}

void tcr_server_set_callback(Server* s, tcr_batch_cb cb, void* user) {
  s->cb = cb;
  s->user = user;
}

int32_t tcr_server_start(Server* s) {
  if (!s->cb) return -1;
  std::lock_guard<std::mutex> lk(s->mu);
  if (s->running) return -2;
  s->running = true;
  s->stopping = false;
  s->worker = std::thread([s] { s->run(); });
  return 0;
}

// 0 = admitted; -1 = queue full; -2 = not running. Never blocks.
int32_t tcr_server_enqueue(Server* s, uint64_t id, int32_t priority) {
  std::lock_guard<std::mutex> lk(s->mu);
  if (!s->running || s->stopping) return -2;
  if (s->depth_locked() >= s->capacity) {
    s->rejected.fetch_add(1, std::memory_order_relaxed);
    return -1;
  }
  (priority > 0 ? s->high : s->normal).push_back({id, Clock::now()});
  s->enqueued.fetch_add(1, std::memory_order_relaxed);
  s->cv.notify_all();
  return 0;
}

// Drains pending requests (they are dispatched, not dropped), then
// joins the batcher thread.
void tcr_server_stop(Server* s) {
  {
    std::lock_guard<std::mutex> lk(s->mu);
    if (!s->running) return;
    s->stopping = true;
    s->cv.notify_all();
  }
  s->worker.join();
  std::lock_guard<std::mutex> lk(s->mu);
  s->running = false;
}

void tcr_server_stats(Server* s, tcr_stats* out) {
  std::lock_guard<std::mutex> lk(s->mu);
  out->enqueued = s->enqueued.load(std::memory_order_relaxed);
  out->rejected_full = s->rejected.load(std::memory_order_relaxed);
  out->batches = s->batches;
  out->batched_requests = s->batched_requests;
  out->timeout_closes = s->timeout_closes;
  out->size_closes = s->size_closes;
  out->queue_depth = s->depth_locked();
  out->mean_batch =
      s->batches ? static_cast<double>(s->batched_requests) / s->batches : 0.0;
  out->mean_queue_us =
      s->batched_requests ? s->queue_us_sum / s->batched_requests : 0.0;
}

void tcr_server_destroy(Server* s) {
  tcr_server_stop(s);
  delete s;
}

// ---- tcr_arena: fixed-slot aligned host buffer pool ------------------

struct Arena {
  size_t slot_bytes;
  int32_t n_slots;
  char* base;
  std::mutex mu;
  std::vector<int32_t> freelist;
};

Arena* tcr_arena_create(size_t slot_bytes, int32_t n_slots) {
  if (slot_bytes == 0 || n_slots < 1) return nullptr;
  // Round slots to 64B so every slot starts cache-line aligned.
  const size_t stride = (slot_bytes + 63) & ~size_t{63};
  void* base = nullptr;
  if (posix_memalign(&base, 64, stride * n_slots) != 0) return nullptr;
  auto* a = new Arena();
  a->slot_bytes = stride;
  a->n_slots = n_slots;
  a->base = static_cast<char*>(base);
  a->freelist.reserve(n_slots);
  for (int32_t i = n_slots - 1; i >= 0; --i) a->freelist.push_back(i);
  return a;
}

// Returns a slot pointer or NULL when exhausted (caller falls back to
// regular allocation — admission control, not a hard failure).
void* tcr_arena_acquire(Arena* a) {
  std::lock_guard<std::mutex> lk(a->mu);
  if (a->freelist.empty()) return nullptr;
  int32_t slot = a->freelist.back();
  a->freelist.pop_back();
  return a->base + static_cast<size_t>(slot) * a->slot_bytes;
}

int32_t tcr_arena_release(Arena* a, void* p) {
  auto off = static_cast<char*>(p) - a->base;
  if (off < 0 || off % static_cast<ptrdiff_t>(a->slot_bytes) != 0) return -1;
  auto slot = static_cast<int32_t>(off / a->slot_bytes);
  if (slot >= a->n_slots) return -1;
  std::lock_guard<std::mutex> lk(a->mu);
  a->freelist.push_back(slot);
  return 0;
}

size_t tcr_arena_slot_bytes(Arena* a) { return a->slot_bytes; }

int32_t tcr_arena_free_slots(Arena* a) {
  std::lock_guard<std::mutex> lk(a->mu);
  return static_cast<int32_t>(a->freelist.size());
}

void tcr_arena_destroy(Arena* a) {
  free(a->base);
  delete a;
}

}  // extern "C"
