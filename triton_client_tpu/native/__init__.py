"""ctypes bindings over libtcr_runtime.so (native queue/batcher/arena).

The C++ side owns admission + batch formation timing (off the GIL);
tensor payloads never cross the boundary — Python keeps them keyed by
request id and the batch callback receives only the id list. ctypes
re-acquires the GIL for the callback, so it can run JAX directly.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from triton_client_tpu.native.build import NativeUnavailable, ensure_built

__all__ = ["Arena", "NativeBatchServer", "NativeUnavailable", "load"]

_BATCH_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32
)


class Stats(ctypes.Structure):
    _fields_ = [
        ("enqueued", ctypes.c_uint64),
        ("rejected_full", ctypes.c_uint64),
        ("batches", ctypes.c_uint64),
        ("batched_requests", ctypes.c_uint64),
        ("timeout_closes", ctypes.c_uint64),
        ("size_closes", ctypes.c_uint64),
        ("queue_depth", ctypes.c_int32),
        ("mean_batch", ctypes.c_double),
        ("mean_queue_us", ctypes.c_double),
    ]

    def as_dict(self) -> dict:
        return {name: getattr(self, name) for name, _ in self._fields_}


_lib = None
_lib_lock = threading.Lock()


def load() -> ctypes.CDLL:
    """Build (if needed) and dlopen the native library, once."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(str(ensure_built()))

        lib.tcr_server_create.restype = ctypes.c_void_p
        lib.tcr_server_create.argtypes = [
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.tcr_server_set_callback.argtypes = [
            ctypes.c_void_p,
            _BATCH_CB,
            ctypes.c_void_p,
        ]
        lib.tcr_server_start.restype = ctypes.c_int32
        lib.tcr_server_start.argtypes = [ctypes.c_void_p]
        lib.tcr_server_enqueue.restype = ctypes.c_int32
        lib.tcr_server_enqueue.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int32,
        ]
        lib.tcr_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcr_server_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(Stats)]
        lib.tcr_server_destroy.argtypes = [ctypes.c_void_p]

        lib.tcr_arena_create.restype = ctypes.c_void_p
        lib.tcr_arena_create.argtypes = [ctypes.c_size_t, ctypes.c_int32]
        lib.tcr_arena_acquire.restype = ctypes.c_void_p
        lib.tcr_arena_acquire.argtypes = [ctypes.c_void_p]
        lib.tcr_arena_release.restype = ctypes.c_int32
        lib.tcr_arena_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.tcr_arena_slot_bytes.restype = ctypes.c_size_t
        lib.tcr_arena_slot_bytes.argtypes = [ctypes.c_void_p]
        lib.tcr_arena_free_slots.restype = ctypes.c_int32
        lib.tcr_arena_free_slots.argtypes = [ctypes.c_void_p]
        lib.tcr_arena_destroy.argtypes = [ctypes.c_void_p]

        _lib = lib
        return lib


class NativeBatchServer:
    """Queue + micro-batcher. ``on_batch(ids: list[int])`` runs on the
    native batcher thread (with the GIL, via ctypes)."""

    def __init__(
        self,
        on_batch,
        max_batch: int = 8,
        timeout_us: int = 2000,
        capacity: int = 256,
    ) -> None:
        self._lib = load()
        self._handle = self._lib.tcr_server_create(max_batch, timeout_us, capacity)
        if not self._handle:
            raise NativeUnavailable("tcr_server_create failed")
        self._on_batch = on_batch

        def trampoline(_user, ids_ptr, count):
            try:
                self._on_batch([ids_ptr[i] for i in range(count)])
            except Exception:  # never let an exception cross the C boundary
                import logging

                logging.getLogger(__name__).exception("batch callback failed")

        # Keep a reference: the C side holds a raw function pointer.
        self._cb = _BATCH_CB(trampoline)
        self._lib.tcr_server_set_callback(self._handle, self._cb, None)

    def _require_handle(self):
        if not self._handle:
            raise RuntimeError("server is closed")
        return self._handle

    def start(self) -> None:
        rc = self._lib.tcr_server_start(self._require_handle())
        if rc != 0:
            raise RuntimeError(f"tcr_server_start -> {rc}")

    def enqueue(self, request_id: int, priority: int = 0) -> bool:
        """False when the queue is full (admission control)."""
        rc = self._lib.tcr_server_enqueue(
            self._require_handle(), request_id, priority
        )
        if rc == -2:
            raise RuntimeError("server not running")
        return rc == 0

    def stats(self) -> dict:
        out = Stats()
        self._lib.tcr_server_stats(self._require_handle(), ctypes.byref(out))
        return out.as_dict()

    def stop(self) -> None:
        self._lib.tcr_server_stop(self._require_handle())

    def close(self) -> None:
        if self._handle:
            self._lib.tcr_server_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()


class Arena:
    """Fixed-slot 64B-aligned host buffer pool; slots surface as numpy
    arrays viewing native memory (no per-frame allocation in the IO
    path)."""

    def __init__(self, slot_bytes: int, n_slots: int) -> None:
        self._lib = load()
        self._handle = self._lib.tcr_arena_create(slot_bytes, n_slots)
        if not self._handle:
            raise NativeUnavailable("tcr_arena_create failed")
        self._stride = self._lib.tcr_arena_slot_bytes(self._handle)
        self._ptrs: dict[int, int] = {}

    def acquire(self, shape, dtype) -> np.ndarray | None:
        """An ndarray view over a free slot, or None when exhausted."""
        dtype = np.dtype(dtype)
        if not self._handle:
            raise RuntimeError("arena is closed")
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes > self._stride:
            raise ValueError(f"slot is {self._stride} B; need {nbytes} B")
        ptr = self._lib.tcr_arena_acquire(self._handle)
        if not ptr:
            return None
        buf = (ctypes.c_char * self._stride).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=nbytes // dtype.itemsize)
        arr = arr.reshape(shape)
        self._ptrs[id(arr)] = ptr
        return arr

    def release(self, arr: np.ndarray) -> None:
        if not self._handle:
            raise RuntimeError("arena is closed")
        ptr = self._ptrs.pop(id(arr), None)
        if ptr is None:
            raise ValueError("array does not belong to this arena")
        if self._lib.tcr_arena_release(self._handle, ptr) != 0:
            raise ValueError("native release rejected pointer")

    def free_slots(self) -> int:
        if not self._handle:
            raise RuntimeError("arena is closed")
        return self._lib.tcr_arena_free_slots(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.tcr_arena_destroy(self._handle)
            self._handle = None
