"""Build libtcr_runtime.so on demand (g++ direct; CMakeLists.txt is the
equivalent recipe for packaging builds).

The .so is compiled into ``_lib/`` next to this file the first time the
native runtime is imported, and recompiled whenever the source is newer
— the toolchain (g++) is part of the supported environment. Import-time
failures are surfaced as NativeUnavailable so pure-Python fallbacks can
take over (mirrors the reference's optional-dependency degradation
pattern, communicator/__init__.py:5-8).
"""

from __future__ import annotations

import os
import pathlib
import subprocess

_HERE = pathlib.Path(__file__).resolve().parent
SRC = _HERE / "src" / "tcr_runtime.cc"
LIB = _HERE / "_lib" / "libtcr_runtime.so"


class NativeUnavailable(RuntimeError):
    pass


def ensure_built() -> pathlib.Path:
    if LIB.exists() and LIB.stat().st_mtime >= SRC.stat().st_mtime:
        return LIB
    LIB.parent.mkdir(parents=True, exist_ok=True)
    # Compile to a unique temp name and os.replace() into place so an
    # interrupted or concurrent build can never leave a corrupt .so
    # that passes the mtime check.
    tmp = LIB.with_suffix(f".so.tmp{os.getpid()}")
    cmd = [
        "g++",
        "-std=c++17",
        "-O2",
        "-Wall",
        "-fPIC",
        "-shared",
        "-pthread",
        str(SRC),
        "-o",
        str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, LIB)
    except FileNotFoundError as e:
        raise NativeUnavailable("g++ not found; native runtime disabled") from e
    except subprocess.CalledProcessError as e:
        raise NativeUnavailable(
            f"native build failed:\n{e.stderr[-2000:]}"
        ) from e
    finally:
        tmp.unlink(missing_ok=True)
    return LIB
