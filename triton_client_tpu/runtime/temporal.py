"""Temporal compute reuse: adaptive keyframes, ROI tiles, suppression.

ROADMAP item 4. PR 15 made streams first-class — device-resident
tracker state, session affinity, per-stream device-seconds — but every
frame still paid the full detector even though live streams are ~95%
temporally redundant. This plane decides, per stream per frame, how
much of the detector to run:

  * **full** — the detector runs; the frame is a *keyframe*. One full
    detection every K frames, where K adapts per stream to scene
    dynamics: the tracker step (ops/tracking.py) already computes the
    Mahalanobis position innovation, and it rides back with the
    response outputs (``innovation``) at zero extra device cost. Quiet
    scene -> K grows toward ``k_max``; a burst (innovation above
    ``innovation_high``) collapses K to ``k_min`` so the very next
    frame detects.
  * **coast** — the detector is skipped entirely;
    :meth:`runtime.sessions.SessionManager.coast` advances the stream
    by Kalman predict alone (one jit dispatch over the resident state
    pytree). The frame's device-seconds — just the predict — are still
    charged to ``stream:<id>`` in the PR 11 ledger, so the ledger
    stays the honest scoreboard for the >=3x streams-per-chip claim.
  * **partial** — ROI-gated recompute: only image tiles whose content
    changed (cheap per-tile diff statistic vs the previous frame) plus
    tiles containing coasting tracks are re-detected. The variable
    tile sets are issued as *stateless* sub-requests against a
    tile-capable detector (``spec.extra["tile_recompute"]``), which
    the continuous batcher packs ACROSS streams into one ragged launch
    (runtime/continuous.py + parallel/ragged_kernels.py — session
    frames themselves solo-dispatch, but the tile sub-requests carry
    no sequence id precisely so they can merge). Tile detections merge
    back to full-frame coordinates (:func:`merge_tile_detections`),
    unchanged-region tracks ride as virtual detections at their
    predicted positions, and the composite advances the tracker
    normally.

Safety: reuse trades accuracy for throughput, so it is gated twice.
The plane keeps its own per-stream ID-churn window over keyframes
(births + deaths between consecutive keyframe track tables — the
leading indicator of an over-aggressive K) and auto-disables reuse for
that stream when it trips, exactly like a canary rollback. The PR 17
quality plane's rolling ID-switch/mAP windows gate the whole model:
:meth:`TemporalReusePlane.note_quality_violation` (wired from
``eval/quality_plane.py``) turns reuse off for every stream of a
model whose online quality regressed.

Every decision is counted (``tpu_serving_frames_total{mode=...}``,
per-stream effective-K gauge, suppression counters — obs/collector.py)
and the ``reuse_mode`` output tensor stamps each response 0=full /
1=coast / 2=partial so replay scoring (utils/loadgen.py) can hold
coasted frames to their own accuracy bar.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time

import numpy as np

from triton_client_tpu.channel.base import (
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.parallel.ragged_kernels import RaggedLayout, pack_rows
from triton_client_tpu.runtime import faults

log = logging.getLogger(__name__)

#: response output stamped on every session frame the plane touches
REUSE_MODE_KEY = "reuse_mode"
MODE_FULL, MODE_COAST, MODE_PARTIAL = 0, 1, 2
MODE_NAMES = {MODE_FULL: "full", MODE_COAST: "coast", MODE_PARTIAL: "partial"}

#: ``spec.extra`` key marking a model tile-recompute-capable; value is
#: a dict: ``model`` (the registered ragged tile detector), ``image``
#: (the image input name, default "image"), ``tile`` (tile edge,
#: pixels), optional ``diff_threshold`` and output-name overrides
TILE_EXTRA_KEY = "tile_recompute"
#: ``spec.extra`` key overriding the serve-wide mode per model
MODE_EXTRA_KEY = "temporal_reuse"


@dataclasses.dataclass(frozen=True)
class TemporalReuseConfig:
    """Serve-wide reuse policy (``serve --temporal-reuse ...``).

    ``mode``: ``auto`` adapts K per stream from the innovation;
    ``on`` runs a fixed K = ``k_max`` (no adaptation — benchmarking
    and forced-cadence tests); ``off`` disables the plane. Per-model
    ``spec.extra["temporal_reuse"]`` overrides the serve-wide mode.
    """

    mode: str = "auto"
    #: keyframe-interval bounds; K adapts inside [k_min, k_max]
    k_min: int = 1
    k_max: int = 8
    #: innovation EMA below this -> scene is quiet, K may grow
    innovation_low: float = 0.5
    #: instantaneous innovation above this -> K collapses to k_min
    innovation_high: float = 3.0
    ema_alpha: float = 0.4
    #: default tile edge (pixels) for ROI partial recompute
    tile: int = 8
    #: per-tile mean-abs-diff above this -> tile re-detects
    tile_diff_threshold: float = 0.08
    #: per-stream quality gate: mean ID churn (births+deaths between
    #: consecutive keyframes) over the last ``churn_window`` keyframes
    #: above ``churn_limit`` auto-disables reuse for the stream
    churn_window: int = 6
    churn_limit: float = 2.0
    #: test/fault override: force K to this value, no adaptation
    forced_k: int = 0

    def __post_init__(self):
        if self.mode not in ("auto", "on", "off"):
            raise ValueError(
                f"temporal-reuse mode must be auto|on|off, not {self.mode!r}"
            )
        if not 1 <= self.k_min <= self.k_max:
            raise ValueError(
                f"need 1 <= k_min <= k_max, got [{self.k_min}, {self.k_max}]"
            )


# -- tile geometry (host-side helpers, pure numpy) -----------------------------


def tile_grid(h: int, w: int, tile: int) -> tuple[int, int]:
    """(rows, cols) of the tile grid covering an h x w frame —
    ceil-division, so edge tiles may be partial (zero-padded)."""
    t = max(1, int(tile))
    return (-(-int(h) // t), -(-int(w) // t))


def _as_hwc(image: np.ndarray) -> np.ndarray:
    img = np.asarray(image, np.float32)
    return img[..., None] if img.ndim == 2 else img


def _pad_to_grid(img: np.ndarray, tile: int) -> np.ndarray:
    h, w = img.shape[0], img.shape[1]
    gy, gx = tile_grid(h, w, tile)
    return np.pad(img, ((0, gy * tile - h), (0, gx * tile - w), (0, 0)))


def tile_diff(prev, cur, tile: int) -> np.ndarray:
    """(gy*gx,) mean absolute per-tile difference — the cheap change
    statistic that gates partial recompute. Identical zero padding on
    both frames, so edge tiles compare like-for-like."""
    p, c = _as_hwc(prev), _as_hwc(cur)
    if p.shape != c.shape:
        raise ValueError(f"frame shape changed {p.shape} -> {c.shape}")
    gy, gx = tile_grid(c.shape[0], c.shape[1], tile)
    d = _pad_to_grid(np.abs(c - p), tile)
    ch = d.shape[2]
    return (
        d.reshape(gy, tile, gx, tile, ch)
        .mean(axis=(1, 3, 4))
        .reshape(-1)
        .astype(np.float32)
    )


def tiles_covering(
    points: np.ndarray, h: int, w: int, tile: int
) -> np.ndarray:
    """(gy*gx,) bool — tiles containing any of the (m, 2) ``[x, y]``
    points (track centers): the confirmation set a partial frame must
    re-detect even when the pixels look static."""
    gy, gx = tile_grid(h, w, tile)
    mask = np.zeros(gy * gx, bool)
    pts = np.asarray(points, np.float32).reshape(-1, 2)
    if pts.size:
        xs = np.clip((pts[:, 0] // tile).astype(np.int64), 0, gx - 1)
        ys = np.clip((pts[:, 1] // tile).astype(np.int64), 0, gy - 1)
        mask[ys * gx + xs] = True
    return mask


def select_tiles(
    diff_stat: np.ndarray, threshold: float, cover: np.ndarray | None = None
) -> np.ndarray:
    """Ascending int32 ids of tiles to re-detect: changed-content tiles
    union the track-cover set."""
    sel = np.asarray(diff_stat, np.float32) > np.float32(threshold)
    if cover is not None:
        sel = sel | np.asarray(cover, bool)
    return np.nonzero(sel)[0].astype(np.int32)


def extract_tiles(
    image, tile_ids: np.ndarray, tile: int
) -> tuple[np.ndarray, np.ndarray]:
    """Selected tiles as flat rows.

    Returns ``(rows, origins)``: ``rows`` (n, tile*tile*C) f32 — the
    fixed-width row format the ragged pack ships — and ``origins``
    (n, 2) f32 ``[x0, y0]`` full-frame offsets that invert the crop
    (:func:`merge_tile_detections`)."""
    img = _pad_to_grid(_as_hwc(image), tile)
    h, w = _as_hwc(image).shape[0], _as_hwc(image).shape[1]
    gy, gx = tile_grid(h, w, tile)
    ch = img.shape[2]
    view = (
        img.reshape(gy, tile, gx, tile, ch)
        .transpose(0, 2, 1, 3, 4)
        .reshape(gy * gx, tile * tile * ch)
    )
    ids = np.asarray(tile_ids, np.int64).reshape(-1)
    rows = view[ids]
    origins = np.stack(
        [(ids % gx) * tile, (ids // gx) * tile], axis=1
    ).astype(np.float32)
    return rows, origins


def pack_tile_sets(
    parts: list[np.ndarray],
) -> tuple[RaggedLayout, np.ndarray]:
    """Pack per-stream tile-row blocks into ONE ragged batch — the
    cross-stream launch shape (parallel/ragged_kernels.py owns the
    layout/padding contract). In serving this packing happens inside
    the continuous batcher; this wrapper is the direct path bench and
    the round-trip tests drive."""
    layout = RaggedLayout(tuple(int(np.shape(p)[0]) for p in parts))
    return layout, pack_rows([np.asarray(p) for p in parts], layout)


def split_tile_sets(
    packed: np.ndarray, layout: RaggedLayout
) -> list[np.ndarray]:
    """Inverse of :func:`pack_tile_sets`: per-stream row blocks back
    out of the packed batch (pad rows dropped)."""
    off = layout.offsets
    return [
        np.asarray(packed)[off[i]: off[i + 1]]
        for i in range(layout.n_segments)
    ]


def merge_tile_detections(
    dets, det_tile, valid, origins
) -> np.ndarray:
    """Tile-local detections -> full-frame coordinates.

    ``dets`` (m, D) packed detection rows in TILE-LOCAL coordinates,
    ``det_tile`` (m,) index of the producing tile into ``origins``
    (n, 2) ``[x0, y0]``, ``valid`` (m,) bool. Returns the valid rows
    with columns 0:2 offset back to full-frame coordinates — the array
    the tracker step consumes as if the full detector had run."""
    d = np.array(dets, np.float32, copy=True)
    d = d.reshape(-1, d.shape[-1]) if d.ndim != 2 else d
    idx = np.asarray(det_tile, np.int64).reshape(-1)
    v = np.asarray(valid, bool).reshape(-1)
    org = np.asarray(origins, np.float32).reshape(-1, 2)
    if d.shape[0] == 0 or not v.any():
        return np.zeros((0, d.shape[1]), np.float32)
    idx = np.clip(idx, 0, len(org) - 1)
    d[:, 0:2] += org[idx]
    return d[v]


# -- per-stream scheduler state ------------------------------------------------


class _Stream:
    __slots__ = (
        "k", "since_key", "ema", "disabled", "prev_ids", "churn",
        "full", "coast", "partial", "prev_image", "last_tracks",
        "last_valid", "det_shape",
    )

    def __init__(self, k: int) -> None:
        self.reset(k)

    def reset(self, k: int) -> None:
        self.k = k
        self.since_key = 0
        self.ema = 0.0
        self.disabled = False
        self.prev_ids: frozenset | None = None
        self.churn: collections.deque = collections.deque(maxlen=64)
        self.full = 0
        self.coast = 0
        self.partial = 0
        self.prev_image: np.ndarray | None = None
        self.last_tracks: np.ndarray | None = None
        self.last_valid: np.ndarray | None = None
        self.det_shape: tuple | None = None


class TemporalReusePlane:
    """The per-frame reuse decision, wired into ``_Servicer._issue``.

    ``sessions``: the SessionManager holding device-resident tracker
    state. ``channel``: the serving channel stack (tile sub-requests
    enter at the top so the continuous batcher can pack them across
    streams). ``ledger``: the DeviceTimeLedger; coast/partial frames
    charge their (small) device windows to ``stream:<id>`` exactly
    like full frames, keeping per-stream device-seconds honest.
    ``spec_extra_fn``: ``model_name -> spec.extra`` mapping for the
    per-model mode / tile-capability lookup.
    """

    def __init__(
        self,
        sessions,
        config: TemporalReuseConfig | None = None,
        channel=None,
        ledger=None,
        spec_extra_fn=None,
        time_fn=time.perf_counter,
    ) -> None:
        self.config = config or TemporalReuseConfig()
        self._sessions = sessions
        self._channel = channel
        self._ledger = ledger
        self._spec_extra = spec_extra_fn
        self._time = time_fn
        self._lock = threading.Lock()
        self._streams: dict[str, _Stream] = {}
        self._extra_cache: dict[str, dict] = {}
        self._model_disabled: set[str] = set()
        self._full = 0
        self._coast = 0
        self._partial = 0
        self._auto_disabled = 0
        self._quality_disabled = 0
        self._suppressed_views = 0
        self._partial_tiles = 0
        self._partial_tiles_possible = 0

    def attach_ledger(self, ledger) -> None:
        """Late-bind the DeviceTimeLedger (InferenceServer builds it
        after the serving channel stack exists)."""
        self._ledger = ledger

    def attach_channel(self, channel) -> None:
        """Late-bind the channel stack tile sub-requests dispatch on."""
        self._channel = channel

    # -- config plumbing ------------------------------------------------------

    def _extra_for(self, model: str) -> dict:
        try:
            return self._extra_cache[model]
        except KeyError:
            pass
        extra = None
        if self._spec_extra is not None:
            try:
                extra = self._spec_extra(model)
            except Exception:
                extra = None
        return self._extra_cache.setdefault(model, dict(extra or {}))

    def _mode_for(self, model: str) -> str:
        if model in self._model_disabled:
            return "off"
        mode = self._extra_for(model).get(MODE_EXTRA_KEY)
        return mode if mode in ("auto", "on", "off") else self.config.mode

    def _tile_cfg(self, model: str) -> dict | None:
        tr = self._extra_for(model).get(TILE_EXTRA_KEY)
        return tr if isinstance(tr, dict) and tr.get("model") else None

    def _stream(self, sid: str) -> _Stream:
        st = self._streams.get(sid)
        if st is None:
            with self._lock:
                st = self._streams.setdefault(
                    sid, _Stream(self.config.k_min)
                )
        return st

    # -- the per-frame decision (hot path: runtime/server.py _issue) ----------

    def dispatch(self, request: InferRequest):
        """Decide this session frame's mode. Returns an InferFuture
        when the plane serves the frame itself (coast / partial), or
        ``None`` when the full detector must run (keyframe, reuse off,
        stream disabled, or no resident state yet)."""
        sid = request.sequence_id
        if not sid or self._sessions is None:
            return None
        cfg = self.config
        mode = self._mode_for(request.model_name)
        st = self._stream(sid)
        if request.sequence_start:
            st.reset(cfg.k_min)
        if mode == "off" or st.disabled:
            self._count(st, MODE_FULL)
            st.since_key = 0
            return None
        k = cfg.forced_k or (cfg.k_max if mode == "on" else st.k)
        if faults.probe_flag("temporal_overskip", sid):
            # injected over-aggressive scheduler: pin K wide open and
            # ignore the innovation collapse — the churn gate must
            # catch the damage (the ISSUE 19 auto-disable drive)
            k = cfg.k_max
        if st.since_key + 1 >= max(1, k):
            self._count(st, MODE_FULL)
            st.since_key = 0
            return None
        # non-key frame: partial when the model is tile-capable and
        # the stream has the context for it, else pure coast
        tile_cfg = self._tile_cfg(request.model_name)
        if tile_cfg is not None and self._channel is not None:
            fut = self._try_partial(request, st, tile_cfg)
            if fut == "full":
                self._count(st, MODE_FULL)
                st.since_key = 0
                return None
            if fut is not None:
                return fut
        out = self._sessions.coast(request)
        if out is None:
            # no resident state yet (first frame / restart): keyframe
            self._count(st, MODE_FULL)
            st.since_key = 0
            return None
        self._count(st, MODE_COAST)
        st.since_key += 1
        return self._coast_future(request, out)

    def _count(self, st: _Stream, mode: int) -> None:
        with self._lock:
            if mode == MODE_FULL:
                st.full += 1
                self._full += 1
            elif mode == MODE_COAST:
                st.coast += 1
                self._coast += 1
            else:
                st.partial += 1
                self._partial += 1

    def _coast_future(self, request: InferRequest, out) -> InferFuture:
        import jax

        sid = request.sequence_id
        t0 = self._time()

        def resolve() -> InferResponse:
            try:
                # same device window the staged resolve charges for a
                # full launch: dispatch -> execution complete. A coast
                # frame's honest cost is one predict-only jit.
                jax.block_until_ready(out)
                t_ready = self._time()
                if self._ledger is not None:
                    self._ledger.record(
                        request.model_name, t_ready - t0, None,
                        tenant=f"stream:{sid}",
                    )
                host = {k: np.asarray(v) for k, v in out.items()}
                host[REUSE_MODE_KEY] = np.asarray(MODE_COAST, np.int32)
                return InferResponse(
                    model_name=request.model_name,
                    model_version=request.model_version,
                    outputs=host,
                    request_id=request.request_id,
                    latency_s=self._time() - t0,
                )
            finally:
                self._sessions.release(sid)

        return InferFuture(resolve)

    # -- ROI-gated partial recompute ------------------------------------------

    def _try_partial(self, request: InferRequest, st: _Stream, tr: dict):
        """Issue the changed-tile sub-request; returns the partial
        InferFuture, ``"full"`` when a full detection is the cheaper
        correct move (most of the frame changed), or ``None`` to fall
        back to pure coast (nothing changed, or missing context)."""
        img_name = tr.get("image", "image")
        img = request.inputs.get(img_name)
        if (
            img is None
            or st.prev_image is None
            or st.det_shape is None
            or len(st.det_shape) != 2
            or st.last_tracks is None
        ):
            return None
        cur = np.asarray(img, np.float32)
        if cur.shape != st.prev_image.shape:
            return None
        tile = int(tr.get("tile") or self.config.tile)
        h, w = cur.shape[0], cur.shape[1]
        stat = tile_diff(st.prev_image, cur, tile)
        centers = st.last_tracks[st.last_valid][:, 0:2]
        cover = tiles_covering(centers, h, w, tile)
        threshold = float(
            tr.get("diff_threshold", self.config.tile_diff_threshold)
        )
        sel = select_tiles(stat, threshold, cover)
        gy, gx = tile_grid(h, w, tile)
        n_tiles = gy * gx
        if sel.size == 0:
            st.prev_image = cur
            return None
        if sel.size >= n_tiles:
            return "full"  # everything changed: the shortcut costs more
        rows, origins = extract_tiles(cur, sel, tile)
        sub = InferRequest(
            model_name=str(tr["model"]),
            inputs={"tiles": rows, "tile_origin": origins},
            request_id=(
                f"{request.request_id}/tiles" if request.request_id else ""
            ),
            deadline_s=request.deadline_s,
            priority=request.priority,
        )
        try:
            subfut = self._channel.do_inference_async(sub)
        except Exception:
            return None  # tile detector unavailable: coast instead
        # unchanged-region tracks ride as virtual detections at their
        # predicted positions so they neither age out nor re-detect
        sel_mask = np.zeros(n_tiles, bool)
        sel_mask[sel] = True
        xs = np.clip((centers[:, 0] // tile).astype(np.int64), 0, gx - 1)
        ys = np.clip((centers[:, 1] // tile).astype(np.int64), 0, gy - 1)
        outside = ~sel_mask[ys * gx + xs]
        virtual = st.last_tracks[st.last_valid][outside]
        st.prev_image = cur
        self._count(st, MODE_PARTIAL)
        st.since_key += 1
        with self._lock:
            self._partial_tiles += int(sel.size)
            self._partial_tiles_possible += int(n_tiles)
        return self._partial_future(request, st, tr, subfut, origins, virtual)

    def _partial_future(
        self, request, st, tr, subfut, origins, virtual
    ) -> InferFuture:
        import jax

        sid = request.sequence_id
        t0 = self._time()
        det_key = tr.get("detections_output", "tile_detections")
        idx_key = tr.get("tile_index_output", "tile_det_tile")
        valid_key = tr.get("valid_output", "tile_valid")
        n_rows, det_dim = st.det_shape

        def resolve() -> InferResponse:
            resp = subfut.result()  # tile launch (ragged-packed upstream)
            tile_dets = merge_tile_detections(
                np.asarray(resp.outputs[det_key]),
                np.asarray(resp.outputs[idx_key]),
                np.asarray(resp.outputs[valid_key]),
                origins,
            )
            rows = [r for r in (tile_dets, np.asarray(virtual)) if len(r)]
            merged = (
                np.concatenate(rows)[:n_rows]
                if rows
                else np.zeros((0, det_dim), np.float32)
            )
            n = merged.shape[0]
            detections = np.zeros((n_rows, det_dim), np.float32)
            detections[:n] = merged
            valid = np.zeros((n_rows,), bool)
            valid[:n] = True
            t_adv = self._time()
            out = self._sessions.advance(
                request, {"detections": detections, "valid": valid}
            )
            try:
                jax.block_until_ready(out)
                t_ready = self._time()
                if self._ledger is not None:
                    # the tile launch already accrued under the tile
                    # model; this charges the stream's tracker window
                    self._ledger.record(
                        request.model_name, t_ready - t_adv, None,
                        tenant=f"stream:{sid}",
                    )
                host = {k: np.asarray(v) for k, v in out.items()}
                host[REUSE_MODE_KEY] = np.asarray(MODE_PARTIAL, np.int32)
                return InferResponse(
                    model_name=request.model_name,
                    model_version=request.model_version,
                    outputs=host,
                    request_id=request.request_id,
                    latency_s=self._time() - t0,
                )
            finally:
                self._sessions.release(sid)

        return InferFuture(resolve)

    # -- feedback (runtime/server.py finish(), post-readback) -----------------

    def observe(self, model: str, sid: str, inputs, outputs) -> None:
        """Fold one resolved frame back into the scheduler: stamp
        ``reuse_mode`` on full frames, adapt K from the keyframe
        innovation, cache the track/image context the partial path
        needs, and run the per-stream ID-churn quality gate. Host-side
        numpy throughout — the response was already read back."""
        if not sid:
            return
        cfg = self.config
        st = self._stream(sid)
        mode_out = outputs.get(REUSE_MODE_KEY)
        if mode_out is None:
            outputs[REUSE_MODE_KEY] = np.asarray(MODE_FULL, np.int32)
            mode_val = MODE_FULL
        else:
            mode_val = int(np.asarray(mode_out))
        tracks, tvalid = outputs.get("tracks"), outputs.get("tracks_valid")
        if tracks is not None and tvalid is not None:
            tk = np.asarray(tracks)
            if tk.ndim == 2:  # partial/tile context is single-camera only
                st.last_tracks = tk
                st.last_valid = np.asarray(tvalid, bool)
        tile_cfg = self._tile_cfg(model)
        if tile_cfg is not None:
            img = inputs.get(tile_cfg.get("image", "image")) \
                if inputs is not None else None
            if img is not None and np.ndim(img) in (2, 3):
                st.prev_image = np.asarray(img, np.float32)
        if mode_val != MODE_FULL:
            return
        det = outputs.get("detections")
        if det is not None and np.ndim(det) == 2:
            st.det_shape = tuple(np.shape(det))
        mode = self._mode_for(model)
        innov = outputs.get("innovation")
        if innov is not None and mode == "auto" and not cfg.forced_k:
            v = float(np.mean(np.asarray(innov, np.float32)))
            st.ema = cfg.ema_alpha * v + (1.0 - cfg.ema_alpha) * st.ema
            if faults.probe_flag("temporal_overskip", sid):
                pass  # injected scheduler ignores the innovation signal
            elif v >= cfg.innovation_high:
                st.k = cfg.k_min
            elif st.ema <= cfg.innovation_low:
                st.k = min(cfg.k_max, st.k + 1)
            else:
                st.k = max(cfg.k_min, st.k - 1)
        # ID-churn gate: births+deaths between consecutive keyframe
        # track tables. Only armed once reuse actually skipped work —
        # a reuse-off stream can never be disabled by its own churn.
        tid = outputs.get("track_ids")
        if tid is not None and tvalid is not None and np.ndim(tid) == 1:
            ids = frozenset(
                int(i) for i in np.asarray(tid)[np.asarray(tvalid, bool)]
            )
            if st.prev_ids is not None:
                st.churn.append(len(ids ^ st.prev_ids))
            st.prev_ids = ids
            recent = list(st.churn)[-cfg.churn_window:]
            if (
                not st.disabled
                and (st.coast + st.partial) > 0
                and len(recent) >= cfg.churn_window
                and sum(recent) / len(recent) > cfg.churn_limit
            ):
                st.disabled = True
                with self._lock:
                    self._auto_disabled += 1
                log.warning(
                    "temporal reuse auto-disabled for stream %s: "
                    "ID churn %.2f/keyframe over %d keyframes "
                    "(limit %.2f) — coasting is costing track identity",
                    sid, sum(recent) / len(recent), len(recent),
                    cfg.churn_limit,
                )

    # -- external gates / counters --------------------------------------------

    def note_quality_violation(self, model: str) -> None:
        """Quality-plane hook (eval/quality_plane.py): a rolling-window
        quality violation on ``model`` turns reuse off for every one of
        its streams — same reflex as a canary rollback."""
        with self._lock:
            if model in self._model_disabled:
                return
            self._model_disabled.add(model)
            self._quality_disabled += 1
        log.warning(
            "temporal reuse disabled for model '%s': online quality "
            "window violated", model,
        )

    def record_suppressed(self, views: int = 1) -> None:
        """Count cross-camera suppressed views (drivers/multicam.py)."""
        with self._lock:
            self._suppressed_views += int(views)

    def end_stream(self, sid: str) -> None:
        with self._lock:
            self._streams.pop(sid, None)

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            streams = dict(self._streams)
            return {
                "mode": self.config.mode,
                "frames_full_total": self._full,
                "frames_coast_total": self._coast,
                "frames_partial_total": self._partial,
                "streams": len(streams),
                "disabled_streams": sum(
                    1 for s in streams.values() if s.disabled
                ),
                "auto_disabled_total": self._auto_disabled,
                "quality_disabled_models": sorted(self._model_disabled),
                "quality_disabled_total": self._quality_disabled,
                "suppressed_views_total": self._suppressed_views,
                "partial_tiles_total": self._partial_tiles,
                "partial_tiles_possible_total": self._partial_tiles_possible,
                "effective_k": {
                    sid: int(s.k) for sid, s in streams.items()
                },
            }
