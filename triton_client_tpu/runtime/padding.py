"""Batch padding/bucketing helpers — the ONE copy of the bucket table.

Both batch producers pad to a bounded set of device batch sizes so the
inner executable cache stays small (the role Triton's
preferred_batch_size plays): the micro-batcher
(``BatchingChannel._merge_parts``) pads merged request groups, and the
mesh-sharded serving channel (``channel/sharded_channel.py``) pads each
request batch so it splits evenly over the mesh's ``data`` axis. Before
this module each carried its own ``_bucket`` — two tables that could
drift apart and double XLA's compiled-shape set. Now:

  * :func:`bucket`      — the classic next-power-of-two table;
  * :func:`bucket_for`  — the mesh-aware table: smallest padded size
    that is both bucketed AND divisible by the data-axis width, so one
    table serves single-device and sharded channels (for the common
    power-of-two meshes the two tables coincide at sizes >= the axis);
  * :func:`pad_rows` / :func:`unpad_rows` — the padding policy itself.
    Pad rows REPLICATE a real row rather than zero-filling: zeros can
    steer a model down numerically different paths (different NMS
    survivors, different argmax ties), a copied row cannot, which is
    what keeps padded launches bitwise identical after the slice-back.
"""

from __future__ import annotations

import numpy as np


def bucket(n: int) -> int:
    """Smallest power of two >= n (the padded device batch size)."""
    b = 1
    while b < n:
        b *= 2
    return b


def bucket_for(n: int, multiple: int = 1) -> int:
    """Smallest bucketed batch size >= n that divides evenly into
    ``multiple`` shards (the mesh data-axis width).

    ``multiple=1`` is exactly :func:`bucket`. For ``multiple=m`` the
    padded size is the smallest multiple of ``m`` that covers
    ``bucket(n)`` — i.e. round to the classic power-of-two table first,
    then up to the next axis multiple. The size set stays log2-bounded
    (one entry per power of two), every entry splits evenly over the
    axis — required before ``jax.device_put`` with a batch sharding can
    place the array at all — and for power-of-two meshes the table
    coincides with :func:`bucket` at every size >= m, so stacking the
    batcher's padding in front of a sharded channel never double-pads.

    Non-power-of-two axes (a data=6 mesh of paired trays) used to go
    through ``m * bucket(ceil(n/m))``, which jumps past valid sizes:
    13 rows on 6 shards padded to 24 when 18 (= 6 * ceil(16/6)) already
    covers the classic bucket — an extra 46% of pad work for nothing.
    """
    if multiple <= 1:
        return bucket(n)
    if n <= multiple:
        # one row per shard is the floor: a 1-row request on a 6-wide
        # mesh still ships 6 rows
        return multiple
    b = bucket(n)
    return multiple * -(-b // multiple)  # ceil to the next axis multiple


def pad_rows(parts: list[np.ndarray], pad: int) -> list[np.ndarray]:
    """Append ``pad`` replicated rows (copies of the first non-empty
    part's first row) to a list of batch fragments about to be
    concatenated.

    Replicating from a 0-row fragment would contribute ``0`` pad rows
    (``empty[:1]`` is empty) and the concatenated batch silently
    under-pads — a shape-mismatch launch downstream. An all-empty
    fragment list has no real row to copy, so it zero-fills."""
    if pad <= 0:
        return parts
    for p in parts:
        if p.shape[0]:
            return list(parts) + [np.repeat(p[:1], pad, axis=0)]
    return list(parts) + [
        np.zeros((pad, *parts[0].shape[1:]), parts[0].dtype)
    ]


def pad_batch(arr: np.ndarray, target: int) -> np.ndarray:
    """Pad one batch-leading array up to ``target`` rows by replicating
    its first row (no-op when already at target)."""
    if arr.shape[0] >= target:
        return arr
    return np.concatenate(pad_rows([arr], target - arr.shape[0]))


def unpad_rows(arr, total: int):
    """Slice the real ``total`` rows back off a padded batch output.

    Works on numpy and on device arrays (a lazy slice — for a sharded
    device output the host copy that follows only ever pays for the
    real rows)."""
    if arr.ndim >= 1 and arr.shape[0] > total:
        return arr[:total]
    return arr
