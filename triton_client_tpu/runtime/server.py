"""KServe v2 gRPC serving façade over the model repository.

This is the in-tree replacement for the Triton Inference Server binary
the reference deploys in docker (SURVEY.md §2.9 row 1): a gRPC server
speaking the same KServe v2 protocol (so the reference's ROS tooling
and any tritonclient-based caller work unchanged), dispatching to
jit-compiled JAX functions through a BaseChannel (normally TPUChannel
on a device mesh) instead of CUDA backends.

Differences from the reference's serving story, by design:
  * message size limits are computed from the registered model specs
    (the reference hardcodes batch_size * 8568044 bytes with a "make
    dynamic" TODO, grpc_channel.py:26-29 / README.md:118);
  * ModelStreamInfer is implemented, not a dangling flag
    (main.py:59-70 exposes --streaming but the refactored client never
    exercises it);
  * errors surface as rich gRPC status codes rather than a returned
    exception object (yolov5_postprocess.py:124-125).
"""

from __future__ import annotations

import concurrent.futures
import itertools
import json
import logging
import os
import threading
import time

import grpc

from triton_client_tpu import __version__
from triton_client_tpu.channel.base import BaseChannel, InferRequest
from triton_client_tpu.channel.kserve import codec, pb, service
from triton_client_tpu.config import FRAMING_BYTES
from triton_client_tpu.runtime import faults
from triton_client_tpu.runtime.admission import (
    AdmissionController,
    AdmissionRejectedError,
    CircuitOpenError,
    DeadlineExpiredError,
    OverloadError,
    ReplicaDownError,
    ServerDrainingError,
)
from triton_client_tpu.obs.logs import log_tag
from triton_client_tpu.obs.trace import (
    SUMMARY_PARAM_KEY,
    TraceContext,
    encode_span_summary,
)
from triton_client_tpu.runtime import wire_encoding
from triton_client_tpu.runtime.repository import ModelRepository

log = logging.getLogger(__name__)

# Floor for the gRPC message cap; specs with dynamic (-1) dims fall back
# to this. 64 MiB covers the reference's largest contract (batch 8
# images, grpc_channel.py:26-29) with headroom.
_MIN_MSG_BYTES = 64 << 20


def message_limit(repository: ModelRepository) -> int:
    """Dynamic per-repository message cap (README.md:118's TODO).

    Computed from the specs registered *now*; InferenceServer reads it
    once at construction (gRPC server options are bind-time fixed), so
    register large models before constructing the server or pass an
    explicit ``max_message_bytes``.
    """
    best = _MIN_MSG_BYTES
    for name in repository.names():
        for version in repository.versions(name):
            spec = repository.metadata(name, version)
            best = max(best, 2 * spec.wire_bytes() + FRAMING_BYTES)
    return best


def _grpc_code(exc: BaseException) -> str:
    """gRPC status-code label for the per-model error counter, matching
    the codes ModelInfer aborts with. The overload family is mapped
    deliberately: RESOURCE_EXHAUSTED is non-retryable for ModelInfer
    clients (shedding must not amplify load), DEADLINE_EXCEEDED tells
    the caller its budget — not the server — killed the request, and
    UNAVAILABLE (breaker open / draining) is the connection-class code
    retry ladders and load balancers key on to go elsewhere."""
    if isinstance(exc, AdmissionRejectedError):  # incl. QueueFullError
        return "RESOURCE_EXHAUSTED"
    if isinstance(exc, DeadlineExpiredError):
        return "DEADLINE_EXCEEDED"
    if isinstance(
        exc, (CircuitOpenError, ServerDrainingError, ReplicaDownError)
    ):
        return "UNAVAILABLE"
    if isinstance(exc, KeyError):
        return "NOT_FOUND"
    if isinstance(exc, ValueError):
        return "INVALID_ARGUMENT"
    return "INTERNAL"


_GRPC_STATUS = {
    "RESOURCE_EXHAUSTED": grpc.StatusCode.RESOURCE_EXHAUSTED,
    "DEADLINE_EXCEEDED": grpc.StatusCode.DEADLINE_EXCEEDED,
    "UNAVAILABLE": grpc.StatusCode.UNAVAILABLE,
}


class _Servicer(service.GRPCInferenceServiceServicer):
    def __init__(
        self,
        repository: ModelRepository,
        channel: BaseChannel,
        profiler=None,
        shm_registry=None,
        stream_pipeline_depth: int = 2,
        tracer=None,
        collector=None,
        slo=None,
        admission: AdmissionController | None = None,
        draining: threading.Event | None = None,
        lifecycle=None,
        replica_of: str | None = None,
        quality=None,
        temporal=None,
    ) -> None:
        self._repo = repository
        self._channel = channel
        self._lifecycle = lifecycle
        # replica label (--replica-of): names the replica set this
        # server belongs to. It keys the replica_down fault point so a
        # chaos plan can kill ONE labeled replica in a fleet, and rides
        # ServerMetadata.extensions so the route tool can display it.
        self._replica_of = replica_of
        self._profiler = profiler
        self._shm = shm_registry
        self._stream_depth = max(1, int(stream_pipeline_depth))
        self._tracer = tracer
        self._collector = collector
        self._slo = slo
        self._admission = admission
        self._draining = draining
        # continuous quality plane (ISSUE 17): canary routing before
        # dispatch, trace-hash shadow sampling after the readback —
        # both one attribute read on the un-wired hot path. The counter
        # backs an anonymous per-request key for id-less untraced
        # requests (sampling stays live, just not replay-deterministic)
        self._quality = quality
        self._quality_seq = itertools.count()
        # temporal-reuse plane (ISSUE 19): consulted before dispatch on
        # session frames — a coast/partial decision bypasses the full
        # detector launch entirely; the keyframe innovation feeds back
        # through finish(). One attribute read on the un-wired path.
        self._temporal = temporal
        # in-flight request count independent of the (optional)
        # collector — drain() polls it to know when the building is empty
        self._active = 0
        self._active_lock = threading.Lock()

    def active_requests(self) -> int:
        with self._active_lock:
            return self._active

    def _draining_now(self) -> bool:
        return self._draining is not None and self._draining.is_set()

    # -- health ---------------------------------------------------------------

    def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    def _replica_down_now(self) -> bool:
        return faults.probe_flag("replica_down", self._replica_of)

    def ServerReady(self, request, context):
        # a draining server flips not-ready FIRST so orchestrators pull
        # it from rotation before in-flight work finishes; an injected
        # replica_down fault answers not-ready the same way a dead
        # process would simply not answer
        return pb.ServerReadyResponse(
            ready=not self._draining_now() and not self._replica_down_now()
        )

    def ModelReady(self, request, context):
        if self._draining_now() or self._replica_down_now():
            return pb.ModelReadyResponse(ready=False)
        try:
            self._repo.get(request.name, request.version)
            ready = True
        except KeyError:
            ready = False
        return pb.ModelReadyResponse(ready=ready)

    # -- metadata -------------------------------------------------------------

    def ServerMetadata(self, request, context):
        extensions = [
            "model_repository",
            "binary_tensor_data",
            "system_shared_memory",
        ]
        if self._replica_of:
            # replica-set label as a metadata extension: the route tool
            # reads it back to confirm which fleet an endpoint claims
            extensions.append(f"replica_of:{self._replica_of}")
        return pb.ServerMetadataResponse(
            name="triton_client_tpu",
            version=__version__,
            extensions=extensions,
        )

    def _spec_or_abort(self, name, version, context):
        try:
            return self._repo.metadata(name, version)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))

    def ModelMetadata(self, request, context):
        spec = self._spec_or_abort(request.name, request.version, context)
        resp = pb.ModelMetadataResponse(
            name=spec.name,
            versions=list(self._repo.versions(spec.name)),
            platform=spec.platform,
        )
        for t in spec.inputs:
            resp.inputs.add(name=t.name, datatype=t.dtype, shape=t.shape)
        for t in spec.outputs:
            resp.outputs.add(name=t.name, datatype=t.dtype, shape=t.shape)
        return resp

    def ModelConfig(self, request, context):
        spec = self._spec_or_abort(request.name, request.version, context)
        config = pb.ModelConfig(
            name=spec.name,
            platform=spec.platform,
            max_batch_size=spec.max_batch_size,
        )
        for t in spec.inputs:
            config.input.add(
                name=t.name,
                data_type=codec.config_datatype(t.dtype),
                dims=t.shape,
            )
        for t in spec.outputs:
            config.output.add(
                name=t.name,
                data_type=codec.config_datatype(t.dtype),
                dims=t.shape,
            )
        # ModelSpec.extra rides the config parameters map (JSON values)
        # so remote clients self-configure host-side prep — the role the
        # reference's client-side parse_model plays over ModelConfig
        # (base_client.py:32-104).
        import json

        for key, value in spec.extra.items():
            config.parameters[key] = json.dumps(value)
        return pb.ModelConfigResponse(config=config)

    def RepositoryIndex(self, request, context):
        resp = pb.RepositoryIndexResponse()
        for name in self._repo.names():
            for version in self._repo.versions(name):
                resp.models.add(name=name, version=version, state="READY")
        return resp

    # -- shared memory (Triton system-shared-memory extension) ----------------

    @staticmethod
    def _is_local_peer(context) -> bool:
        peer = context.peer()
        # ipv6:[::ffff:127.*] is the v4-mapped loopback a dual-stack
        # bind reports for a 127.0.0.1 dial
        return peer.startswith(
            ("ipv4:127.", "ipv6:[::1]", "ipv6:[::ffff:127.", "unix:")
        )

    @classmethod
    def _require_local(cls, context) -> None:
        """Shared memory is a SAME-HOST transport: registration maps a
        /dev/shm file into the server and infer requests can read/write
        it, so a remote peer must never reach it (a remote client could
        otherwise attach any flat-named segment on the server host and
        exfiltrate or corrupt it through model IO). Loopback and unix
        sockets only."""
        if not cls._is_local_peer(context):
            context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"shared-memory extension is restricted to same-host "
                f"clients (peer {context.peer()})",
            )

    def SystemSharedMemoryRegister(self, request, context):
        self._require_local(context)
        try:
            self._shm.register(
                request.name, request.key, request.offset, request.byte_size
            )
        except (ValueError, OSError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.SystemSharedMemoryRegisterResponse()

    def SystemSharedMemoryUnregister(self, request, context):
        self._require_local(context)
        if request.name:
            self._shm.unregister(request.name)
        else:
            self._shm.unregister_all()
        return pb.SystemSharedMemoryUnregisterResponse()

    def SystemSharedMemoryStatus(self, request, context):
        self._require_local(context)
        resp = pb.SystemSharedMemoryStatusResponse()
        try:
            regions = self._shm.status(request.name)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        for name, reg in regions.items():
            resp.regions[name].name = name
            resp.regions[name].key = reg.key
            resp.regions[name].offset = reg.offset
            resp.regions[name].byte_size = reg.byte_size
        return resp

    # -- inference ------------------------------------------------------------

    def _issue(self, request, inputs_override=None, id_override=None):
        """Parse + dispatch one request; returns a finisher callable.

        ``inputs_override``/``id_override``: set by _issue_group when
        this "request" is one member of a packed multi-frame stream
        message — the member's input views (already split off the
        group parse) and its per-member id replace the wire message's;
        parse, content decoding, and response shm placement are then
        skipped (the group was parsed once, encoded groups are not
        packed client-side, and a shared output region cannot serve G
        members).

        The dispatch goes through ``do_inference_async`` so the device
        (or inner batcher) starts while THIS thread still prepares the
        response scaffolding; the finisher resolves the future (the
        only blocking step — deferred readback) and encodes the
        response. Stream pipelining keeps several finishers pending.

        Telemetry: a request-scoped trace (when tracing is on) rides
        the InferRequest through the batcher and channel, collecting
        parse/queue/stage/launch/device/readback/encode spans; the
        per-model latency histogram sample is recorded in a finally so
        FAILING requests are measured and counted too (they previously
        vanished from the metrics entirely).

        SLO plane: when a tracker with a budget is wired, the request's
        absolute deadline is stamped HERE — at admission, before parse —
        and rides the InferRequest through the batcher (a merge takes
        the min of its members') to the staged launchers; _account
        scores met/missed on every exit path."""
        t0 = time.perf_counter()
        request_id = id_override if id_override is not None else request.id
        trace = None
        if self._tracer is not None:
            # adopt the inbound distributed context (router- or client-
            # originated traceparent in the request parameters) so this
            # replica's spans join the fleet-wide trace; absent or
            # malformed context degrades to a purely local trace
            context = TraceContext.decode(
                codec.get_string_param(request, TraceContext.PARAM_KEY) or ""
            )
            trace = self._tracer.start(
                model=request.model_name, request_id=request_id,
                context=context,
            )
        # quality plane: the sampling/canary key is the trace id when
        # tracing is on (stable fleet-wide: the router's traceparent is
        # adopted above, so router and replica decide identically) and
        # the request id otherwise; routing may rewrite which registered
        # model actually serves this request (canary slice)
        tctx = getattr(trace, "context", None)
        tid = tctx.trace_id if tctx is not None else (request_id or "")
        served_name = request.model_name
        if self._quality is not None:
            if not tid:
                tid = f"anon-{next(self._quality_seq)}"
            served_name = self._quality.route(request.model_name, tid)
        deadline_s, priority = None, 0
        if self._slo is not None:
            deadline_s = self._slo.deadline_for(request.model_name, t0)
            try:
                params = request.parameters
                if params and "priority" in params:
                    priority = int(params["priority"].int64_param)
            except (AttributeError, TypeError, ValueError):
                priority = 0  # malformed parameter: never fail the request
        # streaming-session identity (runtime/sessions.py) — decoded
        # independent of the SLO plane; absent on stateless requests
        sequence_id = codec.get_string_param(request, codec.SEQUENCE_ID_PARAM)
        sequence_start = sequence_end = False
        if sequence_id:
            sequence_start = codec.get_bool_param(
                request, codec.SEQUENCE_START_PARAM
            )
            sequence_end = codec.get_bool_param(
                request, codec.SEQUENCE_END_PARAM
            )
        if self._collector is not None:
            self._collector.request_started()
        with self._active_lock:
            self._active += 1
        admitted = False
        lifecycle_key = None
        try:
            # overload plane, cheapest checks first, BEFORE parse: a
            # shed request must cost microseconds, not a deserialize.
            # Raising from inside this try routes through _account, so
            # sheds are traced, error-counted, and SLO-scored as missed.
            if self._draining_now():
                raise ServerDrainingError(
                    "server is draining; retry against another replica"
                )
            if self._replica_down_now():
                # simulated process death: UNAVAILABLE with NO drain
                # marker, so routers run their ejection/budget path
                raise ReplicaDownError("replica is down (injected)")
            if self._admission is not None:
                try:
                    self._admission.admit(
                        request.model_name,
                        deadline_s=deadline_s,
                        priority=priority,
                        now=t0,
                    )
                except AdmissionRejectedError:
                    if self._collector is not None:
                        self._collector.record_shed(
                            request.model_name, priority, "admission"
                        )
                    raise
                admitted = True
            if self._lifecycle is not None:
                # promotion wait happens HERE, on the RPC thread: a
                # request for a cold model blocks (deadline-aware) while
                # the model pages in, so the batcher's single dispatcher
                # never head-of-line blocks on a warming model. The
                # reference is dropped in _account; the channel holds its
                # own acquire across the device window.
                try:
                    lifecycle_key = self._lifecycle.acquire(
                        served_name,
                        request.model_version,
                        deadline_s=deadline_s,
                    )
                except OverloadError:
                    if self._collector is not None:
                        self._collector.record_shed(
                            request.model_name, priority, "lifecycle"
                        )
                    raise
            if inputs_override is not None:
                inputs = inputs_override
            else:
                # chaos point: drop every attached segment right before
                # parse, so the parse fails exactly like a freshly
                # restarted server ('not registered' -> INVALID_ARGUMENT)
                # and clients must exercise their re-registration path
                if self._shm is not None and faults.probe_flag(
                    "shm_detach", request.model_name
                ):
                    self._shm.unregister_all()
                if trace is not None:
                    with trace.span("parse"):
                        inputs = codec.parse_infer_request(
                            request, shm=self._shm
                        )
                else:
                    inputs = codec.parse_infer_request(request, shm=self._shm)
                encodings = wire_encoding.encodings_of(request)
                if encodings:
                    # compressed wire payloads (JPEG frames, quantized
                    # pointclouds) decode on the host pool / device here;
                    # in a pipelined stream this runs on the reader
                    # thread while the previous request owns the device
                    if trace is not None:
                        with trace.span("decode"):
                            inputs = wire_encoding.decode_inputs(
                                inputs, encodings
                            )
                    else:
                        inputs = wire_encoding.decode_inputs(
                            inputs, encodings
                        )
            if trace is not None:
                # closed in finish() once the future resolves: the whole
                # channel-stack residence (queue/stage/device/readback
                # land inside it, plus the cross-thread hand-off gaps
                # none of those sub-spans can see)
                trace.begin("channel")
            ireq = InferRequest(
                model_name=served_name,
                model_version=request.model_version,
                inputs=inputs,
                request_id=request_id,
                trace=trace,
                deadline_s=deadline_s,
                priority=priority,
                sequence_id=sequence_id or "",
                sequence_start=sequence_start,
                sequence_end=sequence_end,
            )
            future = None
            if self._temporal is not None and sequence_id:
                # temporal reuse: the plane may serve this frame from
                # the stream's device-resident tracker alone (coast) or
                # from a changed-tiles sub-launch (partial); None means
                # keyframe — run the full detector below
                future = self._temporal.dispatch(ireq)
            if future is None:
                future = self._channel.do_inference_async(ireq)
            # overlapped with device execution: shm placement parsing
            # needs only the request, not the result
            shm_outputs = (
                {}
                if inputs_override is not None
                else {
                    t.name: params
                    for t in request.outputs
                    if (params := codec.shm_params(t)) is not None
                }
            )
        except BaseException as e:
            # parse/dispatch failed before a finisher existed: close out
            # the request's accounting here (finish() will never run)
            self._account(
                request.model_name, t0, trace, error=e,
                deadline_s=deadline_s, priority=priority,
                admitted=admitted, lifecycle_key=lifecycle_key,
            )
            raise

        def finish():
            error = None
            try:
                try:
                    result = future.result()
                finally:
                    if trace is not None:
                        trace.end("channel")
                if self._quality is not None:
                    # post-readback: outputs are host numpy here, so the
                    # sampled copy handed to the mirror queue costs no
                    # device sync on the serving path
                    try:
                        self._quality.observe(
                            request.model_name, served_name, tid,
                            inputs, result.outputs,
                        )
                    except Exception:
                        log.debug("quality observe failed", exc_info=True)
                if self._temporal is not None and sequence_id:
                    # keyframe feedback: stamps reuse_mode on the
                    # response, adapts K from the ridden-along
                    # innovation, runs the per-stream ID-churn gate
                    try:
                        self._temporal.observe(
                            request.model_name, sequence_id,
                            inputs, result.outputs,
                        )
                    except Exception:
                        log.debug("temporal observe failed", exc_info=True)
                if trace is not None:
                    t_e0 = time.perf_counter()
                    resp = codec.build_infer_response(
                        model_name=result.model_name,
                        model_version=result.model_version,
                        outputs=result.outputs,
                        request_id=result.request_id,
                        shm_outputs=shm_outputs,
                        shm=self._shm,
                        fallback_to_wire=True,
                    )
                    trace.add("encode", t_e0, time.perf_counter())
                    # compact span summary in the response parameters
                    # (AFTER the encode span lands, so the far side's
                    # grafted timeline includes it): the router/client
                    # merges it onto the end-to-end trace
                    codec.set_request_params(
                        resp, {SUMMARY_PARAM_KEY: encode_span_summary(trace)}
                    )
                    return resp
                return codec.build_infer_response(
                    model_name=result.model_name,
                    model_version=result.model_version,
                    outputs=result.outputs,
                    request_id=result.request_id,
                    shm_outputs=shm_outputs,
                    shm=self._shm,
                    fallback_to_wire=True,
                )
            except BaseException as e:
                error = e
                raise
            finally:
                self._account(
                    request.model_name, t0, trace, error=error,
                    deadline_s=deadline_s, priority=priority,
                    admitted=admitted, lifecycle_key=lifecycle_key,
                )

        return finish

    def _account(
        self, model_name, t0, trace, error=None, deadline_s=None, priority=0,
        admitted=False, lifecycle_key=None,
    ) -> None:
        """Per-request bookkeeping, success or failure: latency sample
        (the Triton :8002 serving-metrics role, README.md:88-95), error
        counter with a gRPC status-code label, in-flight gauge, trace
        finish, SLO attainment score. Reached from a ``finally`` on
        every request path (tpulint TPL503 pins that), so the
        deadline-missed and error paths are scored too."""
        now = time.perf_counter()
        if error is not None:
            # correlated failure line: the trace tag greps across the
            # router's and client's logs for the same request
            log.debug(
                "request for model %s failed with %s: %s%s",
                model_name, _grpc_code(error), error, log_tag(trace),
            )
        elif log.isEnabledFor(logging.DEBUG):
            log.debug(
                "request for model %s served in %.1f ms%s",
                model_name, (now - t0) * 1e3, log_tag(trace),
            )
        if self._tracer is not None:
            # close the trace FIRST: everything below is bookkeeping
            # that would otherwise show up as an uncovered tail on the
            # request wall. Finishing also feeds the per-(model, stage)
            # latency histograms, so the SLO tracker's p99 tail
            # criterion below sees this request's e2e sample.
            self._tracer.finish(
                trace, status="ok" if error is None else _grpc_code(error)
            )
        if self._slo is not None:
            self._slo.observe_request(
                model_name,
                wall_s=now - t0,
                deadline_s=deadline_s,
                priority=priority,
                status="ok" if error is None else _grpc_code(error),
                trace=trace,
                now=now,
            )
        if self._profiler is not None:
            self._profiler.record(
                f"infer_{model_name}", time.perf_counter() - t0
            )
        if self._collector is not None:
            if error is not None:
                self._collector.record_error(model_name, _grpc_code(error))
            self._collector.request_finished()
        if self._admission is not None and admitted:
            # successful requests feed the EWMA the estimated-wait
            # check divides by; failures only release their slot
            self._admission.finished(
                model_name,
                service_s=(now - t0) if error is None else None,
            )
        if self._lifecycle is not None and lifecycle_key is not None:
            self._lifecycle.release(*lifecycle_key)
        with self._active_lock:
            self._active -= 1

    def _infer(self, request):
        return self._issue(request)()

    def _uses_shm(self, request) -> bool:
        return any(
            "shared_memory_region" in t.parameters
            for t in list(request.inputs) + list(request.outputs)
        )

    @staticmethod
    def _stream_group_size(request) -> int:
        return max(1, codec.get_int_param(request, codec.STREAM_GROUP_PARAM, 1))

    def _record_transport(self, request, context) -> None:
        """Feed the transport-mix counters: which transport carried
        this request's tensors and how many payload bytes each moved.
        (Input side only — it dominates for perception serving, and
        response bytes are not knowable until resolution.)"""
        if self._collector is None:
            return
        wire_bytes = sum(len(b) for b in request.raw_input_contents)
        shm_bytes = 0
        for t in request.inputs:
            p = t.parameters
            if "shared_memory_region" in p and "shared_memory_byte_size" in p:
                shm_bytes += int(p["shared_memory_byte_size"].int64_param)
        uds = context.peer().startswith("unix:")
        if shm_bytes:
            transport = "uds+shm" if uds else "shm"
        else:
            transport = "uds" if uds else "grpc"
        self._collector.record_transport(transport, wire_bytes, shm_bytes)

    def _issue_group(self, request):
        """Fan one multi-frame stream message into per-member batcher
        requests; returns one finisher per member, in member order.

        The packed message concatenates G equal-shape frames along the
        leading axis (client: GRPCChannel._stage_stream_group); each
        member is issued through the full admission/lifecycle/batcher
        path as its own request with its own id, so the continuous
        batcher schedules members individually and responses stream
        back as each resolves. Member inputs are zero-copy views into
        the group parse — no unpack copy. A member whose ISSUE fails
        (shed, cold model) becomes a finisher that raises its error,
        so the other members still serve and the client sees a
        per-member error_message."""
        g = self._stream_group_size(request)
        if g == 1:
            return [self._issue(request)]
        if self._shm is not None and faults.probe_flag(
            "shm_detach", request.model_name
        ):
            self._shm.unregister_all()
        inputs = codec.parse_infer_request(request, shm=self._shm)
        members: list[dict] = [{} for _ in range(g)]
        for name, arr in inputs.items():
            if arr.ndim < 1 or arr.shape[0] % g:
                raise ValueError(
                    f"stream group of {g} needs every input's leading "
                    f"axis divisible by {g}; input {name!r} has shape "
                    f"{tuple(arr.shape)}"
                )
            b = arr.shape[0] // g
            for i in range(g):
                members[i][name] = arr[i * b : (i + 1) * b]
        raw_ids = codec.get_string_param(
            request, codec.STREAM_GROUP_IDS_PARAM
        )
        try:
            ids = json.loads(raw_ids) if raw_ids else []
        except ValueError:
            ids = []
        if len(ids) != g:
            ids = [f"{request.id}#{i}" if request.id else "" for i in range(g)]
        def deferred_error(err):
            def fin():
                raise err
            return fin

        finishers = []
        for i in range(g):
            try:
                fin = self._issue(
                    request, inputs_override=members[i], id_override=ids[i]
                )
            except Exception as e:
                # already accounted by _issue's except path; defer the
                # error to this member's response slot
                fin = deferred_error(e)
            finishers.append(fin)
        return finishers

    @staticmethod
    def _group_error(request, e: BaseException) -> str:
        """error_message for a failure that consumed a WHOLE stream
        entry before any member was issued (group parse/validation):
        the prefix tells the client to retire all G member slots at
        once instead of waiting for per-member responses."""
        g = _Servicer._stream_group_size(request)
        if g > 1:
            return f"stream group failed: {e}"
        return str(e)

    def ModelInfer(self, request, context):
        if self._uses_shm(request):
            self._require_local(context)
        self._record_transport(request, context)
        try:
            return self._infer(request)
        except OverloadError as e:
            context.abort(_GRPC_STATUS[_grpc_code(e)], str(e))
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Exception as e:
            # launch/readback faults (incl. injected ones) abort as
            # INTERNAL — matching the _grpc_code error-counter label —
            # instead of grpc's opaque UNKNOWN, so clients can key
            # retry-elsewhere policy on a stable code
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def ModelStreamInfer(self, request_iterator, context):
        """Pipelined stream serving: up to ``stream_pipeline_depth``
        requests stay in flight per stream — request N+1 parses and
        launches (on a reader thread) while request N's compute runs;
        responses come back in request order, each sent the moment it
        resolves. Responses are NEVER withheld pending further
        requests, so a lock-step client (send, wait, send) sees
        strictly serial semantics regardless of depth — the pipelining
        only engages when the client itself keeps requests in flight.
        Depth 1 skips the reader thread entirely."""
        if self._stream_depth <= 1:
            for request in request_iterator:
                if self._uses_shm(request):
                    self._require_local(context)
                self._record_transport(request, context)
                if (
                    self._collector is not None
                    and (g := self._stream_group_size(request)) > 1
                ):
                    self._collector.record_stream_group(g)
                try:
                    finishers = self._issue_group(request)
                except (KeyError, ValueError, OverloadError) as e:
                    yield pb.ModelStreamInferResponse(
                        error_message=self._group_error(request, e)
                    )
                    continue
                for fin in finishers:
                    try:
                        yield pb.ModelStreamInferResponse(
                            infer_response=fin()
                        )
                    except (KeyError, ValueError, OverloadError) as e:
                        yield pb.ModelStreamInferResponse(
                            error_message=str(e)
                        )
            return

        import queue
        import threading

        # bounded handoff: the reader blocks once `depth` issued
        # requests are awaiting resolution — the device-side
        # backpressure for a client that floods the stream
        q: queue.Queue = queue.Queue(maxsize=self._stream_depth)

        def issue_loop() -> None:
            try:
                for request in request_iterator:
                    if self._uses_shm(request) and not self._is_local_peer(
                        context
                    ):
                        # the abort must run on the handler thread
                        q.put(("non_local", None))
                        return
                    self._record_transport(request, context)
                    if (
                        self._collector is not None
                        and (g := self._stream_group_size(request)) > 1
                    ):
                        self._collector.record_stream_group(g)
                    try:
                        finishers = self._issue_group(request)
                    except (KeyError, ValueError, OverloadError) as e:
                        q.put(("error", self._group_error(request, e)))
                        continue
                    # members are already issued (the batcher owns
                    # them); the bounded puts pace the READER so the
                    # next group is not parsed until this one's
                    # finishers are draining
                    for finish in finishers:
                        q.put(("finish", finish))
            except Exception as e:  # surface reader crashes to the RPC
                q.put(("crash", e))
            finally:
                q.put(("done", None))

        reader = threading.Thread(
            target=issue_loop, name="stream-issue", daemon=True
        )
        reader.start()
        try:
            while True:
                kind, payload = q.get()
                if kind == "done":
                    return
                if kind == "finish":
                    try:
                        yield pb.ModelStreamInferResponse(
                            infer_response=payload()
                        )
                    except (KeyError, ValueError, OverloadError) as e:
                        yield pb.ModelStreamInferResponse(
                            error_message=str(e)
                        )
                elif kind == "error":
                    yield pb.ModelStreamInferResponse(error_message=payload)
                elif kind == "non_local":
                    self._require_local(context)
                else:  # crash
                    raise payload
        finally:
            reader.join(timeout=5.0)


class InferenceServer:
    """Owns the grpc.Server; serve(), then shutdown()."""

    def __init__(
        self,
        repository: ModelRepository,
        channel: BaseChannel,
        address: str = "0.0.0.0:8001",
        uds_address: str | None = None,
        max_workers: int = 8,
        max_message_bytes: int | None = None,
        profiler=None,
        metrics_port: int | str = 0,
        stream_pipeline_depth: int = 2,
        trace_capacity: int = 256,
        slo_ms: float = 0.0,
        slo_per_model: dict | None = None,
        slo_tail_capacity: int = 64,
        admission_max_queue: int = 0,
        admission_concurrency: int = 4,
        lifecycle=None,
        tenants=None,
        replica_of: str | None = None,
        op_sample_interval_s: float = 0.0,
        op_sample_window_s: float = 0.2,
        history_interval_s: float = 10.0,
        history_capacity: int = 360,
        history_path: str | None = None,
        quality=None,
        temporal=None,
    ) -> None:
        """``metrics_port``: serve the telemetry endpoint — Prometheus
        exposition on ``/metrics`` (Triton's :8002 role), Chrome-trace
        JSON on ``/traces``, raw collector state on ``/snapshot``.
        0 disables; ``"auto"`` binds an ephemeral port (read it back
        from ``.metrics_port`` — tests and multi-server processes).
        ``profiler``: a StageProfiler to record into (created
        automatically when metrics_port is set).
        ``stream_pipeline_depth``: in-flight requests per
        ModelStreamInfer stream (request N+1 launches while N computes;
        1 = strictly serial, the pre-round-6 behavior).
        ``trace_capacity``: bounded ring of recent request traces kept
        for export (0 disables request tracing; spans then cost one
        attribute read per pipeline phase).
        ``slo_ms``: default per-request latency budget — requests are
        deadline-stamped at admission and scored met/missed on every
        exit path (0 = no SLO; histograms and the tail sampler's p99
        criterion still run). ``slo_per_model`` overrides budgets per
        model name; ``slo_tail_capacity`` bounds the ring of
        SLO-violating / p99+ exemplar traces exported at
        ``/traces?slo_violations=1``. The SLO ring requires
        ``metrics_port`` (it lives on the telemetry plane).
        ``admission_max_queue``: per-model admitted-but-unfinished cap
        for the admission controller (0 = no admission control, the
        pre-round-7 behavior); requests beyond it — or whose estimated
        queue wait exceeds their deadline budget — are rejected with
        RESOURCE_EXHAUSTED before parse. ``admission_concurrency``:
        assumed per-model service concurrency for the estimated-wait
        math (batcher width x pipeline depth, roughly).
        ``lifecycle``: a ModelLifecycleManager (runtime/lifecycle.py,
        already attached to the serving channel) — requests for COLD
        models then block on the RPC thread with a deadline-aware bound
        while the model pages in, instead of erroring.
        ``tenants``: a TenantTable mapping models to tenants; feeds the
        admission controller's per-tenant in-flight caps (fair-share
        ready ordering is attached on the batcher via
        ``attach_tenants``).
        ``replica_of``: replica-set label (``serve --replica-of``) —
        keys the ``replica_down`` fault point and is advertised via
        ServerMetadata.extensions for the route tool.
        ``uds_address``: additionally listen on a unix socket
        (``unix:/path`` / bare path / ``"auto"`` for a generated
        per-process path) alongside TCP — same-host clients then skip
        the loopback TCP stack entirely and their ``unix:`` peer
        passes the shared-memory locality gate by construction. Read
        the bound target back from ``.uds_address``; the socket file
        is unlinked on stop().
        ``op_sample_interval_s``: > 0 starts the continuous op sampler
        (obs/sampler.py): a short jax.profiler window every interval,
        parsed into top-K per-op device time on the collector
        (structurally capped at a 1% capture duty cycle;
        ``op_sample_window_s`` bounds one window). Shares the
        /profile capture guard — on-demand captures always win.
        ``history_interval_s``/``history_capacity``: the metric-history
        ring (obs/history.py) of per-model×tenant rate/util/MFU
        snapshots served at ``/history``; ``history_path`` persists the
        ring there on drain (and restores from it on startup).
        ``quality``: an eval.quality_plane.QualityPlane — the servicer
        then consults its canary router before dispatch and hands every
        response to its trace-hash sampler; shadow mirroring runs
        against this server's own channel stack unless the plane was
        built with an explicit (router) channel. Exports as the
        ``tpu_quality_*`` families, ``/snapshot["quality"]``, and the
        history ring's ``quality`` rows when telemetry is on.
        ``temporal``: a runtime.temporal.TemporalReusePlane — session
        frames then consult the per-stream keyframe scheduler before
        dispatch (coast/partial frames skip the detector), the device-
        time ledger is attached so skipped work is charged honestly,
        and the quality plane's window violations disable reuse per
        model. Exports under ``/snapshot["temporal"]`` and the
        ``tpu_serving_frames_total{mode=...}`` families."""
        self.lifecycle = lifecycle
        self.tenants = tenants
        self.replica_of = replica_of
        self.admission = (
            AdmissionController(
                max_queue=admission_max_queue,
                concurrency=admission_concurrency,
                tenants=tenants,
            )
            if admission_max_queue > 0
            else None
        )
        self._draining = threading.Event()
        if metrics_port and profiler is None:
            from triton_client_tpu.obs.profiling import StageProfiler

            profiler = StageProfiler()
        self.profiler = profiler
        self.tracer = None
        self.collector = None
        self.histograms = None
        self.slo = None
        self.device_time = None
        self.sampler = None
        self.history = None
        self._history_path = history_path
        self.quality = quality
        self.temporal = temporal
        if temporal is not None and quality is not None and hasattr(
            quality, "attach_temporal"
        ):
            # quality-gated reuse: a rolling-window violation on a
            # model turns its temporal shortcuts off, canary-style
            quality.attach_temporal(temporal)
        if quality is not None and getattr(
            quality.mirror, "_channel", None
        ) is None:
            # shadow dispatch defaults to this server's own stack: the
            # mirror re-issues sampled inputs at the back of the same
            # batcher/channel queue every live request rides
            quality.attach_channel(channel)
        self.metrics_enabled = False
        self._telemetry = None
        if metrics_port:
            # Degrade, don't die: telemetry is optional observability —
            # a missing prometheus_client or an occupied port must not
            # take down the inference service (the reference's optional
            # import pattern, communicator/__init__.py:5-8).
            registry = None
            try:
                import prometheus_client

                from triton_client_tpu.obs.profiling import (
                    PrometheusStageExporter,
                )

                # per-server registry: several InferenceServers in one
                # process each export their own complete metric set
                registry = prometheus_client.CollectorRegistry()
                PrometheusStageExporter(
                    0, registry=registry
                ).attach(profiler)
            except ImportError:
                log.warning(
                    "prometheus_client not installed; /metrics on port %s "
                    "disabled (traces still export)", metrics_port,
                )
            from triton_client_tpu.obs.collector import RuntimeCollector
            from triton_client_tpu.obs.histogram import HistogramFamily
            from triton_client_tpu.obs.slo import SLOTracker
            from triton_client_tpu.obs.trace import Tracer

            # the SLO ring: per-(model, stage) latency histograms fed
            # from finished traces, and the deadline/attainment tracker
            # whose tail sampler keeps slow-request exemplars. Built
            # whenever telemetry is on — with no slo_ms the histograms
            # and tail p99 criterion still run, only met/missed scoring
            # waits for a budget.
            self.histograms = HistogramFamily()
            self.slo = SLOTracker(
                slo_ms=slo_ms,
                per_model=slo_per_model,
                tail_capacity=slo_tail_capacity,
                histograms=self.histograms,
            )
            if trace_capacity > 0:
                self.tracer = Tracer(
                    capacity=trace_capacity, profiler=profiler,
                    histograms=self.histograms,
                )
            from triton_client_tpu.obs.device_time import DeviceTimeLedger

            # device-time ledger on the innermost staged channel (walk
            # one `inner` level for a batcher-wrapped stack): every
            # launch's device-execute window then accrues into per-
            # model×tenant device-seconds + live MFU, exported below
            target = channel
            if not hasattr(target, "attach_device_time"):
                target = getattr(channel, "inner", None)
            if target is not None and hasattr(target, "attach_device_time"):
                devices = 1
                try:
                    devices = int(target.fetch_channel().devices.size)
                except Exception:
                    pass
                tenant_table = tenants
                if tenant_table is None and lifecycle is not None:
                    tenant_table = getattr(lifecycle, "tenants", None)
                self.device_time = DeviceTimeLedger(
                    tenants=tenant_table, devices=devices
                )
                target.attach_device_time(self.device_time)
                if temporal is not None:
                    # coast/partial frames charge their (small) device
                    # windows to stream:<id> like full frames do — the
                    # per-stream device-seconds scoreboard stays honest
                    temporal.attach_ledger(self.device_time)
            # metric history: a fixed-interval ring of ledger deltas
            # (per-model×tenant rates, utilization, MFU) served at
            # /history and persisted across the drain/restart boundary
            if self.device_time is not None and history_interval_s > 0:
                from triton_client_tpu.obs.history import MetricHistory

                self.history = MetricHistory(
                    ledger=self.device_time,
                    interval_s=history_interval_s,
                    capacity=history_capacity,
                )
                if history_path and os.path.exists(history_path):
                    try:
                        self.history.restore(MetricHistory.load(history_path))
                    except (OSError, ValueError):
                        log.warning(
                            "could not restore metric history from %s",
                            history_path, exc_info=True,
                        )
                self.history.start()
            self.collector = RuntimeCollector(
                channel=channel, tracer=self.tracer, registry=registry,
                repository=repository, histograms=self.histograms,
                slo=self.slo, admission=self.admission,
                lifecycle=lifecycle, device_time=self.device_time,
            )
            if self.history is not None:
                self.collector.attach_history(self.history)
            if quality is not None:
                self.collector.attach_quality(quality)
                if self.history is not None:
                    self.history.attach_quality(quality)
            if temporal is not None:
                self.collector.attach_temporal(temporal)
            try:
                from triton_client_tpu.obs.http import TelemetryServer

                self._telemetry = TelemetryServer(
                    port=0 if metrics_port == "auto" else int(metrics_port),
                    registry=registry,
                    tracer=self.tracer,
                    collector=self.collector,
                    slo=self.slo,
                    history=self.history,
                )
                self.metrics_enabled = registry is not None
                if op_sample_interval_s > 0:
                    from triton_client_tpu.obs.sampler import (
                        ContinuousSampler,
                    )

                    # shares the /profile capture guard: a background
                    # window never collides with an on-demand capture
                    # (jax.profiler is a process-global singleton)
                    self.sampler = ContinuousSampler(
                        sink=self.collector,
                        interval_s=op_sample_interval_s,
                        window_s=op_sample_window_s,
                        lock=self._telemetry.profile_lock,
                        hlo_modules=self.collector.hlo_modules,
                    )
                    self.collector.attach_sampler(self.sampler)
                    self.sampler.start()
            except OSError as e:
                log.warning(
                    "could not bind metrics port %s (%s); telemetry "
                    "endpoint disabled", metrics_port, e,
                )
        limit = max_message_bytes or message_limit(repository)
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", limit),
                ("grpc.max_receive_message_length", limit),
            ],
        )
        from triton_client_tpu.runtime.shared_memory import (
            SystemSharedMemoryRegistry,
        )

        self.shm_registry = SystemSharedMemoryRegistry()
        self._servicer = _Servicer(
            repository,
            channel,
            profiler=profiler,
            shm_registry=self.shm_registry,
            stream_pipeline_depth=stream_pipeline_depth,
            tracer=self.tracer,
            collector=self.collector,
            slo=self.slo,
            admission=self.admission,
            draining=self._draining,
            lifecycle=lifecycle,
            replica_of=replica_of,
            quality=quality,
            temporal=temporal,
        )
        service.add_servicer_to_server(self._servicer, self._server)
        self._port = self._server.add_insecure_port(address)
        if self._port == 0:
            raise RuntimeError(f"could not bind {address}")
        self._address = address
        self.uds_address: str | None = None
        self._uds_path: str | None = None
        if uds_address:
            from triton_client_tpu.channel import transport as transports

            path = uds_address
            if path == "auto":
                import tempfile

                path = os.path.join(
                    tempfile.gettempdir(),
                    f"tct_serve_{os.getpid()}_{self._port}.sock",
                )
            elif transports.is_uds(path):
                path = transports.uds_path(path)
            try:
                # a stale socket from a crashed run blocks the bind;
                # a LIVE server's socket would too — last binder wins,
                # same as SO_REUSEADDR semantics on the TCP side
                os.unlink(path)
            except FileNotFoundError:
                pass
            if self._server.add_insecure_port(f"unix:{path}") == 0:
                raise RuntimeError(f"could not bind unix:{path}")
            self.uds_address = f"unix:{path}"
            self._uds_path = path
        # the channel stack is part of the server's public surface:
        # embedders read stats()/batch_multiple off it, and start()
        # logs the mesh-serving shape it implies
        self.channel = channel

    def _channel_multiple(self) -> int:
        """Data-axis width of the serving channel stack (walk one
        ``inner`` level for a batcher-wrapped mesh channel)."""
        c = self.channel
        m = getattr(c, "batch_multiple", 1)
        inner = getattr(c, "inner", None)
        if inner is not None:
            m = max(m, getattr(inner, "batch_multiple", 1))
        return int(m)

    @property
    def port(self) -> int:
        return self._port

    @property
    def metrics_port(self) -> int:
        """Bound telemetry port (0 when telemetry is disabled)."""
        return self._telemetry.port if self._telemetry is not None else 0

    def start(self) -> None:
        self._server.start()
        multiple = self._channel_multiple()
        listening = self._address
        if self.uds_address:
            listening = f"{listening} + {self.uds_address}"
        if multiple > 1:
            log.info(
                "KServe v2 server listening on %s (mesh serving: batches "
                "shard over a data axis of %d)", listening, multiple,
            )
        else:
            log.info("KServe v2 server listening on %s", listening)

    def wait(self) -> None:
        self._server.wait_for_termination()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout_s: float = 10.0, poll_s: float = 0.02) -> bool:
        """Graceful shutdown (the SIGTERM path): flip health not-ready
        and refuse NEW requests with UNAVAILABLE, let in-flight work
        complete up to ``timeout_s``, then tear down in order — gRPC
        transport, telemetry endpoint, collector, shared-memory
        mappings, and finally the channel stack (batcher dispatcher /
        executors / arena, via its ``close()``). Returns True when the
        building emptied inside the timeout, False when stragglers were
        force-cancelled. Idempotent with :meth:`stop`."""
        self._draining.set()
        if self.collector is not None:
            self.collector.set_draining(True)
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        drained = False
        while time.monotonic() < deadline:
            if self._servicer.active_requests() <= 0:
                drained = True
                break
            time.sleep(poll_s)
        # let the shadow mirror finish scoring what it already holds —
        # the final history tick below should carry the last window
        if self.quality is not None:
            self.quality.drain(
                max(0.0, deadline - time.monotonic()) or 1.0
            )
        # final history tick + persist: the restart this ring is most
        # needed across is the one about to happen
        if self.history is not None:
            self.history.tick()
            if self._history_path:
                try:
                    self.history.persist(self._history_path)
                except OSError:
                    log.warning(
                        "could not persist metric history to %s",
                        self._history_path, exc_info=True,
                    )
        # stop(grace) rejects anything new at the transport and waits
        # out stragglers up to the remaining budget before cancelling
        self.stop(grace=max(0.0, deadline - time.monotonic()) + 0.1)
        close = getattr(self.channel, "close", None)
        if close is not None:
            close()
        return drained

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()
        if self.quality is not None:
            self.quality.close()
        if self.sampler is not None:
            self.sampler.close()
            self.sampler = None
        if self.history is not None:
            self.history.close()
        if self._telemetry is not None:
            self._telemetry.close()
            self._telemetry = None
        if self.collector is not None:
            self.collector.close()
        # detach (never unlink — the segments are client-owned)
        self.shm_registry.unregister_all()
        if self._uds_path is not None:
            # the SOCKET file is server-owned (unlike the shm segments)
            try:
                os.unlink(self._uds_path)
            except FileNotFoundError:
                pass
            self._uds_path = None
