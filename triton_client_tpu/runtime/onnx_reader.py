"""Minimal ONNX weight reader (no onnx package dependency).

The reference serves .onnx artifacts straight from the Triton model
repository (examples/YOLOv5/config.pbtxt:2 'platform:
"onnxruntime_onnx"'; deploy.sh converts .pth -> .onnx before pushing).
To import those same artifacts into flax without the onnx pip package
(not in this image), this module hand-parses the protobuf wire format —
ONNX ModelProto is plain proto3, and for weights we only need:

  ModelProto.graph (field 7) -> GraphProto.initializer (field 5, repeated
  TensorProto) -> {name (8), dims (1), data_type (2), raw_data (9) or
  the typed *_data arrays (4/5/7/10/11)}.

Wire format: each record is a varint key (field_no << 3 | wire_type);
wire types used by ONNX are 0 (varint), 1 (64-bit), 2 (length-
delimited), 5 (32-bit).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

# TensorProto.DataType enum -> numpy dtype (bfloat16 resolved lazily).
_ONNX_DTYPES: dict[int, object] = {
    1: np.float32,
    2: np.uint8,
    3: np.int8,
    4: np.uint16,
    5: np.int16,
    6: np.int32,
    7: np.int64,
    9: np.bool_,
    10: np.float16,
    11: np.float64,
    12: np.uint32,
    13: np.uint64,
}
_BFLOAT16 = 16


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: memoryview) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    Length-delimited values come back as memoryviews (zero-copy)."""
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            value, pos = _read_varint(buf, pos)
        elif wire == 1:
            value = bytes(buf[pos:pos + 8])
            pos += 8
        elif wire == 2:
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == 5:
            value = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, value


def _packed_varints(value: object, wire: int) -> list[int]:
    """A repeated int field arrives either packed (one length-delimited
    blob) or as individual varint records."""
    if wire == 0:
        return [int(value)]  # type: ignore[arg-type]
    out = []
    pos = 0
    buf = value
    while pos < len(buf):  # type: ignore[arg-type]
        v, pos = _read_varint(buf, pos)  # type: ignore[arg-type]
        out.append(v)
    return out


def _unzigzag64(v: int) -> int:
    """ONNX dims are int64 varints (not zigzag); map 2^63.. to negative."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_tensor(buf: memoryview) -> tuple[str, np.ndarray]:
    dims: list[int] = []
    data_type = 1
    name = ""
    raw: memoryview | None = None
    typed: dict[int, list[object]] = {}
    for field, wire, value in _iter_fields(buf):
        if field == 1:
            dims.extend(_unzigzag64(v) for v in _packed_varints(value, wire))
        elif field == 2:
            data_type = int(value)  # type: ignore[arg-type]
        elif field == 8:
            name = bytes(value).decode()  # type: ignore[arg-type]
        elif field == 9:
            raw = value  # type: ignore[assignment]
        elif field in (4, 10):  # float_data / double_data (packed f32/f64)
            typed.setdefault(field, []).append((value, wire))
        elif field in (5, 7, 11):  # int32/int64/uint64 (packed varints)
            typed.setdefault(field, []).append((value, wire))

    if data_type == _BFLOAT16:
        import ml_dtypes

        np_dtype = np.dtype(ml_dtypes.bfloat16)
    elif data_type in _ONNX_DTYPES:
        np_dtype = np.dtype(_ONNX_DTYPES[data_type])
    else:
        raise ValueError(f"tensor '{name}': unsupported ONNX data_type {data_type}")

    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype.newbyteorder("<"))
    elif typed:
        field, chunks = next(iter(typed.items()))
        if field in (4, 10):
            width = np.float32 if field == 4 else np.float64
            parts = [
                np.frombuffer(v, dtype=np.dtype(width).newbyteorder("<"))
                if w == 2
                else np.frombuffer(bytes(v), dtype=width)
                for v, w in chunks
            ]
            arr = np.concatenate(parts).astype(np_dtype)
        else:
            ints: list[int] = []
            for v, w in chunks:
                ints.extend(_packed_varints(v, w))
            # Varints arrive as raw unsigned 64-bit patterns: negatives
            # are sign-extended (10-byte) encodings, and fp16/bf16 in
            # int32_data are IEEE bit patterns per the ONNX spec — both
            # need reinterpretation, not numeric conversion.
            u64 = np.asarray(ints, dtype=np.uint64)
            if np_dtype.kind == "f" or data_type == _BFLOAT16:
                arr = u64.astype(np.uint16).view(np_dtype)
            elif np_dtype.kind == "i":
                arr = u64.view(np.int64).astype(np_dtype)
            else:  # unsigned / bool
                arr = u64.astype(np_dtype)
    else:
        arr = np.zeros(0, np_dtype)
    return name, arr.reshape(dims) if dims else arr.reshape(())


def read_onnx_initializers(path_or_bytes) -> dict[str, np.ndarray]:
    """Parse an .onnx file's graph initializers into {name: ndarray}.

    Raises on external-data tensors (field 13/14) implicitly: those
    tensors carry no raw_data and come back empty — callers converting
    real weights will fail shape checks loudly rather than silently.
    """
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        blob = memoryview(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            blob = memoryview(f.read())

    out: dict[str, np.ndarray] = {}
    for field, _, value in _iter_fields(blob):
        if field == 7:  # ModelProto.graph
            for gfield, _, gvalue in _iter_fields(value):  # type: ignore[arg-type]
                if gfield == 5:  # GraphProto.initializer
                    name, arr = _parse_tensor(gvalue)  # type: ignore[arg-type]
                    out[name] = arr
    return out


def onnx_to_state_dict(initializers: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Normalize ONNX initializer names to torch state_dict style so the
    checkpoint name maps apply unchanged: exporters (torch.onnx, the
    reference's deploy.sh path) name initializers after the module
    parameters ('model.0.conv.weight'); strip any leading '/' graph
    scoping some exporters add."""
    return {k.lstrip("/").replace("::", "."): v for k, v in initializers.items()}
