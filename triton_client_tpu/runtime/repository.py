"""Model repository: versioned registry of jit-compiled model functions.

Triton's model repository is a directory tree of config.pbtxt + backend
artifacts loaded by a C++ backend manager (reference examples/ layout,
SURVEY.md section 2 #20-21). Here a model is a ModelSpec plus a python
callable over jax arrays; versions are kept in a sorted dict and "the
latest version" is the default serve target, matching Triton's
version_policy default.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Mapping

from triton_client_tpu.config import ModelSpec

# An infer function maps {input_name: jax.Array} -> {output_name: jax.Array}.
InferFn = Callable[[Mapping[str, object]], dict[str, object]]


def _version_key(v: str):
    """Single source of the 'latest version' ordering used by get() and
    versions(): numeric-style compare ('10' > '9') with lexical tiebreak."""
    return (len(v), v)


@dataclasses.dataclass
class RegisteredModel:
    # CONTRACT (round 4 dtype policy): infer_fn may receive inputs
    # NARROWER than the declared wire dtype (e.g. uint8 frames against
    # an FP32 spec) — TPUChannel deliberately skips host-side widening
    # so the 4x-inflated host->device copy never happens
    # (channel/tpu_channel.py). Every pipeline registered here must
    # therefore widen/normalize INSIDE its jitted program, where the
    # cast fuses for free, and must not trust the declared dtype of a
    # leading input. Out-of-tree pipelines that cannot widen internally
    # should declare the narrow dtype in their spec instead.
    spec: ModelSpec
    infer_fn: InferFn
    # Optional warmup callable (compile-ahead on register)
    warmup: Callable[[], None] | None = None
    # Optional jit-traceable form of the model: {name: jax.Array} ->
    # {name: jax.Array} with the SAME tensor names as the wire spec but
    # device arrays end to end. Ensembles compose members through this
    # under ONE jit so intermediates stay in HBM (runtime/ensemble.py);
    # None means the model is host-only (wire path still works).
    device_fn: InferFn | None = None
    # Optional explicit param pytree for the replicate-params /
    # shard-batch serving shape (channel/sharded_channel.py): when set,
    # device_fn must accept ``(inputs, params)`` and the sharded channel
    # uploads the tree ONCE per mesh (replicated on every device) at
    # launcher build instead of letting the closure re-trace captured
    # host constants per executable. None keeps the closure-captured
    # convention every in-tree pipeline uses today.
    params: object | None = None
    # Optional serving PrecisionPolicy (runtime/precision.py), applied
    # at registration: the builder already cast/quantized the param
    # tree; the serving channels consult this for the WIRE half of the
    # policy (host-side narrowing in staged.cast_wire_input, int8
    # dequant inside the cached launcher). None serves the legacy f32
    # wire unchanged.
    precision: object | None = None
    # Optional segment-aware form of the model for packed-ragged
    # batches (runtime/continuous.py): ``ragged_fn(inputs, segment_ids,
    # num_segments) -> outputs`` where each input named in
    # ``spec.extra["ragged_inputs"]`` is a packed (R, ...) row
    # concatenation, ``segment_ids`` is the (R,) int32 row->request
    # table (pad rows carry an out-of-range id), ``num_segments`` is a
    # STATIC python int, and every output has leading dim
    # ``num_segments`` (request-major). None means the model only runs
    # dense.
    ragged_fn: object | None = None


class ModelRepository:
    """Thread-safe name -> version -> model registry."""

    def __init__(self) -> None:
        self._models: dict[str, dict[str, RegisteredModel]] = {}
        self._lock = threading.Lock()
        # unregister listeners: fn(name, version), called once per
        # removed version OUTSIDE the registry lock. Serving channels
        # subscribe so a dropped model also drops its cached launcher
        # (and the replicated params that closure pins in HBM) — the
        # same invalidation path the circuit breaker uses.
        self._unregister_listeners: list[Callable[[str, str], None]] = []
        # access accounting for lifecycle LRU: per-name hit count and
        # last-touch monotonic sequence, maintained by get().
        self._access_count: dict[str, int] = {}
        self._access_seq: dict[str, int] = {}
        self._seq = 0

    def add_unregister_listener(self, fn: Callable[[str, str], None]) -> None:
        with self._lock:
            self._unregister_listeners.append(fn)

    def register(
        self,
        spec: ModelSpec,
        infer_fn: InferFn,
        warmup: Callable[[], None] | None = None,
        device_fn: InferFn | None = None,
        params: object | None = None,
        precision: object | None = None,
        ragged_fn: object | None = None,
    ) -> None:
        with self._lock:
            self._models.setdefault(spec.name, {})[spec.version] = RegisteredModel(
                spec, infer_fn, warmup, device_fn, params, precision, ragged_fn
            )

    def unregister(self, name: str, version: str = "") -> None:
        removed: list[tuple[str, str]] = []
        with self._lock:
            if version:
                if self._models.get(name, {}).pop(version, None) is not None:
                    removed.append((name, version))
                if not self._models.get(name):
                    self._models.pop(name, None)
            else:
                for v in self._models.pop(name, {}):
                    removed.append((name, v))
            listeners = list(self._unregister_listeners)
        # notify outside the lock: listeners take channel locks of
        # their own and must be free to call back into the repository
        for n, v in removed:
            for fn in listeners:
                fn(n, v)

    def get(self, name: str, version: str = "") -> RegisteredModel:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"model '{name}' is not registered")
            self._seq += 1
            self._access_count[name] = self._access_count.get(name, 0) + 1
            self._access_seq[name] = self._seq
            if version:
                if version not in versions:
                    raise KeyError(f"model '{name}' has no version '{version}'")
                return versions[version]
            latest = max(versions, key=_version_key)
            return versions[latest]

    def metadata(self, name: str, version: str = "") -> ModelSpec:
        return self.get(name, version).spec

    def list_models(self) -> list[tuple[str, str]]:
        with self._lock:
            return [(n, v) for n, vs in self._models.items() for v in vs]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self, name: str) -> list[str]:
        with self._lock:
            return sorted(self._models.get(name, {}), key=_version_key)

    def access_stats(self) -> dict[str, dict[str, int]]:
        """Per-name get() hit count and last-touch sequence (monotonic,
        repository-wide) — the lifecycle manager's LRU raw material."""
        with self._lock:
            return {
                name: {
                    "count": self._access_count.get(name, 0),
                    "last_seq": self._access_seq.get(name, 0),
                }
                for name in self._models
            }
