"""Compressed wire payloads for the REMOTE serving path.

Same-host clients ride shared memory (channel/transport.py); clients
on the far side of a real network cannot, and BENCH_r04's 93 ms tunnel
RTT makes every wire byte count. This module lets the wire carry
compressed payloads instead of raw tensors: the client encodes (JPEG
for camera frames, linear quantization for pointclouds / feature
maps), the request's per-tensor ``content_encoding`` parameter names
the scheme, and the server decodes on a small host thread pool —
overlapped with the stream pipeline, so request N+1's decode hides
under request N's device window. A 512x512 RGB frame travels tens of
KB as JPEG instead of 786 KB raw; an FP32 pointcloud shrinks 4x as q8
(8x the information density of the wire per byte, at a quantization
error bounded by the tensor's dynamic range / 255).

Schemes (the ``content_encoding`` per-tensor parameter):

  * ``jpeg`` — payload is a 1-D uint8 tensor of JPEG bytes; decodes
    to the image's natural HxWxC uint8 array (PIL, import-guarded: a
    server without it rejects encoded tensors with a clear error
    instead of dying at import);
  * ``q8`` / ``q16`` — payload is the tensor linearly quantized to
    uint8/uint16 with ``q_scale`` / ``q_min`` parameters; dequantizes
    on-device through a cached jax.jit scale-multiply, so the host
    never materializes the full-precision array — the device does the
    upcast where FLOPs are free.
"""

from __future__ import annotations

import concurrent.futures
import functools
import io
import threading

import numpy as np

ENCODING_PARAM = "content_encoding"
Q_SCALE_PARAM = "q_scale"
Q_MIN_PARAM = "q_min"
Q_DTYPE_PARAM = "q_dtype"

try:  # optional: camera-frame JPEG path only
    from PIL import Image as _PILImage
except ImportError:  # pragma: no cover - PIL ships in the image
    _PILImage = None

# decode pool: a few threads is enough — JPEG decode releases the GIL
# inside libjpeg, and the pool exists to OVERLAP decode with staging,
# not to win a throughput race against the device
_POOL_WORKERS = 4
_pool: concurrent.futures.ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def decode_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=_POOL_WORKERS,
                    thread_name_prefix="wire-decode",
                )
    return _pool


# -- client-side encoders ------------------------------------------------------


def encode_jpeg(image: np.ndarray, quality: int = 90):
    """(payload, per-tensor params) for one HxW[xC] uint8 frame. The
    payload is a 1-D uint8 tensor of the compressed bytes; attach the
    params via ``InferRequest.input_params[name]``."""
    if _PILImage is None:
        raise RuntimeError("JPEG encoding needs PIL (not installed)")
    image = np.asarray(image)
    if image.dtype != np.uint8:
        raise ValueError(f"JPEG encodes uint8 frames, got {image.dtype}")
    buf = io.BytesIO()
    _PILImage.fromarray(image).save(buf, format="JPEG", quality=quality)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    return payload, {ENCODING_PARAM: "jpeg"}


def quantize(arr: np.ndarray, bits: int = 8):
    """(payload, per-tensor params) for one float tensor linearly
    quantized to ``bits`` (8 or 16). Shape is preserved; the server
    dequantizes on-device from the ``q_scale``/``q_min`` params."""
    if bits not in (8, 16):
        raise ValueError(f"quantization supports 8 or 16 bits, got {bits}")
    a = np.asarray(arr)
    lo = float(a.min()) if a.size else 0.0
    hi = float(a.max()) if a.size else 0.0
    qmax = (1 << bits) - 1
    scale = (hi - lo) / qmax if hi > lo else 1.0
    q = np.round((a - lo) / scale).astype(
        np.uint8 if bits == 8 else np.uint16
    )
    return q, {
        ENCODING_PARAM: f"q{bits}",
        Q_SCALE_PARAM: repr(scale),
        Q_MIN_PARAM: repr(lo),
        Q_DTYPE_PARAM: np.dtype(a.dtype).name,
    }


# -- server-side decoders ------------------------------------------------------


def decode_jpeg(payload) -> np.ndarray:
    if _PILImage is None:
        raise ValueError(
            "request carries a JPEG-encoded tensor but this server has "
            "no PIL to decode it"
        )
    # bytes() copies the (small, compressed) payload out of its wire
    # view — PIL needs a real buffer; the decoded frame is the big one
    # and it is written exactly once by libjpeg
    return np.asarray(_PILImage.open(io.BytesIO(bytes(payload))))


@functools.lru_cache(maxsize=1)
def _dequant_jit():
    import jax

    # cached scale-multiply: jit re-specializes per (shape, dtype), so
    # one compiled kernel per model input serves every request
    def _dq(q, scale, lo):
        return q * scale + lo

    return jax.jit(_dq)


def dequantize(payload, scale: float, lo: float, dtype) -> np.ndarray:
    """On-device linear dequantization: the uint payload is placed on
    the default device and upcast there (device FLOPs, not a host
    loop); callers downstream (TPUChannel placement) treat the result
    like any other array."""
    import jax.numpy as jnp

    out = _dequant_jit()(
        payload, jnp.asarray(scale, dtype=dtype), jnp.asarray(lo, dtype=dtype)
    )
    return out.astype(dtype) if out.dtype != np.dtype(dtype) else out


def encodings_of(request) -> dict[str, dict]:
    """{input name: decode directive} for one wire ModelInferRequest;
    empty on the (common) unencoded path — one parameters-map probe
    per input tensor."""
    out = {}
    for t in request.inputs:
        p = t.parameters
        if ENCODING_PARAM not in p:
            continue
        enc = p[ENCODING_PARAM].string_param
        if not enc:
            continue
        info = {"encoding": enc}
        if enc in ("q8", "q16"):
            try:
                info["scale"] = float(p[Q_SCALE_PARAM].string_param)
                info["min"] = float(p[Q_MIN_PARAM].string_param)
            except (KeyError, ValueError) as e:
                raise ValueError(
                    f"input {t.name!r} is {enc}-encoded but its "
                    f"q_scale/q_min parameters are missing or malformed"
                ) from e
            info["dtype"] = (
                p[Q_DTYPE_PARAM].string_param
                if Q_DTYPE_PARAM in p
                else "float32"
            ) or "float32"
        out[t.name] = info
    return out


def decode_one(payload: np.ndarray, info: dict) -> np.ndarray:
    enc = info["encoding"]
    if enc == "jpeg":
        return decode_jpeg(payload)
    if enc in ("q8", "q16"):
        return dequantize(
            payload, info["scale"], info["min"], np.dtype(info["dtype"])
        )
    raise ValueError(f"unknown content_encoding {enc!r}")


def decode_inputs(
    inputs: dict[str, np.ndarray], encodings: dict[str, dict]
) -> dict[str, np.ndarray]:
    """Replace encoded inputs with their decoded arrays. Multiple
    encoded tensors decode concurrently on the module pool (libjpeg
    releases the GIL); a single one decodes inline — the pool's real
    overlap win is across pipelined stream requests, where the reader
    thread decodes request N+1 while N owns the device."""
    todo = {k: v for k, v in encodings.items() if k in inputs}
    if not todo:
        return inputs
    out = dict(inputs)
    if len(todo) == 1:
        name, info = next(iter(todo.items()))
        out[name] = decode_one(inputs[name], info)
        return out
    futures = {
        name: decode_pool().submit(decode_one, inputs[name], info)
        for name, info in todo.items()
    }
    for name, fut in futures.items():
        out[name] = fut.result()
    return out
