"""Multi-tenant model lifecycle: HBM paging, warm/cold states, quotas.

Registration used to pin every model's params into device HBM forever:
``ShardedTPUChannel._make_launcher`` replicates an explicit param tree
at launcher build, closure-captured weights pin at first jit trace, and
nothing ever let go — so a fleet could co-locate only as many variants
as fit HBM at once. The production story (ROADMAP item 2; PAPERS.md's
FlexNPU dynamic co-location) is dozens of per-crop detectors and A/B
candidates sharing one fixed mesh, which needs the opposite default:
models are COLD until asked for, page in on demand, and page out under
pressure.

:class:`ModelLifecycleManager` owns that policy. Each registered model
moves through

    COLD ──acquire──▶ WARMING ──warm hook──▶ WARM
      ▲                                        │
      └────────── evict hook ◀── EVICTING ◀────┘  (budget pressure)

* **promotion** — the first acquirer of a COLD model claims the
  WARMING transition, makes room under the HBM budget, runs the
  channel's warm hook (build + cache the jitted launcher; the sharded
  channel replicates the param tree here — the actual page-in), then
  broadcasts WARM. Concurrent acquirers block with a deadline-aware
  bound instead of erroring, so a cold model's first request pays the
  promotion and everyone queued behind it rides along.
* **eviction** — LRU crossed with a pinned/priority tier: candidates
  are WARM, unpinned, idle (``inflight == 0``) models, lowest
  ``priority`` first, least-recently-used inside a tier. A model with
  in-flight work is NEVER evicted (the acquire/release refcount brackets
  stage→resolve). The evict hook drops the channel's cached launcher —
  and with it the replicated param tree the closure holds — so XLA
  frees the HBM copy.
* **budget accounting** — per-model cost comes from
  ``spec.extra["param_bytes"]`` (recorded by the precision builder,
  PR 5) with a configurable default for closure-captured models; the
  sharded channel refines it with the measured bytes of the placed tree
  via :meth:`note_cost`.
* **tenancy** — a :class:`TenantTable` (``tenants.yaml``) maps models
  to tenants with HBM quotas, request-rate shares, and in-flight caps.
  Quotas are enforced here (a tenant over its quota evicts its own
  models first and cannot displace another tenant's), shares feed the
  continuous scheduler's deficit-round-robin ordering
  (``runtime/continuous.py``), and in-flight caps layer onto the
  admission controller (``runtime/admission.py``).

Everything is stdlib + obs.histogram; the fast path (acquire of a WARM
model) is one lock, two dict reads, and a counter bump.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from triton_client_tpu.obs.histogram import LatencyHistogram
from triton_client_tpu.runtime.admission import (
    AdmissionRejectedError,
    DeadlineExpiredError,
)

# lifecycle states, exported as the tpu_serving_lifecycle_models gauge
COLD, WARMING, WARM, EVICTING = 0, 1, 2, 3
STATE_NAMES = {COLD: "cold", WARMING: "warming", WARM: "warm",
               EVICTING: "evicting"}

#: Cost assumed for a model that declares no ``param_bytes`` (closure
#: captured weights): 64 MiB, roughly a f32 yolov5s tree. Deliberately
#: conservative — an unmeasured model should not look free.
DEFAULT_COST_BYTES = 64 << 20

#: Default tenant every unmapped model bills to.
DEFAULT_TENANT = "default"


class HBMBudgetExceededError(AdmissionRejectedError):
    """A promotion could not fit under the HBM budget (every resident
    model is pinned or has in-flight work). Maps to RESOURCE_EXHAUSTED
    like any other shed — the request is deliberately rejected, the
    server is not broken."""


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's serving policy (a ``tenants.yaml`` entry)."""

    name: str
    #: deficit-round-robin weight in the continuous scheduler's ready
    #: ordering; relative, so (4, 1) and (8, 2) mean the same split
    share: float = 1.0
    #: HBM ceiling for this tenant's resident models (0 = unlimited;
    #: the global budget still applies)
    hbm_quota_bytes: int = 0
    #: admitted-but-unfinished request cap across the tenant's models
    #: (0 = no per-tenant cap; per-model caps still apply)
    max_inflight: int = 0
    #: model names billed to this tenant
    models: tuple = ()
    #: models never evicted while this policy is active
    pinned: frozenset = frozenset()


class TenantTable:
    """model name -> :class:`TenantPolicy` resolution, plus the share
    lookups the scheduler and admission controller key on. Unmapped
    models bill to ``default`` (share ``default_share``, no quota)."""

    def __init__(
        self, policies: list[TenantPolicy], default_share: float = 1.0
    ) -> None:
        self._policies: dict[str, TenantPolicy] = {}
        self._by_model: dict[str, str] = {}
        for pol in policies:
            self._policies[pol.name] = pol
            for model in pol.models:
                self._by_model[str(model)] = pol.name
        if DEFAULT_TENANT not in self._policies:
            self._policies[DEFAULT_TENANT] = TenantPolicy(
                name=DEFAULT_TENANT, share=float(default_share)
            )

    def tenant_of(self, model_name: str) -> str:
        return self._by_model.get(model_name, DEFAULT_TENANT)

    def policy(self, tenant: str) -> TenantPolicy:
        return self._policies.get(
            tenant, self._policies[DEFAULT_TENANT]
        )

    def share(self, tenant: str) -> float:
        return max(1e-6, float(self.policy(tenant).share))

    def max_inflight(self, tenant: str) -> int:
        return int(self.policy(tenant).max_inflight)

    def pinned(self, model_name: str) -> bool:
        return model_name in self.policy(self.tenant_of(model_name)).pinned

    def tenants(self) -> list[str]:
        return sorted(self._policies)

    def describe(self) -> dict:
        return {
            name: {
                "share": pol.share,
                "hbm_quota_bytes": pol.hbm_quota_bytes,
                "max_inflight": pol.max_inflight,
                "models": list(pol.models),
                "pinned": sorted(pol.pinned),
            }
            for name, pol in self._policies.items()
        }


def parse_tenants(doc: dict) -> TenantTable:
    """Build a :class:`TenantTable` from a parsed ``tenants.yaml``::

        tenants:
          crop-inspection:
            share: 4            # DRR weight in the ready ordering
            hbm_quota_mb: 256   # resident-bytes ceiling (0 = none)
            max_inflight: 32    # admitted-but-unfinished cap (0 = none)
            models: [yolov5_crop, yolov5_weed]
            pinned: [yolov5_crop]
          batch-analytics:
            share: 1
            models: [centerpoint]

    Unknown top-level or per-tenant keys fail loudly (the config.yaml
    discipline from runtime/disk_repository.py)."""
    allowed_top = {"tenants", "default_share"}
    unknown = set(doc) - allowed_top
    if unknown:
        raise ValueError(
            f"tenants config: unknown top-level keys {sorted(unknown)} "
            f"(allowed: {sorted(allowed_top)})"
        )
    allowed = {
        "share", "hbm_quota_mb", "hbm_quota_bytes", "max_inflight",
        "models", "pinned",
    }
    policies = []
    for name, body in (doc.get("tenants") or {}).items():
        body = dict(body or {})
        unknown = set(body) - allowed
        if unknown:
            raise ValueError(
                f"tenant '{name}': unknown keys {sorted(unknown)} "
                f"(allowed: {sorted(allowed)})"
            )
        quota = int(body.get("hbm_quota_bytes", 0) or 0)
        if not quota and body.get("hbm_quota_mb"):
            quota = int(float(body["hbm_quota_mb"]) * (1 << 20))
        policies.append(
            TenantPolicy(
                name=str(name),
                share=float(body.get("share", 1.0)),
                hbm_quota_bytes=quota,
                max_inflight=int(body.get("max_inflight", 0) or 0),
                models=tuple(str(m) for m in body.get("models") or ()),
                pinned=frozenset(str(m) for m in body.get("pinned") or ()),
            )
        )
    return TenantTable(
        policies, default_share=float(doc.get("default_share", 1.0))
    )


def load_tenants(path: str) -> TenantTable:
    """Parse a ``tenants.yaml`` file into a :class:`TenantTable`."""
    import yaml

    with open(path) as fh:
        doc = yaml.safe_load(fh) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"tenants config {path}: expected a mapping")
    return parse_tenants(doc)


class _Entry:
    """Lifecycle state for one (name, version)."""

    __slots__ = (
        "state", "cost", "tenant", "pinned", "priority", "last_used",
        "inflight", "promotions", "evictions",
    )

    def __init__(self, cost: int, tenant: str, pinned: bool) -> None:
        self.state = COLD
        self.cost = int(cost)
        self.tenant = tenant
        self.pinned = bool(pinned)
        self.priority = 0
        self.last_used = 0
        self.inflight = 0
        self.promotions = 0
        self.evictions = 0


class ModelLifecycleManager:
    """HBM-budgeted COLD/WARMING/WARM/EVICTING state machine over the
    repository's registered models (see module docstring).

    ``budget_bytes=0`` disables budget pressure (models still move
    COLD -> WARM so promotion latency and residency are observable, but
    nothing is ever evicted). Hooks are wired by
    ``StagedChannel.attach_lifecycle``: ``warmer(name, version)`` does
    the page-in, ``evictor(name, version)`` the page-out."""

    def __init__(
        self,
        repository,
        budget_bytes: int = 0,
        tenants: TenantTable | None = None,
        default_cost_bytes: int = DEFAULT_COST_BYTES,
        warming_timeout_s: float = 60.0,
    ) -> None:
        self._repository = repository
        self._budget = max(0, int(budget_bytes))
        self._tenants = tenants
        self._default_cost = max(1, int(default_cost_bytes))
        self._warming_timeout_s = max(0.1, float(warming_timeout_s))
        self._cv = threading.Condition()
        self._entries: dict[tuple[str, str], _Entry] = {}
        self._resident = 0
        self._tenant_resident: dict[str, int] = {}
        self._clock = 0  # LRU sequence, bumped on every touch
        self._warmer = None
        self._evictor = None
        self._promotion_hist = LatencyHistogram()
        self._counts = {
            "promotions": 0,
            "evictions": 0,
            "promotion_failures": 0,
        }

    # -- wiring ---------------------------------------------------------------

    def set_hooks(self, warmer=None, evictor=None) -> None:
        """Channel page-in/page-out callables (StagedChannel wires its
        launcher-cache build and per-version invalidation here)."""
        if warmer is not None:
            self._warmer = warmer
        if evictor is not None:
            self._evictor = evictor

    @property
    def tenants(self) -> TenantTable | None:
        return self._tenants

    @property
    def budget_bytes(self) -> int:
        return self._budget

    # -- per-model knobs ------------------------------------------------------

    def pin(self, name: str, version: str = "", pinned: bool = True) -> None:
        """Pin (never evict) / unpin a model, on top of any tenant
        ``pinned`` list."""
        key, model = self._resolve(name, version)
        with self._cv:
            self._ensure_entry_locked(key, model).pinned = bool(pinned)

    def set_priority(self, name: str, priority: int, version: str = "") -> None:
        """Eviction tier: lower-priority models evict first; ties break
        least-recently-used."""
        key, model = self._resolve(name, version)
        with self._cv:
            self._ensure_entry_locked(key, model).priority = int(priority)

    def note_cost(self, name: str, version: str, nbytes: int) -> None:
        """Refine a model's HBM cost with measured bytes (the sharded
        channel reports the placed param tree's size from its launcher
        build). Resident accounting re-bases if the model is WARM."""
        if nbytes <= 0:
            return
        with self._cv:
            ent = self._entries.get((name, version))
            if ent is None:
                return
            if ent.state == WARM:
                self._resident += int(nbytes) - ent.cost
                self._tenant_resident[ent.tenant] = (
                    self._tenant_resident.get(ent.tenant, 0)
                    + int(nbytes) - ent.cost
                )
            ent.cost = int(nbytes)

    # -- the serving-path contract -------------------------------------------

    def acquire(
        self, name: str, version: str = "", deadline_s: float | None = None
    ) -> tuple[str, str]:
        """Block until (name, version) is WARM, then take an in-flight
        reference protecting it from eviction. Returns the resolved
        ``(name, version)`` key for the paired :meth:`release`.

        A COLD model promotes on demand: the first acquirer claims the
        WARMING transition and pays the page-in; later acquirers wait.
        The wait is deadline-aware — a request whose ``deadline_s``
        (absolute, ``time.perf_counter`` base) passes while warming
        raises :class:`DeadlineExpiredError`; with no deadline the wait
        is bounded by ``warming_timeout_s``. A promotion that cannot
        fit raises :class:`HBMBudgetExceededError`."""
        key, model = self._resolve(name, version)
        bound = time.perf_counter() + self._warming_timeout_s
        with self._cv:
            ent = self._ensure_entry_locked(key, model)
            while True:
                self._clock += 1
                ent.last_used = self._clock
                if ent.state == WARM:
                    ent.inflight += 1
                    return key
                if ent.state == COLD:
                    ent.state = WARMING
                    break
                # WARMING by a peer, or EVICTING: wait for the
                # transition to settle, bounded by deadline/timeout
                now = time.perf_counter()
                limit = bound if deadline_s is None else min(bound, deadline_s)
                if now >= limit:
                    if deadline_s is not None and now >= deadline_s:
                        raise DeadlineExpiredError(
                            f"model '{key[0]}': deadline expired while "
                            f"waiting for promotion"
                        )
                    raise HBMBudgetExceededError(
                        f"model '{key[0]}': promotion did not complete "
                        f"within {self._warming_timeout_s:.1f}s"
                    )
                self._cv.wait(timeout=min(0.05, limit - now))
        # this thread owns the COLD -> WARMING claim: page in outside
        # the lock (eviction + the channel's launcher build can be slow)
        t0 = time.perf_counter()
        try:
            self._make_room(key)
            if self._warmer is not None:
                self._warmer(key[0], key[1])
        except BaseException:
            with self._cv:
                ent.state = COLD
                self._counts["promotion_failures"] += 1
                self._cv.notify_all()
            raise
        with self._cv:
            ent.state = WARM
            ent.promotions += 1
            ent.inflight += 1
            self._resident += ent.cost
            self._tenant_resident[ent.tenant] = (
                self._tenant_resident.get(ent.tenant, 0) + ent.cost
            )
            self._counts["promotions"] += 1
            self._cv.notify_all()
        self._promotion_hist.observe(time.perf_counter() - t0)
        return key

    def release(self, name: str, version: str) -> None:
        """Drop one in-flight reference taken by :meth:`acquire` (the
        channel calls this when the request resolves or fails)."""
        with self._cv:
            ent = self._entries.get((name, version))
            if ent is not None and ent.inflight > 0:
                ent.inflight -= 1
                if ent.inflight == 0:
                    self._cv.notify_all()

    def prefetch(self, name: str, version: str = "") -> None:
        """Promote ahead of demand (the staged-promotion hook): warm a
        model without taking an in-flight reference, so its first
        request pays only the queue, not the page-in."""
        key = self.acquire(name, version)
        self.release(*key)

    def evict(self, name: str, version: str = "") -> bool:
        """Explicitly page a model out (operator/runbook path). Returns
        False when the model is not resident, pinned, or busy."""
        key, model = self._resolve(name, version)
        with self._cv:
            ent = self._entries.get(key)
            if (
                ent is None or ent.state != WARM
                or ent.inflight > 0 or self._pinned_locked(key, ent)
            ):
                return False
            ent.state = EVICTING
        self._evict_one(key, self._entries[key])
        return True

    # -- internals ------------------------------------------------------------

    def _resolve(self, name: str, version: str):
        model = self._repository.get(name, version)
        return (model.spec.name, model.spec.version), model

    def _ensure_entry_locked(self, key, model) -> _Entry:
        ent = self._entries.get(key)
        if ent is None:
            extra = getattr(model.spec, "extra", None) or {}
            cost = int(extra.get("param_bytes", 0) or 0) or self._default_cost
            tenant = (
                self._tenants.tenant_of(key[0])
                if self._tenants is not None
                else DEFAULT_TENANT
            )
            pinned = bool(extra.get("pinned", False))
            ent = self._entries[key] = _Entry(cost, tenant, pinned)
        return ent

    def _pinned_locked(self, key, ent) -> bool:
        if ent.pinned:
            return True
        return self._tenants is not None and self._tenants.pinned(key[0])

    def _quota(self, tenant: str) -> int:
        if self._tenants is None:
            return 0
        return int(self._tenants.policy(tenant).hbm_quota_bytes)

    def _make_room(self, key) -> None:
        """Evict until ``key`` fits its tenant quota and the global
        budget. Victims: WARM, unpinned, idle; lowest priority tier
        first, least-recently-used inside a tier. A tenant over ITS
        quota may only displace its own models — quota pressure must
        not let one tenant flush another's working set."""
        ent = self._entries[key]
        quota = self._quota(ent.tenant)
        while True:
            with self._cv:
                over_quota = (
                    quota > 0
                    and self._tenant_resident.get(ent.tenant, 0) + ent.cost
                    > quota
                )
                over_budget = (
                    self._budget > 0
                    and self._resident + ent.cost > self._budget
                )
                if not over_quota and not over_budget:
                    return
                victim_key = self._pick_victim_locked(
                    tenant=ent.tenant if over_quota else None
                )
                if victim_key is None:
                    scope = (
                        f"tenant '{ent.tenant}' quota {quota}"
                        if over_quota
                        else f"budget {self._budget}"
                    )
                    self._counts["promotion_failures"] += 1
                    raise HBMBudgetExceededError(
                        f"model '{key[0]}' (cost {ent.cost}B) cannot fit "
                        f"under {scope}: every resident model is pinned "
                        f"or has in-flight work"
                    )
                victim = self._entries[victim_key]
                victim.state = EVICTING
            self._evict_one(victim_key, victim)

    def _pick_victim_locked(self, tenant: str | None = None):
        best_key, best_rank = None, None
        for key, ent in self._entries.items():
            if ent.state != WARM or ent.inflight > 0:
                continue
            if self._pinned_locked(key, ent):
                continue
            if tenant is not None and ent.tenant != tenant:
                continue
            rank = (ent.priority, ent.last_used)
            if best_rank is None or rank < best_rank:
                best_key, best_rank = key, rank
        return best_key

    def _evict_one(self, key, ent) -> None:
        """Run the channel's page-out hook for an entry already marked
        EVICTING, then settle it COLD (hook failures still settle — a
        broken invalidation must not wedge the state machine)."""
        try:
            if self._evictor is not None:
                self._evictor(key[0], key[1])
        finally:
            with self._cv:
                ent.state = COLD
                self._resident -= ent.cost
                self._tenant_resident[ent.tenant] = max(
                    0, self._tenant_resident.get(ent.tenant, 0) - ent.cost
                )
                ent.evictions += 1
                self._counts["evictions"] += 1
                self._cv.notify_all()

    # -- reading --------------------------------------------------------------

    def state(self, name: str, version: str = "") -> int:
        key, _ = self._resolve(name, version)
        with self._cv:
            ent = self._entries.get(key)
            return COLD if ent is None else ent.state

    def stats(self) -> dict:
        """One structured read for the collector: budget/residency,
        per-state counts, per-tenant resident bytes, promotion latency
        histogram, and a per-model table."""
        with self._cv:
            states = {name: 0 for name in STATE_NAMES.values()}
            models = {}
            for (name, version), ent in self._entries.items():
                states[STATE_NAMES[ent.state]] += 1
                models[f"{name}:{version}"] = {
                    "state": STATE_NAMES[ent.state],
                    "cost_bytes": ent.cost,
                    "tenant": ent.tenant,
                    "pinned": self._pinned_locked((name, version), ent),
                    "priority": ent.priority,
                    "inflight": ent.inflight,
                    "promotions": ent.promotions,
                    "evictions": ent.evictions,
                }
            out = {
                "budget_bytes": self._budget,
                "resident_bytes": self._resident,
                "tenant_resident_bytes": dict(self._tenant_resident),
                "states": states,
                "models": models,
            }
            out.update(self._counts)
        out["promotion_latency"] = self._promotion_hist.snapshot()
        if self._tenants is not None:
            out["tenants"] = self._tenants.describe()
        return out
