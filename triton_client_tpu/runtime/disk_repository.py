"""On-disk model repository: Triton's directory layout, JAX semantics.

The reference serves from a model-repository directory tree —
``<repo>/<model>/config.pbtxt`` + numbered version dirs with backend
artifacts (examples/pointpillar_kitti/config.pbtxt, examples/YOLOv5/
config.pbtxt; loaded by tritonserver --model-repository, README.md:66).
This module is that layout for the TPU runtime::

    <root>/<model_name>/
        config.yaml      # family + model/pipeline config (config.pbtxt)
        1/weights.msgpack # flax-native weights (or .pt/.pth/.onnx
        2/weights.pt      # imported via runtime.importers)

``scan_disk`` builds every model's fused jit pipeline and registers it
(name, version) into a ModelRepository, so the gRPC serving facade and
TPUChannel can dispatch to any version. Unlike Triton there is no
backend zoo: every family maps to an in-tree flax pipeline builder.
"""

from __future__ import annotations

import dataclasses
import logging
import pathlib
from typing import Any, Callable, Mapping

import jax

from triton_client_tpu.dataset_config import (
    _apply_overrides,
    _SEQ_KEYS,
    load_yaml,
    model_config_from_dict,
)
from triton_client_tpu.runtime.repository import ModelRepository, RegisteredModel

log = logging.getLogger(__name__)

_WEIGHT_NAMES = ("weights.msgpack", "weights.pt", "weights.pth", "weights.onnx", "model.pt", "model.pth", "model.onnx")


def _families_2d() -> tuple[str, ...]:
    from triton_client_tpu.pipelines.detect2d import BUILDERS_2D

    return tuple(BUILDERS_2D)


def _families_3d() -> tuple[str, ...]:
    from triton_client_tpu.pipelines.detect3d import BUILDERS_3D

    return tuple(BUILDERS_3D)

# family -> importer fn(state_dict, template_variables) for torch/onnx
# artifacts; families without one accept only flax-native msgpack.
def _torch_importers() -> dict[str, Callable]:
    from triton_client_tpu.runtime import importers

    return {
        "yolov5": importers.load_yolov5,
        "yolov4": importers.load_yolov4,
        "retinanet": importers.load_retinanet,
        "fcos": importers.load_fcos,
        "pointpillars": importers.load_pointpillars,
        "second_iou": importers.load_second,
        "centerpoint": importers.load_centerpoint,
    }


def save_flax_weights(path: str | pathlib.Path, variables: Mapping) -> None:
    """Write a variables tree as flax-native msgpack bytes."""
    import flax.serialization

    pathlib.Path(path).write_bytes(flax.serialization.to_bytes(variables))


def load_weights(path: str | pathlib.Path, family: str, template: Mapping) -> Mapping:
    """Load a version dir's weight artifact onto a template tree."""
    path = pathlib.Path(path)
    ext = path.suffix
    if ext == ".msgpack":
        import flax.serialization

        return flax.serialization.from_bytes(template, path.read_bytes())
    importer = _torch_importers().get(family)
    if importer is None:
        raise ValueError(
            f"family {family!r} has no torch/onnx importer; provide "
            f"weights.msgpack (got {path.name})"
        )
    if ext in (".pt", ".pth"):
        return importer(str(path), template)
    if ext == ".onnx":
        from triton_client_tpu.runtime.onnx_reader import (
            onnx_to_state_dict,
            read_onnx_initializers,
        )

        state = onnx_to_state_dict(read_onnx_initializers(str(path)))
        return importer(state, template)
    raise ValueError(f"unrecognized weight artifact {path.name}")


def _resolve(path_str: str, model_dir: pathlib.Path) -> str:
    """Resolve a config-referenced file: relative to the model dir
    first, then the repository root, then cwd. Raises with the bases
    tried so a wrong serving cwd is diagnosable immediately."""
    p = pathlib.Path(path_str)
    if p.is_absolute():
        return str(p)
    bases = (model_dir, model_dir.parent, pathlib.Path.cwd())
    for base in bases:
        if (base / p).exists():
            return str(base / p)
    raise FileNotFoundError(
        f"{model_dir / 'config.yaml'} references {path_str!r}, not found "
        f"relative to any of {[str(b) for b in bases]}"
    )


def _build_2d(family: str, doc: Mapping[str, Any], model_dir: pathlib.Path):
    from triton_client_tpu.pipelines import detect2d

    builders = detect2d.BUILDERS_2D
    model_kwargs = dict(doc.get("model", {}))
    if "input_hw" in model_kwargs:
        model_kwargs["input_hw"] = tuple(model_kwargs["input_hw"])
    if "dtype" in model_kwargs:
        from triton_client_tpu.config import parse_compute_dtype

        model_kwargs["dtype"] = parse_compute_dtype(model_kwargs["dtype"])
    if "precision" in model_kwargs:
        # validate at scan time so a typo'd policy fails at startup,
        # not at first inference (fail-loudly policy)
        from triton_client_tpu.runtime.precision import PrecisionPolicy

        model_kwargs["precision"] = PrecisionPolicy.parse(
            model_kwargs["precision"]
        )

    if family == "preprocess":
        # paramless host-prep pipeline: nothing to cast/quantize, so a
        # repository-wide --precision override passes it by
        model_kwargs.pop("precision", None)

    pipe_d = dict(doc.get("pipeline", {}))
    names_file = pipe_d.pop("class_names_file", None)
    names = (
        detect2d.load_class_names(_resolve(names_file, model_dir))
        if names_file
        else None
    )
    if names:
        model_kwargs.setdefault("num_classes", len(names))

    def build(variables=None, config=None):
        return builders[family](
            rng=jax.random.PRNGKey(0), variables=variables, config=config,
            **model_kwargs,
        )

    def make_cfg(default_cfg):
        # Overlay config.yaml's pipeline section onto the FAMILY's
        # default config (detectron pipelines differ from YOLO in head
        # style and thresholds) — unknown keys fail loudly.
        cfg = _apply_overrides(default_cfg, pipe_d, _SEQ_KEYS)
        if names:
            cfg = dataclasses.replace(
                cfg, class_names=names, num_classes=model_kwargs["num_classes"]
            )
        if "input_hw" in model_kwargs:
            cfg = dataclasses.replace(cfg, input_hw=model_kwargs["input_hw"])
        return cfg

    return build, make_cfg


def _build_3d(family: str, doc: Mapping[str, Any], model_dir: pathlib.Path):
    from triton_client_tpu.dataset_config import detect3d_from_yaml
    from triton_client_tpu.pipelines import detect3d

    builders = detect3d.BUILDERS_3D
    model_doc = dict(doc.get("model", {}))
    from triton_client_tpu.config import parse_compute_dtype
    from triton_client_tpu.runtime.precision import PrecisionPolicy

    dtype = parse_compute_dtype(model_doc.pop("dtype", "fp32"))
    precision = PrecisionPolicy.parse(model_doc.pop("precision", None))
    if "dataset" in doc:
        got_family, model_cfg, pipe_cfg = detect3d_from_yaml(
            _resolve(doc["dataset"], model_dir)
        )
        if got_family != family:
            raise ValueError(
                f"config.yaml family {family!r} != dataset yaml model {got_family!r}"
            )
    else:
        model_cfg = model_config_from_dict(family, model_doc)
        pipe_cfg = _apply_overrides(
            detect3d.default_detect3d_config(family),
            dict(doc.get("pipeline", {})),
            _SEQ_KEYS,
        )

    def build(variables=None, config=pipe_cfg):
        return builders[family](
            rng=jax.random.PRNGKey(0), model_cfg=model_cfg, config=config,
            variables=variables, dtype=dtype, precision=precision,
        )

    return build, lambda _default: pipe_cfg


_TOP_KEYS = {"family", "model", "pipeline", "dataset", "max_batch_size", "warmup"}


class _Entry:
    """One model dir's parsed config + lazily-shared init template, so
    N version dirs cost ONE random init (the template tree), not N."""

    def __init__(
        self,
        model_dir: str | pathlib.Path,
        doc: Mapping[str, Any] | None = None,
        precision: str | None = None,
    ) -> None:
        self.model_dir = pathlib.Path(model_dir)
        if doc is None:
            doc = load_yaml(str(self.model_dir / "config.yaml"))
        doc = dict(doc)
        if precision:
            # serve --precision: a repository-wide override of each
            # entry's config.yaml model.precision (both select the same
            # policy machinery, runtime/precision.py)
            doc["model"] = {
                **dict(doc.get("model", {})), "precision": precision,
            }
        unknown = set(doc) - _TOP_KEYS
        if unknown:
            raise KeyError(
                f"{self.model_dir / 'config.yaml'}: unknown keys "
                f"{sorted(unknown)}; known: {sorted(_TOP_KEYS)}"
            )
        self.doc = doc
        self.family = doc.get("family")
        if self.family in _families_2d():
            self._build, make_cfg = _build_2d(self.family, doc, self.model_dir)
        elif self.family in _families_3d():
            self._build, make_cfg = _build_3d(self.family, doc, self.model_dir)
        else:
            raise ValueError(
                f"{self.model_dir}: unknown family {self.family!r} "
                f"(known: {_families_2d() + _families_3d()})"
            )
        # Probe with empty variables (builders skip init when variables
        # is given; forward closures are lazy) to get the family-default
        # pipeline config without paying for a random init.
        probe, _, _ = self._build(variables={})
        self.cfg = make_cfg(probe.config)
        self._template = None

    def template(self) -> Mapping:
        if self._template is None:
            _, _, self._template = self._build(config=self.cfg)
        return self._template

    def registered(
        self, version: str, weights: str | pathlib.Path | None = None
    ) -> RegisteredModel:
        if weights is not None:
            variables = load_weights(weights, self.family, self.template())
        else:
            variables = self.template()
        pipeline, spec, _ = self._build(variables=variables, config=self.cfg)
        spec = dataclasses.replace(
            spec,
            name=self.model_dir.name,
            version=version,
            max_batch_size=int(self.doc.get("max_batch_size", spec.max_batch_size)),
        )

        def warmup(p=pipeline, c=self.cfg):
            # Compile the shape real traffic uses: batch 1 at the
            # model's native resolution (2D re-traces per distinct
            # camera resolution anyway; this covers the native one) or
            # the smallest point bucket (3D).
            import numpy as np

            if hasattr(c, "input_hw"):
                p.infer(np.zeros((1, *c.input_hw, 3), np.float32))
            else:
                p.infer(np.zeros((16, 4), np.float32))

        return RegisteredModel(
            spec=spec,
            infer_fn=pipeline.infer_fn(),
            warmup=warmup,
            # pipelines that expose a jit-traceable form make their
            # models fusable as ensemble members (intermediates stay
            # in HBM); host-only pipelines still serve the wire path
            device_fn=(
                pipeline.device_fn()
                if hasattr(pipeline, "device_fn")
                else None
            ),
            # the serving channels read the policy off the registered
            # model for the wire half (host narrowing + int8 ingest)
            precision=getattr(pipeline, "precision", None),
        )


def build_model(
    model_dir: str | pathlib.Path,
    version: str = "1",
    weights: str | pathlib.Path | None = None,
) -> RegisteredModel:
    """Build one model dir's pipeline (optionally a specific version's
    weights) into a RegisteredModel, without registering it."""
    return _Entry(model_dir).registered(version, weights)


def load_pipeline(
    model_dir: str | pathlib.Path,
    version: str = "",
    config_overrides: Mapping[str, Any] | None = None,
    kind: str = "",
):
    """One model dir -> (pipeline, spec) with its TRAINED weights, for
    direct in-process use — the detect CLIs' --repo path (the reference
    always runs served artifacts, never random init; this is the
    client-side equivalent of Triton loading a version dir). Empty
    ``version`` picks the latest; ``config_overrides`` overlays the
    entry's pipeline config (e.g. eval-time conf/iou thresholds);
    ``kind`` ('2d'/'3d') rejects a wrong-dimensionality entry up front
    instead of crashing deep in the pipeline."""
    entry = _Entry(model_dir)
    if kind:
        families = _families_2d() if kind == "2d" else _families_3d()
        if entry.family not in families:
            other = "3d" if kind == "2d" else "2d"
            raise ValueError(
                f"{entry.model_dir}: family {entry.family!r} is a {other} "
                f"model; use the detect{other} CLI for this entry"
            )
    cfg = entry.cfg
    if config_overrides:
        cfg = dataclasses.replace(cfg, **config_overrides)
    if version:
        vdir = entry.model_dir / version
        if not vdir.is_dir():
            raise FileNotFoundError(
                f"{entry.model_dir}: no version dir {version!r}"
            )
    else:
        vdirs = version_dirs(entry.model_dir)
        if not vdirs:
            raise FileNotFoundError(
                f"{entry.model_dir}: no version dirs with weights "
                "(a --repo entry must carry trained artifacts)"
            )
        vdir = vdirs[-1]
    variables = load_weights(find_weights(vdir), entry.family, entry.template())
    pipeline, spec, _ = entry._build(variables=variables, config=cfg)
    spec = dataclasses.replace(
        spec, name=entry.model_dir.name, version=vdir.name
    )
    return pipeline, spec


def conversion_template(
    family: str | None = None,
    model_kwargs: Mapping[str, Any] | None = None,
    doc: Mapping[str, Any] | None = None,
) -> Mapping:
    """Random-init variables tree for a family — the shape/structure
    template load_weights converts upstream checkpoints onto. Public
    entry for deploy tooling (no model dir needed): pass either an
    already-built config ``doc`` or ``family`` (+ ``model_kwargs``)."""
    if doc is None:
        doc = {"family": family}
        if model_kwargs:
            doc["model"] = dict(model_kwargs)
    return _Entry(pathlib.Path.cwd(), doc=doc).template()


def version_dirs(model_dir: pathlib.Path) -> list[pathlib.Path]:
    return sorted(
        (d for d in model_dir.iterdir() if d.is_dir() and d.name.isdigit()),
        key=lambda d: int(d.name),
    )


def find_weights(version_dir: pathlib.Path) -> pathlib.Path:
    """A version dir MUST carry a recognized artifact — registering
    random-init weights for a typo'd filename would serve garbage
    silently (fail-loudly policy; Triton likewise errors on a version
    dir its backend can't load)."""
    for name in _WEIGHT_NAMES:
        if (version_dir / name).exists():
            return version_dir / name
    present = sorted(p.name for p in version_dir.iterdir())
    raise FileNotFoundError(
        f"{version_dir}: no weight artifact (found {present}; "
        f"recognized names: {list(_WEIGHT_NAMES)})"
    )


def scan_disk(
    root: str | pathlib.Path,
    repository: ModelRepository | None = None,
    precision: str | None = None,
) -> ModelRepository:
    """Load every ``<root>/<model>/config.yaml`` entry into a repository.

    Version dirs (numeric names) each register separately; a model with
    no version dirs registers as version 1 with fresh-init weights
    (useful for spec-only entries and tests). A ``warmup: true`` entry
    compiles at scan time; every model also carries a warmup callable
    for serve --warmup. Broken entries raise — a serving process should
    fail loudly at startup, not skip models (the reference's Triton does
    the same for malformed config.pbtxt). ``precision`` overrides every
    entry's ``model.precision`` policy (the serve --precision flag).
    """
    root = pathlib.Path(root)
    repo = repository or ModelRepository()
    ensembles: list[tuple[pathlib.Path, dict]] = []
    for model_dir in sorted(p for p in root.iterdir() if p.is_dir()):
        if not (model_dir / "config.yaml").exists():
            log.info("skipping %s (no config.yaml)", model_dir)
            continue
        doc = dict(load_yaml(str(model_dir / "config.yaml")))
        if doc.get("family") == "ensemble":
            # composed over member models — register after them all
            # (steps inherit their members' precision, or override per
            # stage via a step-level ``precision`` key)
            ensembles.append((model_dir, doc))
            continue
        entry = _Entry(model_dir, doc=doc, precision=precision)
        versions = version_dirs(model_dir)
        pairs = (
            [(v.name, find_weights(v)) for v in versions]
            if versions
            else [("1", None)]
        )
        for version, weights in pairs:
            rm = entry.registered(version, weights)
            repo.register(
                rm.spec, rm.infer_fn, warmup=rm.warmup,
                device_fn=rm.device_fn, precision=rm.precision,
            )
            if entry.doc.get("warmup"):
                rm.warmup()
    if ensembles:
        from triton_client_tpu.runtime.ensemble import build_ensemble_doc

        # Dependency-order fixpoint: an ensemble whose step references a
        # not-yet-registered sibling ensemble waits for the next round
        # (nested ensembles must not depend on directory sort order).
        pending = {d.name: (d, doc) for d, doc in ensembles}
        while pending:
            ready = [
                name
                for name, (_, doc) in pending.items()
                if not any(
                    s.get("model") in pending for s in doc.get("steps", [])
                )
            ]
            if not ready:
                raise ValueError(
                    f"ensemble dependency cycle among {sorted(pending)}"
                )
            for name in ready:
                model_dir, doc = pending.pop(name)
                rm = build_ensemble_doc(repo, name, doc)
                # device_fn travels along so a fused ensemble can be a
                # member of a PARENT fused ensemble (nested fusion)
                repo.register(
                    rm.spec, rm.infer_fn, warmup=rm.warmup,
                    device_fn=rm.device_fn,
                )
                if doc.get("warmup"):
                    rm.warmup()
    return repo


def export_model(
    root: str | pathlib.Path,
    name: str,
    config_doc: Mapping[str, Any],
    variables: Mapping | None = None,
    version: str = "1",
) -> pathlib.Path:
    """Materialize a repository entry on disk (deploy.sh:56-65 parity:
    convert + place artifacts + write the config contract)."""
    import yaml

    model_dir = pathlib.Path(root) / name
    model_dir.mkdir(parents=True, exist_ok=True)
    with open(model_dir / "config.yaml", "w") as f:
        yaml.safe_dump(dict(config_doc), f, sort_keys=False)
    if variables is not None:
        vdir = model_dir / version
        vdir.mkdir(exist_ok=True)
        save_flax_weights(vdir / "weights.msgpack", variables)
    return model_dir
