"""System shared-memory regions for the KServe serving path.

Triton's system-shared-memory extension lets a same-host client hand
tensors to the server through a POSIX shm segment instead of the gRPC
wire: the client registers a region (name -> shm key + byte range),
then sends infer requests whose input tensors carry
``shared_memory_region`` / ``shared_memory_offset`` /
``shared_memory_byte_size`` parameters and NO raw content. The
reference deploys stock Triton which ships this extension (the
tritonclient package the reference pulls in exposes it as
``tritonclient.utils.shared_memory``); for a 512x512 camera frame the
wire path serializes ~786 KB into protobuf, copies it through HTTP/2
framing, and deserializes it server side — per request, per direction.
The shm path replaces all of that with one memcpy into a mapped page.

POSIX ``shm_open(key)`` maps to ``/dev/shm/<key>`` on Linux, so
regions are implemented as plain mmaps over files there — byte-for-
byte the same segments tritonclient's ``create_shared_memory_region``
creates, without python's ``multiprocessing.shared_memory`` resource-
tracker (which unlinks attached segments at interpreter exit on
< 3.13).

Lifecycle contract (same as Triton's):
  * the CLIENT creates the segment, writes tensors, and eventually
    unlinks it;
  * the SERVER only registers (attaches) and unregisters (detaches) —
    it never unlinks the backing file.
"""

from __future__ import annotations

import collections
import mmap
import os
import threading
from dataclasses import dataclass

import numpy as np

_SHM_DIR = "/dev/shm"


def _shm_path(key: str) -> str:
    # POSIX keys conventionally start with "/"; shm_open("/foo") is
    # /dev/shm/foo. Reject path traversal — keys are wire-controlled.
    name = key[1:] if key.startswith("/") else key
    if not name or "/" in name or name.startswith("."):
        raise ValueError(f"invalid shared-memory key {key!r}")
    return os.path.join(_SHM_DIR, name)


class SharedMemoryRegion:
    """One mapped shm segment. ``create`` (client side) makes and owns
    the backing file; ``attach`` (server side) maps an existing one."""

    def __init__(self, key: str, mm: mmap.mmap, size: int, owns: bool):
        self.key = key
        self._mm = mm
        self.size = size
        self._owns = owns
        self._closed = False

    @classmethod
    def create(cls, key: str, byte_size: int) -> "SharedMemoryRegion":
        path = _shm_path(key)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            # a stale segment from a crashed run (same pid after a
            # container restart): reclaim it. O_EXCL on the retry keeps
            # the window race-free; a symlink planted at the name fails
            # both opens rather than being followed.
            os.unlink(path)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, byte_size)
            mm = mmap.mmap(fd, byte_size)
        finally:
            os.close(fd)
        return cls(key, mm, byte_size, owns=True)

    @classmethod
    def attach(cls, key: str, byte_size: int = 0) -> "SharedMemoryRegion":
        path = _shm_path(key)
        fd = os.open(path, os.O_RDWR)
        try:
            actual = os.fstat(fd).st_size
            if byte_size and byte_size > actual:
                raise ValueError(
                    f"shared-memory region {key!r} is {actual} bytes; "
                    f"{byte_size} requested"
                )
            mm = mmap.mmap(fd, actual)
        finally:
            os.close(fd)
        return cls(key, mm, actual, owns=False)

    # -- tensor IO ------------------------------------------------------------

    def write(self, arr: np.ndarray, offset: int = 0) -> int:
        """Copy ``arr``'s bytes into the region; returns bytes written."""
        arr = np.ascontiguousarray(arr)
        n = arr.nbytes
        if offset < 0 or offset + n > self.size:
            raise ValueError(
                f"write of {n} bytes at offset {offset} exceeds region "
                f"{self.key!r} ({self.size} bytes)"
            )
        # numpy-to-numpy copy releases the GIL (a plain mmap slice
        # assignment holds it) — concurrent serving clients on a small
        # host overlap their memcpys
        dst = np.frombuffer(self._mm, np.uint8, count=n, offset=offset)
        np.copyto(dst, arr.view(np.uint8).reshape(-1))
        return n

    def read(self, offset: int, byte_size: int) -> memoryview:
        """Zero-copy view of a byte range (valid until close())."""
        if offset < 0 or offset + byte_size > self.size:
            raise ValueError(
                f"read of {byte_size} bytes at offset {offset} exceeds "
                f"region {self.key!r} ({self.size} bytes)"
            )
        return memoryview(self._mm)[offset : offset + byte_size]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except BufferError:
            # zero-copy views handed out by read() are still alive
            # (e.g. a batched request not yet dispatched): leave the
            # mapping to the GC rather than invalidating live tensors.
            pass
        if self._owns:
            try:
                os.unlink(_shm_path(self.key))
            except FileNotFoundError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@dataclass(frozen=True)
class _Registered:
    region: SharedMemoryRegion
    key: str
    offset: int
    byte_size: int


class SystemSharedMemoryRegistry:
    """Server-side name -> attached region map behind the
    SystemSharedMemory{Register,Status,Unregister} RPCs."""

    def __init__(self) -> None:
        self._regions: dict[str, _Registered] = {}
        self._lock = threading.Lock()

    def register(
        self, name: str, key: str, offset: int = 0, byte_size: int = 0
    ) -> None:
        with self._lock:
            if name in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is already registered"
                )
            region = SharedMemoryRegion.attach(key, offset + byte_size)
            self._regions[name] = _Registered(
                region, key, offset, byte_size or (region.size - offset)
            )

    def unregister(self, name: str) -> None:
        with self._lock:
            reg = self._regions.pop(name, None)
        if reg is not None:
            reg.region.close()

    def unregister_all(self) -> None:
        with self._lock:
            regs, self._regions = list(self._regions.values()), {}
        for reg in regs:
            reg.region.close()

    def status(self, name: str = "") -> dict[str, _Registered]:
        with self._lock:
            if name:
                if name not in self._regions:
                    raise KeyError(f"shared-memory region {name!r} not registered")
                return {name: self._regions[name]}
            return dict(self._regions)

    # -- codec hooks ----------------------------------------------------------

    def read(self, name: str, offset: int, byte_size: int) -> memoryview:
        """Bytes of a registered region; ``offset`` is relative to the
        region's registered base offset (Triton semantics)."""
        with self._lock:
            if name not in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is not registered"
                )
            reg = self._regions[name]
        if offset < 0 or byte_size > reg.byte_size - offset:
            raise ValueError(
                f"request for {byte_size} bytes at offset {offset} exceeds "
                f"registered window of {name!r} ({reg.byte_size} bytes)"
            )
        return reg.region.read(reg.offset + offset, byte_size)

    def write(self, name: str, offset: int, arr: np.ndarray) -> int:
        with self._lock:
            if name not in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is not registered"
                )
            reg = self._regions[name]
        if offset < 0 or arr.nbytes > reg.byte_size - offset:
            raise ValueError(
                f"output of {arr.nbytes} bytes at offset {offset} exceeds "
                f"registered window of {name!r} ({reg.byte_size} bytes)"
            )
        # region.write is the single designed host copy on the response
        # path: readback view -> client's mapped segment (it handles
        # non-contiguous inputs itself; no pre-copy here)
        return reg.region.write(arr, reg.offset + offset)


class PoolSlot:
    """One pipeline slot of a :class:`ShmRegionPool`: a set of
    client-owned regions keyed by logical tensor name, each generation-
    tagged so a grown (re-created) segment never reuses a registered
    name. A slot is exclusively owned by one in-flight request between
    ``acquire`` and ``release``; its regions persist across requests so
    registration is amortized to once per (slot, input, size class)."""

    __slots__ = ("index", "busy", "regions", "_gen", "_pool")

    def __init__(self, pool: "ShmRegionPool", index: int) -> None:
        self._pool = pool
        self.index = index
        self.busy = False
        self.regions: dict[str, SharedMemoryRegion] = {}
        self._gen: dict[str, int] = {}

    def region_for(self, name: str, nbytes: int) -> SharedMemoryRegion:
        """The slot's region for one logical tensor, created or grown
        on demand. Growth burns a generation (segment names are
        register-once server-side) and replaces the old registration
        only AFTER the new register succeeds, so a failed register RPC
        leaks nothing and leaves the old region usable."""
        region = self.regions.get(name)
        if region is not None and region.size >= nbytes:
            return region
        gen = self._gen.get(name, 0)
        self._gen[name] = gen + 1
        rname = f"{self._pool.tag}_s{self.index}_{name}_g{gen}"
        new = SharedMemoryRegion.create(f"/{rname}", max(nbytes, 1))
        try:
            self._pool.register_fn(rname, new.key, new.size)
        except Exception:
            new.close()  # unlinks; server maps by its own fd if it
            raise        # did register, so unlinking is safe either way
        if region is not None:
            self._pool.unregister_fn(region.key.lstrip("/"))
            region.close()
        self.regions[name] = new
        return new

    def retire(self, name: str) -> None:
        """Drop one logical region (unregister + unlink). The cancel
        path retires the output arena: a cancelled server may write
        into it arbitrarily late, so the segment must never be handed
        to the slot's next owner — the next use re-creates it under a
        fresh generation name."""
        region = self.regions.pop(name, None)
        if region is not None:
            self._pool.unregister_fn(region.key.lstrip("/"))
            region.close()


class ShmRegionPool:
    """Client-side pool of shm slots sized to the pipeline depth.

    The pre-round-13 channel kept ONE region per input behind a coarse
    lock, which serialized do_inference and forced async/stream calls
    onto the wire (a region must stay untouched until its response
    arrives). Pooling per ``(slot, input, generation)`` gives every
    in-flight request exclusive segments: ``depth`` concurrent requests
    ride shm, the ``depth+1``-th blocks in ``acquire`` — backpressure
    that mirrors the server's staging-slot pipeline depth.

    ``register_fn(name, key, byte_size)`` / ``unregister_fn(name)`` are
    the owner channel's RPC hooks; unregister must be best-effort (it
    is called on the growth path against possibly-gone registrations).
    """

    def __init__(
        self,
        tag: str,
        depth: int,
        register_fn,
        unregister_fn,
    ) -> None:
        self.tag = tag
        self.depth = max(1, int(depth))
        self.register_fn = register_fn
        self.unregister_fn = unregister_fn
        self._slots = [PoolSlot(self, i) for i in range(self.depth)]
        self._free: collections.deque[PoolSlot] = collections.deque(
            self._slots
        )
        self._cv = threading.Condition()
        self._closed = False
        # gate-test observability: acquires, high-water in-flight, and
        # the alias counter a correct pool keeps at zero forever
        self._acquires = 0
        self._max_in_flight = 0
        self._aliased = 0

    def acquire(self, timeout_s: float | None = None) -> PoolSlot:
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._free or self._closed, timeout=timeout_s
            ):
                raise TimeoutError(
                    f"no free shm slot within {timeout_s}s "
                    f"({self.depth} in flight)"
                )
            if self._closed:
                raise RuntimeError("shm region pool is closed")
            slot = self._free.popleft()
            if slot.busy:  # invariant violation — must never happen
                self._aliased += 1
                raise RuntimeError(
                    f"shm slot {slot.index} handed out while busy"
                )
            slot.busy = True
            self._acquires += 1
            in_flight = self.depth - len(self._free)
            if in_flight > self._max_in_flight:
                self._max_in_flight = in_flight
            return slot

    def release(self, slot: PoolSlot) -> None:
        """Idempotent: resolve-path ``finally`` and cancel hooks may
        both fire for one request."""
        with self._cv:
            if self._closed or not slot.busy:
                return
            slot.busy = False
            # LIFO: the just-released slot goes to the front so low
            # concurrency reuses warm slots (regions already sized and
            # registered) instead of rotating cold ones into play
            self._free.appendleft(slot)
            self._cv.notify()

    def regions(self) -> list[SharedMemoryRegion]:
        return [r for s in self._slots for r in s.regions.values()]

    def reregister_all(self) -> None:
        """Restart recovery: push every slot's segments back into a
        server whose registry came up empty. The guarded unregister
        first is ONLY the duplicate-name guard (if merely SOME regions
        were lost, a blind register hits the rejection; unknown-name
        unregister is a no-op)."""
        for region in self.regions():
            rname = region.key.lstrip("/")
            self.unregister_fn(rname)
            self.register_fn(rname, region.key, region.size)

    def stats(self) -> dict:
        with self._cv:
            return {
                "depth": self.depth,
                "in_flight": self.depth - len(self._free),
                "max_in_flight": self._max_in_flight,
                "acquires": self._acquires,
                "aliased": self._aliased,
                "regions": sum(len(s.regions) for s in self._slots),
                "region_bytes": sum(
                    r.size for s in self._slots
                    for r in s.regions.values()
                ),
            }

    def close(self) -> None:
        """Unregister (best effort, via the owner's hook) and unlink
        every segment; wake blocked acquirers with an error."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        for slot in self._slots:
            for region in slot.regions.values():
                self.unregister_fn(region.key.lstrip("/"))
                region.close()
            slot.regions.clear()
