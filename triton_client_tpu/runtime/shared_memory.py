"""System shared-memory regions for the KServe serving path.

Triton's system-shared-memory extension lets a same-host client hand
tensors to the server through a POSIX shm segment instead of the gRPC
wire: the client registers a region (name -> shm key + byte range),
then sends infer requests whose input tensors carry
``shared_memory_region`` / ``shared_memory_offset`` /
``shared_memory_byte_size`` parameters and NO raw content. The
reference deploys stock Triton which ships this extension (the
tritonclient package the reference pulls in exposes it as
``tritonclient.utils.shared_memory``); for a 512x512 camera frame the
wire path serializes ~786 KB into protobuf, copies it through HTTP/2
framing, and deserializes it server side — per request, per direction.
The shm path replaces all of that with one memcpy into a mapped page.

POSIX ``shm_open(key)`` maps to ``/dev/shm/<key>`` on Linux, so
regions are implemented as plain mmaps over files there — byte-for-
byte the same segments tritonclient's ``create_shared_memory_region``
creates, without python's ``multiprocessing.shared_memory`` resource-
tracker (which unlinks attached segments at interpreter exit on
< 3.13).

Lifecycle contract (same as Triton's):
  * the CLIENT creates the segment, writes tensors, and eventually
    unlinks it;
  * the SERVER only registers (attaches) and unregisters (detaches) —
    it never unlinks the backing file.
"""

from __future__ import annotations

import mmap
import os
import threading
from dataclasses import dataclass

import numpy as np

_SHM_DIR = "/dev/shm"


def _shm_path(key: str) -> str:
    # POSIX keys conventionally start with "/"; shm_open("/foo") is
    # /dev/shm/foo. Reject path traversal — keys are wire-controlled.
    name = key[1:] if key.startswith("/") else key
    if not name or "/" in name or name.startswith("."):
        raise ValueError(f"invalid shared-memory key {key!r}")
    return os.path.join(_SHM_DIR, name)


class SharedMemoryRegion:
    """One mapped shm segment. ``create`` (client side) makes and owns
    the backing file; ``attach`` (server side) maps an existing one."""

    def __init__(self, key: str, mm: mmap.mmap, size: int, owns: bool):
        self.key = key
        self._mm = mm
        self.size = size
        self._owns = owns
        self._closed = False

    @classmethod
    def create(cls, key: str, byte_size: int) -> "SharedMemoryRegion":
        path = _shm_path(key)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            # a stale segment from a crashed run (same pid after a
            # container restart): reclaim it. O_EXCL on the retry keeps
            # the window race-free; a symlink planted at the name fails
            # both opens rather than being followed.
            os.unlink(path)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, byte_size)
            mm = mmap.mmap(fd, byte_size)
        finally:
            os.close(fd)
        return cls(key, mm, byte_size, owns=True)

    @classmethod
    def attach(cls, key: str, byte_size: int = 0) -> "SharedMemoryRegion":
        path = _shm_path(key)
        fd = os.open(path, os.O_RDWR)
        try:
            actual = os.fstat(fd).st_size
            if byte_size and byte_size > actual:
                raise ValueError(
                    f"shared-memory region {key!r} is {actual} bytes; "
                    f"{byte_size} requested"
                )
            mm = mmap.mmap(fd, actual)
        finally:
            os.close(fd)
        return cls(key, mm, actual, owns=False)

    # -- tensor IO ------------------------------------------------------------

    def write(self, arr: np.ndarray, offset: int = 0) -> int:
        """Copy ``arr``'s bytes into the region; returns bytes written."""
        arr = np.ascontiguousarray(arr)
        n = arr.nbytes
        if offset < 0 or offset + n > self.size:
            raise ValueError(
                f"write of {n} bytes at offset {offset} exceeds region "
                f"{self.key!r} ({self.size} bytes)"
            )
        # numpy-to-numpy copy releases the GIL (a plain mmap slice
        # assignment holds it) — concurrent serving clients on a small
        # host overlap their memcpys
        dst = np.frombuffer(self._mm, np.uint8, count=n, offset=offset)
        np.copyto(dst, arr.view(np.uint8).reshape(-1))
        return n

    def read(self, offset: int, byte_size: int) -> memoryview:
        """Zero-copy view of a byte range (valid until close())."""
        if offset < 0 or offset + byte_size > self.size:
            raise ValueError(
                f"read of {byte_size} bytes at offset {offset} exceeds "
                f"region {self.key!r} ({self.size} bytes)"
            )
        return memoryview(self._mm)[offset : offset + byte_size]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except BufferError:
            # zero-copy views handed out by read() are still alive
            # (e.g. a batched request not yet dispatched): leave the
            # mapping to the GC rather than invalidating live tensors.
            pass
        if self._owns:
            try:
                os.unlink(_shm_path(self.key))
            except FileNotFoundError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@dataclass(frozen=True)
class _Registered:
    region: SharedMemoryRegion
    key: str
    offset: int
    byte_size: int


class SystemSharedMemoryRegistry:
    """Server-side name -> attached region map behind the
    SystemSharedMemory{Register,Status,Unregister} RPCs."""

    def __init__(self) -> None:
        self._regions: dict[str, _Registered] = {}
        self._lock = threading.Lock()

    def register(
        self, name: str, key: str, offset: int = 0, byte_size: int = 0
    ) -> None:
        with self._lock:
            if name in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is already registered"
                )
            region = SharedMemoryRegion.attach(key, offset + byte_size)
            self._regions[name] = _Registered(
                region, key, offset, byte_size or (region.size - offset)
            )

    def unregister(self, name: str) -> None:
        with self._lock:
            reg = self._regions.pop(name, None)
        if reg is not None:
            reg.region.close()

    def unregister_all(self) -> None:
        with self._lock:
            regs, self._regions = list(self._regions.values()), {}
        for reg in regs:
            reg.region.close()

    def status(self, name: str = "") -> dict[str, _Registered]:
        with self._lock:
            if name:
                if name not in self._regions:
                    raise KeyError(f"shared-memory region {name!r} not registered")
                return {name: self._regions[name]}
            return dict(self._regions)

    # -- codec hooks ----------------------------------------------------------

    def read(self, name: str, offset: int, byte_size: int) -> memoryview:
        """Bytes of a registered region; ``offset`` is relative to the
        region's registered base offset (Triton semantics)."""
        with self._lock:
            if name not in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is not registered"
                )
            reg = self._regions[name]
        if offset < 0 or byte_size > reg.byte_size - offset:
            raise ValueError(
                f"request for {byte_size} bytes at offset {offset} exceeds "
                f"registered window of {name!r} ({reg.byte_size} bytes)"
            )
        return reg.region.read(reg.offset + offset, byte_size)

    def write(self, name: str, offset: int, arr: np.ndarray) -> int:
        with self._lock:
            if name not in self._regions:
                raise ValueError(
                    f"shared-memory region {name!r} is not registered"
                )
            reg = self._regions[name]
        arr = np.ascontiguousarray(arr)
        if offset < 0 or arr.nbytes > reg.byte_size - offset:
            raise ValueError(
                f"output of {arr.nbytes} bytes at offset {offset} exceeds "
                f"registered window of {name!r} ({reg.byte_size} bytes)"
            )
        return reg.region.write(arr, reg.offset + offset)
