"""Serving runtime: model repository, request queue, micro-batcher.

The in-tree replacement for the Triton Inference Server runtime the
reference deploys in docker (docker/server/Dockerfile:23-27): model
versioning + registry, dispatch to pjit-compiled functions, optional
micro-batching, and the KServe v2 gRPC facade for ROS interop.
"""

from triton_client_tpu.runtime.repository import ModelRepository, RegisteredModel
