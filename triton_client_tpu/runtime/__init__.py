"""Serving runtime.

The in-tree replacement for the Triton Inference Server runtime the
reference deploys in docker (docker/server/Dockerfile:23-27).
Currently implemented: the versioned model repository (registry +
dispatch target). Request queue / micro-batcher / KServe v2 gRPC
facade land in this package as they are built.
"""

from triton_client_tpu.runtime.repository import ModelRepository, RegisteredModel
