"""Deterministic fault injection for the serving stack.

The robustness ring (admission shedding, member-only failure fan-out,
circuit breakers, drain) is only trustworthy if every behavior is
provable in tier-1 tests WITHOUT real hardware faults — a TPU that
conveniently throws on the third launch does not exist. This module is
the lever: a seeded :class:`FaultPlan` is installed process-wide (test
fixture or ``serve --fault-plan plan.json``), and the serving hot paths
probe named injection points:

  ==============  ========================================== =========
  point           probed from                                effect
  ==============  ========================================== =========
  launch          StagedChannel.launch, before the jit call  raise
  readback        InferFuture resolve, before host copy      raise
  slow_launch     StagedChannel.launch, before the jit call  sleep
  codec_decode    codec.parse_infer_request                  raise
  batcher_stall   BatchingChannel dispatcher, slot time      sleep
  replica_down    _Servicer ServerReady/ModelReady/_issue    flag
  shm_detach      _Servicer before shm request parse         flag
  quality_corrupt eval ShadowMirror worker, before scoring   flag
  temporal_overskip TemporalReusePlane.dispatch, per stream  flag
  ==============  ========================================== =========

The ``replica_down`` point is flag-class (:func:`probe_flag`): the
server consults it with its ``--replica-of`` label as the model key and
simulates process death while the transport stays up — ServerReady
answers not-ready and inference answers UNAVAILABLE (no drain marker) —
so the router chaos shard can kill a replica deterministically.

``shm_detach`` is flag-class too, keyed by model name: the servicer
drops its whole shared-memory registry before parsing the faulted
request, simulating a server restart under a client that still holds
mapped segments — the client must re-register its pool and re-issue
(unary) or fall back per-member (stream), never serve stale bytes.

``quality_corrupt`` (ISSUE 17) is flag-class, keyed by the *variant*
model name: the shadow mirror's scoring worker consults it and, when
armed, perturbs the variant's served detections deterministically
(``eval.shadow.corrupt_detections``, RNG seeded from the trace id)
before they are scored against the f32 reference — an unmistakably
out-of-budget quality regression with zero real model damage, so the
canary auto-rollback path is drivable in CI and the acceptance drive
("corrupting variant ejected before it serves 1% of traffic") replays
identically under a fixed plan.

``temporal_overskip`` (ISSUE 19) is flag-class, keyed by the STREAM id
(sequence_id), not a model name: while armed, the temporal reuse plane
pins that stream's keyframe interval wide open (K = k_max) and ignores
the innovation feedback that would normally collapse it — a
deterministically over-aggressive scheduler. The acceptance drive uses
it to prove the safety net: the per-stream ID-churn window must detect
the resulting track instability and auto-disable reuse for that stream
(``tpu_serving_temporal_disabled_total{reason="churn"}``) before the
quality budgets are violated.

Determinism: rules fire by COUNT windows (requests ``after`` .. ``after
+ count`` at that point/model), and probabilistic rules draw from a
``random.Random(seed)`` owned by the plan — the same plan over the same
request sequence replays the identical fault timeline, which is what
makes the chaos CI shard (ci.sh) reproducible and the bitwise
surviving-request parity test possible.

The probe is a module-level function guarded by a single global: with
no plan installed it is one ``is None`` check, so the hot paths pay
nothing in production.
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field


class InjectedFault(RuntimeError):
    """The error raised at a faulted injection point. A distinct type
    so tests can assert the failure they see is the one they planned,
    not an incidental bug."""


@dataclass
class FaultRule:
    """One injection rule: fire at ``point`` (optionally only for
    ``model``) on probe numbers ``after`` <= n < ``after + count``,
    each firing gated by ``prob``. ``latency_s`` sleeps instead of
    raising for the sleep-class points (slow_launch/batcher_stall)."""

    point: str
    model: str | None = None
    after: int = 0
    count: int = 1
    prob: float = 1.0
    latency_s: float = 0.0
    message: str = "injected fault"
    # runtime state: probes observed / fires executed (not config)
    seen: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)


class FaultPlan:
    """A seeded set of :class:`FaultRule`\\ s with thread-safe probes."""

    def __init__(self, rules=(), seed: int = 0) -> None:
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(**dict(r))
            for r in rules
        ]
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.fired: list[tuple[str, str | None]] = []

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """Build from the CLI/file form::

            {"seed": 7, "rules": [{"point": "launch", "model": "m",
                                   "after": 2, "count": 3}]}
        """
        doc = json.loads(text)
        return cls(rules=doc.get("rules", ()), seed=doc.get("seed", 0))

    def check(self, point: str, model: str | None = None) -> float:
        """Consult the plan at ``point`` for ``model``. Returns a sleep
        duration (0.0 = no sleep) or raises :class:`InjectedFault`.
        Counting and RNG draws happen under the plan lock so concurrent
        probes see one deterministic global order per (point, model)."""
        sleep_s = 0.0
        raise_msg = None
        with self._lock:
            for rule in self.rules:
                if rule.point != point:
                    continue
                if rule.model is not None and rule.model != model:
                    continue
                n = rule.seen
                rule.seen += 1
                if not (rule.after <= n < rule.after + rule.count):
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                self.fired.append((point, model))
                if rule.latency_s > 0:
                    sleep_s = max(sleep_s, rule.latency_s)
                else:
                    raise_msg = rule.message
        if raise_msg is not None:
            raise InjectedFault(f"{point}: {raise_msg}")
        return sleep_s

    def stats(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "fired": len(self.fired),
                "rules": [
                    {
                        "point": r.point,
                        "model": r.model,
                        "seen": r.seen,
                        "fired": r.fired,
                    }
                    for r in self.rules
                ],
            }


# -- process-wide installation hook ------------------------------------------

_ACTIVE: FaultPlan | None = None


def install_fault_plan(plan: FaultPlan | None) -> FaultPlan | None:
    """Install ``plan`` process-wide (None uninstalls); returns the
    previous plan so test fixtures can restore it."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, plan
    return prev


def active_plan() -> FaultPlan | None:
    return _ACTIVE


def probe(point: str, model: str | None = None) -> None:
    """The hot-path hook: no-op (one global read) without a plan;
    otherwise consult it — sleeping faults sleep HERE, raising faults
    raise :class:`InjectedFault` out of the calling injection point."""
    plan = _ACTIVE
    if plan is None:
        return
    sleep_s = plan.check(point, model)
    if sleep_s > 0:
        time.sleep(sleep_s)


def probe_flag(point: str, model: str | None = None) -> bool:
    """Flag-class probe: True iff a rule fired, never raises or
    sleeps. For injection points where the CALLER owns the failure
    shape (``replica_down``: the servicer must answer a protocol-
    correct not-ready / UNAVAILABLE, not leak an InjectedFault
    traceback). Same counting/seeding discipline as :func:`probe`, so
    flag rules replay identically too."""
    plan = _ACTIVE
    if plan is None:
        return False
    try:
        plan.check(point, model)
    except InjectedFault:
        return True
    return False
