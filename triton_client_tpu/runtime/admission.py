"""Admission control, load shedding, and failure isolation primitives.

PR 6 built the SLO *observability* plane — deadlines stamped at
admission, attainment scored on every exit path — but nothing
*enforced* it: a request whose deadline had already expired still
launched on the device (``staged.py`` only counted it), and the
batcher's bounded queue blocked the submitting RPC thread instead of
shedding. This module is the enforcement half:

  * **typed overload errors** — one exception per degradation decision,
    each mapping to the gRPC status code the client retry ladder keys
    on (``RESOURCE_EXHAUSTED`` is non-retryable for ModelInfer, so
    shedding never amplifies load);
  * :class:`AdmissionController` — per-model queue-depth and
    estimated-wait accounting. A request is rejected AT THE DOOR when
    the queue ahead of it already eats its whole deadline budget:
    rejecting in microseconds is strictly better than timing out after
    consuming a device slot. Low-priority requests hit a lower
    queue-depth knee, so they shed first under pressure;
  * :class:`CircuitBreaker` — the closed -> open -> half-open machine
    the staged channels wrap around launch/readback: consecutive
    failures open the circuit (fail-fast ``UNAVAILABLE``, launch cache
    invalidated), a timed probe half-opens it, one success closes it.

Everything here is stdlib-only and lock-cheap: admit() is a dict read
plus two comparisons on the RPC thread.
"""

from __future__ import annotations

import threading
import time


class OverloadError(RuntimeError):
    """Base for every deliberate degradation decision (vs a bug)."""


class AdmissionRejectedError(OverloadError):
    """Shed at the door: queue depth or estimated wait already exceeds
    the request's deadline budget. Maps to ``RESOURCE_EXHAUSTED``."""


class QueueFullError(AdmissionRejectedError):
    """The batcher's bounded admission queue is full — fail-fast
    rejection instead of blocking the submitting RPC thread. Maps to
    ``RESOURCE_EXHAUSTED`` like any other shed."""


class DeadlineExpiredError(OverloadError):
    """The request's deadline passed while it was queued; it was shed
    before touching the device. Maps to ``DEADLINE_EXCEEDED``."""


class CircuitOpenError(OverloadError):
    """The model's circuit breaker is open (recent consecutive
    failures); fail-fast until the timed probe. Maps to
    ``UNAVAILABLE`` — connection-class, safe for clients to retry
    elsewhere."""


class ServerDrainingError(OverloadError):
    """The server is draining (SIGTERM / ``drain()``): in-flight work
    completes, new work is refused. Maps to ``UNAVAILABLE``."""


class ReplicaDownError(OverloadError):
    """Injected replica death (the ``replica_down`` fault point): the
    server answers as if its process were gone — UNAVAILABLE with no
    drain marker, so a router treats it as a connection-class failure
    (ejection streak, budgeted retry), unlike the orchestrated
    :class:`ServerDrainingError`. Only fault plans raise this; real
    death needs no error class."""


class AdmissionController:
    """Per-model bounded queue-depth / estimated-wait admission.

    ``max_queue``: hard cap on per-model admitted-but-unfinished
    requests (the knee for priority >= 0; lower priorities hit
    ``max_queue * low_priority_fraction``). ``concurrency``: how many
    requests the serving stack works concurrently per model (batcher
    merge width x pipeline depth, roughly) — divides the estimated
    wait so a healthy batched server is not over-shed. The service-time
    estimate is an EWMA over completed requests, seeded by the first
    completion; until then only the depth knee applies.
    """

    def __init__(
        self,
        max_queue: int = 64,
        concurrency: int = 4,
        low_priority_fraction: float = 0.5,
        ewma_alpha: float = 0.2,
        tenants=None,
    ) -> None:
        """``tenants``: optional TenantTable (runtime/lifecycle.py).
        When set, a tenant's ``max_inflight`` caps admitted-but-
        unfinished requests ACROSS its models, layered on the per-model
        knees — one tenant flooding its model set sheds at its own cap
        instead of consuming the whole server's queue."""
        self._max_queue = max(1, int(max_queue))
        self._concurrency = max(1, int(concurrency))
        self._low_frac = min(1.0, max(0.05, float(low_priority_fraction)))
        self._alpha = min(1.0, max(0.01, float(ewma_alpha)))
        self._tenants = tenants
        self._lock = threading.Lock()
        self._inflight: dict[str, int] = {}
        self._tenant_inflight: dict[str, int] = {}
        self._tenant_rejects: dict[str, int] = {}
        self._ewma_s: dict[str, float] = {}
        self._rejects: dict[tuple[str, int], int] = {}
        self._admitted = 0

    def _tenant_of(self, model: str) -> str | None:
        return (
            None if self._tenants is None else self._tenants.tenant_of(model)
        )

    # -- accounting hooks (server request lifecycle) --------------------------

    def admit(
        self,
        model: str,
        deadline_s: float | None = None,
        priority: int = 0,
        now: float | None = None,
    ) -> None:
        """Admit or raise :class:`AdmissionRejectedError`. On admission
        the request counts against the model's queue until
        :meth:`finished`. Callers MUST pair a successful admit with
        finished() on every exit path (the server does both in its
        ``finally``-rooted accounting)."""
        tenant = self._tenant_of(model)
        with self._lock:
            depth = self._inflight.get(model, 0)
            limit = self._max_queue
            if priority < 0:
                # low-priority knee: shed the background class first,
                # long before the interactive class feels the queue
                limit = max(1, int(limit * self._low_frac))
            reason = None
            if depth >= limit:
                reason = (
                    f"queue depth {depth} >= limit {limit} "
                    f"(priority {priority})"
                )
            if reason is None and tenant is not None:
                cap = self._tenants.max_inflight(tenant)
                t_depth = self._tenant_inflight.get(tenant, 0)
                if cap > 0 and t_depth >= cap:
                    reason = (
                        f"tenant '{tenant}' in-flight {t_depth} >= "
                        f"cap {cap}"
                    )
            if reason is None and deadline_s is not None:
                ewma = self._ewma_s.get(model)
                if ewma is not None:
                    if now is None:
                        now = time.perf_counter()
                    est_wait = depth * ewma / self._concurrency
                    budget = deadline_s - now
                    if est_wait > budget:
                        reason = (
                            f"estimated queue wait {est_wait * 1e3:.1f}ms "
                            f"exceeds deadline budget {budget * 1e3:.1f}ms"
                        )
            if reason is not None:
                key = (model, int(priority))
                self._rejects[key] = self._rejects.get(key, 0) + 1
                if tenant is not None:
                    self._tenant_rejects[tenant] = (
                        self._tenant_rejects.get(tenant, 0) + 1
                    )
                raise AdmissionRejectedError(
                    f"model '{model}' overloaded: {reason}"
                )
            self._inflight[model] = depth + 1
            if tenant is not None:
                self._tenant_inflight[tenant] = (
                    self._tenant_inflight.get(tenant, 0) + 1
                )
            self._admitted += 1

    def finished(self, model: str, service_s: float | None = None) -> None:
        """One admitted request left the building (any outcome).
        ``service_s`` (wall seconds, successful requests only) feeds
        the EWMA the estimated-wait check divides by."""
        tenant = self._tenant_of(model)
        with self._lock:
            depth = self._inflight.get(model, 0)
            if depth > 0:
                self._inflight[model] = depth - 1
            if tenant is not None:
                t_depth = self._tenant_inflight.get(tenant, 0)
                if t_depth > 0:
                    self._tenant_inflight[tenant] = t_depth - 1
            if service_s is not None and service_s >= 0:
                prev = self._ewma_s.get(model)
                self._ewma_s[model] = (
                    service_s
                    if prev is None
                    else prev + self._alpha * (service_s - prev)
                )

    # -- reading --------------------------------------------------------------

    def estimated_wait_s(self, model: str) -> float:
        with self._lock:
            ewma = self._ewma_s.get(model)
            if ewma is None:
                return 0.0
            return self._inflight.get(model, 0) * ewma / self._concurrency

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_queue": self._max_queue,
                "concurrency": self._concurrency,
                "admitted": self._admitted,
                "inflight": dict(self._inflight),
                "ewma_ms": {
                    m: round(v * 1e3, 3) for m, v in self._ewma_s.items()
                },
                "rejects": {
                    f"{m}|{p}": n for (m, p), n in self._rejects.items()
                },
                "tenant_inflight": dict(self._tenant_inflight),
                "tenant_rejects": dict(self._tenant_rejects),
            }


# breaker states, exported as the tpu_serving_breaker_state gauge value
CLOSED, HALF_OPEN, OPEN = 0, 1, 2


class _BreakerCell:
    __slots__ = ("state", "consecutive", "opens", "open_until", "probing")

    def __init__(self) -> None:
        self.state = CLOSED
        self.consecutive = 0
        self.opens = 0
        self.open_until = 0.0
        self.probing = False


class CircuitBreaker:
    """Per-key (model) closed -> open -> half-open circuit breaker.

    ``threshold`` consecutive failures open the circuit for
    ``reset_s`` seconds; the first :meth:`allow` after the window
    half-opens it and admits exactly ONE probe (other callers keep
    failing fast); the probe's success closes the circuit, its failure
    re-opens the window. The staged channels call this around every
    launch/readback and invalidate their launch cache on open, so a
    recovery recompiles from a clean slate."""

    def __init__(self, threshold: int = 3, reset_s: float = 30.0) -> None:
        self._threshold = max(1, int(threshold))
        self._reset_s = max(0.0, float(reset_s))
        self._lock = threading.Lock()
        self._cells: dict[str, _BreakerCell] = {}

    def _cell(self, key: str) -> _BreakerCell:
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells[key] = _BreakerCell()
        return cell

    def allow(self, key: str, now: float | None = None) -> bool:
        """May a request for ``key`` proceed right now? False means
        fail fast with :class:`CircuitOpenError` — the caller must not
        touch the device."""
        if now is None:
            now = time.perf_counter()
        with self._lock:
            # materialize the cell even while healthy so states() (and
            # the tpu_serving_breaker_state gauge) report an explicit
            # CLOSED for every model this breaker guards — a dashboard
            # distinguishes "closed" from "never served"
            cell = self._cell(key)
            if cell.state == CLOSED:
                return True
            if cell.state == OPEN:
                if now < cell.open_until:
                    return False
                cell.state = HALF_OPEN
                cell.probing = True
                return True  # this caller IS the probe
            # HALF_OPEN: one probe in flight at a time
            if cell.probing:
                return False
            cell.probing = True
            return True

    def record_success(self, key: str) -> None:
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                return
            cell.state = CLOSED
            cell.consecutive = 0
            cell.probing = False

    def record_failure(self, key: str, now: float | None = None) -> bool:
        """Count one failure; returns True when this failure OPENED the
        circuit (the caller then invalidates its launch cache)."""
        if now is None:
            now = time.perf_counter()
        with self._lock:
            cell = self._cell(key)
            cell.consecutive += 1
            was_open = cell.state == OPEN
            if cell.state == HALF_OPEN or cell.consecutive >= self._threshold:
                cell.state = OPEN
                cell.open_until = now + self._reset_s
                cell.probing = False
                if not was_open:
                    cell.opens += 1
                    return True
        return False

    def state(self, key: str) -> int:
        with self._lock:
            cell = self._cells.get(key)
            return CLOSED if cell is None else cell.state

    def states(self) -> dict:
        """{key: {"state": 0|1|2, "opens": n, "consecutive": n}} for
        the collector's breaker gauges."""
        with self._lock:
            return {
                k: {
                    "state": c.state,
                    "opens": c.opens,
                    "consecutive": c.consecutive,
                }
                for k, c in self._cells.items()
            }
