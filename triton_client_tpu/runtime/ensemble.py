"""Ensemble models: serve a DAG of registered models as one model.

Triton ensembles (``platform: "ensemble"`` + ``ensemble_scheduling``
steps with input_map/output_map) are the reference's acknowledged gap —
"Ensemble mode for Triton server" sits unchecked in its TODO list
(README.md:119) and nothing in its tree implements it. This module is
the TPU-native version.

Data movement (round 4): when every member exposes a jit-traceable
``device_fn`` (RegisteredModel.device_fn), the DAG is composed under
ONE jit — intermediates stay in HBM and XLA fuses across member
boundaries — the TPU-first answer to Triton's GPU-tensor ensembles.
Members without a device form fall back to composition through their
wire-facing ``infer_fn``s (numpy on host between steps, the cost
Triton's default non-GPU-tensor ensembles pay; fine for box-sized
intermediates, measured against the fused path for image-sized ones
in perf/profile_ensemble.py). ``fuse`` selects: "auto" (default —
fuse when possible), "always" (error if a member is host-only),
"never" (host path, the pre-round-4 behavior).

An ensemble is declared in the model repository like any other entry::

    <root>/<name>/config.yaml
        family: ensemble
        steps:
          - model: detector            # registered model name
            version: "2"               # optional (default: latest)
            input_map:  {images: raw}  # step input <- ensemble tensor
            output_map: {detections: boxes}  # step output -> ensemble tensor
          - model: tracker
            input_map:  {boxes: boxes}
            output_map: {tracks: tracks}
        outputs: [tracks]              # ensemble-level outputs

Steps execute in declaration order (Triton semantics); build-time
validation checks that every consumed tensor is an ensemble input or
was produced by an earlier step, that referenced models/tensors exist,
and that declared outputs are produced. The composed callable is just
another InferFn, so ensembles serve through TPUChannel, the gRPC
facade, and the micro-batcher unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

from triton_client_tpu.config import ModelSpec, TensorSpec
from triton_client_tpu.runtime.repository import (
    ModelRepository,
    RegisteredModel,
)


@dataclasses.dataclass(frozen=True)
class EnsembleStep:
    """One scheduling step: run ``model`` with inputs pulled from the
    ensemble tensor pool via ``input_map`` (step input name -> pool
    name) and publish outputs back via ``output_map`` (step output
    name -> pool name)."""

    model: str
    input_map: Mapping[str, str]
    output_map: Mapping[str, str]
    version: str = ""
    # serving precision for THIS stage (runtime/precision.py policy
    # name). "" inherits the member's registered policy; an explicit
    # value casts the stage's pool inputs to that policy's compute
    # dtype on the device paths (a bf16 stage consumes upstream f32
    # intermediates as bf16 without a host round-trip). Weight format
    # is fixed at member registration — a step override only moves the
    # stage-boundary activation dtype.
    precision: str = ""


# step-level precision values accepted by parse_steps ("" = inherit).
_STEP_PRECISIONS = ("", "f32", "bf16", "int8w", "int8")


def parse_steps(doc_steps: Sequence[Mapping]) -> list[EnsembleStep]:
    steps = []
    for i, d in enumerate(doc_steps):
        d = dict(d)
        unknown = set(d) - {
            "model", "version", "input_map", "output_map", "precision",
        }
        if unknown:
            raise KeyError(
                f"ensemble step {i}: unknown keys {sorted(unknown)}"
            )
        for key in ("model", "input_map", "output_map"):
            if key not in d:
                raise KeyError(f"ensemble step {i}: missing '{key}'")
        precision = str(d.get("precision", ""))
        if precision not in _STEP_PRECISIONS:
            raise ValueError(
                f"ensemble step {i}: precision must be one of "
                f"{[p for p in _STEP_PRECISIONS if p]} (got {precision!r})"
            )
        steps.append(
            EnsembleStep(
                model=str(d["model"]),
                version=str(d.get("version", "")),
                input_map=dict(d["input_map"]),
                output_map=dict(d["output_map"]),
                precision=precision,
            )
        )
    if not steps:
        raise ValueError("ensemble needs at least one step")
    return steps


def _rename(spec: TensorSpec, name: str) -> TensorSpec:
    return dataclasses.replace(spec, name=name)


def _check_compatible(
    ensemble: str, step: str, pool_name: str, have: TensorSpec, want: TensorSpec
) -> None:
    """Producer/consumer contract check for one pool tensor: dtypes must
    match exactly; dims must agree where both sides are static (-1 is a
    wildcard). Triton validates ensemble tensor consistency at load
    time; failing here keeps scan_disk's fail-loudly-at-startup policy."""
    if have.dtype != want.dtype:
        raise ValueError(
            f"ensemble '{ensemble}': tensor '{pool_name}' is {have.dtype} "
            f"but step '{step}' consumes it as {want.dtype}"
        )
    if len(have.shape) != len(want.shape) or any(
        a != b for a, b in zip(have.shape, want.shape) if a >= 0 and b >= 0
    ):
        raise ValueError(
            f"ensemble '{ensemble}': tensor '{pool_name}' has shape "
            f"{have.shape} but step '{step}' expects {want.shape}"
        )


def build_ensemble(
    repository: ModelRepository,
    name: str,
    steps: Sequence[EnsembleStep],
    outputs: Sequence[str],
    version: str = "1",
    max_batch_size: int = 1,
    fuse: str = "auto",
) -> RegisteredModel:
    """Compose registered models into one RegisteredModel.

    The ensemble's input contract is derived, not declared: every pool
    tensor consumed before it is produced becomes an ensemble input,
    typed by the first member input bound to it. Members are resolved
    at BUILD time (snapshot semantics): reloading a member model means
    rebuilding ensembles over it, exactly like Triton's.
    """
    if not outputs:
        raise ValueError(f"ensemble '{name}': declare at least one output")
    members = [repository.get(s.model, s.version) for s in steps]

    produced: dict[str, TensorSpec] = {}
    needed: dict[str, TensorSpec] = {}
    for step, member in zip(steps, members):
        in_names = {t.name for t in member.spec.inputs}
        missing = set(step.input_map) - in_names
        if missing:
            raise KeyError(
                f"ensemble '{name}': step '{step.model}' has no inputs "
                f"{sorted(missing)} (has {sorted(in_names)})"
            )
        unbound = in_names - set(step.input_map)
        if unbound:
            raise KeyError(
                f"ensemble '{name}': step '{step.model}' inputs "
                f"{sorted(unbound)} are not bound in input_map"
            )
        for step_in, pool_name in step.input_map.items():
            spec = member.spec.input_by_name(step_in)
            have = produced.get(pool_name) or needed.get(pool_name)
            if have is None:
                needed[pool_name] = _rename(spec, pool_name)
            else:
                _check_compatible(name, step.model, pool_name, have, spec)
        out_specs = {t.name: t for t in member.spec.outputs}
        missing = set(step.output_map) - set(out_specs)
        if missing:
            raise KeyError(
                f"ensemble '{name}': step '{step.model}' has no outputs "
                f"{sorted(missing)} (has {sorted(out_specs)})"
            )
        for step_out, pool_name in step.output_map.items():
            produced[pool_name] = _rename(out_specs[step_out], pool_name)

    missing = [o for o in outputs if o not in produced]
    if missing:
        # ensemble inputs don't qualify: echoing an input back is almost
        # always a config typo (Triton likewise requires every ensemble
        # output to come from a step's output_map)
        raise ValueError(
            f"ensemble '{name}': outputs {missing} are never produced "
            f"by any step (produced: {sorted(produced)})"
        )

    step_list = list(zip(steps, members))
    output_names = tuple(outputs)
    # effective per-stage precision: an explicit step key overrides,
    # "" inherits whatever policy the member registered with (round 10)
    step_precision = [
        s.precision or str(m.spec.extra.get("precision", "f32"))
        for s, m in step_list
    ]

    if fuse not in ("auto", "always", "never"):
        raise ValueError(
            f"ensemble '{name}': fuse must be auto/always/never, "
            f"got {fuse!r}"
        )
    host_only = [s.model for s, m in step_list if m.device_fn is None]
    if fuse == "always" and host_only:
        raise ValueError(
            f"ensemble '{name}': fuse: always, but members {host_only} "
            f"expose no device_fn (host-only)"
        )
    fused = fuse != "never" and not host_only
    # partial device residency (round 6): a MIXED ensemble (some
    # members device-capable, some host-only) keeps intermediates in
    # HBM across consecutive device-capable steps and pays the host
    # round-trip only at host-only member boundaries — the in-process
    # preprocess -> detector hop stays on device. fuse="never" keeps
    # the all-host path (the compatibility fallback for cross-runtime
    # consumers that must see numpy between every step).
    mixed = (
        fuse != "never"
        and not fused
        and any(m.device_fn is not None for _, m in step_list)
    )

    spec = ModelSpec(
        name=name,
        version=version,
        platform="ensemble",
        inputs=tuple(needed.values()),
        outputs=tuple(produced[o] for o in outputs),
        max_batch_size=max_batch_size,
        # "fused" surfaces which data path this ensemble serves
        # (tests/operators read it via model metadata); "data_path"
        # refines it: fused | device-resident (mixed) | host
        extra={
            "steps": [s.model for s in steps],
            "fused": fused,
            "data_path": (
                "fused" if fused else "device-resident" if mixed else "host"
            ),
            # effective (post-inheritance) policy per stage, in step
            # order; the ensemble's own wire stays f32 — outputs cast
            # back at the boundary like any other pipeline
            "step_precision": step_precision,
        },
    )

    def host_infer_fn(inputs: Mapping) -> dict:
        pool = dict(inputs)
        for step, member in step_list:
            step_inputs = {
                step_in: pool[pool_name]
                for step_in, pool_name in step.input_map.items()
            }
            result = member.infer_fn(step_inputs)
            for step_out, pool_name in step.output_map.items():
                pool[pool_name] = result[step_out]
        return {o: pool[o] for o in output_names}

    ensemble_device_fn = None
    if fused or mixed:
        import jax.numpy as jnp

        from triton_client_tpu.runtime.precision import PrecisionPolicy

        # per-stage activation dtype at the step boundary (bf16 stages
        # take bf16 intermediates; everything else stays f32). Integer
        # tensors (num_points, labels) pass through untouched.
        _step_dtype = [
            PrecisionPolicy.parse(p).compute_dtype for p in step_precision
        ]

        def _stage_cast(x, dt):
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt:
                return x.astype(dt)
            return x

    if fused:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from triton_client_tpu.config import config_dtypes

        def _compose(pool_in):
            # the whole DAG is ONE XLA program: step outputs feed the
            # next step as device values, XLA fuses across members,
            # and the only host transfers are the ensemble's own
            # inputs in / declared outputs out. Unjitted form so a
            # PARENT ensemble can compose this ensemble as a member
            # (nested fusion) under its own jit.
            pool = dict(pool_in)
            for (step, member), dt in zip(step_list, _step_dtype):
                result = member.device_fn(
                    {
                        step_in: _stage_cast(pool[pool_name], dt)
                        for step_in, pool_name in step.input_map.items()
                    }
                )
                for step_out, pool_name in step.output_map.items():
                    pool[pool_name] = result[step_out]
            return {o: pool[o] for o in output_names}

        ensemble_device_fn = _compose
        _device_dag = jax.jit(_compose)
        # wire-contract dtypes for each declared output: device traces
        # run with x64 disabled, so e.g. a scored head's INT64 classes
        # come back int32 from the DAG — the boundary cast keeps the
        # fused path's outputs identical to the host path's
        out_np_dtype = {
            o: config_dtypes().get(produced[o].dtype) for o in output_names
        }

        def infer_fn(inputs: Mapping) -> dict:
            out = _device_dag(
                {k: jnp.asarray(v) for k, v in inputs.items()}
            )
            return {
                k: np.asarray(v, dtype=out_np_dtype[k] or None)
                for k, v in out.items()
            }
    elif mixed:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from triton_client_tpu.config import config_dtypes

        # one jit per device-capable member: consecutive device steps
        # hand intermediates over as device arrays (jnp.asarray on an
        # already-device value is a no-op), host-only steps force the
        # boundary readback via np.asarray, and the ensemble boundary
        # casts to the wire contract exactly like the fused path
        member_jit = {
            i: jax.jit(member.device_fn)
            for i, (_, member) in enumerate(step_list)
            if member.device_fn is not None
        }
        out_np_dtype = {
            o: config_dtypes().get(produced[o].dtype) for o in output_names
        }

        def infer_fn(inputs: Mapping) -> dict:
            pool = dict(inputs)
            for i, (step, member) in enumerate(step_list):
                step_inputs = {
                    step_in: pool[pool_name]
                    for step_in, pool_name in step.input_map.items()
                }
                jitted = member_jit.get(i)
                if jitted is not None:
                    result = jitted(
                        {
                            k: _stage_cast(jnp.asarray(v), _step_dtype[i])
                            for k, v in step_inputs.items()
                        }
                    )
                else:
                    result = member.infer_fn(
                        {k: np.asarray(v) for k, v in step_inputs.items()}
                    )
                for step_out, pool_name in step.output_map.items():
                    pool[pool_name] = result[step_out]
            return {
                o: np.asarray(pool[o], dtype=out_np_dtype[o] or None)
                for o in output_names
            }
    else:
        infer_fn = host_infer_fn

    if fused or mixed:
        import numpy as np

        from triton_client_tpu.config import config_dtypes

        def warmup() -> None:
            # member warmups compile the members' STANDALONE wire
            # programs, which neither the fused nor the mixed
            # device-resident path executes — warm the served DAG
            # itself instead, on a nominal spec-shaped batch
            # (wildcard dims -> 64, batch -> 1; like the member
            # pipelines, a new input resolution retraces at request
            # time — warmup covers the whole-DAG compile cost once)
            zeros = {}
            for t in spec.inputs:
                shape = [1] + [
                    (64 if d < 0 else int(d)) for d in t.shape[1:]
                ]
                zeros[t.name] = np.zeros(
                    shape, config_dtypes().get(t.dtype) or np.float32
                )
            infer_fn(zeros)
    else:

        def warmup() -> None:
            for _, member in step_list:
                if member.warmup is not None:
                    member.warmup()

    return RegisteredModel(
        spec=spec,
        infer_fn=infer_fn,
        warmup=warmup,
        device_fn=ensemble_device_fn,
    )


def build_ensemble_doc(
    repository: ModelRepository, name: str, doc: Mapping, version: str = "1"
) -> RegisteredModel:
    """config.yaml dict -> RegisteredModel (the disk-repository hook)."""
    unknown = set(doc) - {
        "family", "steps", "outputs", "max_batch_size", "warmup", "fuse",
    }
    if unknown:
        raise KeyError(
            f"ensemble '{name}': unknown config keys {sorted(unknown)}"
        )
    if "steps" not in doc or "outputs" not in doc:
        raise KeyError(f"ensemble '{name}': config needs 'steps' and 'outputs'")
    fuse = doc.get("fuse", "auto")
    if isinstance(fuse, bool):  # yaml `fuse: true` reads as a bool
        fuse = "always" if fuse else "never"
    return build_ensemble(
        repository,
        name,
        parse_steps(doc["steps"]),
        outputs=list(doc["outputs"]),
        version=version,
        max_batch_size=int(doc.get("max_batch_size", 1)),
        fuse=str(fuse),
    )
