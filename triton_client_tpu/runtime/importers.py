"""Model-specific weight importers: upstream checkpoints -> flax trees.

The reference never converts weights client-side — the server loads
.pth (examples/pointpillar_kitti/1/model.py:93-112) or serves .onnx /
.pt artifacts declared in config.pbtxt (examples/YOLOv5/config.pbtxt:2),
with deploy.sh doing pth->ONNX conversion offline (deploy.sh:56-65).
Here the models run in JAX, so importing the SAME upstream artifacts is
the mAP-parity bridge (SURVEY.md §7 hard part (e)): these functions map
published checkpoint naming (ultralytics YOLOv5, OpenPCDet PointPillars,
ONNX initializer graphs) onto our flax module trees via
checkpoint.convert_state_dict's layout rules.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Mapping

import numpy as np

from triton_client_tpu.runtime.checkpoint import (
    convert_state_dict,
    default_name_map,
    load_torch_checkpoint,
)

log = logging.getLogger(__name__)

# Our yolov5 module name -> ultralytics yolov5 layer index ("model.N").
# The index layout is fixed across ultralytics v5.x n/s/m/l variants
# (yolov5 models/yolov5n.yaml): backbone 0-9, head 10-23, detect 24;
# indices 11/15 are Upsample and 12/16/19/22 are Concat (no params).
_YOLOV5_LAYER_IDX = {
    "stem": 0,
    "down2": 1,
    "c3_2": 2,
    "down3": 3,
    "c3_3": 4,
    "down4": 5,
    "c3_4": 6,
    "down5": 7,
    "c3_5": 8,
    "sppf": 9,
    "lat5": 10,
    "c3_up4": 13,
    "lat4": 14,
    "c3_up3": 17,
    "pan3": 18,
    "c3_pan4": 20,
    "pan4": 21,
    "c3_pan5": 23,
}

_BOTTLENECK_RE = re.compile(r"^m(\d+)$")


def yolov5_torch_key(path: tuple[str, ...]) -> str:
    """flax yolov5 path -> ultralytics state_dict key.

    ('params','c3_3','m0','cv1','conv','kernel')
        -> 'model.4.m.0.cv1.conv.weight'
    ('params','detect1','kernel') -> 'model.24.m.1.weight'
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    if head.startswith("detect"):
        scale = head[len("detect"):]
        leaf = {"kernel": "weight", "bias": "bias"}[parts[-1]]
        return f"model.24.m.{scale}.{leaf}"
    idx = _YOLOV5_LAYER_IDX[head]
    mapped = []
    for p in rest[:-1]:
        m = _BOTTLENECK_RE.match(p)
        mapped.append(f"m.{m.group(1)}" if m else p)
    return ".".join([f"model.{idx}", *mapped, default_name_map((rest[-1],))])


def _stem_s2d_kernel(natural: np.ndarray) -> np.ndarray:
    """Vanilla (6, 6, cin, out) stride-2 stem kernel -> the exactly
    equivalent (3, 3, 4*cin, out) kernel for the space-to-depth stem
    (models/yolov5.py s2d): output row 2o+ky reads s2d block
    bi = ky//2, within-block row a = ky%2, and the blocked channel
    order is (a*2 + b)*cin + c — the same order the forward's
    reshape/transpose produces."""
    kh, kw, cin, out = natural.shape
    if (kh, kw) != (6, 6):
        raise ValueError(f"s2d stem expects a 6x6 source kernel, got {natural.shape}")
    w = natural.reshape(3, 2, 3, 2, cin, out)   # (bi, a, bj, b, c, o)
    w = w.transpose(0, 2, 1, 3, 4, 5)           # (bi, bj, a, b, c, o)
    return np.ascontiguousarray(w.reshape(3, 3, 4 * cin, out))


def _embed_padded(natural: np.ndarray, target_shape, leaf_name: str) -> np.ndarray:
    """Zero/neutral-pad a vanilla leaf into a ch_floor-padded template
    shape. Padded channels stay EXACTLY zero through the net: kernel
    columns/rows zero, BN scale/var one + bias/mean zero -> BN output 0
    -> SiLU(0) = 0 -> next layer's padded input columns are zero too."""
    target_shape = tuple(target_shape)
    if natural.shape == target_shape:
        return natural
    if len(natural.shape) != len(target_shape) or any(
        n > t for n, t in zip(natural.shape, target_shape)
    ):
        raise ValueError(
            f"cannot embed {leaf_name} {natural.shape} into {target_shape}"
        )
    fill = 1.0 if leaf_name in ("scale", "var") else 0.0
    out = np.full(target_shape, fill, natural.dtype)
    out[tuple(slice(0, s) for s in natural.shape)] = natural
    return out


def load_yolov5(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """Ultralytics YOLOv5 checkpoint (.pt path or state_dict) -> flax
    variables shaped like ``variables`` (from init_yolov5).

    MXU-optimized templates import LOSSLESSLY: an s2d stem template
    ((3, 3, 4*cin, out)) gets the reshaped 6x6 kernel, and a padded
    stem stage gets zero kernels + neutral BN rows for the padded
    channels — the optimized model computes the identical detection
    function (verified end-to-end in tests/test_import_fidelity.py).
    Adaptation is deliberately restricted to the STEM-LOCAL cases whose
    exactness is provable (the stem's own leaves + down2's input rows):
    padding a stage that feeds a concat would silently misalign the
    concat segments, so any other shape mismatch — wrong num_classes,
    wrong variant, a too-aggressive ch_floor — raises."""
    state = _as_state_dict(path_or_state)
    # Ultralytics .pt stores the full pickled model; its state_dict keys
    # may carry a 'model.' prefix already ('model.model.0...').
    state = _strip_prefix(state, "model.model.", "model.")

    def transform(key_path, nat, leaf):
        parts = tuple(p for p in key_path if p not in ("params", "batch_stats"))
        leaf_name = key_path[-1]
        target = tuple(leaf.shape)
        if nat.shape == target:
            return nat
        if parts[0] == "stem":
            if (
                leaf_name == "kernel"
                and nat.shape[:2] == (6, 6)
                and target[:2] == (3, 3)
            ):
                nat = _stem_s2d_kernel(nat)
            # only the OUT-channel axis may grow (ch_floor): a spatial
            # or cin mismatch (e.g. a grayscale fork's 1-channel stem)
            # is a different model and must still raise
            if nat.shape[:-1] == target[:-1] and nat.shape[-1] <= target[-1]:
                return _embed_padded(nat, target, leaf_name)
        if (
            parts[:2] == ("down2", "conv")
            and leaf_name == "kernel"
            and nat.shape[:2] == target[:2]
            and nat.shape[3] == target[3]
            and nat.shape[2] < target[2]
        ):
            # extra input rows read the stem's padded (all-zero)
            # channels: zero rows keep the function identical
            return _embed_padded(nat, target, leaf_name)
        raise ValueError(
            f"yolov5 import: {'.'.join(parts)} {nat.shape} does not fit "
            f"the template {target}. Only stem-local MXU adaptations "
            "(s2d; ch_floor that pads the stem stage alone, e.g. 32 on "
            "variant n) are exactness-preserving — this mismatch means "
            "wrong num_classes/variant, or a ch_floor that pads "
            "concatenated stages (segment layouts would silently shift)"
        )

    return convert_state_dict(
        state, variables, name_map=yolov5_torch_key, strict=strict,
        leaf_transform=transform,
    )


# --- PointPillars (OpenPCDet naming, tools/cfgs/kitti_models/pointpillar.yaml) ---

_PP_BLOCK_DOWN = re.compile(r"^block(\d+)_down(_bn)?$")
_PP_BLOCK_CONV = re.compile(r"^block(\d+)_(conv|bn)(\d+)$")
_PP_UP = re.compile(r"^up(\d+)(_bn)?$")
_PP_HEADS = {
    "cls_head": "dense_head.conv_cls",
    "box_head": "dense_head.conv_box",
    "dir_head": "dense_head.conv_dir_cls",
}


def _bev_backbone_key(name: str, leaf: str, prefix: str) -> str | None:
    """BEVBackbone flax child name -> '<prefix>.blocks/deblocks.…' key.

    The second.pytorch-lineage BEV backbone (OpenPCDet BaseBEVBackbone
    under ``backbone_2d``, det3d RPN under ``neck`` — both pcdet/models/
    backbones_2d/base_bev_backbone.py shape) builds each block as
    Sequential(ZeroPad2d, Conv2d, BN, ReLU, [Conv2d, BN, ReLU] * L), so
    the down conv sits at index 1, its BN at 2, and layer li's conv/BN
    at 4+3*li / 5+3*li. Deblocks are Sequential(ConvTranspose2d, BN,
    ReLU). Returns None for a non-backbone name.
    """
    m = _PP_BLOCK_DOWN.match(name)
    if m:
        b, is_bn = m.group(1), bool(m.group(2))
        return f"{prefix}.blocks.{b}.{2 if is_bn else 1}.{leaf}"
    m = _PP_BLOCK_CONV.match(name)
    if m:
        b, kind, li = m.group(1), m.group(2), int(m.group(3))
        idx = 4 + 3 * li if kind == "conv" else 5 + 3 * li
        return f"{prefix}.blocks.{b}.{idx}.{leaf}"
    m = _PP_UP.match(name)
    if m:
        b, is_bn = m.group(1), bool(m.group(2))
        return f"{prefix}.deblocks.{b}.{1 if is_bn else 0}.{leaf}"
    return None


def pointpillars_torch_key(path: tuple[str, ...]) -> str:
    """flax PointPillars path -> OpenPCDet state_dict key."""
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))
    if head == "vfe":
        # PillarVFE keeps one PFNLayer; OpenPCDet names its BN 'norm'.
        sub = "linear" if rest[0] == "linear" else "norm"
        return f"vfe.pfn_layers.0.{sub}.{leaf}"
    if head in _PP_HEADS:
        return f"{_PP_HEADS[head]}.{leaf}"
    if head == "backbone":
        key = _bev_backbone_key(rest[0], leaf, "backbone_2d")
        if key:
            return key
    raise KeyError(f"unmapped PointPillars path: {path}")


def _pp_is_transposed_conv(path: tuple[str, ...]) -> bool:
    return any(_PP_UP.match(p) and not p.endswith("_bn") for p in path)


def load_pointpillars(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """OpenPCDet PointPillars checkpoint -> flax variables."""
    state = _as_state_dict(path_or_state)
    return convert_state_dict(
        state,
        variables,
        name_map=pointpillars_torch_key,
        strict=strict,
        transposed_conv=_pp_is_transposed_conv,
    )


# --- SECOND-IoU (OpenPCDet naming, examples/second_iou/1/model.py:96-117) ---

_SECOND_HEADS = {
    "cls_head": "dense_head.conv_cls",
    "box_head": "dense_head.conv_box",
    "dir_head": "dense_head.conv_dir_cls",
    # The per-anchor IoU-quality conv — this framework's dense re-design
    # of the reference's SECONDHead ROI IoU branch (examples/second_iou/
    # 1/second_iou.yaml:92 IOU_FC) — imports under OpenPCDet's
    # conv-head naming convention.
    "iou_head": "dense_head.conv_iou",
}
_MIDDLE_CONV = re.compile(r"^conv(\d+)$")
_MIDDLE_BN = re.compile(r"^bn(\d+)$")


def second_torch_key(path: tuple[str, ...]) -> str:
    """flax SECONDIoU path -> OpenPCDet state_dict key.

    The middle encoder maps onto spconv's SparseSequential index
    convention (pcdet backbone_3d: each stage is Sequential(conv, BN,
    ReLU) -> conv at .0, BN at .1): stage si lives at
    ``backbone_3d.conv{si}``. MeanVFE is parameter-free on both sides.
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))
    if head == "middle":
        name = rest[0]
        m = _MIDDLE_CONV.match(name)
        if m and len(rest) == 1:
            # sparse middle: the (k^3, cin, cout) gather-conv param IS
            # the leaf (no nn.Conv wrapper)
            return f"backbone_3d.conv{m.group(1)}.0.weight"
        if m:
            return f"backbone_3d.conv{m.group(1)}.0.{leaf}"
        m = _MIDDLE_BN.match(name)
        if m:
            return f"backbone_3d.conv{m.group(1)}.1.{leaf}"
    if head in _SECOND_HEADS:
        return f"{_SECOND_HEADS[head]}.{leaf}"
    if head == "backbone":
        key = _bev_backbone_key(rest[0], leaf, "backbone_2d")
        if key:
            return key
    raise KeyError(f"unmapped SECOND path: {path}")


def load_second(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """OpenPCDet-named SECOND(-IoU) checkpoint -> flax variables.

    Works for both middle encoders: the dense stages import Conv3d
    kernels directly (OIDHW -> DHWIO); the SPARSE middle's (27, cin,
    cout) gather weights are the row-major reshape of the same 3^3
    kernel (ops/sparse_conv.py kernel_offsets ordering, parity pinned
    by tests/test_sparse_conv.py) — so ONE trained checkpoint serves
    either encoder. A 2^3 stride kernel has no 3^3 source and raises.
    """
    state = _as_state_dict(path_or_state)

    def transform(key_path, nat, leaf):
        key_path = tuple(
            p for p in key_path if p not in ("params", "batch_stats")
        )
        target = tuple(leaf.shape)
        if nat.shape == target:
            return nat
        if (
            len(key_path) >= 2
            and key_path[-2] == "middle"
            and _MIDDLE_CONV.match(key_path[-1])
            and nat.ndim == 5
            and len(target) == 3
        ):
            # torch Conv3d (out, in, kd, kh, kw) -> (kd, kh, kw, in,
            # out) -> row-major (k^3, in, out): exactly the
            # kernel_offsets(3) enumeration the sparse conv gathers by.
            w = nat.transpose(2, 3, 4, 1, 0)
            k3 = w.shape[0] * w.shape[1] * w.shape[2]
            if (k3,) + w.shape[3:] != target:
                raise ValueError(
                    f"sparse middle stage {key_path[-1]} expects "
                    f"{target} (a {target[0]}^(1/3)-kernel); the "
                    f"checkpoint's {nat.shape} kernel does not reshape "
                    "to it — stride_kernel=2 stages have no upstream "
                    "3^3 source, import a dense-template checkpoint "
                    "or serve with sparse_stride_kernel=3"
                )
            return np.ascontiguousarray(w.reshape(target))
        raise ValueError(
            f"second import: {'.'.join(key_path)} {nat.shape} does not "
            f"fit the template {target} (wrong grid/filters/classes?)"
        )

    return convert_state_dict(
        state, variables, name_map=second_torch_key, strict=strict,
        transposed_conv=_pp_is_transposed_conv, leaf_transform=transform,
    )


# --- CenterPoint (det3d naming, data/nusc_centerpoint_pp_02voxel_...py) ---

_CP_BRANCH = {
    "heatmap": "hm",
    "offset": "reg",
    "height": "height",
    "size": "dim",
    "rot": "rot",
    "vel": "vel",
}


def centerpoint_torch_key(path: tuple[str, ...]) -> str:
    """flax CenterPoint path -> det3d state_dict key.

    det3d's pillar CenterPoint names its trunk ``reader`` (the
    PillarFeatureNet), ``neck`` (the second.pytorch RPN — same
    Sequential layout as OpenPCDet's BEV backbone) and ``bbox_head``
    (CenterHead: shared_conv Sequential + per-task SepHead branches
    hm/reg/height/dim/rot/vel, each a Sequential of convs). This
    framework's head is a single-task re-design (one shared 3x3 + 1x1
    branches), so branches sit at ``bbox_head.tasks.0.<name>.0``.
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))
    if head == "vfe":
        sub = "linear" if rest[0] == "linear" else "norm"
        return f"reader.pfn_layers.0.{sub}.{leaf}"
    if head == "backbone":
        key = _bev_backbone_key(rest[0], leaf, "neck")
        if key:
            return key
    if head == "head":
        name = rest[0]
        if name == "shared":
            return f"bbox_head.shared_conv.0.{leaf}"
        if name == "shared_bn":
            return f"bbox_head.shared_conv.1.{leaf}"
        if name in _CP_BRANCH:
            return f"bbox_head.tasks.0.{_CP_BRANCH[name]}.0.{leaf}"
    raise KeyError(f"unmapped CenterPoint path: {path}")


def load_centerpoint(
    path_or_state: Any, variables: Mapping, strict: bool = True
) -> dict:
    """det3d-named CenterPoint checkpoint -> flax variables.

    det3d's shared_conv uses Conv2d(bias=True) + BN; this framework's
    shared conv is bias-free (the BN immediately consumes any bias).
    An upstream bias is folded EXACTLY into the imported BN running
    mean — BN((conv+b) - m) == BN(conv - (m-b)) — so the forward is
    unchanged rather than silently dropping the term.
    """
    state = dict(_as_state_dict(path_or_state))
    bias_key = "bbox_head.shared_conv.0.bias"
    mean_key = "bbox_head.shared_conv.1.running_mean"
    if bias_key in state:
        if mean_key not in state:
            raise KeyError(
                f"{bias_key} present but {mean_key} missing — cannot "
                "fold the shared-conv bias into BN"
            )
        state[mean_key] = np.asarray(state[mean_key]) - np.asarray(state[bias_key])
        del state[bias_key]
        log.info("folded %s into %s (bias-free shared conv)", bias_key, mean_key)
    return convert_state_dict(
        state, variables, name_map=centerpoint_torch_key, strict=strict,
        transposed_conv=_pp_is_transposed_conv,
    )


# --- RetinaNet / FCOS (detectron2 naming, the reference's libtorch
#     export lineage: examples/RetinaNet_detectron/config.pbtxt:2) -----------

_D2_BLOCK = re.compile(r"^s(\d+)b(\d+)$")
_D2_CONV = {"c1": "conv1", "c2": "conv2", "c3": "conv3", "down": "shortcut"}
_D2_LAT = re.compile(r"^lat(\d)$")
_D2_OUT = re.compile(r"^out(\d)$")
_D2_SUBNET = re.compile(r"^(cls|box|reg)(\d+)$")
_D2_SCALE = re.compile(r"^scale(\d+)$")


def detectron_torch_key(path: tuple[str, ...]) -> str:
    """flax RetinaNet/FCOS path -> detectron2 state_dict key.

    detectron2 layout (modeling/meta_arch/retinanet.py + fcos.py):
    ``backbone.bottom_up.stem.conv1`` / ``res{2-5}.{i}.conv{1-3}`` (+
    ``.shortcut``) with norms as ``.norm`` children,
    ``backbone.fpn_lateral{l}`` / ``fpn_output{l}`` /
    ``top_block.p6/p7``, and heads ``head.cls_subnet.{2i}`` /
    ``bbox_subnet.{2i}`` (ReLU at odd indices), ``head.cls_score`` /
    ``bbox_pred`` / ``ctrness``. Residual stride sits on conv2 — the
    torchvision-style STRIDE_IN_1X1=False layout; caffe-style R50
    checkpoints share key names but put stride on conv1, which a
    state_dict cannot reveal, so that variant is out of contract.
    FCOS per-level scales use the AdelaiDet ``head.scales.{l}.scale``
    naming (stock detectron2 FCOS has none — see load_fcos).
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))
    if head == "backbone":
        name = rest[0]
        if name == "stem":
            base = "backbone.bottom_up.stem.conv1"
            return f"{base}.{leaf}" if rest[1] == "conv" else f"{base}.norm.{leaf}"
        m = _D2_BLOCK.match(name)
        if m:
            conv = _D2_CONV[rest[1]]
            base = (
                f"backbone.bottom_up.res{int(m.group(1)) + 2}."
                f"{int(m.group(2))}.{conv}"
            )
            return f"{base}.{leaf}" if rest[2] == "conv" else f"{base}.norm.{leaf}"
        m = _D2_LAT.match(name)
        if m:
            return f"backbone.fpn_lateral{m.group(1)}.{leaf}"
        m = _D2_OUT.match(name)
        if m:
            return f"backbone.fpn_output{m.group(1)}.{leaf}"
        if name in ("p6", "p7"):
            return f"backbone.top_block.{name}.{leaf}"
    if head == "head":
        name = rest[0]
        m = _D2_SCALE.match(name)
        if m:
            return f"head.scales.{m.group(1)}.scale"
        m = _D2_SUBNET.match(name)
        if m:
            sub = "cls_subnet" if m.group(1) == "cls" else "bbox_subnet"
            return f"head.{sub}.{2 * int(m.group(2))}.{leaf}"
        if name == "cls_out":
            return f"head.cls_score.{leaf}"
        if name in ("box_out", "reg_out"):
            return f"head.bbox_pred.{leaf}"
        if name == "ctr_out":
            return f"head.ctrness.{leaf}"
    raise KeyError(f"unmapped detectron path: {path}")


def load_retinanet(
    path_or_state: Any, variables: Mapping, strict: bool = True
) -> dict:
    """detectron2-named RetinaNet checkpoint -> flax variables."""
    state = {
        k.removeprefix("model."): v
        for k, v in _as_state_dict(path_or_state).items()
    }
    return convert_state_dict(
        state, variables, name_map=detectron_torch_key, strict=strict
    )


def load_fcos(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """detectron2/AdelaiDet-named FCOS checkpoint -> flax variables.

    Stock detectron2 FCOS predicts unscaled distances (no Scale
    modules); AdelaiDet checkpoints carry ``head.scales.{l}.scale``.
    Missing scales default to the neutral 1.0 — exactly stock d2's
    function — rather than failing strict import.
    """
    state = dict(_as_state_dict(path_or_state))
    state = {k.removeprefix("model."): v for k, v in state.items()}
    params = variables.get("params", variables)
    n_scales = sum(1 for k in params.get("head", {}) if _D2_SCALE.match(str(k)))
    for li in range(n_scales):
        state.setdefault(f"head.scales.{li}.scale", np.ones((1,), np.float32))
    return convert_state_dict(
        state, variables, name_map=detectron_torch_key, strict=strict
    )


# --- YOLOv4 (pytorch-YOLOv4 naming — the torch source of the ONNX the
#     reference serves: examples/YOLOv4/config.pbtxt:2, deploy.sh) ----------

# Tianxiaomo/pytorch-YOLOv4 module layout: backbone DownSample1..5
# ('down{k}'), neck ('neek' [sic]), head. Every Conv_Bn_Activation
# stores its layers in a ModuleList 'conv' -> conv at .conv.0, BN at
# .conv.1. DownSample1 inlines the first CSP stage as conv1..conv8;
# DownSample2-5 use conv1..conv5 + ResBlock ('resblock.module_list.
# {i}.{0,1}'). The flax model's stage-local names map as:
_V4_DOWN1 = {  # stem + stage1 (first=True) -> down1.conv{n}
    "stem": 1, "down": 2, "split_short": 3, "split_main": 4,
    "res0_cv1": 5, "res0_cv2": 6, "post": 7, "merge": 8,
}
_V4_STAGE = {  # stage2-5 locals -> down{k}.conv{n}
    "down": 1, "split_short": 2, "split_main": 3, "post": 4, "merge": 5,
}
_V4_RES = re.compile(r"^res(\d+)_cv([12])$")
_V4_TOP = {  # neck/head ConvBnActs and detect convs, in upstream order
    "pre_spp0": "neek.conv1", "pre_spp1": "neek.conv2",
    "pre_spp2": "neek.conv3", "post_spp0": "neek.conv5",
    "post_spp1": "neek.conv6", "td4_up": "neek.conv7",
    "td4_lat": "neek.conv8", "td3_up": "neek.conv14",
    "td3_lat": "neek.conv15",
    "head0_cv": "head.conv1", "detect0": "head.conv2",
    "bu4_down": "head.conv3", "head1_cv": "head.conv9",
    "detect1": "head.conv10", "bu5_down": "head.conv11",
    "head2_cv": "head.conv17", "detect2": "head.conv18",
}
_V4_CONV5_BASE = {  # 1-3-1-3-1 neck blocks: _cv{i} -> base+i
    "td4": ("neek", 9), "td3": ("neek", 16),
    "bu4": ("head", 4), "bu5": ("head", 12),
}
_V4_CV = re.compile(r"^(td4|td3|bu4|bu5)_cv(\d)$")


def yolov4_torch_key(path: tuple[str, ...]) -> str:
    """flax YoloV4 path -> pytorch-YOLOv4 state_dict key."""
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))

    def cba(mod: str, sub: str) -> str:
        # Conv_Bn_Activation: ModuleList 'conv' -> [Conv2d, BN, act]
        idx = 0 if sub == "conv" else 1
        return f"{mod}.conv.{idx}.{leaf}"

    if head == "stem":
        return cba(f"down1.conv{_V4_DOWN1['stem']}", rest[0])
    if head == "stage1":
        name = rest[0]
        if name in _V4_DOWN1:
            return cba(f"down1.conv{_V4_DOWN1[name]}", rest[1])
    elif head.startswith("stage"):
        k, name = head[len("stage"):], rest[0]
        if name in _V4_STAGE:
            return cba(f"down{k}.conv{_V4_STAGE[name]}", rest[1])
        m = _V4_RES.match(name)
        if m:
            i, cv = m.group(1), int(m.group(2)) - 1
            return cba(f"down{k}.resblock.module_list.{i}.{cv}", rest[1])
    if head == "spp":  # SPP merge conv == neek.conv4
        return cba("neek.conv4", rest[1])
    if head in _V4_TOP:
        mod = _V4_TOP[head]
        if head.startswith("detect"):  # bare Conv2d (bn=False, bias)
            return f"{mod}.conv.0.{leaf}"
        return cba(mod, rest[0])
    m = _V4_CV.match(head)
    if m:
        mod, base = _V4_CONV5_BASE[m.group(1)]
        return cba(f"{mod}.conv{base + int(m.group(2))}", rest[0])
    raise KeyError(f"unmapped YOLOv4 path: {path}")


def load_yolov4(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """pytorch-YOLOv4 checkpoint (.pth, or its ONNX export read back
    through onnx_reader) -> flax variables.

    One upstream/flax divergence needs a kernel fix-up: upstream's SPP
    concatenates [pool13, pool9, pool5, x] (models.py Neck.forward)
    while this model concatenates [x, pool5, pool9, pool13] — so the
    SPP merge conv's INPUT-channel blocks import block-reversed. The
    function is identical; only the concat bookkeeping differs.
    """
    state = _as_state_dict(path_or_state)
    # torch.onnx initializer names / some forks use 'neck.'; canonical
    # upstream spells it 'neek.'.
    state = {
        ("neek." + k[len("neck."):] if k.startswith("neck.") else k): v
        for k, v in state.items()
    }

    def transform(key_path, nat, leaf):
        key_path = tuple(
            p for p in key_path if p not in ("params", "batch_stats")
        )
        target = tuple(leaf.shape)
        if key_path[:2] == ("spp", "merge") and key_path[-1] == "kernel":
            kh, kw, cin, cout = nat.shape
            blocks = nat.reshape(kh, kw, 4, cin // 4, cout)
            nat = np.ascontiguousarray(
                blocks[:, :, ::-1].reshape(kh, kw, cin, cout)
            )
        if nat.shape != target:
            raise ValueError(
                f"yolov4 import: {'.'.join(key_path)} {nat.shape} does "
                f"not fit the template {target} (wrong width multiple "
                "or num_classes?)"
            )
        return nat

    return convert_state_dict(
        state, variables, name_map=yolov4_torch_key, strict=strict,
        leaf_transform=transform,
    )


def _as_state_dict(path_or_state: Any) -> Mapping[str, Any]:
    if isinstance(path_or_state, Mapping):
        return path_or_state
    return load_torch_checkpoint(path_or_state)


def _strip_prefix(state: Mapping[str, Any], *prefixes: str) -> dict:
    """Normalize keys to the longest matching prefix removed + re-added
    canonical 'model.' (ultralytics wraps the Detection model once or
    twice depending on export path)."""
    out = dict(state)
    for prefix in prefixes:
        if any(k.startswith(prefix) for k in out):
            return {
                ("model." + k[len(prefix):] if k.startswith(prefix) else k): v
                for k, v in out.items()
            }
    return out
