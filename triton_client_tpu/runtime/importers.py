"""Model-specific weight importers: upstream checkpoints -> flax trees.

The reference never converts weights client-side — the server loads
.pth (examples/pointpillar_kitti/1/model.py:93-112) or serves .onnx /
.pt artifacts declared in config.pbtxt (examples/YOLOv5/config.pbtxt:2),
with deploy.sh doing pth->ONNX conversion offline (deploy.sh:56-65).
Here the models run in JAX, so importing the SAME upstream artifacts is
the mAP-parity bridge (SURVEY.md §7 hard part (e)): these functions map
published checkpoint naming (ultralytics YOLOv5, OpenPCDet PointPillars,
ONNX initializer graphs) onto our flax module trees via
checkpoint.convert_state_dict's layout rules.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Mapping

import numpy as np

from triton_client_tpu.runtime.checkpoint import (
    convert_state_dict,
    default_name_map,
    load_torch_checkpoint,
)

log = logging.getLogger(__name__)

# Our yolov5 module name -> ultralytics yolov5 layer index ("model.N").
# The index layout is fixed across ultralytics v5.x n/s/m/l variants
# (yolov5 models/yolov5n.yaml): backbone 0-9, head 10-23, detect 24;
# indices 11/15 are Upsample and 12/16/19/22 are Concat (no params).
_YOLOV5_LAYER_IDX = {
    "stem": 0,
    "down2": 1,
    "c3_2": 2,
    "down3": 3,
    "c3_3": 4,
    "down4": 5,
    "c3_4": 6,
    "down5": 7,
    "c3_5": 8,
    "sppf": 9,
    "lat5": 10,
    "c3_up4": 13,
    "lat4": 14,
    "c3_up3": 17,
    "pan3": 18,
    "c3_pan4": 20,
    "pan4": 21,
    "c3_pan5": 23,
}

_BOTTLENECK_RE = re.compile(r"^m(\d+)$")


def yolov5_torch_key(path: tuple[str, ...]) -> str:
    """flax yolov5 path -> ultralytics state_dict key.

    ('params','c3_3','m0','cv1','conv','kernel')
        -> 'model.4.m.0.cv1.conv.weight'
    ('params','detect1','kernel') -> 'model.24.m.1.weight'
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    if head.startswith("detect"):
        scale = head[len("detect"):]
        leaf = {"kernel": "weight", "bias": "bias"}[parts[-1]]
        return f"model.24.m.{scale}.{leaf}"
    idx = _YOLOV5_LAYER_IDX[head]
    mapped = []
    for p in rest[:-1]:
        m = _BOTTLENECK_RE.match(p)
        mapped.append(f"m.{m.group(1)}" if m else p)
    return ".".join([f"model.{idx}", *mapped, default_name_map((rest[-1],))])


def _stem_s2d_kernel(natural: np.ndarray) -> np.ndarray:
    """Vanilla (6, 6, cin, out) stride-2 stem kernel -> the exactly
    equivalent (3, 3, 4*cin, out) kernel for the space-to-depth stem
    (models/yolov5.py s2d): output row 2o+ky reads s2d block
    bi = ky//2, within-block row a = ky%2, and the blocked channel
    order is (a*2 + b)*cin + c — the same order the forward's
    reshape/transpose produces."""
    kh, kw, cin, out = natural.shape
    if (kh, kw) != (6, 6):
        raise ValueError(f"s2d stem expects a 6x6 source kernel, got {natural.shape}")
    w = natural.reshape(3, 2, 3, 2, cin, out)   # (bi, a, bj, b, c, o)
    w = w.transpose(0, 2, 1, 3, 4, 5)           # (bi, bj, a, b, c, o)
    return np.ascontiguousarray(w.reshape(3, 3, 4 * cin, out))


def _embed_padded(natural: np.ndarray, target_shape, leaf_name: str) -> np.ndarray:
    """Zero/neutral-pad a vanilla leaf into a ch_floor-padded template
    shape. Padded channels stay EXACTLY zero through the net: kernel
    columns/rows zero, BN scale/var one + bias/mean zero -> BN output 0
    -> SiLU(0) = 0 -> next layer's padded input columns are zero too."""
    target_shape = tuple(target_shape)
    if natural.shape == target_shape:
        return natural
    if len(natural.shape) != len(target_shape) or any(
        n > t for n, t in zip(natural.shape, target_shape)
    ):
        raise ValueError(
            f"cannot embed {leaf_name} {natural.shape} into {target_shape}"
        )
    fill = 1.0 if leaf_name in ("scale", "var") else 0.0
    out = np.full(target_shape, fill, natural.dtype)
    out[tuple(slice(0, s) for s in natural.shape)] = natural
    return out


def load_yolov5(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """Ultralytics YOLOv5 checkpoint (.pt path or state_dict) -> flax
    variables shaped like ``variables`` (from init_yolov5).

    MXU-optimized templates import LOSSLESSLY: an s2d stem template
    ((3, 3, 4*cin, out)) gets the reshaped 6x6 kernel, and a padded
    stem stage gets zero kernels + neutral BN rows for the padded
    channels — the optimized model computes the identical detection
    function (verified end-to-end in tests/test_import_fidelity.py).
    Adaptation is deliberately restricted to the STEM-LOCAL cases whose
    exactness is provable (the stem's own leaves + down2's input rows):
    padding a stage that feeds a concat would silently misalign the
    concat segments, so any other shape mismatch — wrong num_classes,
    wrong variant, a too-aggressive ch_floor — raises."""
    state = _as_state_dict(path_or_state)
    # Ultralytics .pt stores the full pickled model; its state_dict keys
    # may carry a 'model.' prefix already ('model.model.0...').
    state = _strip_prefix(state, "model.model.", "model.")

    def transform(key_path, nat, leaf):
        parts = tuple(p for p in key_path if p not in ("params", "batch_stats"))
        leaf_name = key_path[-1]
        target = tuple(leaf.shape)
        if nat.shape == target:
            return nat
        if parts[0] == "stem":
            if (
                leaf_name == "kernel"
                and nat.shape[:2] == (6, 6)
                and target[:2] == (3, 3)
            ):
                nat = _stem_s2d_kernel(nat)
            # only the OUT-channel axis may grow (ch_floor): a spatial
            # or cin mismatch (e.g. a grayscale fork's 1-channel stem)
            # is a different model and must still raise
            if nat.shape[:-1] == target[:-1] and nat.shape[-1] <= target[-1]:
                return _embed_padded(nat, target, leaf_name)
        if (
            parts[:2] == ("down2", "conv")
            and leaf_name == "kernel"
            and nat.shape[:2] == target[:2]
            and nat.shape[3] == target[3]
            and nat.shape[2] < target[2]
        ):
            # extra input rows read the stem's padded (all-zero)
            # channels: zero rows keep the function identical
            return _embed_padded(nat, target, leaf_name)
        raise ValueError(
            f"yolov5 import: {'.'.join(parts)} {nat.shape} does not fit "
            f"the template {target}. Only stem-local MXU adaptations "
            "(s2d; ch_floor that pads the stem stage alone, e.g. 32 on "
            "variant n) are exactness-preserving — this mismatch means "
            "wrong num_classes/variant, or a ch_floor that pads "
            "concatenated stages (segment layouts would silently shift)"
        )

    return convert_state_dict(
        state, variables, name_map=yolov5_torch_key, strict=strict,
        leaf_transform=transform,
    )


# --- PointPillars (OpenPCDet naming, tools/cfgs/kitti_models/pointpillar.yaml) ---

_PP_BLOCK_DOWN = re.compile(r"^block(\d+)_down(_bn)?$")
_PP_BLOCK_CONV = re.compile(r"^block(\d+)_(conv|bn)(\d+)$")
_PP_UP = re.compile(r"^up(\d+)(_bn)?$")
_PP_HEADS = {
    "cls_head": "dense_head.conv_cls",
    "box_head": "dense_head.conv_box",
    "dir_head": "dense_head.conv_dir_cls",
}


def pointpillars_torch_key(path: tuple[str, ...]) -> str:
    """flax PointPillars path -> OpenPCDet state_dict key.

    OpenPCDet's BaseBEVBackbone builds each block as
    Sequential(ZeroPad2d, Conv2d, BN, ReLU, [Conv2d, BN, ReLU] * L)
    (pcdet/models/backbones_2d/base_bev_backbone.py), so the down conv
    sits at index 1, its BN at 2, and layer li's conv/BN at 4+3*li /
    5+3*li. Deblocks are Sequential(ConvTranspose2d, BN, ReLU).
    """
    parts = [p for p in path if p not in ("params", "batch_stats")]
    head, *rest = parts
    leaf = default_name_map((parts[-1],))
    if head == "vfe":
        # PillarVFE keeps one PFNLayer; OpenPCDet names its BN 'norm'.
        sub = "linear" if rest[0] == "linear" else "norm"
        return f"vfe.pfn_layers.0.{sub}.{leaf}"
    if head in _PP_HEADS:
        return f"{_PP_HEADS[head]}.{leaf}"
    if head == "backbone":
        name = rest[0]
        m = _PP_BLOCK_DOWN.match(name)
        if m:
            b, is_bn = m.group(1), bool(m.group(2))
            return f"backbone_2d.blocks.{b}.{2 if is_bn else 1}.{leaf}"
        m = _PP_BLOCK_CONV.match(name)
        if m:
            b, kind, li = m.group(1), m.group(2), int(m.group(3))
            idx = 4 + 3 * li if kind == "conv" else 5 + 3 * li
            return f"backbone_2d.blocks.{b}.{idx}.{leaf}"
        m = _PP_UP.match(name)
        if m:
            b, is_bn = m.group(1), bool(m.group(2))
            return f"backbone_2d.deblocks.{b}.{1 if is_bn else 0}.{leaf}"
    raise KeyError(f"unmapped PointPillars path: {path}")


def _pp_is_transposed_conv(path: tuple[str, ...]) -> bool:
    return any(_PP_UP.match(p) and not p.endswith("_bn") for p in path)


def load_pointpillars(path_or_state: Any, variables: Mapping, strict: bool = True) -> dict:
    """OpenPCDet PointPillars checkpoint -> flax variables."""
    state = _as_state_dict(path_or_state)
    return convert_state_dict(
        state,
        variables,
        name_map=pointpillars_torch_key,
        strict=strict,
        transposed_conv=_pp_is_transposed_conv,
    )


def _as_state_dict(path_or_state: Any) -> Mapping[str, Any]:
    if isinstance(path_or_state, Mapping):
        return path_or_state
    return load_torch_checkpoint(path_or_state)


def _strip_prefix(state: Mapping[str, Any], *prefixes: str) -> dict:
    """Normalize keys to the longest matching prefix removed + re-added
    canonical 'model.' (ultralytics wraps the Detection model once or
    twice depending on export path)."""
    out = dict(state)
    for prefix in prefixes:
        if any(k.startswith(prefix) for k in out):
            return {
                ("model." + k[len(prefix):] if k.startswith(prefix) else k): v
                for k, v in out.items()
            }
    return out
