"""Streaming-session state: device-resident per-stream tracking slots.

ROADMAP item 5's session layer. A :class:`SessionManager` owns a
bounded pool of per-stream slots, each holding the on-device tracker
state pytree from ops/tracking.py between frames — the KV-cache
pattern from PAPERS.md's ragged-paged-attention exemplar transplanted
to track state: per-sequence state lives in HBM for the stream's
lifetime and the per-frame step is appended to the detector's launch,
so on the steady-state path NOTHING crosses the host boundary (the
parity/residency gate in tests/test_sessions.py runs a whole stream
under ``jax.transfer_guard_device_to_host("disallow")``).

Wiring (the ``sequence_id`` thread): kserve clients set
``sequence_id`` / ``sequence_start`` / ``sequence_end`` request
parameters (channel/kserve/codec.py), ``_Servicer._issue`` decodes
them onto the InferRequest, the batchers solo-dispatch session frames
(state depends on frame order — merging two streams' frames into one
launch would interleave their steps), and StagedChannel.launch calls
:meth:`SessionManager.advance` on the launch outputs before the
response futures form. ``advance`` bumps the slot's refcount;
``release`` (called from the launch's resolve, success or failure)
drops it — exactly the lifecycle manager's acquire/release bracket, so
TTL/LRU reclaim can never free a slot with an in-flight launch.

Slot reclaim mirrors runtime/lifecycle.py's eviction ladder: ended
slots first, then TTL-expired, then LRU — always refs==0 only; a full
pool with every slot in flight rejects the new stream with
:class:`SessionLimitError` (RESOURCE_EXHAUSTED on the wire, same
non-retryable overload contract as admission).

Track-id namespace: ids are int32 ``namespace(4b) | epoch(11b) |
local(16b)`` — ``namespace`` distinguishes replicas (serve
``--session-id-namespace``), ``epoch`` increments on every session
(re)start, so a stream re-homed to a new replica after failover — or
restarted on the same one — mints ids PROVABLY disjoint from its
previous life's. 16 local bits bound one session life at 65k track
births; 11 epoch bits wrap at 2048 session lives per process
(documented in OPERATIONS.md).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

from triton_client_tpu.ops import tracking
from triton_client_tpu.runtime.admission import AdmissionRejectedError

log = logging.getLogger(__name__)


class SessionLimitError(AdmissionRejectedError):
    """Session pool full and nothing reclaimable — maps to
    RESOURCE_EXHAUSTED (non-retryable overload) like every admission
    reject."""


#: output tensors ``advance`` consumes from the detector launch
DET_KEY = "detections"
VALID_KEY = "valid"

_NAMESPACE_BITS = 4
_EPOCH_BITS = 11
_LOCAL_BITS = 16


def id_base_for(namespace: int, epoch: int) -> int:
    """int32-positive id floor for one session life — see module doc."""
    ns = int(namespace) & ((1 << _NAMESPACE_BITS) - 1)
    ep = int(epoch) & ((1 << _EPOCH_BITS) - 1)
    return (ns << (_EPOCH_BITS + _LOCAL_BITS)) | (ep << _LOCAL_BITS)


@dataclasses.dataclass
class _Slot:
    stream_id: str
    epoch: int
    id_base: int
    state: dict | None = None  # device pytree, lazily built on frame 1
    group: int = 0  # 0 single-frame; >0 synchronized-camera group size
    refs: int = 0
    frames: int = 0
    ended: bool = False
    created: float = 0.0
    last_used: float = 0.0
    # serializes the per-frame step: frames of one stream must advance
    # in order even if a client pipelines requests
    step_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False
    )


class SessionManager:
    """Bounded pool of device-resident streaming-session slots.

    ``tracker``: the ops/tracking.py config every session runs.
    ``id_namespace``: replica-distinguishing 4-bit id prefix.
    ``time_fn``: injectable clock (tests drive TTL deterministically).
    """

    def __init__(
        self,
        max_sessions: int = 64,
        ttl_s: float = 60.0,
        tracker: tracking.TrackerConfig | None = None,
        id_namespace: int = 0,
        time_fn=time.monotonic,
    ) -> None:
        self.tracker = tracker or tracking.TrackerConfig()
        self._max = max(1, int(max_sessions))
        self._ttl_s = float(ttl_s)
        self._namespace = int(id_namespace)
        self._time = time_fn
        self._lock = threading.Lock()
        self._slots: dict[str, _Slot] = {}
        # dead sessions' state pytrees awaiting a counter fold (device
        # reads deferred to scrape time — see _drain_folds)
        self._dead_states: list = []
        self._epochs = 0
        # host-side counters; device birth/death totals fold in when a
        # session ends or restarts (one read per session LIFE, never on
        # the steady-state frame path)
        self._created = 0
        self._restarted = 0
        self._expired = 0
        self._reclaimed = 0
        self._rejected = 0
        self._ended = 0
        self._frames = 0
        self._coasted = 0
        self._births_total = 0
        self._deaths_total = 0

    # -- pool bookkeeping (locked) --------------------------------------------

    def _next_epoch_locked(self) -> int:
        self._epochs += 1
        return self._epochs

    def _make_room_locked(self, now: float) -> None:
        """Free one refs==0 slot: ended > TTL-expired > LRU. Raises
        SessionLimitError when every slot has in-flight work."""
        if len(self._slots) < self._max:
            return
        idle = [s for s in self._slots.values() if s.refs == 0]
        victim = None
        for s in idle:
            if s.ended:
                victim = s
                break
        if victim is None and self._ttl_s > 0:
            for s in idle:
                if now - s.last_used > self._ttl_s:
                    victim = s
                    self._expired += 1
                    break
        if victim is None and idle:
            victim = min(idle, key=lambda s: s.last_used)
            self._reclaimed += 1
        if victim is None:
            self._rejected += 1
            raise SessionLimitError(
                f"session pool full ({self._max} slots, all in flight)"
            )
        del self._slots[victim.stream_id]
        self._fold_async_locked(victim)

    def _fold_async_locked(self, slot: _Slot) -> None:
        """Queue a dead slot's device counters for the next stats()
        fold (caller holds the pool lock) — the device READ happens
        later, outside the lock and off the frame path."""
        if slot.state is not None:
            self._dead_states.append(slot.state)

    def _drain_folds(self) -> None:
        """Fold queued dead sessions' device birth/death counters into
        the host totals. Device reads, so: never called from advance /
        release (the hot bracket) — only from stats() scrapes and
        end-of-stream folds."""
        while True:
            with self._lock:
                if not self._dead_states:
                    return
                state = self._dead_states.pop()
            births = int(np.asarray(state["births"]))
            deaths = int(np.asarray(state["deaths"]))
            with self._lock:
                self._births_total += births
                self._deaths_total += deaths

    # -- the frame bracket ----------------------------------------------------

    def advance(self, request, outputs):
        """Run one tracking step on a detector launch's device outputs.

        Called from StagedChannel.launch with the raw (device) output
        dict; returns the dict extended with the track tensors. Bumps
        the slot refcount — the caller MUST pair with :meth:`release`
        (the launch's resolve does, on success and failure alike).
        Pure device work: the step is an async jit dispatch on arrays
        already in HBM; no host transfer happens here.
        """
        sid = request.sequence_id
        now = self._time()
        with self._lock:
            slot = self._slots.get(sid)
            fresh = None
            if slot is None:
                self._make_room_locked(now)
                slot = _Slot(
                    stream_id=sid,
                    epoch=self._next_epoch_locked(),
                    id_base=0,
                    created=now,
                    last_used=now,
                )
                slot.id_base = id_base_for(self._namespace, slot.epoch)
                self._slots[sid] = slot
                self._created += 1
            elif request.sequence_start or slot.ended:
                # clean in-place restart: fresh epoch, disjoint ids —
                # the failover contract (router re-homes with
                # sequence_start=True on the new owner)
                fresh = slot.state
                slot.epoch = self._next_epoch_locked()
                slot.id_base = id_base_for(self._namespace, slot.epoch)
                slot.state = None
                slot.group = 0
                slot.frames = 0
                slot.ended = False
                slot.created = now
                self._restarted += 1
            slot.refs += 1
            slot.last_used = now
            if fresh is not None:
                self._dead_states.append(fresh)
        try:
            out = self._step(slot, outputs)
        except Exception:
            with self._lock:
                slot.refs -= 1
            raise
        if request.sequence_end:
            with self._lock:
                slot.ended = True
                self._ended += 1
        return out

    def _cfg_for(self, det_dim: int) -> tracking.TrackerConfig:
        """The stream tracker config adapted to this model's detection
        row width. The default config carries CenterPoint's
        ``velocity_cols=(7, 9)``; a 2D detector's 6-column rows hold no
        measured velocity, so the window must narrow to ``None`` rather
        than slice past the row (a width-0 ``z_vel`` crashes the
        update)."""
        cfg = self.tracker
        if cfg.velocity_cols is not None and det_dim < cfg.velocity_cols[1]:
            cfg = dataclasses.replace(cfg, velocity_cols=None)
        return cfg

    def _step(self, slot: _Slot, outputs):
        det = outputs.get(DET_KEY)
        valid = outputs.get(VALID_KEY)
        if det is None or valid is None:
            return outputs  # model has no tracking-compatible head
        ndim = getattr(det, "ndim", 2)
        cfg = self._cfg_for(int(det.shape[-1]))
        with slot.step_lock:
            if ndim == 3:
                # leading dim = synchronized camera group (B==1 is a
                # group of one): vmapped step, stacked state
                group = int(det.shape[0])
                if slot.state is None:
                    base = tracking.init_state(
                        cfg, int(det.shape[-1]), slot.id_base
                    )
                    # disjoint per-camera id ranges: split the session's
                    # 16-bit local id space evenly across the group
                    span = (1 << _LOCAL_BITS) // group
                    stacked = {
                        k: np.stack([v] * group) for k, v in base.items()
                    }
                    stacked["next_id"] = np.asarray(
                        [slot.id_base + 1 + c * span for c in range(group)],
                        np.int32,
                    )
                    slot.state = stacked
                    slot.group = group
                elif slot.group != group:
                    raise ValueError(
                        f"stream '{slot.stream_id}': camera-group size "
                        f"changed mid-stream ({slot.group} -> {group})"
                    )
                step = tracking.make_group_step(cfg)
            else:
                if slot.state is None:
                    slot.state = tracking.init_state(
                        cfg, int(det.shape[-1]), slot.id_base
                    )
                    slot.group = 0
                step = tracking.make_step(cfg)
            new_state, track_out = step(slot.state, det, valid)
            slot.state = new_state
            slot.frames += 1
        with self._lock:
            self._frames += 1
        out = dict(outputs)
        out.update(track_out)
        return out

    def coast(self, request):
        """Advance one frame by Kalman predict alone — the detector is
        skipped entirely (runtime/temporal.py's keyframe scheduler
        decided this frame is temporally redundant). Returns the coast
        outputs dict (track table only), or ``None`` when the stream
        has no device state yet — a coast before the first keyframe is
        meaningless and the caller must fall back to full detection.

        Same refcount contract as :meth:`advance`: bumps the slot ref,
        caller MUST pair with :meth:`release`. Pure device work — one
        jit dispatch over the resident state pytree, nothing crosses
        the host boundary."""
        sid = request.sequence_id
        now = self._time()
        with self._lock:
            slot = self._slots.get(sid)
            if slot is None or slot.state is None or slot.ended \
                    or request.sequence_start:
                return None
            slot.refs += 1
            slot.last_used = now
        try:
            with slot.step_lock:
                if slot.state is None:  # reset raced us
                    with self._lock:
                        slot.refs -= 1
                    return None
                # same det-width-narrowed config as _step, so the coast
                # jit shares the keyframe step's cache entry per stream
                cfg = self._cfg_for(int(slot.state["box"].shape[-1]))
                coast = (
                    tracking.make_group_coast(cfg)
                    if slot.group
                    else tracking.make_coast_step(cfg)
                )
                new_state, track_out = coast(slot.state)
                slot.state = new_state
                slot.frames += 1
        except Exception:
            with self._lock:
                slot.refs -= 1
            raise
        with self._lock:
            self._frames += 1
            self._coasted += 1
        if request.sequence_end:
            with self._lock:
                slot.ended = True
                self._ended += 1
        return dict(track_out)

    def release(self, stream_id: str) -> None:
        """Drop the in-flight ref taken by :meth:`advance`. Ended slots
        free (and queue their counters for the next stats fold) once
        the last ref drops."""
        with self._lock:
            slot = self._slots.get(stream_id)
            if slot is None:
                return
            slot.refs = max(0, slot.refs - 1)
            if slot.ended and slot.refs == 0:
                del self._slots[stream_id]
                self._fold_async_locked(slot)

    def end(self, stream_id: str) -> None:
        """Explicitly end a session (server drain, client abort)."""
        with self._lock:
            slot = self._slots.get(stream_id)
            if slot is None:
                return
            slot.ended = True
            if slot.refs == 0:
                del self._slots[stream_id]
                self._fold_async_locked(slot)
        self._drain_folds()

    def reset(self) -> None:
        """Drop every session (drain/shutdown). In-flight launches keep
        their state pytrees alive via closure; new frames restart."""
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
            for s in slots:
                self._fold_async_locked(s)

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        """Pool counters for the collector. Folds queued dead-session
        device counters first (scrape-time device reads only — the
        frame path stays transfer-free)."""
        self._drain_folds()
        with self._lock:
            active = len(self._slots)
            inflight = sum(s.refs for s in self._slots.values())
            return {
                "active_sessions": active,
                "max_sessions": self._max,
                "slot_occupancy": active / self._max,
                "inflight_frames": inflight,
                "created_total": self._created,
                "restarted_total": self._restarted,
                "ended_total": self._ended,
                "expired_total": self._expired,
                "reclaimed_total": self._reclaimed,
                "rejected_total": self._rejected,
                "frames_total": self._frames,
                "coast_frames_total": self._coasted,
                "track_births_total": self._births_total,
                "track_deaths_total": self._deaths_total,
            }
