"""Checkpoint / resume + external weight conversion.

The reference's "checkpointing" is server-side weight loading at model
init from fixed paths (examples/pointpillar_kitti/1/model.py:93-112
loads yaml + .pth; ONNX/libtorch artifacts named by config.pbtxt),
provisioned by scp (deploy.sh:56-65) or S3/Keycloak
(docker/server/Dockerfile:9-18). The TPU equivalents here:

  * orbax-backed save/restore of model variables and full train states
    (resume-at-step), with versioned step directories and retention —
    the framework's answer to both "load weights to serve" and
    "resume training";
  * torch .pth state_dict -> flax variables conversion utilities so
    models trained elsewhere can be served (weight provisioning parity
    with deploy.sh's pth->ONNX->server flow, minus the ONNX hop).

Conversion is explicit-mapping-based: convert_state_dict walks the
flax variable tree, looks up each leaf through a caller-supplied
name-mapping function, and transposes torch's OIHW conv / (out, in)
linear layouts into flax's HWIO / (in, out).
"""

from __future__ import annotations

import logging
import pathlib
from typing import Any, Callable, Mapping

import jax
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class CheckpointManager:
    """Versioned checkpoints under ``directory/<step>/`` with retention.

    Works for bare variable pytrees (serving weights) and TrainState
    pytrees (resume) alike — anything jax.tree-mappable.
    """

    def __init__(self, directory: str | pathlib.Path, keep: int = 3) -> None:
        self._dir = pathlib.Path(directory).resolve()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=False
            ),
        )

    def save(self, step: int, tree: Any) -> None:
        self._manager.save(step, args=ocp.args.StandardSave(tree))
        self._manager.wait_until_finished()

    def restore(self, step: int | None = None, like: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``like`` provides the
        target pytree structure/shardings; restoring without it returns
        plain numpy leaves."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._dir}")
        if like is not None:
            target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        return self._manager.restore(step)

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._manager.all_steps())

    def close(self) -> None:
        self._manager.close()


# ---------------------------------------------------------------------------
# torch state_dict conversion
# ---------------------------------------------------------------------------


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor without importing torch
        return t.detach().cpu().numpy()
    return np.asarray(t)


def torch_to_flax_leaf(
    name: str,
    value: np.ndarray,
    flax_shape,
    leaf_name: str | None = None,
    transposed_conv: bool = False,
) -> np.ndarray:
    """Layout-convert one torch tensor to a flax leaf shape.

    Rules:
      * flax ``kernel`` leaves ALWAYS transpose by rank — torch Linear
        (out, in) -> (in, out), conv OIHW/OIDHW -> HWIO/DHWIO — even
        when the tensor is square and the shapes already match (a
        square Linear weight is shape-ambiguous, so shape checking
        alone would silently skip the transpose);
      * ``transposed_conv`` kernels use torch ConvTranspose's (in, out,
        kH, kW) layout -> flax's (kH, kW, in, out) — a DIFFERENT axis
        order than regular convs, and shape-indistinguishable from one
        when in == out, so callers must flag those paths explicitly;
      * everything else (biases, BN scale/bias/stats): passthrough;
      * without ``leaf_name`` (legacy callers) fall back to
        shape-directed heuristics.
    """
    value = _to_numpy(value)
    flax_shape = tuple(flax_shape)
    if leaf_name == "kernel":
        if value.ndim == 2:
            out = value.T  # (out, in) -> (in, out)
        elif value.ndim == 4:
            if transposed_conv:
                # IOHW -> HWIO plus a spatial flip: torch ConvTranspose
                # convolves with the flipped kernel (gradient-of-conv),
                # flax's lax.conv_transpose does not flip.
                out = np.ascontiguousarray(value.transpose(2, 3, 0, 1)[::-1, ::-1])
            else:
                out = value.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        elif value.ndim == 5:
            out = value.transpose(2, 3, 4, 1, 0)  # OIDHW -> DHWIO
        else:
            out = value
        if out.shape != flax_shape:
            raise ValueError(
                f"cannot map torch kernel '{name}' {value.shape} onto "
                f"flax leaf {flax_shape}"
            )
        return out
    if leaf_name is not None:
        if value.shape != flax_shape:
            raise ValueError(
                f"cannot map torch tensor '{name}' {value.shape} onto "
                f"flax leaf '{leaf_name}' {flax_shape}"
            )
        return value
    # legacy shape-directed path (no leaf context)
    if value.shape == flax_shape:
        return value
    if value.ndim == 4 and value.transpose(2, 3, 1, 0).shape == flax_shape:
        return value.transpose(2, 3, 1, 0)
    if value.ndim == 5 and value.transpose(2, 3, 4, 1, 0).shape == flax_shape:
        return value.transpose(2, 3, 4, 1, 0)
    if value.ndim == 2 and value.T.shape == flax_shape:
        return value.T
    raise ValueError(
        f"cannot map torch tensor '{name}' {value.shape} onto flax leaf "
        f"{flax_shape}"
    )


_DEFAULT_LEAF_MAP = {
    # flax leaf name -> torch suffix (BatchNorm naming differs)
    "kernel": "weight",
    "scale": "weight",
    "bias": "bias",
    "mean": "running_mean",
    "var": "running_var",
}


def default_name_map(path: tuple[str, ...]) -> str:
    """flax variable path -> torch state_dict key.

    ('params', 'backbone', 'conv', 'kernel') -> 'backbone.conv.weight'.
    Collections ('params'/'batch_stats') are dropped; the leaf name maps
    through _DEFAULT_LEAF_MAP.
    """
    *mods, leaf = [p for p in path if p not in ("params", "batch_stats")]
    return ".".join([*mods, _DEFAULT_LEAF_MAP.get(leaf, leaf)])


def _natural_flax_shape(leaf_name: str, value, transposed_conv: bool = False) -> tuple:
    """The flax shape a torch tensor lands on BEFORE any template
    adaptation (kernel transposes only). ConvTranspose kernels use
    torch's (in, out, kH, kW) layout -> (kH, kW, in, out)."""
    shape = tuple(value.shape)
    if leaf_name == "kernel" and len(shape) == 4:
        if transposed_conv:
            return (shape[2], shape[3], shape[0], shape[1])
        return (shape[2], shape[3], shape[1], shape[0])
    if leaf_name == "kernel" and len(shape) == 5:
        return (shape[2], shape[3], shape[4], shape[1], shape[0])
    if leaf_name == "kernel" and len(shape) == 2:
        return (shape[1], shape[0])
    return shape


def convert_state_dict(
    state_dict: Mapping[str, Any],
    variables: Mapping,
    name_map: Callable[[tuple[str, ...]], str] = default_name_map,
    strict: bool = True,
    transposed_conv: Callable[[tuple[str, ...]], bool] | None = None,
    leaf_transform: Callable[[tuple, Any, Any], Any] | None = None,
) -> dict:
    """torch state_dict -> flax variables with the target's structure.

    Walks ``variables`` (the flax init tree used as the shape template),
    resolves each leaf's torch key via ``name_map``, converts layout,
    and returns a new tree. With strict=False, missing torch keys keep
    the template's (random-init) leaf and are logged.
    ``transposed_conv`` marks flax paths whose torch source is a
    ConvTranspose (different kernel axis order). ``leaf_transform(
    key_path, natural, template_leaf)`` lets a caller adapt each
    layout-converted tensor onto a template whose shapes deliberately
    differ (e.g. the yolov5 MXU layouts); without it any shape mismatch
    raises as before.
    """
    missing = []
    used = set()

    def visit(path, leaf):
        key_path = tuple(str(getattr(p, "key", p)) for p in path)
        torch_key = name_map(key_path)
        if torch_key in state_dict:
            used.add(torch_key)
            value = state_dict[torch_key]
            is_tc = bool(transposed_conv and transposed_conv(key_path))
            target = (
                leaf.shape
                if leaf_transform is None
                else _natural_flax_shape(key_path[-1], value, is_tc)
            )
            nat = torch_to_flax_leaf(
                torch_key, value, target,
                leaf_name=key_path[-1],
                transposed_conv=is_tc,
            )
            return nat if leaf_transform is None else leaf_transform(
                key_path, nat, leaf
            )
        missing.append(torch_key)
        return leaf

    out = jax.tree_util.tree_map_with_path(visit, variables)
    if missing:
        msg = f"{len(missing)} torch keys missing (e.g. {missing[:5]})"
        if strict:
            raise KeyError(msg)
        log.warning("%s; kept template init for those leaves", msg)
    unused = set(state_dict) - used
    if unused:
        log.info("%d torch keys unused (e.g. %s)", len(unused), sorted(unused)[:5])
    return out


def load_torch_checkpoint(path: str | pathlib.Path) -> dict:
    """Load a .pth file's state_dict (handles the {'state_dict': ...} and
    {'model_state': ...} wrappers OpenPCDet/ultralytics use)."""
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=False)
    for key in ("state_dict", "model_state", "model"):
        if isinstance(raw, dict) and key in raw and isinstance(raw[key], dict):
            raw = raw[key]
            break
    return {k: _to_numpy(v) for k, v in raw.items() if hasattr(v, "shape")}
