"""Checkpoint / resume + external weight conversion.

The reference's "checkpointing" is server-side weight loading at model
init from fixed paths (examples/pointpillar_kitti/1/model.py:93-112
loads yaml + .pth; ONNX/libtorch artifacts named by config.pbtxt),
provisioned by scp (deploy.sh:56-65) or S3/Keycloak
(docker/server/Dockerfile:9-18). The TPU equivalents here:

  * orbax-backed save/restore of model variables and full train states
    (resume-at-step), with versioned step directories and retention —
    the framework's answer to both "load weights to serve" and
    "resume training";
  * torch .pth state_dict -> flax variables conversion utilities so
    models trained elsewhere can be served (weight provisioning parity
    with deploy.sh's pth->ONNX->server flow, minus the ONNX hop).

Conversion is explicit-mapping-based: convert_state_dict walks the
flax variable tree, looks up each leaf through a caller-supplied
name-mapping function, and transposes torch's OIHW conv / (out, in)
linear layouts into flax's HWIO / (in, out).
"""

from __future__ import annotations

import logging
import pathlib
from typing import Any, Callable, Mapping

import jax
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class CheckpointManager:
    """Versioned checkpoints under ``directory/<step>/`` with retention.

    Works for bare variable pytrees (serving weights) and TrainState
    pytrees (resume) alike — anything jax.tree-mappable.
    """

    def __init__(self, directory: str | pathlib.Path, keep: int = 3) -> None:
        self._dir = pathlib.Path(directory).resolve()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=False
            ),
        )

    def save(self, step: int, tree: Any) -> None:
        self._manager.save(step, args=ocp.args.StandardSave(tree))
        self._manager.wait_until_finished()

    def restore(self, step: int | None = None, like: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``like`` provides the
        target pytree structure/shardings; restoring without it returns
        plain numpy leaves."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._dir}")
        if like is not None:
            target = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        return self._manager.restore(step)

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._manager.all_steps())

    def close(self) -> None:
        self._manager.close()


# ---------------------------------------------------------------------------
# torch state_dict conversion
# ---------------------------------------------------------------------------


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor without importing torch
        return t.detach().cpu().numpy()
    return np.asarray(t)


def torch_to_flax_leaf(name: str, value: np.ndarray, flax_shape) -> np.ndarray:
    """Layout-convert one torch tensor to a flax leaf shape.

    Rules (checked against the target shape, not guessed from names):
      * conv kernels: torch OIHW / OIDHW -> flax HWIO / DHWIO;
      * linear kernels: torch (out, in) -> flax (in, out);
      * everything else (biases, BN scale/bias/stats): passthrough.
    """
    value = _to_numpy(value)
    flax_shape = tuple(flax_shape)
    if value.shape == flax_shape:
        return value
    if value.ndim == 4 and value.transpose(2, 3, 1, 0).shape == flax_shape:
        return value.transpose(2, 3, 1, 0)  # OIHW -> HWIO
    if value.ndim == 5 and value.transpose(2, 3, 4, 1, 0).shape == flax_shape:
        return value.transpose(2, 3, 4, 1, 0)  # OIDHW -> DHWIO
    if value.ndim == 2 and value.T.shape == flax_shape:
        return value.T  # (out, in) -> (in, out)
    raise ValueError(
        f"cannot map torch tensor '{name}' {value.shape} onto flax leaf "
        f"{flax_shape}"
    )


_DEFAULT_LEAF_MAP = {
    # flax leaf name -> torch suffix (BatchNorm naming differs)
    "kernel": "weight",
    "scale": "weight",
    "bias": "bias",
    "mean": "running_mean",
    "var": "running_var",
}


def default_name_map(path: tuple[str, ...]) -> str:
    """flax variable path -> torch state_dict key.

    ('params', 'backbone', 'conv', 'kernel') -> 'backbone.conv.weight'.
    Collections ('params'/'batch_stats') are dropped; the leaf name maps
    through _DEFAULT_LEAF_MAP.
    """
    *mods, leaf = [p for p in path if p not in ("params", "batch_stats")]
    return ".".join([*mods, _DEFAULT_LEAF_MAP.get(leaf, leaf)])


def convert_state_dict(
    state_dict: Mapping[str, Any],
    variables: Mapping,
    name_map: Callable[[tuple[str, ...]], str] = default_name_map,
    strict: bool = True,
) -> dict:
    """torch state_dict -> flax variables with the target's structure.

    Walks ``variables`` (the flax init tree used as the shape template),
    resolves each leaf's torch key via ``name_map``, converts layout,
    and returns a new tree. With strict=False, missing torch keys keep
    the template's (random-init) leaf and are logged.
    """
    flat = {}
    missing = []

    def visit(path, leaf):
        key_path = tuple(str(getattr(p, "key", p)) for p in path)
        torch_key = name_map(key_path)
        if torch_key in state_dict:
            return torch_to_flax_leaf(torch_key, state_dict[torch_key], leaf.shape)
        missing.append(torch_key)
        return leaf

    out = jax.tree_util.tree_map_with_path(visit, variables)
    if missing:
        msg = f"{len(missing)} torch keys missing (e.g. {missing[:5]})"
        if strict:
            raise KeyError(msg)
        log.warning("%s; kept template init for those leaves", msg)
    unused = set(state_dict) - {
        name_map(tuple(str(getattr(p, "key", p)) for p in path))
        for path, _ in jax.tree_util.tree_flatten_with_path(variables)[0]
    }
    if unused:
        log.info("%d torch keys unused (e.g. %s)", len(unused), sorted(unused)[:5])
    _ = flat
    return out


def load_torch_checkpoint(path: str | pathlib.Path) -> dict:
    """Load a .pth file's state_dict (handles the {'state_dict': ...} и
    {'model_state': ...} wrappers OpenPCDet/ultralytics use)."""
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=False)
    for key in ("state_dict", "model_state", "model"):
        if isinstance(raw, dict) and key in raw and isinstance(raw[key], dict):
            raw = raw[key]
            break
    return {k: _to_numpy(v) for k, v in raw.items() if hasattr(v, "shape")}
