"""Per-model serving precision policies: f32 | bf16 | int8w | int8.

BENCH_r05 pinned MFU at 2.3-4.1% across yolov5n/pointpillars — the
perception models this stack serves are HBM-bandwidth-bound, so the
largest single-chip lever left (after dispatch overlap and data-parallel
sharding) is moving fewer bytes per call. TPUs run bf16 and int8 on the
MXU natively; production TPU serving stacks treat precision as a
*serving config*, not a model property. This module is that config:

  * ``f32``   — the legacy path, byte-for-byte unchanged.
  * ``bf16``  — params cast to bfloat16 (half the HBM reads per call),
    pipeline compute in bf16, float wire inputs staged as bf16 (half
    the H2D bytes; ml_dtypes provides the host-side numpy dtype).
  * ``int8w`` — weight-only quantization: conv/dense kernels stored as
    int8 with per-output-channel symmetric scales (max|w|/127), wire
    and compute stay f32. A quarter of the param HBM traffic;
    dequantization happens inside the jitted forward where it fuses.
  * ``int8``  — ``int8w`` plus activation quantization on the wire:
    float inputs are quantized host-side with per-tensor scales from a
    calibration pass over synthetic/eval frames and dequantized inside
    the launched program (``ingest``), quartering the H2D bytes.

The policy is applied ONCE at model-registration time:

  * :meth:`PrecisionPolicy.cast_params` tree-maps the variables tree
    (bf16 cast / int8 per-channel quantize into :class:`QuantizedParam`
    pytree nodes) BEFORE ``replicate_params`` runs, so the mesh-sharded
    channel ships the small tree to every device;
  * pipelines thread :meth:`cast_in` (ingress cast to the compute
    dtype) and :meth:`boundary` (the keep-list: box decode, NMS
    scores and voxelize coords stay f32 — see ``KEEP_F32_2D`` /
    ``KEEP_F32_3D``, recorded in each pipeline spec's
    ``extra["precision_keep_f32"]``);
  * the staged channels consult :meth:`wire_cast` when staging host
    arrays and wrap ``device_fn`` with :meth:`ingest` in their cached
    launchers, so the jit stages inputs in the wire dtype, runs the
    body in the policy dtype, and emits f32 outputs.

Accuracy contract (tests/test_precision.py): bf16 holds detection
outputs within tolerance of f32 and int8 holds synthetic-set mAP within
the policy's declared ``map_budget`` vs the f32 reference.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

# Host-side bfloat16 (ships with jax): staging a float32 frame as bf16
# halves the host->device copy without touching the round-4 "never
# widen on the host" rule — this is a DOWN-cast.
BF16 = np.dtype(ml_dtypes.bfloat16)

# The four policy names, in increasing compression order.
POLICIES = ("f32", "bf16", "int8w", "int8")

# Explicit keep-lists: precision-sensitive boundary ops that stay f32
# regardless of policy. Recorded in each pipeline spec's
# ``extra["precision_keep_f32"]`` so remote clients (and the docs) see
# the contract; enforced by the pipelines' ``boundary()`` casts.
KEEP_F32_2D = ("box_decode", "nms_scores", "box_rescale")
KEEP_F32_3D = ("voxelize_coords", "box_decode", "nms_scores")

# int8 symmetric range: +-127 keeps the scale invertible without the
# asymmetric -128 corner.
_QMAX = 127.0

# Declared accuracy budgets: max allowed synthetic-set mAP drop vs the
# f32 reference (tests/test_precision.py asserts 1 - budget as the
# floor; docs/OPERATIONS.md publishes the table). MAP_BUDGETS is the
# public spelling: the continuous quality plane's QualityGate (ISSUE
# 17, eval/quality_plane.py) gates live canary windows against these
# SAME numbers, so the offline parity suite and the runtime rollback
# trigger can never disagree about what "within budget" means.
_MAP_BUDGETS = {"f32": 0.0, "bf16": 0.05, "int8w": 0.10, "int8": 0.15}
MAP_BUDGETS = _MAP_BUDGETS


@jax.tree_util.register_pytree_node_class
class QuantizedParam:
    """One int8-quantized parameter leaf: ``q`` (int8) plus the
    per-output-channel f32 ``scale`` that dequantizes it.

    Registered as a jax pytree node so a quantized variables tree flows
    through ``tree_map``, ``device_put`` and ``replicate_params``
    unchanged — the mesh-sharded channel replicates the SMALL tree and
    the dequant multiply happens inside the trace (:func:`realize`),
    reading a quarter of the f32 bytes from HBM.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale) -> None:
        self.q = q
        self.scale = scale

    def dequant(self):
        return self.q.astype(jnp.float32) * self.scale

    @property
    def nbytes(self) -> int:
        return int(np.asarray(self.q).nbytes + np.asarray(self.scale).nbytes)

    @property
    def shape(self):
        return self.q.shape

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"QuantizedParam(shape={tuple(self.q.shape)})"


def quantize_channelwise(arr, axis: int = -1) -> QuantizedParam:
    """Symmetric per-channel int8 quantization: scale = max|x|/127 along
    every axis EXCEPT ``axis`` (the output-channel axis for conv/dense
    kernels, where per-channel ranges differ by orders of magnitude)."""
    x = np.asarray(arr, dtype=np.float32)
    reduce_axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    amax = np.max(np.abs(x), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0, amax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(x / scale), -_QMAX, _QMAX).astype(np.int8)
    return QuantizedParam(jnp.asarray(q), jnp.asarray(scale))


def _is_quant(x) -> bool:
    return isinstance(x, QuantizedParam)


def realize(tree):
    """Dequantize every :class:`QuantizedParam` leaf back to f32.

    Called INSIDE the jitted forward (pipelines' closure), so XLA reads
    the int8 bytes from HBM and fuses the scale multiply — the whole
    point of weight quantization on a bandwidth-bound model."""
    return jax.tree_util.tree_map(
        lambda x: x.dequant() if _is_quant(x) else x, tree, is_leaf=_is_quant
    )


def tree_bytes(tree) -> int:
    """Total parameter bytes of a (possibly quantized) variables tree —
    the number the collector's ``param_bytes`` gauge reports, so a
    quantized registration visibly shrinks HBM occupancy."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_quant):
        if _is_quant(leaf):
            total += leaf.nbytes
        else:
            total += int(np.asarray(leaf).nbytes)
    return total


def _is_float(arr) -> bool:
    return jnp.issubdtype(jnp.asarray(arr).dtype, jnp.floating)


def resolve_policy(precision, dtype):
    """Builder-shared policy resolution: parse the policy and pick the
    model compute dtype — the bf16 policy switches a default-f32 model
    to bf16 layers, while an explicit caller ``dtype`` wins (the legacy
    ``dtype=bf16`` bench path keeps its policy-less f32 wire). Returns
    ``(policy, model_dtype)``."""
    policy = PrecisionPolicy.parse(precision)
    if policy.name == "bf16" and dtype == jnp.float32:
        dtype = jnp.bfloat16
    return policy, dtype


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One model's serving precision config (see module docstring).

    ``act_scales`` (int8 only): per-input-tensor symmetric scales from
    :meth:`calibrated`, stored as a sorted tuple of (name, scale) so the
    policy stays hashable. ``keep_f32_inputs``: wire inputs exempt from
    narrowing (the 3D pipelines keep ``points`` f32 — voxelize cell
    coords are precision-sensitive)."""

    name: str = "f32"
    act_scales: tuple[tuple[str, float], ...] = ()
    keep_f32_inputs: tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.name not in POLICIES:
            raise ValueError(
                f"unknown precision policy {self.name!r} "
                f"(expected one of {'|'.join(POLICIES)})"
            )

    # -- parsing ------------------------------------------------------------

    @classmethod
    def parse(cls, value) -> "PrecisionPolicy":
        """str | PrecisionPolicy | None -> PrecisionPolicy (None = f32).
        Single source for the CLI ``--precision`` flag and repository
        ``config.yaml model.precision`` entries."""
        if value is None or value == "":
            return cls()
        if isinstance(value, cls):
            return value
        return cls(name=str(value))

    # -- derived properties --------------------------------------------------

    @property
    def compute_dtype(self):
        """Pipeline/model compute dtype: bf16 only for the bf16 policy —
        int8 policies dequantize to f32 compute."""
        return jnp.bfloat16 if self.name == "bf16" else jnp.float32

    @property
    def quantize_weights(self) -> bool:
        return self.name in ("int8w", "int8")

    @property
    def quantize_acts(self) -> bool:
        return self.name == "int8"

    @property
    def wire_ingest_needed(self) -> bool:
        """True when launched programs must dequantize wire inputs."""
        return self.name == "int8" and bool(self.act_scales)

    @property
    def map_budget(self) -> float:
        """Declared max synthetic-set mAP drop vs the f32 reference."""
        return _MAP_BUDGETS[self.name]

    def scale_for(self, name: str) -> float | None:
        for k, s in self.act_scales:
            if k == name:
                return s
        return None

    # -- registration-time param transform ------------------------------------

    def cast_params(self, tree):
        """Tree-map the variables tree into policy storage, ONCE at
        registration (before ``replicate_params`` for sharded serving):

          * ``bf16``: every float leaf -> bfloat16 (half the HBM);
          * ``int8w``/``int8``: float leaves with ndim >= 2 (conv/dense
            kernels) -> :class:`QuantizedParam`; 1-D leaves (biases,
            norm scales/stats) stay f32 — quantizing those costs
            accuracy for no measurable bandwidth;
          * ``f32``: identity.
        """
        if self.name == "f32":
            return tree
        if self.name == "bf16":
            return jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16) if _is_float(x) else x, tree
            )

        def quant(x):
            if _is_float(x) and getattr(x, "ndim", 0) >= 2:
                return quantize_channelwise(x)
            return x

        return jax.tree_util.tree_map(quant, tree)

    # -- pipeline hooks --------------------------------------------------------

    def cast_in(self, x):
        """Pipeline ingress cast (replaces the unconditional
        ``astype(float32)``): widen/narrow the staged wire input to the
        compute dtype inside the trace, where the cast fuses for free
        (the round-4 registration contract)."""
        return x.astype(self.compute_dtype)

    def boundary(self, tree):
        """The keep-list cast: model outputs re-enter f32 BEFORE the
        precision-sensitive boundary ops (box decode / NMS scoring /
        rescale — ``KEEP_F32_2D``/``KEEP_F32_3D``), so ranking ties and
        pixel coordinates never resolve in reduced precision."""
        if self.name == "f32":
            return tree
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32) if _is_float(x) else x, tree
        )

    # -- wire (channel) hooks ---------------------------------------------------

    def wire_cast(self, name: str, arr: np.ndarray) -> np.ndarray:
        """Host-side staging cast for one wire input. Extends the
        round-4 dtype policy (never widen on the host): bf16 DOWN-casts
        f32 floats to bfloat16 (half the H2D bytes), int8 quantizes
        calibrated float inputs to int8 (quarter), and everything
        else — integer frames, keep-list inputs, uncalibrated
        tensors — uploads as-is."""
        if self.name in ("f32", "int8w") or name in self.keep_f32_inputs:
            return arr
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        if self.name == "bf16":
            if arr.dtype.itemsize > BF16.itemsize:
                return arr.astype(BF16)
            return arr
        # int8: only inputs the calibration pass covered
        scale = self.scale_for(name)
        if scale is None or scale <= 0:
            return arr
        return np.clip(np.rint(arr / scale), -_QMAX, _QMAX).astype(np.int8)

    def ingest(self, inputs: dict) -> dict:
        """Device-side inverse of :meth:`wire_cast` for int8 wire
        inputs, applied INSIDE the launched jit (channel/staged.py):
        int8 tensors dequantize by their calibration scale; everything
        else passes through. Branches below are on static python/dtype
        facts, never tracer values."""
        if not self.wire_ingest_needed:
            return inputs
        out = {}
        for k in inputs:
            v = inputs[k]
            scale = self.scale_for(k)
            if scale is not None and v.dtype == jnp.int8:
                out[k] = v.astype(jnp.float32) * jnp.float32(scale)
            else:
                out[k] = v
        return out

    # -- calibration -------------------------------------------------------------

    def calibrated(self, samples: dict) -> "PrecisionPolicy":
        """Derive per-tensor activation scales from sample inputs
        (synthetic or eval frames), at registration time: scale =
        max|x|/127 over the whole calibration batch. No-op for
        non-quantizing policies; keep-list inputs are skipped."""
        if not self.quantize_acts:
            return self
        scales = dict(self.act_scales)
        for name, arr in samples.items():
            if name in self.keep_f32_inputs:
                continue
            a = np.asarray(arr)
            if not np.issubdtype(a.dtype, np.floating):
                # integer wire inputs (uint8 frames) already travel in
                # <= 1 byte; nothing to quantize
                continue
            amax = float(np.max(np.abs(a))) if a.size else 0.0
            scales[name] = (amax / _QMAX) if amax > 0 else 1.0
        return dataclasses.replace(
            self, act_scales=tuple(sorted(scales.items()))
        )

    # -- accounting ---------------------------------------------------------------

    def spec_extra(self, variables, keep_ops=KEEP_F32_2D) -> dict:
        """The spec ``extra`` entries every precision-aware builder
        records: policy name, keep-list, and post-cast param bytes (the
        collector's ``param_bytes`` gauge source)."""
        return {
            "precision": self.name,
            "precision_keep_f32": list(keep_ops),
            "param_bytes": tree_bytes(variables),
        }
