"""Continuous batching: windowless EDF admission + packed ragged batches.

ISSUE 8's tentpole. The window batcher (``runtime/batching.py``) pays
two taxes BENCH_r05 made visible:

  * **the window barrier** — requests pool behind an admission window
    even when an execution slot is free, so under open-loop traffic the
    device idles while arrivals wait for a timer;
  * **the padding tax** — every merge group rounds up to a static
    power-of-two bucket (229/721 served frames were padding, ~32% of
    device work), and variable-size 3D inputs pad to the widest member
    besides.

This scheduler removes both, keeping the proven dispatch machinery
(permits, executor, launch-time slot free, shed/trace planes) of
``BatchingChannel`` and replacing its two policy surfaces:

  * **admission** — no window, no admission thread. ``do_inference``
    stages the request straight into the ready set, kept ordered
    earliest-deadline-first (ties: higher priority, then arrival), so
    the dispatcher — which keeps forming batches while device work is
    in flight, exactly the continuous-admission discipline of FlexNPU's
    dynamic co-location (PAPERS.md) — always launches the work closest
    to its deadline and merges compatible later arrivals into it.
  * **batch shape** — models that register a segment-aware body
    (``RegisteredModel.ragged_fn`` + ``spec.extra["ragged_inputs"]``)
    execute as PACKED ragged batches: member rows concatenate back to
    back and a row->segment table rides along
    (``parallel/ragged_kernels.py``), so every request runs at its true
    size — zero pad rows beyond lane alignment. Fixed-shape 2D models
    keep the dense padded path, but pad targets come from a LIVE
    occupancy histogram (:class:`LiveBuckets`) instead of the static
    power-of-two table, so steady traffic converges to near-zero
    padding there too. The dense path stays bitwise identical per
    request (pad rows replicate a real row and are sliced back off —
    the `runtime/padding.py` contract — and data-parallel splits never
    change a row's compute).

Stacking is unchanged: ``ContinuousBatchingChannel(inner)`` drops in
anywhere ``BatchingChannel(inner)`` did, including in front of the
mesh-sharded channel — ragged batches are then packed SHARD-major
(``ShardedRaggedLayout``) so each device gets whole segments and the
sharded body needs no collectives.

Migration note: the window-timeout knob (``timeout_us`` /
``--batch-timeout-us``) has no meaning here — there is no window. The
constructor accepts and ignores it so existing call sites and configs
keep working; ``merge_hold_us`` is likewise forced to 0 (the scheduler
self-clocks on slot frees, and EDF ordering makes a hold actively
harmful: it would delay the tightest-deadline work).
"""

from __future__ import annotations

import bisect
import collections
import concurrent.futures
import math
import threading
import time

import numpy as np

from triton_client_tpu.channel.base import (
    BaseChannel,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.obs.trace import MultiTrace
from triton_client_tpu.parallel.ragged_kernels import (
    RaggedLayout,
    pack_rows,
    shard_layout,
    shard_pack_rows,
    shard_stack_segments,
)
from triton_client_tpu.runtime.admission import QueueFullError
from triton_client_tpu.runtime.batching import BatchingChannel, _merge_key
from triton_client_tpu.runtime.padding import bucket_for, pad_batch


class LiveBuckets:
    """Pad-bucket table learned from the live merge-size distribution.

    The static power-of-two table pads a steady stream of 6-frame
    merges to 8 forever — a 25% tax the workload never stops paying.
    This table watches the totals the dispatcher actually forms and
    promotes the frequent ones (>= ``min_share`` of observations, top
    ``max_sizes``) to first-class buckets, so recurring sizes pad to
    themselves. Rare sizes still fall back to the static table, keeping
    the compiled-shape set bounded: at most ``max_sizes`` learned
    entries + log2 static ones. Every entry is rounded up to
    ``multiple`` so a sharded inner channel can always split it.

    Callers synchronize externally (the batcher's ``_ready_cv``)."""

    def __init__(
        self,
        multiple: int = 1,
        max_sizes: int = 6,
        min_share: float = 0.10,
        warmup: int = 32,
    ) -> None:
        self._multiple = max(1, int(multiple))
        self._max_sizes = int(max_sizes)
        self._min_share = float(min_share)
        self._warmup = int(warmup)
        self._seen: collections.Counter = collections.Counter()
        self._n = 0
        self._table: tuple[int, ...] = ()

    def observe(self, total: int) -> None:
        m = self._multiple
        self._seen[((max(1, total) + m - 1) // m) * m] += 1
        self._n += 1
        # re-derive on a stride: the table is a snapshot, not a cache
        # that must be exact per observation
        if self._n >= self._warmup and self._n % 16 == 0:
            floor = self._min_share * self._n
            self._table = tuple(
                sorted(
                    s
                    for s, c in self._seen.most_common(self._max_sizes)
                    if c >= floor
                )
            )

    def target(self, total: int) -> int:
        """Smallest learned bucket >= total; static table fallback."""
        for size in self._table:
            if size >= total:
                return size
        return bucket_for(total, self._multiple)

    @property
    def table(self) -> tuple[int, ...]:
        return self._table


class ContinuousBatchingChannel(BatchingChannel):
    """Windowless EDF scheduler with packed-ragged execution (see
    module docstring). Accepts the :class:`BatchingChannel` signature
    so call sites migrate by swapping the class; ``timeout_us`` and
    ``merge_hold_us`` are accepted for compatibility and ignored."""

    def __init__(
        self,
        inner: BaseChannel,
        max_batch: int = 8,
        timeout_us: int = 0,  # ignored: no admission window exists
        capacity: int = 256,
        use_native: bool = False,  # ignored: no admission thread exists
        pipeline_depth: int = 2,
        max_merge: int | None = None,
        pad_to_buckets: bool = True,
        merge_hold_us: int = 0,  # ignored: EDF head must not be held
        arena_slots: int = 0,
        shed_expired: bool = False,
        live_buckets: bool = True,
    ) -> None:
        self._capacity = max(1, int(capacity))
        # (model, version) -> frozenset of packed-input names, or None
        # when the model has no segment-aware body; filled lazily from
        # inner.get_metadata so registration order doesn't matter.
        # Filled from RPC threads AND the dispatcher/executor threads,
        # so writes go through _ragged_cache_lock (the metadata RPC
        # itself runs outside the lock; racing fillers converge via
        # setdefault)
        self._ragged_inputs_cache: dict = {}
        self._ragged_cache_lock = threading.Lock()
        self._ragged_stats = {
            "ragged_batches": 0,
            "ragged_segments": 0,
            "ragged_rows": 0,
            "ragged_pad_rows": 0,
        }
        # optional multi-tenant fair share (runtime/lifecycle.py
        # TenantTable): deficit-round-robin virtual time folded into the
        # EDF key — set via attach_tenants(); None keeps pure EDF
        self._tenant_table = None
        self._fair_quantum_s = 0.005
        self._vtime: dict[str, float] = {}
        self._tenant_frames: collections.Counter = collections.Counter()
        super().__init__(
            inner,
            max_batch=max_batch,
            timeout_us=0,
            capacity=capacity,
            use_native=False,
            pipeline_depth=pipeline_depth,
            max_merge=max_merge,
            pad_to_buckets=pad_to_buckets,
            merge_hold_us=0,
            arena_slots=arena_slots,
            shed_expired=shed_expired,
        )
        self._live_buckets = (
            LiveBuckets(multiple=self._batch_multiple) if live_buckets else None
        )
        with self._ready_cv:
            # the ready set is an EDF-SORTED list, not the base FIFO
            # deque (same item tuples; _form_group_locked is overridden
            # to match). Swapped under the cv so the already-running
            # dispatcher never sees a half-state.
            self._ready = []

    # -- admission: straight into the EDF ready set ---------------------------

    def _start_admission(self, use_native, max_batch, timeout_us, capacity):
        """No admission window: requests stage in ``do_inference``."""
        # _impl/_py stay None; close() and stats() branch on that

    def attach_tenants(self, table, quantum_s: float = 0.005) -> None:
        """Fold deficit-round-robin fair share over a TenantTable
        (runtime/lifecycle.py) into the ready ordering. Each tenant
        accrues virtual time ``frames / share`` as its work dispatches;
        a tenant ahead of the pack (``lag`` = its vtime minus the
        minimum) has its requests' effective deadlines pushed back by
        ``lag * quantum_s``, so a low-share tenant flooding the queue
        cannot starve a high-share tenant's SLO — the backlogged
        tenant's own requests sort later, they are not dropped.

        Ordering is approximate by design: ``insort`` re-evaluates the
        key against items placed under older vtimes, so the ready set
        drifts slightly as lags move. DRR only needs the drift to be
        bounded (it is — charges are applied at group formation under
        ``_ready_cv`` and lags renormalize), not a total order."""
        with self._ready_cv:
            self._tenant_table = table
            self._fair_quantum_s = float(quantum_s)

    def _edf_key(self, item):
        """Sort key over staged items: earliest deadline first,
        deadline-less requests last; higher priority breaks ties and
        ``insort`` keeps arrival order inside a class. With a tenant
        table attached, a tenant's DRR lag pushes its effective
        deadline back (deadline-less items order by lag directly)."""
        request = item[2]
        deadline = (
            request.deadline_s if request.deadline_s is not None else math.inf
        )
        table = self._tenant_table
        if table is None:
            return (deadline, -request.priority, 0.0)
        lag = 0.0
        if self._vtime:
            floor = min(self._vtime.values())
            lag = max(
                0.0,
                self._vtime.get(table.tenant_of(request.model_name), floor)
                - floor,
            )
        return (deadline + lag * self._fair_quantum_s, -request.priority, lag)

    def _charge_tenants_locked(self, group) -> None:
        """DRR accounting at group formation (caller holds
        ``_ready_cv``): each dispatched frame charges its tenant
        ``1 / share`` virtual time, so equal traffic advances a
        share-4 tenant's clock 4x slower than a share-1 tenant's."""
        table = self._tenant_table
        floor = min(self._vtime.values()) if self._vtime else 0.0
        for item in group:
            request, frames = item[2], item[1]
            tenant = table.tenant_of(request.model_name)
            self._vtime[tenant] = self._vtime.get(tenant, floor) + (
                frames / table.share(tenant)
            )
            self._tenant_frames[tenant] += frames
        # renormalize so vtimes (and the lags derived from them) stay
        # bounded over long uptimes
        floor = min(self._vtime.values())
        if floor > 1e6:
            for tenant in self._vtime:
                self._vtime[tenant] -= floor

    def do_inference(self, request: InferRequest):
        future: concurrent.futures.Future = concurrent.futures.Future()
        if request.trace is not None:
            request.trace.begin("batch_queue")
        ragged_names = self._ragged_names(
            request.model_name, request.model_version
        )
        if request.sequence_id:
            # session frames bypass BOTH merge paths (ragged packing
            # included): _merge_key solos them, so the tracking step
            # sees exactly one stream's frame per launch in order
            ragged_names = None
        if ragged_names:
            # one segment per request: same-model ragged requests merge
            # regardless of their (wildly varying) row counts — that
            # variance is exactly what the packed layout absorbs
            key = ("__ragged__", request.model_name, request.model_version)
            size = 1
        else:
            try:
                key = _merge_key(request)
                size = next(
                    iter(
                        int(np.asarray(a).shape[0])
                        for a in request.inputs.values()
                    )
                )
            except Exception:
                key, size = ("__solo__", next(self._ids)), 1
        with self._ready_cv:
            if len(self._ready) >= self._capacity:
                self._shed[
                    f"{request.model_name}|{request.priority}|queue"
                ] += 1
                raise QueueFullError(
                    f"model '{request.model_name}': inference queue full"
                )
            bisect.insort(
                self._ready,
                (key, size, request, future, time.perf_counter()),
                key=self._edf_key,
            )
            self._ready_cv.notify()
        return future.result()

    # -- group formation: EDF head + compatible followers ---------------------

    def _form_group_locked(self):
        """Pop the EDF head, then walk the (still-sorted) ready set
        absorbing same-key items under the frame cap — later-deadline
        compatible work rides along with the most urgent request's
        launch. Incompatible items stay in place, keeping their EDF
        positions for the next slot (caller holds ``_ready_cv``)."""
        first = self._ready.pop(0)
        group = [first]
        frames = first[1]
        i = 0
        while i < len(self._ready) and frames < self._max_merge:
            item = self._ready[i]
            if item[0] == first[0] and frames + item[1] <= self._max_merge:
                group.append(self._ready.pop(i))
                frames += item[1]
            else:
                i += 1
        if self._tenant_table is not None:
            self._charge_tenants_locked(group)
        return group

    # -- dense pad targets from the live histogram ----------------------------

    def _pad_target(self, total: int) -> int:
        if self._live_buckets is None:
            return super()._pad_target(total)
        with self._ready_cv:
            self._live_buckets.observe(total)
            return self._live_buckets.target(total)

    # -- ragged capability ----------------------------------------------------

    def _ragged_names(self, model_name: str, model_version: str):
        """Packed-input names for a model with a segment-aware body
        (``spec.extra["ragged_inputs"]``), else None. Cached, including
        negative answers — this sits on the per-request path.

        Called from RPC threads (``do_inference``) and from the
        dispatcher/executor threads (``_run_group``), so the cache fill
        is double-checked: the lock-free fast path covers the steady
        state, the metadata RPC runs unlocked (it can block), and the
        insert goes through ``setdefault`` under ``_ragged_cache_lock``
        so racing fillers agree on one winner."""
        key = (model_name, model_version)
        try:
            return self._ragged_inputs_cache[key]
        except KeyError:
            pass
        names = None
        try:
            spec = self._inner.get_metadata(model_name, model_version)
            declared = (getattr(spec, "extra", None) or {}).get(
                "ragged_inputs"
            )
            if declared:
                names = frozenset(declared)
        except Exception:
            names = None
        with self._ragged_cache_lock:
            return self._ragged_inputs_cache.setdefault(key, names)

    # -- ragged execution -----------------------------------------------------

    def _run_group(self, group, free_slot=None) -> None:
        if self._ragged_names(
            group[0][1].model_name, group[0][1].model_version
        ):
            if len(group) == 1:
                # a lone ragged request runs solo at its TRUE size —
                # never through the dense merged path, whose bucket
                # padding is exactly the tax the ragged plane removes
                if self._shed_expired:
                    group = self._shed_expired_members(group)
                    if not group:
                        return
                t_staged, request, future = group[0]
                self._run_solo(request, future, free_slot, t_staged=t_staged)
            else:
                self._run_ragged_group(group, free_slot)
            return
        # dense groups keep the (bitwise-identical) base path
        super()._run_group(group, free_slot)

    def _run_ragged_group(self, group, free_slot=None) -> None:
        """Execute one ragged group as a PACKED batch: member rows
        concatenate, the segment table rides in ``request.ragged``, and
        the inner channel's segment-aware launcher runs every member at
        true size. Mirrors the base ``_run_group`` contract: futures
        always resolve, failures fall back to per-request execution,
        ``free_slot`` fires at launch."""
        if self._shed_expired:
            group = self._shed_expired_members(group)
            if not group:
                return
        requests = [g[1] for g in group]
        futures = [g[2] for g in group]
        traces = [r.trace for r in requests]
        t_dispatch = time.perf_counter()
        for (t_staged, r, _f) in group:
            if r.trace is not None and t_staged is not None:
                r.trace.add("merge_wait", t_staged, t_dispatch)
        for tr in traces:
            if tr is not None:
                tr.end("batch_queue")
        try:
            ragged_names = self._ragged_names(
                requests[0].model_name, requests[0].model_version
            )
            first_ragged = next(
                n for n in requests[0].inputs if n in ragged_names
            )
            sizes = tuple(
                int(np.asarray(r.inputs[first_ragged]).shape[0])
                for r in requests
            )
            layout = RaggedLayout(sizes)
            w = self._batch_multiple
            lay = shard_layout(layout, w) if w > 1 else layout
            t_stage0 = time.perf_counter()
            merged = {}
            for name in requests[0].inputs:
                parts = [np.asarray(r.inputs[name]) for r in requests]
                if name in ragged_names:
                    merged[name] = (
                        shard_pack_rows(parts, lay)
                        if w > 1
                        else pack_rows(parts, layout)
                    )
                elif w > 1:
                    # per-segment inputs ride shard-major next to their
                    # segments
                    merged[name] = shard_stack_segments(parts, lay)
                else:
                    # per-segment inputs stack to the segment bucket
                    # (dead slots replicate the last real entry)
                    merged[name] = pad_batch(
                        np.stack(parts), layout.seg_bucket
                    )
            t_disp = time.perf_counter()
            for tr in traces:
                if tr is not None:
                    tr.add("batch_merge", t_stage0, t_disp)
            if self._shed_expired:
                # same post-pack recheck as the dense path: a slow pack
                # must not launch already-expired members
                live = self._shed_expired_members(group)
                if len(live) != len(group):
                    if live:
                        self._run_ragged_group(
                            [(None, r, f) for (_t, r, f) in live], free_slot
                        )
                    return
            deadlines = [
                r.deadline_s for r in requests if r.deadline_s is not None
            ]
            try:
                fut = self._inner.do_inference_async(
                    InferRequest(
                        model_name=requests[0].model_name,
                        model_version=requests[0].model_version,
                        inputs=merged,
                        trace=(
                            MultiTrace(traces)
                            if any(t is not None for t in traces)
                            else None
                        ),
                        deadline_s=min(deadlines) if deadlines else None,
                        priority=max(r.priority for r in requests),
                        ragged=lay,
                    )
                )
                if free_slot is not None:
                    free_slot()
                resp = fut.result()
            finally:
                t_dev_end = time.perf_counter()
                with self._ready_cv:
                    self._decomp["stage_s"] += t_disp - t_stage0
                    self._decomp["device_s"] += t_dev_end - t_disp
            with self._ready_cv:
                self._ragged_stats["ragged_batches"] += 1
                self._ragged_stats["ragged_segments"] += len(requests)
                self._ragged_stats["ragged_rows"] += layout.total
                self._ragged_stats["ragged_pad_rows"] += (
                    lay.n_shards * lay.rows_pad - layout.total
                    if w > 1
                    else layout.pad_rows
                )
        except Exception:
            # a packed failure must not take down unrelated requests:
            # per-request fallback, same as the dense merged path
            for request, future in zip(requests, futures):
                self._run_solo(request, future)
            return
        t_resp0 = time.perf_counter()
        n = len(requests)
        per_output = {}
        for name, arr in resp.outputs.items():
            arr = np.asarray(arr)
            if arr.ndim >= 1 and arr.shape[0] == n:
                # the channel already sliced dead segment slots off;
                # member i's output is row i WITHOUT the segment dim —
                # matching the model's solo (unbatched) output, which
                # is what the parity contract compares against
                per_output[name] = [arr[i] for i in range(n)]
            else:  # non-segmented output — replicate
                per_output[name] = [arr] * n
        for i, (request, future) in enumerate(zip(requests, futures)):
            if request.trace is not None:
                request.trace.add(
                    "batch_respond", t_resp0, time.perf_counter()
                )
            future.set_result(
                InferResponse(
                    model_name=resp.model_name,
                    model_version=resp.model_version,
                    outputs={k: v[i] for k, v in per_output.items()},
                    request_id=request.request_id,
                    latency_s=resp.latency_s,
                )
            )

    # -- stats ----------------------------------------------------------------

    def stats(self) -> dict:
        out = super().stats()
        out["scheduler"] = "continuous"
        with self._ready_cv:
            out.update(self._ragged_stats)
            if self._live_buckets is not None:
                out["live_bucket_table"] = list(self._live_buckets.table)
            if self._tenant_table is not None:
                out["tenant_served_frames"] = dict(self._tenant_frames)
                out["tenant_vtime"] = dict(self._vtime)
        shipped = (
            out["merged_frames"]
            + out["padded_frames"]
            + out["ragged_rows"]
            + out["ragged_pad_rows"]
        )
        if shipped:
            # fold ragged rows into the headline pad fraction: ragged
            # pad rows are lane-alignment slack, dense pad rows are
            # bucket slack — both are rows the device computed for
            # nobody
            out["pad_fraction"] = (
                out["padded_frames"] + out["ragged_pad_rows"]
            ) / shipped
        return out
