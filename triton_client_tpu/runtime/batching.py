"""Micro-batching channel: coalesce concurrent requests into one TPU call.

Triton's dynamic batcher is a core piece of the serving runtime the
reference leans on (config.pbtxt max_batch_size; SURVEY.md §2.9 row 1).
Here the same policy runs in-tree: admission + batch-window timing live
in the native C++ runtime (triton_client_tpu/native), and the formed
batch is executed as ONE inference over the wrapped channel with the
per-request arrays concatenated on the batch axis — bigger batches keep
the MXU busy and amortize dispatch overhead.

Batch formation is two-stage (round 4, VERDICT r3 #2). The admission
window (native C++ or the Python fallback) only signals arrival; the
DISPATCHER forms the device batch at the moment an execution slot
frees, merging every compatible request queued by then. A fixed
window had to guess how long a client burst takes to arrive — it
guessed wrong under load (r3 serving rows: occupancy 4/8 with 16
closed-loop clients and a 3 ms window, device idle ~60% of it) —
whereas slot-time formation is self-clocking: while ``pipeline_depth``
batches execute, arrivals pool, and the next batch takes them all.
Optional ``pad_to_buckets`` pads each merge to the next power of two
so the inner channel sees a handful of precompiled shapes instead of
every batch size (the role Triton's preferred_batch_size plays), and
``max_merge`` lets the device batch grow past the admission size —
the measured b8->b64 dispatch-amortization win, applied to serving.

BatchingChannel is itself a BaseChannel, so it stacks under the gRPC
façade or above TPUChannel unchanged. Requests are only merged when
model, version and non-batch input shapes match; mismatches run solo.
A pure-Python batcher (same semantics, queue.Queue + thread) backstops
environments without the native toolchain.
"""

from __future__ import annotations

import collections
import concurrent.futures
import itertools
import logging
import queue
import threading
import time

import numpy as np

from triton_client_tpu.channel.base import BaseChannel, InferRequest, InferResponse
from triton_client_tpu.obs.trace import MultiTrace
from triton_client_tpu.runtime import faults
from triton_client_tpu.runtime.admission import (
    DeadlineExpiredError,
    QueueFullError,
)
from triton_client_tpu.runtime.padding import bucket, bucket_for, pad_rows

log = logging.getLogger(__name__)

# compat alias: the bucket table now lives in runtime/padding.py (one
# copy shared with the mesh-sharded channel so the tables can't drift)
_bucket = bucket


def _merge_key(request: InferRequest):
    if request.sequence_id:
        # streaming-session frames NEVER merge: the device-resident
        # tracking step (runtime/sessions.py) consumes the launch's
        # outputs per stream and per frame — batching two streams (or
        # two frames of one) into a single launch would interleave
        # their state advances. A unique key makes every session frame
        # a group of one, dispatched through the solo path.
        return ("__session__", id(request))
    return (
        request.model_name,
        request.model_version,
        tuple(
            (name, np.asarray(a).shape[1:], np.asarray(a).dtype.str)
            for name, a in sorted(request.inputs.items())
        ),
    )


class BatchingChannel(BaseChannel):
    def __init__(
        self,
        inner: BaseChannel,
        max_batch: int = 8,
        timeout_us: int = 2000,
        capacity: int = 256,
        use_native: bool = True,
        pipeline_depth: int = 2,
        max_merge: int | None = None,
        pad_to_buckets: bool = False,
        merge_hold_us: int = 0,
        arena_slots: int = 0,
        shed_expired: bool = False,
    ) -> None:
        """``pipeline_depth``: formed batches executing concurrently
        against the inner channel. At the default 2, batch N+1's
        host->device transfer overlaps batch N's execution (the role
        Triton's per-instance CUDA streams play); jax queues the
        dispatches and the device serializes execution. Depth 1
        restores strictly serial execution.

        ``max_merge``: frame cap for one device batch (default: same
        as ``max_batch``). Setting it higher lets the dispatcher fuse
        several admission windows into one device call — on a
        dispatch-bound path the per-call fixed cost then amortizes
        over max_merge frames instead of max_batch.

        ``pad_to_buckets``: pad each merged batch to the next power of
        two with replicated rows (outputs for the pad rows are
        discarded). Keeps the set of batch shapes the inner channel —
        and therefore XLA — ever sees to log2(max_merge)+1 sizes.

        ``merge_hold_us``: when a slot frees onto a SHALLOW queue (the
        formed group is under max_merge and nothing else is staged),
        hold the dispatch up to this long for the rest of the client
        burst to arrive. Closed-loop clients respond to a finished
        batch nearly simultaneously, but their next requests arrive
        staggered by the transport — eager dispatch ships the first
        arrival as a b1 fragment that burns a full fixed-cost device
        call (measured: fragments held serving to ~49% of the device
        ceiling; a hold of ~4% of the batch time converts them into
        full merges). 0 keeps strictly eager dispatch.

        ``arena_slots`` > 0 stages each merged device batch through the
        native 64-byte-aligned slot pool (native/ Arena, round 5:
        VERDICT r4 Weak #3) instead of a fresh ``np.concatenate``
        allocation per batch. Slots are sized from the first merged
        batch per input name; oversized batches and exhausted pools
        fall back to the allocating path. Requires the native library;
        silently off when it cannot build.

        ``shed_expired`` (round 12 — overload control): at dispatch
        time, members whose deadline already passed are FAILED with
        ``DeadlineExpiredError`` and never reach the device — the
        merged batch would otherwise inherit the expired member's
        deadline and be shed whole by the inner channel. Staged windows
        are also ordered highest-priority-first, so under a backlog the
        low-priority class queues longest and sheds first. Off by
        default (PR 6's count-only behavior).

        Slot lifetime (round 6 — overlapped dispatch): an execution
        slot frees at *launch*, not at readback. Each group dispatches
        through ``inner.do_inference_async`` and releases its permit as
        soon as the call returns (inputs staged on device, compute
        enqueued); the split/respond work then runs outside the permit,
        so batch formation self-clocks off device occupancy instead of
        host copy time. When the inner channel exposes a
        ``pipeline_depth`` staging knob (TPUChannel), it is aligned to
        this batcher's depth so the channel's staging slots provide the
        device-side backpressure."""
        self._inner = inner
        self._pending: dict[int, tuple[InferRequest, concurrent.futures.Future]] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._impl = None
        self._py = None
        # a mesh-sharded inner channel declares its data-axis width as
        # the preferred batch divisor: merged groups then grow to
        # max_batch frames PER DEVICE (max_batch x data_axis total) and
        # pad buckets stay divisible by the axis, so batcher padding and
        # shard padding agree on the same table (runtime/padding.py)
        self._batch_multiple = max(1, int(getattr(inner, "batch_multiple", 1)))
        self._max_merge = int(
            max_merge
            if max_merge is not None
            else max_batch * self._batch_multiple
        )
        self._pad_to_buckets = bool(pad_to_buckets)
        self._merge_hold_s = max(0, int(merge_hold_us)) / 1e6
        self._pipeline_depth = max(1, int(pipeline_depth))
        self._inflight = threading.Semaphore(max(1, pipeline_depth))
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, pipeline_depth),
            thread_name_prefix="batch-exec",
        )
        # dispatch-time merge state: requests the admission stage has
        # released, waiting for an execution slot
        self._ready: collections.deque = collections.deque()
        self._ready_cv = threading.Condition()
        self._dispatch_stop = False
        # dispatcher heartbeat (stall watchdog): stamped every time the
        # dispatch loop makes observable progress — top of each slot AND
        # inside the idle cv-wait, so "idle" stays fresh and only a
        # genuinely wedged dispatcher (batcher_stall exhausting the
        # permit semaphore, a hung device call) goes stale. The
        # watchdog thread logs loudly past stall_threshold_s and the
        # age/stalled pair rides stats() into the collector.
        self.stall_threshold_s = 5.0
        self._hb_ts = time.perf_counter()
        self._stall_logged = False
        self._merge_stats = {
            "merges": 0, "merged_frames": 0, "padded_frames": 0,
            "launch_frees": 0,
        }
        # padding-tax attribution (ISSUE 8 satellite): pad frames per
        # MODEL, so the Prometheus counter can carry a model label and
        # an operator can see WHICH model's buckets waste device rows
        self._padded_by_model: collections.Counter = collections.Counter()
        self._shed_expired = bool(shed_expired)
        # per "model|priority|stage" shed counts ("queue" = admission
        # queue full, "merge" = deadline expired at dispatch), merged
        # into the collector's tpu_serving_shed_total family
        self._shed: collections.Counter = collections.Counter()
        self._merge_occupancy: collections.Counter = collections.Counter()
        # per-slot occupancy: concurrently-active execution slots
        # observed at each group launch (1..pipeline_depth)
        self._active_slots = 0
        self._slot_occupancy: collections.Counter = collections.Counter()
        # per-batch wall decomposition sums (stats() exposes means):
        # queue_wait (first item staged -> executor slot), exec_wait
        # (submit -> run), stage (host merge build), device (inner
        # channel call), respond (split + future resolution)
        self._decomp = collections.defaultdict(float)
        # arena staging: created lazily once the first merged batch
        # reveals its slot size (max_merge rows of the widest input)
        self._arena_slots = max(0, int(arena_slots))
        self._arena = None
        # plumb the depth through to the inner channel's staging slots
        # (TPUChannel double-buffers H2D against execution at depth 2):
        # the channel then backpressures on device occupancy while this
        # batcher's permits backpressure on formed groups
        if hasattr(inner, "pipeline_depth"):
            try:
                inner.pipeline_depth = max(1, int(pipeline_depth))
            except (AttributeError, TypeError):
                pass  # read-only attribute on a custom channel
        self._start_admission(use_native, max_batch, timeout_us, capacity)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="batch-dispatch"
        )
        self._dispatcher.start()
        self._watchdog_stop = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, daemon=True, name="batch-watchdog"
        )
        self._watchdog.start()

    def _start_admission(
        self, use_native: bool, max_batch: int, timeout_us: int, capacity: int
    ) -> None:
        """Bring up the admission window (native C++ server or the
        Python fallback). The continuous scheduler
        (runtime/continuous.py) overrides this to run WITHOUT a window
        — requests stage straight into the ready set."""
        if use_native:
            try:
                from triton_client_tpu.native import NativeBatchServer

                self._impl = NativeBatchServer(
                    self._on_batch,
                    max_batch=max_batch,
                    timeout_us=timeout_us,
                    capacity=capacity,
                )
                self._impl.start()
            except Exception as e:  # NativeUnavailable or load errors
                self._impl = None
                log.warning("native batcher unavailable (%s); python fallback", e)
        if self._impl is None:
            self._py = _PyBatcher(self._on_batch, max_batch, timeout_us, capacity)
            self._py.start()

    # -- BaseChannel ----------------------------------------------------------

    @property
    def inner(self) -> BaseChannel:
        """The wrapped channel (obs.RuntimeCollector walks the stack)."""
        return self._inner

    def register_channel(self) -> None:
        self._inner.register_channel()

    def fetch_channel(self):
        return self._inner.fetch_channel()

    def get_metadata(self, model_name: str, model_version: str = ""):
        return self._inner.get_metadata(model_name, model_version)

    def do_inference(self, request: InferRequest) -> InferResponse:
        future: concurrent.futures.Future = concurrent.futures.Future()
        rid = next(self._ids)
        if request.trace is not None:
            # closed at dispatch time (_run_group/_run_solo): admission
            # window + ready-queue wait + slot backpressure, end to end
            request.trace.begin("batch_queue")
        with self._lock:
            self._pending[rid] = (request, future)
        try:
            admitted = (
                self._impl.enqueue(rid)
                if self._impl is not None
                else self._py.enqueue(rid)
            )
        except Exception:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        if not admitted:
            with self._lock:
                self._pending.pop(rid, None)
            # fail-fast, never block the submitting RPC thread: the
            # server surfaces this as RESOURCE_EXHAUSTED, which the
            # client retry ladder treats as non-retryable for
            # ModelInfer — shedding must not amplify offered load
            with self._ready_cv:
                self._shed[
                    f"{request.model_name}|{request.priority}|queue"
                ] += 1
            raise QueueFullError(
                f"model '{request.model_name}': inference queue full"
            )
        return future.result()

    # -- admission release (runs on the batcher thread) -----------------------

    def _on_batch(self, ids) -> None:
        """The admission stage released a window of requests: stage
        them for the dispatcher. Merging happens THERE, at slot time —
        fragments from separate windows re-coalesce."""
        with self._lock:
            work = [(rid, *self._pending.pop(rid)) for rid in ids if rid in self._pending]
        staged = []
        t_now = time.perf_counter()
        for rid, request, future in work:
            try:
                key = _merge_key(request)
                size = next(
                    iter(int(np.asarray(a).shape[0]) for a in request.inputs.values())
                )
            except Exception:
                key, size = ("__solo__", rid), 1
            staged.append((key, size, request, future, t_now))
        if not staged:
            return
        if self._shed_expired and len(staged) > 1:
            # priority-aware ordering: within the released window the
            # high-priority class stages (and therefore dispatches)
            # first; under a backlog the low-priority tail queues
            # longest and its deadlines expire — shed — first. Stable
            # sort keeps arrival order within a class.
            staged.sort(key=lambda it: -it[2].priority)
        with self._ready_cv:
            self._ready.extend(staged)
            self._ready_cv.notify()

    # -- dispatch (forms the device batch when a slot frees) ------------------

    def _dispatch_loop(self) -> None:
        while True:
            try:
                if self._dispatch_once():
                    return
            except Exception:
                # The dispatcher is the only thread that forms batches:
                # an escaped error here would stall every later
                # do_inference forever on future.result(). Log and keep
                # serving; the failed slot's futures were already
                # failed by _dispatch_once.
                log.exception("dispatcher slot failed; dispatcher continues")

    def _beat(self) -> None:
        """Stamp the dispatcher heartbeat. Single writer (the dispatch
        thread); the watchdog and stats() only read, and a monotonic
        float store is atomic in CPython — deliberately lock-free so
        the heartbeat itself can never contend with dispatch."""
        self._hb_ts = time.perf_counter()

    def dispatcher_progress_age_s(self) -> float:
        """Seconds since the dispatch loop last made progress (slot
        start or idle wait). Small under load and at rest; grows only
        when the dispatcher is wedged."""
        return max(0.0, time.perf_counter() - self._hb_ts)

    def _watchdog_loop(self) -> None:
        """Stall watchdog: the batcher_stall fault (and any real hang —
        a device call that never returns, a deadlocked executor) can
        freeze the single dispatcher with NO signal: requests just
        queue forever. Log loudly once per stall episode, and again on
        recovery, so the operator sees the window edges."""
        poll = max(0.25, self.stall_threshold_s / 4.0)
        while not self._watchdog_stop.wait(poll):
            age = self.dispatcher_progress_age_s()
            if age >= self.stall_threshold_s:
                if not self._stall_logged:
                    self._stall_logged = True
                    log.error(
                        "dispatcher STALLED: no progress for %.1fs "
                        "(threshold %.1fs) — ready_depth=%d, "
                        "active_slots=%d; requests are queuing",
                        age, self.stall_threshold_s,
                        len(self._ready), self._active_slots,
                    )
            elif self._stall_logged:
                self._stall_logged = False
                log.warning("dispatcher recovered after stall")
            poll = max(0.25, self.stall_threshold_s / 4.0)

    def _dispatch_once(self) -> bool:
        """One dispatcher slot: acquire a permit, form a group, submit.
        Returns True when the loop should exit (close() requested and
        the staging deque is drained). Any unexpected error fails the
        formed group's futures, releases the permit, and re-raises for
        the loop to log — the thread itself survives."""
        self._beat()
        self._inflight.acquire()
        self._beat()
        group = None
        try:
            with self._ready_cv:
                while not self._ready and not self._dispatch_stop:
                    self._ready_cv.wait(timeout=0.1)
                    # idle is progress: only a dispatcher that cannot
                    # reach this loop (wedged on the permit semaphore or
                    # a hung group) lets the heartbeat go stale
                    self._beat()
                if self._ready:
                    group = self._form_group_locked()
                    if (
                        self._merge_hold_s > 0
                        and not self._dispatch_stop
                        and not self._ready  # nothing skipped/left over
                        and sum(it[1] for it in group) < self._max_merge
                    ):
                        # hold for the rest of the client burst: wait
                        # out the FULL hold window (arrival notifies
                        # and spurious wakeups return early from one
                        # wait, so re-wait the remaining deadline),
                        # absorbing same-key arrivals until the group
                        # fills or the hold expires
                        deadline = time.perf_counter() + self._merge_hold_s
                        while not self._dispatch_stop:
                            while self._ready:
                                frames = sum(it[1] for it in group)
                                item = self._ready[0]
                                if (
                                    item[0] != group[0][0]
                                    or frames + item[1] > self._max_merge
                                ):
                                    break
                                group.append(self._ready.popleft())
                            left = deadline - time.perf_counter()
                            if (
                                left <= 0
                                or sum(it[1] for it in group)
                                >= self._max_merge
                                # head is unabsorbable (other key or
                                # over-cap): ship now, it needs a slot
                                or self._ready
                            ):
                                break
                            self._ready_cv.wait(timeout=left)
                    self._merge_stats["merges"] += 1
                    frames = sum(it[1] for it in group)
                    self._merge_stats["merged_frames"] += frames
                    self._merge_occupancy[frames] += 1
                elif self._dispatch_stop:
                    self._inflight.release()
                    return True
            if group is None:
                self._inflight.release()
                return False

            with self._ready_cv:
                self._active_slots += 1

            def run(g=group, t_submit=time.perf_counter()):
                t_run = time.perf_counter()
                with self._ready_cv:
                    self._decomp["n"] += 1
                    self._decomp["exec_wait_s"] += t_run - t_submit
                    self._decomp["queue_wait_s"] += t_run - min(
                        it[4] for it in g
                    )
                    # PER-MEMBER queue delay, not just the merged
                    # batch's (which MultiTrace would fan out as one
                    # shared number): each member's own staging
                    # timestamp to this dispatch
                    self._decomp["members"] += len(g)
                    self._decomp["member_wait_s"] += sum(
                        t_run - it[4] for it in g
                    )
                # the slot frees the moment the group LAUNCHES (inputs
                # staged, compute enqueued on the inner channel) — the
                # dispatcher can then form the next batch against
                # device occupancy while this group's readback/split
                # still runs. Exactly-once: the finally covers groups
                # whose launch never happened (errors before dispatch).
                released = [False]

                def free_slot():
                    if released[0]:
                        return
                    released[0] = True
                    with self._ready_cv:
                        self._slot_occupancy[self._active_slots] += 1
                        self._active_slots -= 1
                        self._merge_stats["launch_frees"] += 1
                    self._inflight.release()

                try:
                    # (t_staged, request, future): the staging timestamp
                    # rides along so each member gets its own merge_wait
                    # span (staged -> this group's dispatch)
                    self._run_group(
                        [(it[4], it[2], it[3]) for it in g], free_slot
                    )
                except Exception as e:
                    # No exception may escape: an unresolved future
                    # hangs its caller forever.
                    for it in g:
                        if not it[3].done():
                            it[3].set_exception(e)
                finally:
                    free_slot()

            try:
                self._exec.submit(run)
            except RuntimeError as e:  # executor shut down mid-close
                with self._ready_cv:
                    self._active_slots -= 1
                self._inflight.release()
                for it in group:
                    if not it[3].done():
                        it[3].set_exception(e)
            return False
        except Exception as e:
            self._inflight.release()
            if group:
                for it in group:
                    if not it[3].done():
                        it[3].set_exception(e)
            raise

    def _form_group_locked(self):
        """Pop the head item plus every queued same-key item that fits
        under max_merge frames (caller holds _ready_cv). Items of other
        keys keep their relative order for the next slot. Stats are
        recorded by the caller once the group is FINAL (the merge-hold
        path may still grow it)."""
        first = self._ready.popleft()
        group = [first]
        frames = first[1]
        skipped = []
        while self._ready and frames < self._max_merge:
            item = self._ready.popleft()
            if item[0] == first[0] and frames + item[1] <= self._max_merge:
                group.append(item)
                frames += item[1]
            else:
                skipped.append(item)
        self._ready.extendleft(reversed(skipped))
        return group

    def _pad_target(self, total: int) -> int:
        """Padded device-batch size for a merged total: the static
        power-of-two table, kept divisible by a sharded inner channel's
        data axis. The continuous scheduler overrides this with a
        live-occupancy-driven table (runtime/continuous.py) so buckets
        track the sizes traffic actually produces."""
        return bucket_for(total, self._batch_multiple)

    # -- batch execution (runs on the executor threads) -----------------------

    def _shed_expired_members(self, group) -> list:
        """Fail members whose deadline already passed (the batcher-merge
        shed point) and return the still-live remainder. A merged batch
        inherits its tightest member's deadline, so ONE expired member
        left in place would get the whole group shed at launch."""
        now = time.perf_counter()
        live = []
        for item in group:
            t_staged, request, future = item
            deadline = request.deadline_s
            if deadline is None or now <= deadline:
                live.append(item)
                continue
            if request.trace is not None:
                request.trace.end("batch_queue")
            with self._ready_cv:
                self._shed[
                    f"{request.model_name}|{request.priority}|merge"
                ] += 1
            future.set_exception(
                DeadlineExpiredError(
                    f"model '{request.model_name}': deadline expired "
                    f"{(now - deadline) * 1e3:.1f}ms before dispatch"
                )
            )
        return live

    def _run_group(self, group, free_slot=None) -> None:
        """Execute one formed group. ``free_slot`` (when given) is
        called exactly once, as soon as the group's device work is
        launched — inputs staged, compute enqueued — so the dispatcher
        slot frees before the readback/split work."""
        faults.probe("batcher_stall", group[0][1].model_name)
        if self._shed_expired:
            group = self._shed_expired_members(group)
            if not group:
                return  # every member expired; caller's finally frees
        if len(group) == 1 and (
            not self._pad_to_buckets or group[0][1].sequence_id
        ):
            # session frames take the solo path even under bucket
            # padding: pad rows would read as extra cameras to the
            # session layer, and the solo path is the one that carries
            # the original request (sequence fields intact) downstream
            t_staged, request, future = group[0]
            self._run_solo(request, future, free_slot, t_staged=t_staged)
            return
        requests = [g[1] for g in group]
        futures = [g[2] for g in group]
        traces = [r.trace for r in requests]
        t_dispatch = time.perf_counter()
        if log.isEnabledFor(logging.DEBUG):
            # correlated dispatch line: each member's trace/request tag,
            # so a fleet trace_id greps straight to ITS device batch
            from triton_client_tpu.obs.logs import log_tag

            log.debug(
                "dispatching merged batch of %d for model %s:%s",
                len(requests), requests[0].model_name,
                "".join(
                    log_tag(r.trace, r.request_id) for r in requests
                ) or " [untraced]",
            )
        for (t_staged, r, _f) in group:
            if r.trace is not None and t_staged is not None:
                # per-member ready-queue residence: own staging
                # timestamp -> this group's dispatch (the merge_wait
                # SLO stage; batch_queue still covers the whole
                # admission+queue+slot window around it)
                r.trace.add("merge_wait", t_staged, t_dispatch)
        for tr in traces:
            if tr is not None:
                tr.end("batch_queue")
        try:
            sizes = [
                next(iter(np.asarray(a).shape[0] for a in r.inputs.values()))
                for r in requests
            ]
            total = sum(sizes)
            # pad only when the ROUNDED size still fits max_merge: a
            # non-power-of-two max_merge (e.g. 6) must not round a
            # total of 6 up to 8 — past the cap and past any size the
            # inner channel precompiled. Oversized single requests
            # (> max_merge) pass through unpadded for the same reason.
            # bucket_for keeps the padded size divisible by a sharded
            # inner channel's data axis (== _bucket at multiple 1); the
            # continuous scheduler overrides _pad_target with a
            # live-occupancy table
            rounded = self._pad_target(total)
            pad = (
                rounded - total
                if self._pad_to_buckets and rounded <= self._max_merge
                else 0
            )
            t_stage0 = time.perf_counter()
            merged = {}
            arena_held = []
            for name in requests[0].inputs:
                parts = [np.asarray(r.inputs[name]) for r in requests]
                if pad:
                    # replicate a real row: zeros can steer a model
                    # down numerically different paths, a copy cannot
                    parts = pad_rows(parts, pad)
                merged[name] = self._merge_parts(name, parts, arena_held)
            t_disp = time.perf_counter()
            for tr in traces:
                if tr is not None:
                    tr.add("batch_merge", t_stage0, t_disp)
            if self._shed_expired:
                # second deadline pass AFTER the pack (ISSUE 8
                # satellite): the host merge build above takes real
                # time under load, so a member that was live at group
                # formation can be expired by now — launching would
                # hand the inner channel a batch whose inherited
                # min-deadline is already past (shed whole at launch,
                # failing every live member). Shed the stragglers and
                # rebuild from the survivors (rare path; t_staged=None
                # so merge_wait is not double-recorded).
                live = self._shed_expired_members(group)
                if len(live) != len(group):
                    if arena_held and self._arena is not None:
                        for arr in arena_held:
                            self._arena.release(arr)
                    if live:
                        self._run_group(
                            [(None, r, f) for (_t, r, f) in live], free_slot
                        )
                    return
            try:
                # async launch + deferred readback: by the time the
                # call returns, the inner channel has device_put the
                # merged batch and enqueued the compute — the slot can
                # free NOW; result() below pays the device wait +
                # host copy outside the permit
                deadlines = [
                    r.deadline_s for r in requests if r.deadline_s is not None
                ]
                fut = self._inner.do_inference_async(
                    InferRequest(
                        model_name=requests[0].model_name,
                        model_version=requests[0].model_version,
                        inputs=merged,
                        # channel-side spans (stage/launch/device/
                        # readback) fan out to every member's trace
                        trace=(
                            MultiTrace(traces)
                            if any(t is not None for t in traces)
                            else None
                        ),
                        # the merged batch inherits its TIGHTEST
                        # member's deadline and HIGHEST priority: the
                        # batch is late the moment any member is
                        deadline_s=min(deadlines) if deadlines else None,
                        priority=max(r.priority for r in requests),
                    )
                )
                if free_slot is not None:
                    free_slot()
                resp = fut.result()
            finally:
                t_dev_end = time.perf_counter()
                if arena_held and self._arena is not None:
                    # device_put copied out of the slot synchronously;
                    # safe to recycle once the call returns
                    for arr in arena_held:
                        self._arena.release(arr)
                with self._ready_cv:
                    self._decomp["stage_s"] += t_disp - t_stage0
                    self._decomp["device_s"] += t_dev_end - t_disp
            if pad:
                # counted only for a padded call that actually ran,
                # under the same lock stats() reads through (executor
                # threads race here at pipeline_depth >= 2)
                with self._ready_cv:
                    self._merge_stats["padded_frames"] += pad
                    self._padded_by_model[requests[0].model_name] += pad
        except Exception:
            # A merged failure must not take down unrelated requests:
            # fall back to per-request execution.
            for request, future in zip(requests, futures):
                self._run_solo(request, future)
            return
        t_resp0 = time.perf_counter()
        total_padded = total + pad
        splits = np.cumsum(sizes)[:-1]
        per_output = {}
        for name, arr in resp.outputs.items():
            arr = np.asarray(arr)
            if arr.ndim >= 1 and arr.shape[0] == total_padded:
                per_output[name] = np.split(arr[:total], splits)
            elif arr.ndim >= 1 and arr.shape[0] == total:
                per_output[name] = np.split(arr, splits)
            else:  # non-batched output — replicate
                per_output[name] = [arr] * len(requests)
        for i, (request, future) in enumerate(zip(requests, futures)):
            if request.trace is not None:
                # before set_result: the waiting thread may finish the
                # trace the moment the future resolves
                request.trace.add("batch_respond", t_resp0, time.perf_counter())
            future.set_result(
                InferResponse(
                    model_name=resp.model_name,
                    model_version=resp.model_version,
                    outputs={k: v[i] for k, v in per_output.items()},
                    request_id=request.request_id,
                    latency_s=resp.latency_s,
                )
            )

    def _merge_parts(self, name: str, parts: list, arena_held: list) -> np.ndarray:
        """Concatenate request tensors into the device-batch buffer —
        through a recycled aligned arena slot when enabled (round 5:
        the serving path consumes native/ Arena), else a fresh
        allocation. An oversized batch (a solo request wider than the
        slot, or an input with wider rows than the one the slot was
        sized from) falls back PER BATCH; only a failure to build/load
        the native pool disables staging for the channel."""
        if self._arena_slots:
            arena = self._arena
            if arena is None:
                with self._lock:  # double-checked: depth>=2 races here
                    arena = self._arena
                    if arena is None and self._arena_slots:
                        try:
                            from triton_client_tpu.native import Arena

                            rows = max(
                                self._max_merge, sum(len(p) for p in parts)
                            )
                            arena = Arena(
                                int(rows * parts[0][:1].nbytes),
                                self._arena_slots,
                            )
                            self._arena = arena
                        except Exception as e:
                            log.warning("arena staging unavailable (%s)", e)
                            self._arena_slots = 0
            if arena is not None:
                total = sum(len(p) for p in parts)
                try:
                    out = arena.acquire(
                        (total, *parts[0].shape[1:]), parts[0].dtype
                    )
                except ValueError:  # batch wider than the slot
                    out = None
                if out is not None:
                    o = 0
                    for p in parts:
                        out[o : o + len(p)] = p
                        o += len(p)
                    arena_held.append(out)
                    return out
        return np.concatenate(parts)

    def _run_solo(
        self, request: InferRequest, future, free_slot=None, t_staged=None
    ) -> None:
        if request.trace is not None:
            if t_staged is not None:
                # solo dispatches report merge_wait too (a group of
                # one), so queue-delay attribution covers every path;
                # None on the merged-failure retry path, whose wait was
                # already recorded by the group dispatch
                request.trace.add("merge_wait", t_staged, time.perf_counter())
            request.trace.end("batch_queue")  # no-op on the retry path
        try:
            fut = self._inner.do_inference_async(request)
            if free_slot is not None:
                free_slot()  # launched: slot frees before the readback
            future.set_result(fut.result())
        except Exception as e:
            future.set_exception(e)

    # -- stats / lifecycle ----------------------------------------------------

    def stats(self) -> dict:
        if self._impl is not None:
            out = self._impl.stats()
        elif self._py is not None:
            out = self._py.stats()
        else:  # windowless scheduler (runtime/continuous.py)
            out = {}
        with self._ready_cv:
            out.update(self._merge_stats)
            out["merge_occupancy"] = dict(
                sorted(self._merge_occupancy.items())
            )
            out["padded_by_model"] = dict(sorted(self._padded_by_model.items()))
            shipped = out["merged_frames"] + out["padded_frames"]
            # share of device rows that were padding — the headline
            # padding-tax number (ISSUE 8: was ~32% under BENCH_r05)
            out["pad_fraction"] = (
                out["padded_frames"] / shipped if shipped else 0.0
            )
            # concurrently-active execution slots observed at each
            # group launch: {slots_active: launches} — 2s and above mean
            # batch N+1 formed/staged while batch N still executed
            out["slot_occupancy"] = dict(sorted(self._slot_occupancy.items()))
            out["active_slots"] = self._active_slots
            out["ready_depth"] = len(self._ready)
            out["shed"] = dict(self._shed)
            out["max_merge"] = self._max_merge
            out["batch_multiple"] = self._batch_multiple
            out["pipeline_depth"] = self._pipeline_depth
            age = self.dispatcher_progress_age_s()
            out["dispatcher_last_progress_age_s"] = age
            out["dispatcher_stalled"] = (
                1 if age >= self.stall_threshold_s else 0
            )
            n = self._decomp.get("n", 0.0)
            if n:
                out["decomp_ms"] = {
                    k[:-2]: round(self._decomp[k] / n * 1e3, 2)
                    for k in (
                        "queue_wait_s", "exec_wait_s", "stage_s", "device_s"
                    )
                }
                out["decomp_batches"] = int(n)
            members = self._decomp.get("members", 0.0)
            if members:
                # mean PER-MEMBER ready-queue wait (merge_wait), vs
                # decomp_ms.queue_wait which is per merged batch from
                # its earliest member
                out["member_queue_delay_ms"] = round(
                    self._decomp["member_wait_s"] / members * 1e3, 2
                )
                out["merge_members"] = int(members)
            if self._arena is not None:
                out["arena_free_slots"] = self._arena.free_slots()
        return out

    def close(self) -> None:
        # the watchdog first: a slow drain below is not a stall
        self._watchdog_stop.set()
        # admission first: its close() drains every admitted id into
        # _on_batch, so by the time it returns all work is staged
        if self._impl is not None:
            self._impl.close()
        if self._py is not None:
            self._py.close()
        # the dispatcher keeps forming batches until the staging deque
        # is empty, THEN exits — no admitted future is stranded
        with self._ready_cv:
            self._dispatch_stop = True
            self._ready_cv.notify_all()
        # The executor must not shut down while the dispatcher can
        # still submit (futures would get 'cannot schedule new
        # futures' instead of executing), and this rig's tunnel stalls
        # run minutes — so loop-join with a progress warning instead of
        # abandoning the thread after a fixed timeout.
        waited = 0.0
        while self._dispatcher.is_alive():
            self._dispatcher.join(timeout=30.0)
            if self._dispatcher.is_alive():
                waited += 30.0
                log.warning(
                    "batcher close(): dispatcher still draining after "
                    "%.0fs (device call in flight?)", waited,
                )
        # after the dispatcher stops, drain in-flight groups so every
        # admitted future resolves before close() returns
        self._exec.shutdown(wait=True)
        # _arena is published under _lock (_merge_parts' double-checked
        # init); tear it down under the same lock — tpulint TPL401
        # caught the bare mutation racing a straggler executor thread
        with self._lock:
            arena, self._arena = self._arena, None
        if arena is not None:
            arena.close()


class _PyBatcher:
    """queue.Queue + thread fallback with the same close semantics."""

    def __init__(self, on_batch, max_batch, timeout_us, capacity) -> None:
        self._on_batch = on_batch
        self._max_batch = max_batch
        self._timeout_s = timeout_us / 1e6
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._n_batches = 0
        self._n_requests = 0

    def start(self) -> None:
        self._thread.start()

    def enqueue(self, rid: int) -> bool:
        if self._stop.is_set():
            # Match the native path: enqueue after close raises rather
            # than accepting work no thread will ever drain.
            raise RuntimeError("server not running")
        try:
            self._q.put_nowait(rid)
            return True
        except queue.Full:
            return False

    def _run(self) -> None:
        while not self._stop.is_set() or not self._q.empty():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            ids = [first]
            deadline = time.perf_counter() + self._timeout_s
            while len(ids) < self._max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    ids.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._n_batches += 1
            self._n_requests += len(ids)
            self._on_batch(ids)

    def stats(self) -> dict:
        return {
            "batches": self._n_batches,
            "batched_requests": self._n_requests,
            "mean_batch": self._n_requests / self._n_batches
            if self._n_batches
            else 0.0,
            "queue_depth": self._q.qsize(),
        }

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
