"""Micro-batching channel: coalesce concurrent requests into one TPU call.

Triton's dynamic batcher is a core piece of the serving runtime the
reference leans on (config.pbtxt max_batch_size; SURVEY.md §2.9 row 1).
Here the same policy runs in-tree: admission + batch-window timing live
in the native C++ runtime (triton_client_tpu/native), and the formed
batch is executed as ONE inference over the wrapped channel with the
per-request arrays concatenated on the batch axis — bigger batches keep
the MXU busy and amortize dispatch overhead.

BatchingChannel is itself a BaseChannel, so it stacks under the gRPC
façade or above TPUChannel unchanged. Requests are only merged when
model, version and non-batch input shapes match; mismatches run solo.
A pure-Python batcher (same semantics, queue.Queue + thread) backstops
environments without the native toolchain.
"""

from __future__ import annotations

import concurrent.futures
import itertools
import logging
import queue
import threading
import time

import numpy as np

from triton_client_tpu.channel.base import BaseChannel, InferRequest, InferResponse

log = logging.getLogger(__name__)


def _merge_key(request: InferRequest):
    return (
        request.model_name,
        request.model_version,
        tuple(
            (name, np.asarray(a).shape[1:], np.asarray(a).dtype.str)
            for name, a in sorted(request.inputs.items())
        ),
    )


class BatchingChannel(BaseChannel):
    def __init__(
        self,
        inner: BaseChannel,
        max_batch: int = 8,
        timeout_us: int = 2000,
        capacity: int = 256,
        use_native: bool = True,
        pipeline_depth: int = 2,
    ) -> None:
        """``pipeline_depth``: formed batches executing concurrently
        against the inner channel. At the default 2, batch N+1's
        host->device transfer overlaps batch N's execution (the role
        Triton's per-instance CUDA streams play) — on a dispatch-bound
        path this nearly doubles batch rate; jax queues the dispatches
        and the device serializes execution. While ``pipeline_depth``
        batches are in flight the batcher thread blocks, so incoming
        requests coalesce into FULLER batches rather than piling up as
        fragments. Depth 1 restores strictly serial execution."""
        self._inner = inner
        self._pending: dict[int, tuple[InferRequest, concurrent.futures.Future]] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._impl = None
        self._py = None
        self._inflight = threading.Semaphore(max(1, pipeline_depth))
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, pipeline_depth),
            thread_name_prefix="batch-exec",
        )
        if use_native:
            try:
                from triton_client_tpu.native import NativeBatchServer

                self._impl = NativeBatchServer(
                    self._on_batch,
                    max_batch=max_batch,
                    timeout_us=timeout_us,
                    capacity=capacity,
                )
                self._impl.start()
            except Exception as e:  # NativeUnavailable or load errors
                self._impl = None
                log.warning("native batcher unavailable (%s); python fallback", e)
        if self._impl is None:
            self._py = _PyBatcher(self._on_batch, max_batch, timeout_us, capacity)
            self._py.start()

    # -- BaseChannel ----------------------------------------------------------

    def register_channel(self) -> None:
        self._inner.register_channel()

    def fetch_channel(self):
        return self._inner.fetch_channel()

    def get_metadata(self, model_name: str, model_version: str = ""):
        return self._inner.get_metadata(model_name, model_version)

    def do_inference(self, request: InferRequest) -> InferResponse:
        future: concurrent.futures.Future = concurrent.futures.Future()
        rid = next(self._ids)
        with self._lock:
            self._pending[rid] = (request, future)
        try:
            admitted = (
                self._impl.enqueue(rid)
                if self._impl is not None
                else self._py.enqueue(rid)
            )
        except Exception:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        if not admitted:
            with self._lock:
                self._pending.pop(rid, None)
            raise RuntimeError("inference queue full")
        return future.result()

    # -- batch execution (runs on the batcher thread) -------------------------

    def _on_batch(self, ids) -> None:
        with self._lock:
            work = [(rid, *self._pending.pop(rid)) for rid in ids if rid in self._pending]
        groups: dict = {}
        for rid, request, future in work:
            try:
                key = _merge_key(request)
            except Exception:
                key = ("__solo__", rid)
            groups.setdefault(key, []).append((rid, request, future))
        for group in groups.values():
            # bounded handoff: at most pipeline_depth groups run
            # concurrently; when full, THIS (batcher) thread blocks,
            # which is what lets the queue coalesce larger batches
            self._inflight.acquire()

            def run(g=group):
                try:
                    self._run_group(g)
                except Exception as e:
                    # No exception may escape: an unresolved future
                    # hangs its caller forever.
                    for _, _, future in g:
                        if not future.done():
                            future.set_exception(e)
                finally:
                    self._inflight.release()

            try:
                self._exec.submit(run)
            except RuntimeError as e:  # executor shut down mid-close
                self._inflight.release()
                for _, _, future in group:
                    if not future.done():
                        future.set_exception(e)

    def _run_group(self, group) -> None:
        if len(group) == 1:
            _, request, future = group[0]
            self._run_solo(request, future)
            return
        requests = [g[1] for g in group]
        futures = [g[2] for g in group]
        try:
            sizes = [
                next(iter(np.asarray(a).shape[0] for a in r.inputs.values()))
                for r in requests
            ]
            merged = {
                name: np.concatenate([np.asarray(r.inputs[name]) for r in requests])
                for name in requests[0].inputs
            }
            resp = self._inner.do_inference(
                InferRequest(
                    model_name=requests[0].model_name,
                    model_version=requests[0].model_version,
                    inputs=merged,
                )
            )
        except Exception:
            # A merged failure must not take down unrelated requests:
            # fall back to per-request execution.
            for request, future in zip(requests, futures):
                self._run_solo(request, future)
            return
        total = sum(sizes)
        splits = np.cumsum(sizes)[:-1]
        per_output = {}
        for name, arr in resp.outputs.items():
            arr = np.asarray(arr)
            if arr.ndim >= 1 and arr.shape[0] == total:
                per_output[name] = np.split(arr, splits)
            else:  # non-batched output — replicate
                per_output[name] = [arr] * len(requests)
        for i, (request, future) in enumerate(zip(requests, futures)):
            future.set_result(
                InferResponse(
                    model_name=resp.model_name,
                    model_version=resp.model_version,
                    outputs={k: v[i] for k, v in per_output.items()},
                    request_id=request.request_id,
                    latency_s=resp.latency_s,
                )
            )

    def _run_solo(self, request: InferRequest, future) -> None:
        try:
            future.set_result(self._inner.do_inference(request))
        except Exception as e:
            future.set_exception(e)

    # -- stats / lifecycle ----------------------------------------------------

    def stats(self) -> dict:
        if self._impl is not None:
            return self._impl.stats()
        return self._py.stats()

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
        if self._py is not None:
            self._py.close()
        # after the batcher thread stops, drain in-flight groups so
        # every admitted future resolves before close() returns
        self._exec.shutdown(wait=True)


class _PyBatcher:
    """queue.Queue + thread fallback with the same close semantics."""

    def __init__(self, on_batch, max_batch, timeout_us, capacity) -> None:
        self._on_batch = on_batch
        self._max_batch = max_batch
        self._timeout_s = timeout_us / 1e6
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._n_batches = 0
        self._n_requests = 0

    def start(self) -> None:
        self._thread.start()

    def enqueue(self, rid: int) -> bool:
        if self._stop.is_set():
            # Match the native path: enqueue after close raises rather
            # than accepting work no thread will ever drain.
            raise RuntimeError("server not running")
        try:
            self._q.put_nowait(rid)
            return True
        except queue.Full:
            return False

    def _run(self) -> None:
        while not self._stop.is_set() or not self._q.empty():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            ids = [first]
            deadline = time.perf_counter() + self._timeout_s
            while len(ids) < self._max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    ids.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._n_batches += 1
            self._n_requests += len(ids)
            self._on_batch(ids)

    def stats(self) -> dict:
        return {
            "batches": self._n_batches,
            "batched_requests": self._n_requests,
            "mean_batch": self._n_requests / self._n_batches
            if self._n_batches
            else 0.0,
            "queue_depth": self._q.qsize(),
        }

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
