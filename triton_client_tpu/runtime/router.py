"""Front-door router: health-aware replica routing with tail tolerance.

The drain path has told clients "retry against another replica" since
PR 7 (runtime/server.py) — this module is the thing that can actually
do that. ``FrontDoorRouter`` owns N ``GRPCChannel`` endpoints (one
serving replica each) and routes unary inference across them with the
four disciplines a replicated front door needs, per *The Tail at
Scale* and Envoy's outlier-detection model:

  * **health** — an active probe loop calls ServerReady (plus
    ModelReady for a configured model set) on every replica each
    interval, and passive outlier ejection removes a replica after
    consecutive connection-class failures for an exponentially growing
    hold-down. Drain detection is distinct from death: a not-ready
    probe or an UNAVAILABLE-with-"draining" response pulls the replica
    from rotation WITHOUT abandoning its in-flight attempts (the
    server finishes them; the router just stops sending new work) and
    without charging the retry budget — a drain is an orchestrated
    event, not a fault.
  * **load** — power-of-two-choices over live per-replica in-flight
    counts: pick two distinct candidates at random, send to the less
    loaded. P2C gets within a constant factor of ideal least-loaded
    while reading only two counters, and avoids the thundering-herd
    flip-flop of deterministic least-loaded under many clients.
  * **tail tolerance** — hedged requests: if the primary attempt has
    not resolved after a hedge delay derived from the router's OWN
    rolling latency quantile (a ``LatencyHistogram``, so the delay
    tracks the workload), launch the same request on a second replica
    and take the first winner, cancelling the loser. Hedges are capped
    by a budget fraction of total traffic so tail-chasing can never
    become a load amplifier.
  * **retry discipline** — a token-bucket retry budget shared across
    the replica set: each routed request deposits ``ratio`` tokens, a
    failover retry spends one. When the fleet is failing faster than
    the budget accrues, retries stop and errors surface — a retry
    storm against a degraded fleet is how outages become cascades.
    Every retry and hedge also respects the request's remaining
    ``deadline_s``; the router never launches work nobody will wait
    for.

The router quacks like a ``BaseChannel`` (get_metadata /
do_inference / do_inference_async / close), so ``utils/loadgen.py``
drives a fleet exactly like a single server and capacity numbers
become fleet numbers.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import queue
import random
import threading
import time
from typing import Callable, Sequence

from triton_client_tpu.channel.base import (
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.obs.histogram import LatencyHistogram
from triton_client_tpu.obs.logs import log_tag
from triton_client_tpu.obs.trace import (
    SUMMARY_PARAM_KEY,
    TraceContext,
    decode_span_summary,
    graft_span_summary,
)

log = logging.getLogger(__name__)

# gRPC status-code names the router classifies on. String names (not
# grpc.StatusCode members) so classification works for any exception
# exposing .code() — real RpcErrors, the channel's synthesized
# DeadlineExceededRpcError, and test fakes alike.
_CONNECTION_CLASS = ("UNAVAILABLE",)  # eject-worthy, retry-elsewhere
_SHED = "RESOURCE_EXHAUSTED"          # deliberate server shed: NEVER retry
_DEADLINE = "DEADLINE_EXCEEDED"       # caller budget gone: surface


def _status_name(exc: BaseException) -> str | None:
    code = getattr(exc, "code", None)
    if not callable(code):
        return None
    try:
        c = code()
    except Exception:
        return None
    return getattr(c, "name", None) or (str(c) if c is not None else None)


def _is_draining(exc: BaseException) -> bool:
    details = getattr(exc, "details", None)
    if not callable(details):
        return False
    try:
        return "draining" in (details() or "")
    except Exception:
        return False


class RetryBudget:
    """Token-bucket retry budget shared across a replica set.

    Envoy's retry-budget model: tokens accrue at ``ratio`` per routed
    request (so sustainable retry traffic is a fixed fraction of real
    traffic), a retry costs one token, and the bucket is capped so a
    long quiet period cannot bank an unbounded burst. ``floor_hits``
    counts denials — the observable signal that the budget is doing
    its job under a failure storm."""

    def __init__(
        self, ratio: float = 0.2, cap: float = 10.0, initial: float = 3.0
    ) -> None:
        self._ratio = float(ratio)
        self._cap = float(cap)
        self._tokens = min(float(initial), self._cap)
        self._floor_hits = 0
        self._spent = 0

    def deposit(self) -> None:
        self._tokens = min(self._tokens + self._ratio, self._cap)

    def try_spend(self) -> bool:
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            self._spent += 1
            return True
        self._floor_hits += 1
        return False

    @property
    def tokens(self) -> float:
        return self._tokens

    @property
    def floor_hits(self) -> int:
        return self._floor_hits

    @property
    def spent(self) -> int:
        return self._spent


class Replica:
    """One endpoint's routing state. All mutation happens under the
    owning ReplicaSet's lock; the channel itself is thread-safe."""

    __slots__ = (
        "endpoint", "channel", "inflight", "consecutive_failures",
        "ejected_until", "ejections", "probe_ready", "draining",
        "successes", "failures",
    )

    def __init__(self, endpoint: str, channel) -> None:
        self.endpoint = endpoint
        self.channel = channel
        self.inflight = 0
        self.consecutive_failures = 0
        self.ejected_until = 0.0
        self.ejections = 0
        # optimistic until the first probe says otherwise: a router in
        # front of a healthy fleet must route before its first probe
        self.probe_ready = True
        self.draining = False
        self.successes = 0
        self.failures = 0

    def ejected(self, now: float) -> bool:
        return now < self.ejected_until

    def available(self, now: float) -> bool:
        return self.probe_ready and not self.draining and not self.ejected(now)


class ReplicaSet:
    """Owns the replicas: health probing, outlier ejection, p2c picks.

    Separated from FrontDoorRouter so the membership/health machinery
    is testable without the hedging state machine on top of it."""

    def __init__(
        self,
        endpoints: Sequence[str],
        *,
        channel_factory: Callable[[str], object] | None = None,
        models: Sequence[str] = (),
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        eject_threshold: int = 3,
        base_ejection_s: float = 1.0,
        max_ejection_s: float = 30.0,
        timeout_s: float = 30.0,
    ) -> None:
        if not endpoints:
            raise ValueError("a replica set needs at least one endpoint")
        if channel_factory is None:
            from triton_client_tpu.channel.grpc_channel import GRPCChannel

            # retries=0: the router IS the retry policy. A channel-level
            # ladder under the router would retry the same dying replica
            # while the router's budget thinks no retry happened.
            channel_factory = lambda ep: GRPCChannel(  # noqa: E731
                ep, timeout_s=timeout_s, retries=0
            )
        self._lock = threading.Lock()
        self.replicas = [
            Replica(ep, channel_factory(ep)) for ep in endpoints
        ]
        self._models = tuple(models)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._eject_threshold = int(eject_threshold)
        self._base_ejection_s = float(base_ejection_s)
        self._max_ejection_s = float(max_ejection_s)
        self._ejections_total = 0
        self._rng = random.Random()
        self._stop = threading.Event()
        self._prober: threading.Thread | None = None
        if self._probe_interval_s > 0:
            self._prober = threading.Thread(
                target=self._probe_loop,
                name="router-prober",
                daemon=True,
            )
            self._prober.start()

    # -- health ---------------------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._probe_interval_s):
            try:
                self.probe_once()
            except Exception:
                log.exception("router probe pass failed")

    def probe_once(self) -> None:
        """One active health pass over every replica (also callable
        directly — tests drive it without the background thread)."""
        for rep in self.replicas:
            ready = rep.channel.server_ready(timeout_s=self._probe_timeout_s)
            if ready:
                for model in self._models:
                    if not rep.channel.model_ready(
                        model, timeout_s=self._probe_timeout_s
                    ):
                        ready = False
                        break
            with self._lock:
                was = rep.probe_ready
                rep.probe_ready = ready
                if ready:
                    # an affirmative probe supersedes stale passive
                    # signals: the replica answered ServerReady, so a
                    # drain flag or running failure streak is over
                    rep.draining = False
                    rep.consecutive_failures = 0
                elif was:
                    log.warning(
                        "replica %s failed health probe; out of rotation",
                        rep.endpoint,
                    )

    def record_success(self, rep: Replica) -> None:
        with self._lock:
            rep.successes += 1
            rep.consecutive_failures = 0

    def record_failure(self, rep: Replica, connection_class: bool) -> None:
        """Passive outlier signal. Connection-class failures streak
        toward ejection; others count but do not eject (a model bug
        returning INTERNAL is not a reason to burn a replica)."""
        with self._lock:
            rep.failures += 1
            if not connection_class:
                return
            rep.consecutive_failures += 1
            if rep.consecutive_failures >= self._eject_threshold:
                hold = min(
                    self._base_ejection_s * (2.0 ** rep.ejections),
                    self._max_ejection_s,
                )
                rep.ejected_until = time.perf_counter() + hold
                rep.ejections += 1
                rep.consecutive_failures = 0
                self._ejections_total += 1
                log.warning(
                    "ejecting replica %s for %.1fs (%d consecutive "
                    "connection failures, ejection #%d)",
                    rep.endpoint, hold, self._eject_threshold, rep.ejections,
                )

    def mark_draining(self, rep: Replica) -> None:
        with self._lock:
            if not rep.draining:
                log.info(
                    "replica %s is draining; out of rotation", rep.endpoint
                )
            rep.draining = True

    # -- load -----------------------------------------------------------------

    def pick(self, exclude: Sequence[Replica] = ()) -> Replica | None:
        """Power-of-two-choices over available replicas (minus
        ``exclude`` — a hedge must land on a different replica than the
        attempt it is hedging). Panic mode: if nothing is available
        (all ejected / not-ready), fall back to the least-bad pool —
        the zero-lost-responses contract says a request must always be
        attempted somewhere rather than failed on the floor."""
        now = time.perf_counter()
        with self._lock:
            pool = [
                r for r in self.replicas
                if r.available(now) and r not in exclude
            ]
            if not pool:
                # panic ladder: non-draining first (they may have
                # recovered), then literally anything not excluded
                pool = [
                    r for r in self.replicas
                    if not r.draining and r not in exclude
                ]
            if not pool:
                pool = [r for r in self.replicas if r not in exclude]
            if not pool:
                return None
            if len(pool) == 1:
                pick = pool[0]
            else:
                a, b = self._rng.sample(pool, 2)
                pick = a if a.inflight <= b.inflight else b
            pick.inflight += 1
            return pick

    def pick_affinity(
        self, stream_id: str, exclude: Sequence[Replica] = ()
    ) -> Replica | None:
        """Rendezvous (highest-random-weight) pick for a stateful
        stream: every router instance hashing the same ``stream_id``
        over the same endpoint set lands on the same replica — no
        shared table, no coordination — and when that replica dies only
        ITS streams move (each to its second-highest score), which is
        the minimal-disruption property plain mod-N hashing lacks.
        Same availability ladder and in-flight accounting as
        :meth:`pick`; ``exclude`` is the failover path (the dead
        owner)."""
        now = time.perf_counter()
        with self._lock:
            pool = [
                r for r in self.replicas
                if r.available(now) and r not in exclude
            ]
            if not pool:
                pool = [
                    r for r in self.replicas
                    if not r.draining and r not in exclude
                ]
            if not pool:
                pool = [r for r in self.replicas if r not in exclude]
            if not pool:
                return None
            pick = max(
                pool,
                key=lambda r: (
                    _rendezvous_score(stream_id, r.endpoint), r.endpoint
                ),
            )
            pick.inflight += 1
            return pick

    def release(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight -= 1

    # -- surface --------------------------------------------------------------

    def available_count(self) -> int:
        now = time.perf_counter()
        with self._lock:
            return sum(1 for r in self.replicas if r.available(now))

    def snapshot(self) -> list[dict]:
        now = time.perf_counter()
        with self._lock:
            return [
                {
                    "endpoint": r.endpoint,
                    # negotiated per-endpoint transport (uds+shm / shm /
                    # uds / grpc); custom channel factories may not
                    # expose one
                    "transport": getattr(r.channel, "transport", "grpc"),
                    "inflight": r.inflight,
                    "probe_ready": r.probe_ready,
                    "draining": r.draining,
                    "ejected": r.ejected(now),
                    "ejections": r.ejections,
                    "consecutive_failures": r.consecutive_failures,
                    "successes": r.successes,
                    "failures": r.failures,
                }
                for r in self.replicas
            ]

    @property
    def ejections_total(self) -> int:
        with self._lock:
            return self._ejections_total

    def close(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=2 * self._probe_interval_s + 2.0)
        for rep in self.replicas:
            try:
                rep.channel.close()
            except Exception:
                pass


def _rendezvous_score(stream_id: str, endpoint: str) -> int:
    """Stable 64-bit weight for (stream, endpoint) — hashlib, not
    hash(), so every process (and every restart) agrees."""
    digest = hashlib.blake2b(
        f"{stream_id}|{endpoint}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class _AttemptCarrier:
    """Minimal trace stand-in for an outbound attempt's InferRequest.

    The transport (grpc_channel._wire_params) reads only ``.context``
    off ``request.trace`` — handing it the router's own RequestTrace
    would let channel-side spans land on the router row AND every
    raced sibling, double-counting device time. Each attempt instead
    carries a fresh child context (sibling span ids under one
    trace_id), and only the WINNER's server summary is grafted back."""

    __slots__ = ("context",)

    def __init__(self, context: TraceContext) -> None:
        self.context = context


class _Attempt:
    __slots__ = ("replica", "future", "kind", "index", "t_sent")

    def __init__(
        self,
        replica: Replica,
        future: InferFuture,
        kind: str,
        index: int = 0,
        t_sent: float = 0.0,
    ):
        self.replica = replica
        self.future = future
        self.kind = kind  # "primary" | "retry" | "hedge"
        self.index = index  # attempt ordinal within the request
        self.t_sent = t_sent

    def attrs(self, **extra) -> dict:
        out = {
            "attempt": self.index,
            "endpoint": self.replica.endpoint,
            "kind": self.kind,
        }
        out.update(extra)
        return out


class FrontDoorRouter:
    """Routes unary inference across a ReplicaSet with hedging and a
    shared retry budget. Quacks like a BaseChannel.

    Knobs (defaults tuned for the in-process chaos rig; production
    fronts raise the timeouts):

      hedge_quantile / hedge_min_samples — the hedge delay is the
        router's own e2e latency quantile; no hedging until the
        histogram has ``hedge_min_samples`` observations, so a cold
        router never hedges on noise.
      hedge_budget_fraction — hedges may never exceed this fraction of
        routed requests (the Tail-at-Scale ~5% discipline).
      max_attempts — total attempts per request (primary + failover
        retries). Hedges do not count: a hedge is the same attempt
        raced on two replicas.
      tracer — optional obs.trace.Tracer. When set, the router is the
        trace ORIGIN: every routed request gets a TraceContext (or
        forwards an inbound one from request.trace), each attempt
        carries a child context on the wire, attempts land as sibling
        spans tagged {attempt, endpoint, kind} (hedge losers get
        cancelled=True), and the winning replica's span summary is
        grafted onto the router trace — one end-to-end timeline.
    """

    def __init__(
        self,
        endpoints: Sequence[str],
        *,
        channel_factory: Callable[[str], object] | None = None,
        models: Sequence[str] = (),
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        eject_threshold: int = 3,
        base_ejection_s: float = 1.0,
        max_ejection_s: float = 30.0,
        timeout_s: float = 30.0,
        hedge_quantile: float = 0.95,
        hedge_min_samples: int = 50,
        hedge_budget_fraction: float = 0.05,
        retry_budget_ratio: float = 0.2,
        retry_budget_cap: float = 10.0,
        max_attempts: int = 3,
        tracer=None,
    ) -> None:
        self.replica_set = ReplicaSet(
            endpoints,
            channel_factory=channel_factory,
            models=models,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            eject_threshold=eject_threshold,
            base_ejection_s=base_ejection_s,
            max_ejection_s=max_ejection_s,
            timeout_s=timeout_s,
        )
        self._timeout_s = float(timeout_s)
        self._hedge_quantile = float(hedge_quantile)
        self._hedge_min_samples = int(hedge_min_samples)
        self._hedge_budget_fraction = float(hedge_budget_fraction)
        self._max_attempts = max(1, int(max_attempts))
        self._tracer = tracer
        self._latency = LatencyHistogram()
        self._lock = threading.Lock()
        self._budget = RetryBudget(
            ratio=retry_budget_ratio, cap=retry_budget_cap
        )
        self._requests_total = 0
        self._hedges_launched = 0
        self._hedges_won = 0
        self._hedges_lost = 0
        self._hedges_denied = 0
        self._failovers = 0
        self._drain_failovers = 0
        self._affinity_routed = 0
        self._affinity_handoffs = 0
        self._errors = 0
        self._quality = None

    def attach_quality(self, quality) -> None:
        """Wire an eval.quality_plane.QualityPlane at the front door:
        canary slices are carved here (before replica pick, so every
        replica serves the rewritten model uniformly) and sampled
        responses feed the shadow mirror. The plane's mirror dispatches
        through THIS router unless it already holds a channel — shadow
        traffic then rides the same hedging/ejection machinery as live
        traffic, hitting whichever replica is healthy."""
        self._quality = quality
        if getattr(quality.mirror, "_channel", None) is None:
            quality.attach_channel(self)

    # -- BaseChannel quack ----------------------------------------------------

    def register_channel(self) -> None:  # channels dialed in __init__
        pass

    def fetch_channel(self):
        return self.replica_set

    def get_metadata(self, model_name: str, model_version: str = ""):
        """Model contract from any available replica (replicas serve
        identical repositories; first answer wins, failures fall
        through to the next replica)."""
        last: Exception | None = None
        now = time.perf_counter()
        reps = sorted(
            self.replica_set.replicas,
            key=lambda r: not r.available(now),
        )
        for rep in reps:
            try:
                return rep.channel.get_metadata(model_name, model_version)
            except Exception as e:
                last = e
        raise last if last is not None else RuntimeError("no replicas")

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """Lazy future over the routed call: the hedging state machine
        runs on whichever thread resolves the future (loadgen's
        resolver pool), so issue-side stays non-blocking."""
        return InferFuture(lambda: self.do_inference(request))

    # -- routing core ---------------------------------------------------------

    def _hedge_delay_s(self) -> float | None:
        """Current hedge trigger: the rolling e2e quantile, or None
        (no hedging) until enough samples exist to trust it."""
        snap = self._latency.snapshot()
        if snap["count"] < self._hedge_min_samples:
            return None
        from triton_client_tpu.obs.histogram import quantile_from_snapshot

        return quantile_from_snapshot(snap, self._hedge_quantile)

    def _hedge_allowed(self) -> bool:
        with self._lock:
            allowed = (
                self._hedges_launched + 1
                <= self._hedge_budget_fraction * max(self._requests_total, 20)
            )
            if not allowed:
                self._hedges_denied += 1
            return allowed

    def _launch(
        self,
        rep: Replica,
        request: InferRequest,
        done: "queue.SimpleQueue",
        kind: str,
        index: int = 0,
        ctx: TraceContext | None = None,
    ) -> _Attempt:
        """Issue one attempt on ``rep``. The done-callback releases the
        replica's in-flight slot and posts completion — it runs on the
        transport's completion thread, so it only queues. With a live
        trace context, the attempt ships a fresh child context so the
        far side's span summary names THIS attempt as its parent."""
        out = request
        if ctx is not None:
            out = dataclasses.replace(
                request, trace=_AttemptCarrier(ctx.child())
            )
        t_sent = time.perf_counter()
        fut = rep.channel.do_inference_async(out)
        att = _Attempt(rep, fut, kind, index, t_sent)
        released = []  # close over a once-flag; gRPC may double-fire

        def _on_done() -> None:
            if not released:
                released.append(True)
                self.replica_set.release(rep)
                done.put(att)

        fut.add_done_callback(_on_done)
        return att

    def do_inference(self, request: InferRequest) -> InferResponse:
        """Route one request, wrapped in the router-side trace (when a
        tracer is configured). The router either FORWARDS an inbound
        distributed context (request.trace.context — this process is a
        middle hop) or ORIGINATES one (the front-door role)."""
        trace = None
        ctx: TraceContext | None = None
        if self._tracer is not None:
            inbound = (
                getattr(request.trace, "context", None)
                if request.trace is not None else None
            )
            ctx = inbound.child() if inbound is not None else TraceContext.new()
            trace = self._tracer.start(
                model=request.model_name,
                request_id=request.request_id,
                context=ctx,
            )
        requested = request.model_name
        tid = None
        if self._quality is not None:
            # canary slice keyed on the front door's trace id — the
            # exact key any replica adopting this traceparent hashes,
            # so both tiers make the same decision for the same request
            tid = (
                ctx.trace_id if ctx is not None
                else (request.request_id or "")
            )
            served = self._quality.route(requested, tid)
            if served != requested:
                request = dataclasses.replace(request, model_name=served)
        if trace is None:
            resp = self._route(request, None, None)
            self._observe_quality(requested, request, tid, resp)
            return resp
        try:
            resp = self._route(request, trace, ctx)
        except BaseException as e:
            self._tracer.finish(
                trace, status=_status_name(e) or type(e).__name__
            )
            raise
        self._tracer.finish(trace, status="ok")
        self._observe_quality(requested, request, tid, resp)
        return resp

    def _observe_quality(self, requested, request, tid, resp) -> None:
        """Post-response sampling hook (no-op without a plane): one
        keyed hash; sampled requests copy into the mirror queue."""
        if self._quality is None:
            return
        try:
            self._quality.observe(
                requested, request.model_name, tid or "",
                request.inputs, resp.outputs,
            )
        except Exception:
            log.debug("quality observe failed", exc_info=True)

    @staticmethod
    def _attempt_span(trace, att: _Attempt, **extra) -> None:
        """Close ``att``'s sibling span on the router trace (no-op when
        untraced): one ``attempt`` span per launch, siblings told apart
        by their {attempt, endpoint, kind} tags."""
        if trace is not None:
            trace.add(
                "attempt", att.t_sent, time.perf_counter(), att.attrs(**extra)
            )

    def _route(
        self,
        request: InferRequest,
        trace,
        ctx: TraceContext | None,
    ) -> InferResponse:
        t0 = time.perf_counter()
        with self._lock:
            self._requests_total += 1
            self._budget.deposit()
        deadline = request.deadline_s
        done: queue.SimpleQueue = queue.SimpleQueue()
        stream_id = request.sequence_id
        if stream_id:
            # stateful request: the stream's device-resident session
            # lives on exactly one replica. Rendezvous hashing pins the
            # stream there, and hedging is OFF — a hedge would run the
            # tracking step twice and corrupt the session's frame order.
            hedge_delay = None
            rep = self.replica_set.pick_affinity(stream_id)
            with self._lock:
                self._affinity_routed += 1
        else:
            hedge_delay = self._hedge_delay_s()
            rep = self.replica_set.pick()
        if rep is None:
            raise RuntimeError("replica set is empty")
        outstanding = [self._launch(rep, request, done, "primary", 0, ctx)]
        attempts_made = 1
        attempt_idx = 0  # span ordinal: hedges count, unlike attempts_made
        hedge_spent = False
        last_error: BaseException | None = None

        while True:
            # -- wait for the next completion (or the hedge trigger) --
            timeout: float | None = None
            if deadline is not None:
                timeout = max(deadline - time.perf_counter(), 0.001)
            if (
                hedge_delay is not None
                and not hedge_spent
                and len(outstanding) == 1
            ):
                until_hedge = max(t0 + hedge_delay - time.perf_counter(), 0.0)
                timeout = (
                    until_hedge if timeout is None
                    else min(timeout, until_hedge)
                )
            try:
                att = done.get(timeout=timeout)
            except queue.Empty:
                if (
                    deadline is not None
                    and time.perf_counter() >= deadline
                ):
                    # nobody is waiting anymore: abandon what's in
                    # flight (their callbacks release the slots) and
                    # surface the deadline
                    for o in outstanding:
                        o.future.cancel()
                        self._attempt_span(trace, o, cancelled=True)
                    self._count_error()
                    raise _deadline_error(
                        "router deadline expired with %d attempt(s) in "
                        "flight" % len(outstanding)
                    )
                # hedge trigger
                hedge_spent = True  # one hedge per request, win or lose
                if self._hedge_allowed():
                    hrep = self.replica_set.pick(
                        exclude=[o.replica for o in outstanding]
                    )
                    if hrep is not None:
                        with self._lock:
                            self._hedges_launched += 1
                        attempt_idx += 1
                        if log.isEnabledFor(logging.DEBUG):
                            log.debug(
                                "hedging on %s after %.1f ms%s",
                                hrep.endpoint, hedge_delay * 1e3,
                                log_tag(trace, request.request_id),
                            )
                        outstanding.append(
                            self._launch(
                                hrep, request, done, "hedge",
                                attempt_idx, ctx,
                            )
                        )
                continue

            # -- one attempt resolved --
            outstanding = [o for o in outstanding if o is not att]
            try:
                resp = att.future.result()
            except BaseException as e:
                last_error = e
                self._attempt_span(
                    trace, att, error=_status_name(e) or type(e).__name__
                )
                handled_retry = self._on_attempt_failure(att, e)
                if not handled_retry:
                    # non-retryable (shed / deadline / unknown): losers
                    # in flight can no longer change the outcome
                    for o in outstanding:
                        o.future.cancel()
                        self._attempt_span(trace, o, cancelled=True)
                    self._count_error()
                    raise
                if outstanding:
                    # the raced hedge is already the retry
                    continue
                retry_rep = self._try_retry(
                    att, e, attempts_made, deadline,
                    tag=log_tag(trace, request.request_id),
                    stream_id=stream_id,
                )
                if retry_rep is None:
                    self._count_error()
                    raise
                if stream_id:
                    # explicit failover handoff: the session re-homes
                    # to the rendezvous runner-up and RESTARTS there —
                    # sequence_start forces a fresh epoch (disjoint
                    # track ids), never a resume of state the old owner
                    # still holds
                    request = dataclasses.replace(
                        request, sequence_start=True
                    )
                    with self._lock:
                        self._affinity_handoffs += 1
                    log.warning(
                        "stream %s re-homed %s -> %s (session restarts)%s",
                        stream_id, att.replica.endpoint, retry_rep.endpoint,
                        log_tag(trace, request.request_id),
                    )
                attempts_made += 1
                attempt_idx += 1
                outstanding.append(
                    self._launch(
                        retry_rep, request, done, "retry", attempt_idx, ctx
                    )
                )
                continue

            # -- winner --
            t_recv = time.perf_counter()
            self.replica_set.record_success(att.replica)
            hedge_in_flight = any(o.kind == "hedge" for o in outstanding)
            for o in outstanding:
                o.future.cancel()
                # hedge losers stay visible: a sibling span tagged
                # cancelled=True, with NO server summary grafted — the
                # joined timeline counts device time exactly once
                self._attempt_span(trace, o, cancelled=True)
            if trace is not None:
                self._attempt_span(trace, att)
                summary = decode_span_summary(
                    (resp.parameters or {}).get(SUMMARY_PARAM_KEY, "")
                )
                if summary is not None:
                    graft_span_summary(
                        trace, summary, att.t_sent, t_recv,
                        attrs=att.attrs(),
                    )
                trace.add("route", t0, time.perf_counter())
            with self._lock:
                if att.kind == "hedge":
                    self._hedges_won += 1
                elif hedge_in_flight:
                    self._hedges_lost += 1
            self._latency.observe(time.perf_counter() - t0)
            return resp

    def _on_attempt_failure(self, att: _Attempt, exc: BaseException) -> bool:
        """Classify one failed attempt; update health. Returns True if
        the failure class is retryable on another replica."""
        name = _status_name(exc)
        if name in _CONNECTION_CLASS:
            if _is_draining(exc):
                # orchestrated drain: pull from rotation, no ejection
                # streak, and the retry is free (not the fleet's fault)
                self.replica_set.mark_draining(att.replica)
            else:
                self.replica_set.record_failure(
                    att.replica, connection_class=True
                )
            return True
        if name == _SHED:
            # deliberate admission shed: retrying feeds the overload
            # the server is shedding; surface it as an accounted shed
            self.replica_set.record_failure(
                att.replica, connection_class=False
            )
            return False
        if name == _DEADLINE:
            self.replica_set.record_failure(
                att.replica, connection_class=False
            )
            return False
        # unknown / application error: count, don't eject, don't retry
        # (the model said no; another replica will say the same no)
        self.replica_set.record_failure(att.replica, connection_class=False)
        return False

    def _try_retry(
        self,
        att: _Attempt,
        exc: BaseException,
        attempts_made: int,
        deadline: float | None,
        tag: str = "",
        stream_id: str = "",
    ) -> Replica | None:
        """Gate + pick for a failover retry. Drain failovers skip the
        budget (orchestrated, not a fault); everything else spends a
        token. Stateful streams re-pick by rendezvous (minus the dead
        owner), so every frame of a re-homed stream lands on the SAME
        survivor. Returns the replica to retry on, or None to
        surface."""
        if attempts_made >= self._max_attempts:
            return None
        if deadline is not None and time.perf_counter() >= deadline:
            return None
        draining = _is_draining(exc)
        if not draining:
            with self._lock:
                if not self._budget.try_spend():
                    log.warning(
                        "retry budget at floor (%d denials); surfacing "
                        "failure from %s%s",
                        self._budget.floor_hits, att.replica.endpoint, tag,
                    )
                    return None
        if stream_id:
            rep = self.replica_set.pick_affinity(
                stream_id, exclude=[att.replica]
            )
        else:
            rep = self.replica_set.pick(exclude=[att.replica])
        if rep is None:
            return None
        with self._lock:
            self._failovers += 1
            if draining:
                self._drain_failovers += 1
        return rep

    def _count_error(self) -> None:
        with self._lock:
            self._errors += 1

    # -- surface --------------------------------------------------------------

    def stats(self) -> dict:
        """Flat counters, collector-style (tests and perf scripts diff
        two of these)."""
        hedge_delay = self._hedge_delay_s()
        with self._lock:
            return {
                "requests_total": self._requests_total,
                "errors_total": self._errors,
                "hedges_launched": self._hedges_launched,
                "hedges_won": self._hedges_won,
                "hedges_lost": self._hedges_lost,
                "hedges_denied": self._hedges_denied,
                "failovers": self._failovers,
                "drain_failovers": self._drain_failovers,
                "affinity_routed": self._affinity_routed,
                "affinity_handoffs": self._affinity_handoffs,
                "retry_budget_tokens": self._budget.tokens,
                "retry_budget_floor_hits": self._budget.floor_hits,
                "retries_spent": self._budget.spent,
                "ejections_total": self.replica_set.ejections_total,
                "replicas_total": len(self.replica_set.replicas),
                "replicas_available": self.replica_set.available_count(),
                "hedge_delay_s": hedge_delay if hedge_delay else 0.0,
            }

    def snapshot(self) -> dict:
        """stats() plus per-replica detail and the latency histogram —
        the structured read the route CLI and the collector export."""
        snap = self.stats()
        snap["replicas"] = self.replica_set.snapshot()
        snap["latency"] = self._latency.snapshot()
        if self._quality is not None:
            snap["quality"] = self._quality.snapshot()
        return snap

    def close(self) -> None:
        self.replica_set.close()


def _deadline_error(msg: str):
    """The channel's client-local DEADLINE_EXCEEDED, reused so callers
    classify router deadline failures like any other."""
    from triton_client_tpu.channel.grpc_channel import (
        DeadlineExceededRpcError,
    )

    return DeadlineExceededRpcError(msg)


class RouterCollector:
    """Prometheus custom collector over a FrontDoorRouter snapshot.

    Registered the same way RuntimeCollector is (obs/collector.py):
    ``registry.register(RouterCollector(router))``. Import of
    prometheus_client is deferred to collect() so the router works on
    images without it."""

    def __init__(self, router: FrontDoorRouter) -> None:
        self._router = router

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        snap = self._router.snapshot()
        counters = {
            "tpu_router_requests_total": ("requests_total", "routed requests"),
            "tpu_router_errors_total": ("errors_total", "surfaced errors"),
            "tpu_router_hedges_total": ("hedges_launched", "hedges launched"),
            "tpu_router_hedges_won_total": ("hedges_won", "hedges that won"),
            "tpu_router_failovers_total": ("failovers", "failover retries"),
            "tpu_router_affinity_routed_total": (
                "affinity_routed", "stream requests routed by rendezvous"
            ),
            "tpu_router_affinity_handoffs_total": (
                "affinity_handoffs", "stream sessions re-homed on failover"
            ),
            "tpu_router_ejections_total": ("ejections_total", "ejections"),
            "tpu_router_retry_budget_floor_total": (
                "retry_budget_floor_hits", "retries denied at budget floor"
            ),
        }
        for fam, (key, help_) in counters.items():
            c = CounterMetricFamily(fam, help_)
            c.add_metric([], float(snap[key]))
            yield c
        g = GaugeMetricFamily(
            "tpu_router_retry_budget_tokens", "retry-budget token level"
        )
        g.add_metric([], float(snap["retry_budget_tokens"]))
        yield g
        g = GaugeMetricFamily(
            "tpu_router_hedge_delay_seconds", "current hedge trigger delay"
        )
        g.add_metric([], float(snap["hedge_delay_s"]))
        yield g
        healthy = GaugeMetricFamily(
            "tpu_router_replica_available",
            "1 if the replica is in rotation",
            labels=["endpoint"],
        )
        inflight = GaugeMetricFamily(
            "tpu_router_replica_inflight",
            "live in-flight attempts on the replica",
            labels=["endpoint"],
        )
        for r in snap["replicas"]:
            ok = r["probe_ready"] and not r["draining"] and not r["ejected"]
            healthy.add_metric([r["endpoint"]], 1.0 if ok else 0.0)
            inflight.add_metric([r["endpoint"]], float(r["inflight"]))
        yield healthy
        yield inflight
