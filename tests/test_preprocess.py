"""Preprocess + decode kernels."""

import numpy as np
import jax.numpy as jnp

from triton_client_tpu.ops import (
    normalize_image,
    letterbox,
    resize_bilinear,
    image_to_nchw,
    decode_yolo_grid,
)


def test_normalize_modes(rng):
    img = rng.integers(0, 255, size=(8, 8, 3)).astype(np.uint8)
    x = jnp.asarray(img)
    np.testing.assert_allclose(np.asarray(normalize_image(x, "yolo")), img / 255.0, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(normalize_image(x, "inception")),
        img / 127.5 - 1.0,
        rtol=1e-5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(normalize_image(x, "coco")), img / 255.0, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(normalize_image(x, "none")), img.astype(np.float32)
    )
    vgg = np.asarray(normalize_image(x, "vgg"))
    np.testing.assert_allclose(vgg, img - np.array([123.0, 117.0, 104.0]), rtol=1e-5)


def test_resize_shape():
    img = jnp.zeros((480, 640, 3), jnp.uint8)
    out = resize_bilinear(img, (512, 512))
    assert out.shape == (512, 512, 3)


def test_letterbox_geometry():
    # 200x100 (h, w) into 400x400: gain 2 -> content 400x200, pad_x 100.
    img = jnp.full((200, 100, 3), 255, jnp.uint8)
    out, meta = letterbox(img, (400, 400))
    out, meta = np.asarray(out), np.asarray(meta)
    assert out.shape == (400, 400, 3)
    np.testing.assert_allclose(meta, [2.0, 100.0, 0.0])
    assert np.all(out[:, :100] == 114.0)  # left pad
    assert np.all(out[:, 300:] == 114.0)  # right pad
    assert np.all(out[:, 100:300] == 255.0)  # content


def test_image_to_nchw():
    img = jnp.zeros((512, 256, 3))
    assert image_to_nchw(img).shape == (1, 3, 512, 256)


def test_decode_v5_center_cell():
    """A zero logit decodes to the cell center with anchor-sized box."""
    h = w = 4
    raw = np.zeros((1, h, w, 3, 7), np.float32)
    anchors = np.array([[10, 13], [16, 30], [33, 23]], np.float32)
    out = np.asarray(decode_yolo_grid(jnp.asarray(raw), anchors, stride=8))
    assert out.shape == (1, h * w * 3, 7)
    # sigmoid(0) = 0.5 -> xy = (2*0.5 - 0.5 + g) * 8 = (g + 0.5)*8
    # wh = (2*0.5)^2 * anchor = anchor
    first = out[0, 0]  # grid cell (0, 0), anchor 0
    np.testing.assert_allclose(first[:2], [4.0, 4.0], rtol=1e-5)
    np.testing.assert_allclose(first[2:4], [10.0, 13.0], rtol=1e-5)
    np.testing.assert_allclose(first[4:], 0.5, rtol=1e-5)


def test_decode_v4_normalized():
    h = w = 2
    raw = np.zeros((1, h, w, 1, 6), np.float32)
    anchors = np.array([[32, 32]], np.float32)
    out = np.asarray(
        decode_yolo_grid(
            jnp.asarray(raw), anchors, stride=16, variant="v4", normalize_hw=(32, 32)
        )
    )
    # sigmoid(0)=0.5 -> xy=(0.5 + g)*16, normalized /32
    np.testing.assert_allclose(out[0, 0, :2], [0.25, 0.25], rtol=1e-5)
    # wh = exp(0)*32 / 32 = 1.0
    np.testing.assert_allclose(out[0, 0, 2:4], [1.0, 1.0], rtol=1e-5)


def test_decode_grid_offsets_distinct():
    h = w = 8
    raw = np.zeros((1, h, w, 3, 7), np.float32)
    anchors = np.array([[10, 13], [16, 30], [33, 23]], np.float32)
    out = np.asarray(decode_yolo_grid(jnp.asarray(raw), anchors, stride=8))
    xy = out[0, :, :2]
    # all 64 cells produce distinct centers per anchor
    assert len({tuple(p) for p in xy[::3].tolist()}) == h * w
