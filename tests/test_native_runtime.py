"""Native C++ runtime: queue/batcher/arena semantics + BatchingChannel.

The reference outsources these to the Triton server binary (SURVEY.md
§2.9); here they are in-tree, so they get the unit coverage Triton's
dynamic batcher gets upstream: size-triggered closes, timeout-triggered
closes, admission control, priority ordering, and end-to-end coalescing
through the channel seam.
"""

import threading
import time

import numpy as np
import pytest

from triton_client_tpu.channel.base import BaseChannel, InferRequest, InferResponse
from triton_client_tpu.runtime.batching import BatchingChannel

try:
    from triton_client_tpu.native import Arena, NativeBatchServer

    NATIVE = True
except Exception:  # pragma: no cover - toolchain-less environments
    NATIVE = False

needs_native = pytest.mark.skipif(not NATIVE, reason="native toolchain unavailable")


@needs_native
class TestNativeBatchServer:
    def test_size_triggered_close(self):
        got = []
        done = threading.Event()

        def on_batch(ids):
            got.append(list(ids))
            if sum(len(b) for b in got) >= 8:
                done.set()

        srv = NativeBatchServer(on_batch, max_batch=4, timeout_us=500_000)
        with srv:
            for i in range(8):
                assert srv.enqueue(i)
            assert done.wait(5.0)
        assert [len(b) for b in got] == [4, 4]
        stats_sizes = sorted(x for b in got for x in b)
        assert stats_sizes == list(range(8))

    def test_timeout_triggered_close(self):
        got = []
        done = threading.Event()

        def on_batch(ids):
            got.append(list(ids))
            done.set()

        srv = NativeBatchServer(on_batch, max_batch=64, timeout_us=10_000)
        with srv:
            srv.enqueue(42)
            t0 = time.perf_counter()
            assert done.wait(5.0)
            waited = time.perf_counter() - t0
            stats = srv.stats()
        assert got == [[42]]
        assert waited < 1.0  # closed by the 10ms window, not the 5s guard
        assert stats["timeout_closes"] >= 1

    def test_priority_order(self):
        got = []
        done = threading.Event()
        release = threading.Event()

        def on_batch(ids):
            release.wait(5.0)  # hold the first batch until all enqueued
            got.append(list(ids))
            if len(got) >= 2:
                done.set()

        srv = NativeBatchServer(on_batch, max_batch=2, timeout_us=1_000)
        with srv:
            srv.enqueue(1, priority=0)
            srv.enqueue(2, priority=0)
            time.sleep(0.05)  # let batch 1 form and block in the callback
            srv.enqueue(3, priority=0)
            srv.enqueue(4, priority=1)  # high priority jumps the line
            release.set()
            assert done.wait(5.0)
        assert got[1][0] == 4

    def test_admission_control(self):
        blocked = threading.Event()

        def on_batch(ids):
            blocked.wait(2.0)

        srv = NativeBatchServer(on_batch, max_batch=1, timeout_us=100, capacity=2)
        with srv:
            time.sleep(0.02)
            results = [srv.enqueue(i) for i in range(8)]
            blocked.set()
            stats = srv.stats()
        # Capacity 2: at least one admitted, several rejected.
        assert any(results) and not all(results)
        assert stats["rejected_full"] >= 1

    def test_drain_on_stop(self):
        got = []

        def on_batch(ids):
            got.extend(ids)

        srv = NativeBatchServer(on_batch, max_batch=4, timeout_us=1_000_000)
        srv.start()
        for i in range(3):
            srv.enqueue(i)
        srv.stop()  # must dispatch the partial batch, not drop it
        assert sorted(got) == [0, 1, 2]
        srv.close()


@needs_native
class TestArena:
    def test_acquire_release_cycle(self):
        arena = Arena(slot_bytes=1024, n_slots=2)
        a = arena.acquire((16, 16), np.float32)
        b = arena.acquire((256,), np.float32)
        assert arena.free_slots() == 0
        assert arena.acquire((4,), np.float32) is None  # exhausted
        a[:] = 7.0
        np.testing.assert_array_equal(np.asarray(a), np.full((16, 16), 7.0))
        arena.release(a)
        assert arena.free_slots() == 1
        c = arena.acquire((8,), np.uint8)
        assert c is not None
        arena.release(b)
        arena.release(c)
        arena.close()

    def test_oversized_request_rejected(self):
        arena = Arena(slot_bytes=64, n_slots=1)
        with pytest.raises(ValueError):
            arena.acquire((1024,), np.float32)
        arena.close()

    def test_foreign_array_rejected(self):
        arena = Arena(slot_bytes=64, n_slots=1)
        with pytest.raises(ValueError):
            arena.release(np.zeros(4, np.float32))
        arena.close()


class _EchoChannel(BaseChannel):
    """Records the batch sizes it sees; output = input + 1."""

    def __init__(self):
        self.batch_sizes = []

    def register_channel(self):
        pass

    def fetch_channel(self):
        return None

    def get_metadata(self, model_name, model_version=""):
        raise KeyError(model_name)

    def do_inference(self, request: InferRequest) -> InferResponse:
        x = np.asarray(request.inputs["x"])
        self.batch_sizes.append(x.shape[0])
        return InferResponse(
            model_name=request.model_name,
            outputs={"y": x + 1.0},
            request_id=request.request_id,
        )


@pytest.mark.parametrize("use_native", [True, False])
def test_batching_channel_coalesces(use_native):
    inner = _EchoChannel()
    channel = BatchingChannel(
        inner, max_batch=8, timeout_us=20_000, use_native=use_native
    )
    frames = [np.full((1, 4), float(i), np.float32) for i in range(8)]

    results = [None] * len(frames)

    def call(i):
        results[i] = channel.do_inference(
            InferRequest(model_name="m", inputs={"x": frames[i]}, request_id=str(i))
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(len(frames))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    channel.close()

    for i, r in enumerate(results):
        assert r is not None
        np.testing.assert_array_equal(r.outputs["y"], frames[i] + 1.0)
        assert r.request_id == str(i)
    # Coalescing happened: fewer inner calls than requests.
    assert len(inner.batch_sizes) < len(frames)
    assert sum(inner.batch_sizes) == len(frames)


def test_batching_channel_mixed_shapes_not_merged():
    inner = _EchoChannel()
    channel = BatchingChannel(inner, max_batch=8, timeout_us=20_000, use_native=False)
    a = np.zeros((1, 4), np.float32)
    b = np.zeros((1, 6), np.float32)
    out = {}

    def call(name, arr):
        out[name] = channel.do_inference(InferRequest(model_name="m", inputs={"x": arr}))

    threads = [
        threading.Thread(target=call, args=("a", a)),
        threading.Thread(target=call, args=("b", b)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    channel.close()
    assert out["a"].outputs["y"].shape == (1, 4)
    assert out["b"].outputs["y"].shape == (1, 6)


class _SlowEchoChannel(_EchoChannel):
    """Echo with a fixed per-dispatch latency and an in-flight counter
    — models the tunnel's ~1 s un-amortized dispatch."""

    def __init__(self, delay_s=0.15):
        super().__init__()
        self.delay_s = delay_s
        self._active = 0
        self.max_concurrent = 0
        self._lk = threading.Lock()

    def do_inference(self, request):
        with self._lk:
            self._active += 1
            self.max_concurrent = max(self.max_concurrent, self._active)
        try:
            time.sleep(self.delay_s)
            return super().do_inference(request)
        finally:
            with self._lk:
                self._active -= 1


@pytest.mark.parametrize("use_native", [True, False])
def test_pipelined_batches_overlap(use_native):
    """pipeline_depth=2: two formed batches execute concurrently
    against the inner channel, so N batches of fixed-latency dispatch
    take ~N/2 wall — and every response still matches its request."""
    inner = _SlowEchoChannel(delay_s=0.15)
    channel = BatchingChannel(
        inner, max_batch=1, timeout_us=500, use_native=use_native,
        pipeline_depth=2,
    )
    n = 8
    frames = [np.full((1, 4), float(i), np.float32) for i in range(n)]
    results = [None] * n

    def call(i):
        results[i] = channel.do_inference(
            InferRequest(model_name="m", inputs={"x": frames[i]},
                         request_id=str(i))
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    wall = time.perf_counter() - t0
    channel.close()
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.outputs["y"], frames[i] + 1.0)
    assert inner.max_concurrent == 2          # overlap really happened
    # serial would be n*delay = 1.2 s; pipelined ~0.6 s. Generous slack
    # (0.9x serial) keeps a loaded 1-core CI host from flaking — the
    # max_concurrent assert above is the real overlap proof
    assert wall < inner.delay_s * n * 0.9, wall


def test_pipeline_depth_one_is_serial():
    inner = _SlowEchoChannel(delay_s=0.05)
    channel = BatchingChannel(
        inner, max_batch=1, timeout_us=500, use_native=False,
        pipeline_depth=1,
    )
    n = 4
    results = [None] * n

    def call(i):
        results[i] = channel.do_inference(
            InferRequest(model_name="m",
                         inputs={"x": np.full((1, 4), float(i), np.float32)})
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    channel.close()
    assert inner.max_concurrent == 1
    assert all(r is not None for r in results)


def test_close_drains_inflight_batches():
    """close() must not strand admitted requests: every future
    resolves (result or exception) before close returns."""
    inner = _SlowEchoChannel(delay_s=0.2)
    channel = BatchingChannel(
        inner, max_batch=1, timeout_us=500, use_native=False,
        pipeline_depth=2,
    )
    results = []

    def call(i):
        try:
            results.append(
                channel.do_inference(
                    InferRequest(
                        model_name="m",
                        inputs={"x": np.full((1, 4), float(i), np.float32)},
                    )
                )
            )
        except Exception as e:
            results.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)  # let some batches get in flight
    channel.close()
    for t in threads:
        t.join(timeout=10.0)
    assert len(results) == 4  # nobody hangs


@pytest.mark.parametrize("use_native", [True, False])
def test_two_models_never_cross_merge(use_native):
    """Concurrent requests to TWO models through one batcher: merge
    keys isolate them — every response comes from its own model even
    when the queue interleaves them (the Triton dynamic batcher's
    per-model grouping contract)."""

    class _TwoModelChannel(_EchoChannel):
        def do_inference(self, request):
            x = np.asarray(request.inputs["x"])
            self.batch_sizes.append(x.shape[0])
            delta = 1.0 if request.model_name == "plus1" else 100.0
            return InferResponse(
                model_name=request.model_name,
                outputs={"y": x + delta},
                request_id=request.request_id,
            )

    inner = _TwoModelChannel()
    channel = BatchingChannel(
        inner, max_batch=8, timeout_us=20_000, use_native=use_native,
        pipeline_depth=2,
    )
    n = 12
    results = [None] * n

    def call(i):
        model = "plus1" if i % 2 == 0 else "plus100"
        results[i] = (
            model,
            channel.do_inference(
                InferRequest(
                    model_name=model,
                    inputs={"x": np.full((1, 4), float(i), np.float32)},
                )
            ),
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    channel.close()
    assert all(r is not None for r in results)  # no worker died/hung
    for i, (model, resp) in enumerate(results):
        want = i + (1.0 if model == "plus1" else 100.0)
        np.testing.assert_array_equal(
            resp.outputs["y"], np.full((1, 4), want, np.float32)
        )
        assert resp.model_name == model
    assert sum(inner.batch_sizes) == n


@pytest.mark.parametrize("use_native", [True, False])
def test_dispatch_time_remerge_exceeds_admission_window(use_native):
    """Round-4 two-stage formation (VERDICT r3 #2): while the device
    is busy, requests released by SEPARATE admission windows pool in
    the dispatcher and re-coalesce into one device batch capped by
    max_merge, not max_batch. r3's fixed 3 ms window shipped 4/8
    occupancy fragments; slot-time formation must beat the window."""
    inner = _SlowEchoChannel(delay_s=0.2)
    channel = BatchingChannel(
        inner, max_batch=2, timeout_us=200, use_native=use_native,
        pipeline_depth=1, max_merge=16,
    )
    n = 12
    results = [None] * n

    def call(i):
        results[i] = channel.do_inference(
            InferRequest(model_name="m",
                         inputs={"x": np.full((1, 4), float(i), np.float32)},
                         request_id=str(i))
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    channel.close()
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.outputs["y"],
                                      np.full((1, 4), i + 1.0, np.float32))
    # the first slot takes whatever arrived; everything admitted while
    # it executed (tiny 0.2 ms windows -> many 1-2 frame releases)
    # must fuse into far fewer device calls than admission windows
    assert sum(inner.batch_sizes) == n
    assert max(inner.batch_sizes) > 2, inner.batch_sizes
    assert len(inner.batch_sizes) <= 6, inner.batch_sizes


def test_pad_to_buckets_rounds_device_batch_up():
    """pad_to_buckets: the inner channel only ever sees power-of-two
    batch sizes (replicated-row padding, pad outputs discarded), so a
    precompiling inner channel needs log2(max_merge)+1 executables."""
    inner = _SlowEchoChannel(delay_s=0.1)
    channel = BatchingChannel(
        inner, max_batch=8, timeout_us=50_000, use_native=False,
        pipeline_depth=1, pad_to_buckets=True,
    )
    n = 3
    results = [None] * n

    def call(i):
        results[i] = channel.do_inference(
            InferRequest(model_name="m",
                         inputs={"x": np.full((1, 4), float(i), np.float32)})
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    channel.close()
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.outputs["y"],
                                      np.full((1, 4), i + 1.0, np.float32))
    assert all(b in (1, 2, 4, 8) for b in inner.batch_sizes), inner.batch_sizes
    stats = channel.stats()
    assert stats["padded_frames"] >= 0
    assert stats["merges"] == len(inner.batch_sizes)


def test_oversized_request_passes_through_unpadded():
    """A single request larger than max_merge runs as-is: rounding a
    rare b5 up to b8 would waste more than it amortizes."""
    inner = _EchoChannel()
    channel = BatchingChannel(
        inner, max_batch=2, timeout_us=500, use_native=False,
        pipeline_depth=1, max_merge=4, pad_to_buckets=True,
    )
    resp = channel.do_inference(
        InferRequest(model_name="m",
                     inputs={"x": np.zeros((5, 4), np.float32)})
    )
    channel.close()
    assert resp.outputs["y"].shape == (5, 4)
    assert inner.batch_sizes == [5]


def test_merge_hold_coalesces_staggered_burst():
    """merge_hold_us: a burst whose arrivals straggle past the first
    dispatch opportunity coalesces into one device batch instead of
    shipping a fragment (the hold re-waits its remaining window after
    each arrival notify, so early wakeups don't end it)."""
    inner = _SlowEchoChannel(delay_s=0.05)
    channel = BatchingChannel(
        inner, max_batch=1, timeout_us=100, use_native=False,
        pipeline_depth=1, max_merge=8, merge_hold_us=150_000,
    )
    n = 6
    results = [None] * n

    def call(i):
        time.sleep(0.01 * i)  # staggered arrivals, ~50 ms span
        results[i] = channel.do_inference(
            InferRequest(model_name="m",
                         inputs={"x": np.full((1, 4), float(i), np.float32)})
        )

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    channel.close()
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.outputs["y"],
                                      np.full((1, 4), i + 1.0, np.float32))
    # admission released them one-by-one (max_batch=1, 100 us window);
    # without the hold the first dispatch ships b1 — with it, the
    # whole stagger span fits in one batch (2 allowed for scheduling
    # slop on a loaded CI host)
    assert len(inner.batch_sizes) <= 2, inner.batch_sizes
    assert max(inner.batch_sizes) >= n - 1, inner.batch_sizes


@needs_native
def test_batching_decomposition_and_arena_staging():
    """Round 5 (VERDICT r4 Weak #3/#6): the serving path consumes the
    native arena — merged device batches stage through recycled
    aligned slots — and stats() decomposes per-batch wall into
    queue-wait / exec-wait / stage / device."""
    import numpy as np

    from triton_client_tpu.channel.base import BaseChannel, InferRequest, InferResponse
    from triton_client_tpu.runtime.batching import BatchingChannel

    class Echo(BaseChannel):
        seen_aligned = []

        def do_inference(self, request):
            out = np.asarray(request.inputs["images"])
            assert out.flags["C_CONTIGUOUS"]
            # solo requests (batch formation edge) arrive as user
            # arrays; only merged batches ride arena slots — record
            # alignment rather than asserting on every path
            Echo.seen_aligned.append(out.ctypes.data % 64 == 0)
            return InferResponse(
                model_name=request.model_name, model_version="1",
                outputs={"y": out.sum(axis=(1, 2, 3))},
            )

        def get_metadata(self, *a, **k):  # pragma: no cover
            raise NotImplementedError

        def register_channel(self):  # pragma: no cover
            pass

        def fetch_channel(self):  # pragma: no cover
            return None

    ch = BatchingChannel(
        Echo(), max_batch=4, timeout_us=1000, max_merge=8,
        pad_to_buckets=True, arena_slots=4,
    )
    try:
        import concurrent.futures as cf

        frames = [
            np.full((1, 8, 8, 3), i, np.float32) for i in range(12)
        ]
        with cf.ThreadPoolExecutor(8) as pool:
            outs = list(
                pool.map(
                    lambda f: ch.do_inference(
                        InferRequest(model_name="m", inputs={"images": f})
                    ),
                    frames,
                )
            )
        for i, resp in enumerate(outs):
            np.testing.assert_allclose(
                np.asarray(resp.outputs["y"]), [i * 8 * 8 * 3]
            )
        stats = ch.stats()
        assert stats.get("decomp_batches", 0) >= 1
        d = stats["decomp_ms"]
        assert set(d) == {"queue_wait", "exec_wait", "stage", "device"}
        assert all(v >= 0 for v in d.values())
        # the arena existed, merged batches rode aligned slots, and
        # every slot was recycled
        assert any(Echo.seen_aligned)
        assert stats.get("arena_free_slots") == 4
    finally:
        ch.close()
