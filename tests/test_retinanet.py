"""RetinaNet/FCOS (detectron family): anchors, decode, model contracts.

Reference parity targets: examples/RetinaNet_detectron/config.pbtxt
(640x480, boxes/classes/scores/dims) and the FCOS_client/detectron
postprocess semantics (clients/postprocess/detectron_postprocess.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_client_tpu.ops.anchor_decode import (
    RETINA_STRIDES,
    cell_anchors,
    decode_deltas,
    fcos_decode,
    fcos_locations,
    pyramid_anchors,
)
from triton_client_tpu.ops.detect_postprocess import extract_boxes_scored

INPUT_HW = (96, 128)  # small, non-square: catches H/W transposes


class TestAnchors:
    def test_cell_anchor_geometry(self):
        a = cell_anchors(32.0)
        assert a.shape == (9, 4)
        # All centered at origin.
        centers = (a[:, :2] + a[:, 2:]) / 2
        np.testing.assert_allclose(centers, 0.0, atol=1e-4)
        # The 1:1 anchor at octave 0 is exactly 32x32.
        w = a[:, 2] - a[:, 0]
        h = a[:, 3] - a[:, 1]
        assert any(abs(wi - 32) < 1e-3 and abs(hi - 32) < 1e-3 for wi, hi in zip(w, h))
        # Aspect ratios h/w cover {0.5, 1, 2}.
        ratios = sorted(set(np.round(h / w, 3)))
        np.testing.assert_allclose(ratios, [0.5, 1.0, 2.0], rtol=1e-3)

    def test_pyramid_count_and_coverage(self):
        anchors = pyramid_anchors(INPUT_HW)
        n = sum(
            -(-INPUT_HW[0] // s) * -(-INPUT_HW[1] // s) * 9 for s in RETINA_STRIDES
        )
        assert anchors.shape == (n, 4)
        # First-level anchors are centered on the stride-8 grid.
        first = anchors[:9]
        centers = (first[:, :2] + first[:, 2:]) / 2
        np.testing.assert_allclose(centers, 4.0, atol=1e-4)

    def test_decode_zero_deltas_identity(self):
        anchors = pyramid_anchors(INPUT_HW)
        out = decode_deltas(jnp.asarray(anchors), jnp.zeros((anchors.shape[0], 4)))
        np.testing.assert_allclose(np.asarray(out), anchors, rtol=1e-5, atol=1e-3)

    def test_decode_shift_and_scale(self):
        anchors = jnp.asarray([[0.0, 0.0, 10.0, 10.0]])
        # dx=0.5 anchor-widths right, dw=log(2) doubles width.
        deltas = jnp.asarray([[[0.5, 0.0, np.log(2.0), 0.0]]])
        out = np.asarray(decode_deltas(anchors, deltas))[0, 0]
        np.testing.assert_allclose(out, [0.0, 0.0, 20.0, 10.0], atol=1e-4)

    def test_fcos_decode(self):
        locs = jnp.asarray(fcos_locations((16, 16), strides=(8,)))
        assert locs.shape == (4, 2)
        ltrb = jnp.full((1, 4, 4), 2.0)
        boxes = np.asarray(fcos_decode(locs, ltrb))
        # First location is (4, 4): box = [2, 2, 6, 6].
        np.testing.assert_allclose(boxes[0, 0], [2.0, 2.0, 6.0, 6.0], atol=1e-5)


class TestExtractScored:
    def test_planted_box_survives(self):
        n, nc = 64, 3
        boxes = np.tile(np.array([0.0, 0.0, 8.0, 8.0], np.float32), (n, 1))
        boxes += np.arange(n, dtype=np.float32)[:, None] * 10  # disjoint
        scores = np.full((n, nc), 0.01, np.float32)
        scores[5, 1] = 0.9
        scores[17, 2] = 0.8
        dets, valid = extract_boxes_scored(
            jnp.asarray(boxes)[None], jnp.asarray(scores)[None], conf_thresh=0.05
        )
        dets, valid = np.asarray(dets)[0], np.asarray(valid)[0]
        assert valid.sum() == 2
        assert dets[0, 4] == pytest.approx(0.9, rel=1e-5)
        assert int(dets[0, 5]) == 1
        np.testing.assert_allclose(dets[0, :4], boxes[5], rtol=1e-5)
        assert dets[1, 4] == pytest.approx(0.8, rel=1e-5)

    def test_multilabel_emits_both_classes(self):
        boxes = np.array([[0.0, 0.0, 10.0, 10.0]], np.float32)
        scores = np.array([[0.7, 0.6]], np.float32)
        dets, valid = extract_boxes_scored(
            jnp.asarray(boxes)[None],
            jnp.asarray(scores)[None],
            conf_thresh=0.05,
            multi_label=True,
        )
        # Same box, two classes: class-aware NMS keeps both.
        assert np.asarray(valid)[0].sum() == 2
        classes = sorted(np.asarray(dets)[0, :2, 5].astype(int))
        assert classes == [0, 1]

    def test_same_class_overlap_suppressed(self):
        boxes = np.array(
            [[0.0, 0.0, 10.0, 10.0], [1.0, 1.0, 11.0, 11.0]], np.float32
        )
        scores = np.array([[0.9], [0.8]], np.float32)
        dets, valid = extract_boxes_scored(
            jnp.asarray(boxes)[None], jnp.asarray(scores)[None], iou_thresh=0.5
        )
        assert np.asarray(valid)[0].sum() == 1


@pytest.fixture(scope="module")
def tiny_retinanet():
    from triton_client_tpu.models.retinanet import init_retinanet

    return init_retinanet(
        jax.random.PRNGKey(0), num_classes=3, depth="tiny", input_hw=INPUT_HW
    )


@pytest.fixture(scope="module")
def tiny_fcos():
    from triton_client_tpu.models.retinanet import init_fcos

    return init_fcos(
        jax.random.PRNGKey(0), num_classes=3, depth="tiny", input_hw=INPUT_HW
    )


@pytest.mark.slow
class TestRetinaNetModel:
    def test_head_and_decode_shapes(self, tiny_retinanet):
        from triton_client_tpu.models.retinanet import num_locations

        model, variables = tiny_retinanet
        x = jnp.zeros((2, *INPUT_HW, 3))
        logits, deltas = model.apply(variables, x, train=False)
        n = num_locations(INPUT_HW, per_cell=9)
        assert logits.shape == (2, n, 3)
        assert deltas.shape == (2, n, 4)
        boxes, scores = model.decode((logits, deltas))
        assert boxes.shape == (2, n, 4)
        assert scores.shape == (2, n, 3)
        s = np.asarray(scores)
        assert (s > 0).all() and (s < 1).all()
        # Prior-prob bias: initial scores should sit near 0.01, the
        # focal-loss stability condition.
        assert 0.001 < s.mean() < 0.2

    def test_boxes_match_anchor_scale(self, tiny_retinanet):
        model, variables = tiny_retinanet
        x = jnp.zeros((1, *INPUT_HW, 3))
        boxes, _ = model.decode(model.apply(variables, x, train=False))
        b = np.asarray(boxes)[0]
        assert np.isfinite(b).all()
        # Near-zero deltas at init: boxes stay within ~2x the image.
        assert b.min() > -600 and b.max() < 1200


@pytest.mark.slow
class TestFCOSModel:
    def test_shapes_and_ranges(self, tiny_fcos):
        from triton_client_tpu.models.retinanet import num_locations

        model, variables = tiny_fcos
        x = jnp.zeros((1, *INPUT_HW, 3))
        logits, ltrb, ctr = model.apply(variables, x, train=False)
        n = num_locations(INPUT_HW)
        assert logits.shape == (1, n, 3)
        assert ltrb.shape == (1, n, 4)
        assert ctr.shape == (1, n)
        assert (np.asarray(ltrb) >= 0).all()  # distances are relu'd
        boxes, scores = model.decode((logits, ltrb, ctr))
        assert boxes.shape == (1, n, 4)
        s = np.asarray(scores)
        assert (s >= 0).all() and (s <= 1).all()

    def test_fcos_boxes_contain_locations(self, tiny_fcos):
        from triton_client_tpu.ops.anchor_decode import fcos_locations

        model, variables = tiny_fcos
        x = jnp.ones((1, *INPUT_HW, 3))
        boxes, _ = model.decode(model.apply(variables, x, train=False))
        locs = fcos_locations(INPUT_HW)
        b = np.asarray(boxes)[0]
        assert (b[:, 0] <= locs[:, 0] + 1e-3).all()
        assert (b[:, 2] >= locs[:, 0] - 1e-3).all()


@pytest.mark.slow
def test_retinanet_pipeline_end_to_end():
    from triton_client_tpu.pipelines.detect2d import (
        build_retinanet_pipeline,
        detectron_infer_fn,
    )

    pipeline, spec, _ = build_retinanet_pipeline(
        jax.random.PRNGKey(0), num_classes=3, depth="tiny", input_hw=INPUT_HW
    )
    assert [t.name for t in spec.outputs] == ["boxes", "scores", "classes", "dims"]
    frame = np.random.default_rng(0).integers(0, 255, (60, 80, 3)).astype(np.float32)
    dets, valid = pipeline.infer(frame)
    assert dets.shape == (100, 6)
    assert valid.shape == (100,)
    # Detectron wire contract adapter.
    out = detectron_infer_fn(pipeline)({"images": frame[None]})
    assert out["boxes"].shape == (1, 100, 4)
    assert out["classes"].dtype == np.int64
    assert out["dims"].shape == (1,)
    assert out["dims"][0] == np.asarray(valid).sum()
