"""SLO observability ring: histograms, deadline scoring, open-loop
loadgen, and tail-exemplar export.

Covers the PR's acceptance contract:
  * fixed-bucket histogram counts/sums are exact and quantile estimates
    sit within one bucket width of numpy's ground truth, on both a raw
    snapshot and a ``RuntimeCollector.delta`` window;
  * ``poisson_schedule`` is a pure function of its seed (the open-loop
    capacity number is replayable) and ``co_percentile`` ranks the
    never-completed tail as +Inf (coordinated-omission safety);
  * ``SLOTracker`` scores met/missed per (model, priority) with the
    admission-stamped deadline authoritative over wall time, counts
    errors as missed, and retains exemplar traces only for violators
    (or p99+ once the e2e histogram has enough samples);
  * a live localhost server under a generous SLO attains 100% and its
    e2e histogram count reconciles with traces finished; under an
    impossible SLO every request scores missed, the staged launcher
    counts deadline-expired launches, and the violating traces export
    at ``/traces?slo_violations=1``;
  * one open-loop window against the live server completes requests
    and feeds the same histograms.
"""

import json
import math
import threading
import urllib.request

import numpy as np
import pytest

from triton_client_tpu.obs.collector import RuntimeCollector
from triton_client_tpu.obs.histogram import (
    DEFAULT_BUCKETS,
    HistogramFamily,
    LatencyHistogram,
    quantile_from_snapshot,
)
from triton_client_tpu.obs.slo import SLOTracker
from triton_client_tpu.utils.loadgen import (
    OpenLoopResult,
    co_percentile,
    poisson_schedule,
)

jax = pytest.importorskip("jax")


# -- helpers ------------------------------------------------------------------


def _repo(name="double", sleep_s=0.0):
    from triton_client_tpu.config import ModelSpec, TensorSpec
    from triton_client_tpu.runtime.repository import ModelRepository

    spec = ModelSpec(
        name=name,
        version="1",
        inputs=(TensorSpec("x", (-1, 4), "FP32"),),
        outputs=(TensorSpec("y", (-1, 4), "FP32"),),
    )

    def infer(inputs):
        if sleep_s:
            import time

            time.sleep(sleep_s)
        return {"y": np.asarray(inputs["x"]) * 2.0}

    repo = ModelRepository()
    repo.register(spec, infer)
    return repo, spec


def _serving_stack(repo, **server_kw):
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.runtime.batching import BatchingChannel
    from triton_client_tpu.runtime.server import InferenceServer

    chan = BatchingChannel(
        TPUChannel(repo), max_batch=4, timeout_us=2000, merge_hold_us=2000
    )
    server = InferenceServer(
        repo, chan, address="127.0.0.1:0", metrics_port="auto", **server_kw
    )
    server.start()
    return chan, server


def _drive_clients(server, model="double", clients=4, rounds=3):
    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.channel.grpc_channel import GRPCChannel

    x = np.arange(8, dtype=np.float32).reshape(2, 4)

    def one():
        c = GRPCChannel(f"127.0.0.1:{server.port}", timeout_s=30.0)
        try:
            for _ in range(rounds):
                c.do_inference(InferRequest(model, {"x": x}))
        finally:
            c.close()

    threads = [threading.Thread(target=one) for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return clients * rounds


def _bucket_width_at(value):
    """Width of the DEFAULT_BUCKETS bucket containing ``value`` — the
    quantile estimator's error bound."""
    lo = 0.0
    for b in DEFAULT_BUCKETS:
        if value <= b:
            return b - lo
        lo = b
    return float("inf")


# -- histogram primitive ------------------------------------------------------


class TestHistogram:
    def test_counts_and_sum_exact(self):
        h = LatencyHistogram(buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(5.56)
        assert snap["buckets"] == {
            repr(0.01): 2, repr(0.1): 1, repr(1.0): 1, "inf": 1,
        }

    def test_bad_samples_clamp_to_zero(self):
        h = LatencyHistogram(buckets=(0.01, 1.0))
        h.observe(-3.0)
        h.observe(float("nan"))
        snap = h.snapshot()
        assert snap["count"] == 2 and snap["sum"] == 0.0
        assert snap["buckets"][repr(0.01)] == 2

    def test_quantiles_within_bucket_width_of_numpy(self):
        rng = np.random.default_rng(7)
        samples = rng.uniform(0.0005, 0.9, size=2000)
        h = LatencyHistogram()
        for v in samples:
            h.observe(float(v))
        for q in (50, 90, 99):
            true = float(np.percentile(samples, q))
            est = h.quantile(q / 100.0)
            assert abs(est - true) <= _bucket_width_at(true), (q, est, true)

    def test_quantile_in_overflow_returns_largest_bound(self):
        h = LatencyHistogram(buckets=(0.01, 1.0))
        h.observe(50.0)
        assert h.quantile(0.99) == 1.0

    def test_empty_quantile_is_zero(self):
        assert quantile_from_snapshot({"buckets": {}}, 0.99) == 0.0
        assert LatencyHistogram().quantile(0.5) == 0.0

    def test_family_delta_windows_the_histogram(self):
        fam = HistogramFamily()
        for _ in range(100):
            fam.observe("m", "e2e", 0.004)
        snap1 = {"histograms": fam.snapshot()}
        for _ in range(100):
            fam.observe("m", "e2e", 0.4)
        snap2 = {"histograms": fam.snapshot()}
        window = RuntimeCollector.delta(snap2, snap1)["histograms"]["m|e2e"]
        # the window holds ONLY the second batch: its p50 sits in the
        # 0.4-second bucket, nowhere near the first batch's 4 ms
        assert window["count"] == 100
        est = quantile_from_snapshot(window, 0.5)
        assert abs(est - 0.4) <= _bucket_width_at(0.4)
        # while the raw snapshot's p50 straddles both batches
        full = snap2["histograms"]["m|e2e"]
        assert full["count"] == 200

    def test_family_keys_and_accessors(self):
        fam = HistogramFamily()
        fam.observe("m", "e2e", 0.01)
        assert "m|e2e" in fam.snapshot()
        assert fam.count("m", "e2e") == 1
        assert fam.count("m", "absent") == 0
        assert fam.quantile("m", "absent", 0.5) == 0.0


# -- open-loop schedule + CO-safe percentiles ---------------------------------


class TestOpenLoopMath:
    def test_poisson_schedule_is_seed_deterministic(self):
        a_off, a_pick = poisson_schedule(50.0, 2.0, seed=3, weights=[1, 3])
        b_off, b_pick = poisson_schedule(50.0, 2.0, seed=3, weights=[1, 3])
        np.testing.assert_array_equal(a_off, b_off)
        np.testing.assert_array_equal(a_pick, b_pick)
        c_off, _ = poisson_schedule(50.0, 2.0, seed=4, weights=[1, 3])
        assert len(a_off) != len(c_off) or not np.array_equal(a_off, c_off)

    def test_poisson_schedule_rate_and_mix(self):
        off, picks = poisson_schedule(200.0, 5.0, seed=0, weights=[1, 3])
        assert np.all(off < 5.0) and np.all(np.diff(off) >= 0)
        # ~1000 arrivals at 200 qps x 5 s; Poisson sd ~32
        assert 800 <= len(off) <= 1200
        frac = np.mean(picks == 1)
        assert 0.6 <= frac <= 0.9  # 3/4 of the mix, with slack

    def test_poisson_schedule_empty_on_zero_rate(self):
        off, picks = poisson_schedule(0.0, 5.0)
        assert len(off) == 0 and len(picks) == 0

    def test_co_percentile_ranks_missing_tail_as_inf(self):
        lats = [10.0] * 90  # 10 of 100 scheduled never completed
        assert co_percentile(lats, 100, 50.0) == 10.0
        assert co_percentile(lats, 100, 90.0) == 10.0
        assert co_percentile(lats, 100, 99.0) == float("inf")

    def test_open_loop_result_attainment_over_scheduled(self):
        res = OpenLoopResult(
            offered_qps=10.0, scheduled=10, completed=8, wall_s=1.0,
            latencies_ms=[5.0] * 6 + [50.0] * 2,
        )
        # 6 of 10 SCHEDULED within 10 ms — drops are not laundered
        assert res.attainment(10.0) == pytest.approx(0.6)
        assert res.percentile(99.0) == float("inf")
        assert res.achieved_qps == pytest.approx(8.0)


# -- SLO tracker (unit) -------------------------------------------------------


class TestSLOTracker:
    def test_wall_clock_scoring_and_attainment(self):
        t = SLOTracker(slo_ms=100.0)
        assert t.enabled
        t.observe_request("m", wall_s=0.05)
        t.observe_request("m", wall_s=0.25)
        s = t.stats()
        assert s["met"] == 1 and s["missed"] == 1
        assert s["requests"] == {"m|0": {"met": 1, "missed": 1}}
        assert t.attainment() == pytest.approx(0.5)

    def test_deadline_is_authoritative_over_wall(self):
        t = SLOTracker(slo_ms=100.0)
        # tiny wall but the admission deadline has passed: missed
        t.observe_request("m", wall_s=0.001, deadline_s=10.0, now=11.0)
        # long wall but the (stretched) deadline has not: met
        t.observe_request("m", wall_s=5.0, deadline_s=100.0, now=50.0)
        s = t.stats()
        assert s["requests"]["m|0"] == {"met": 1, "missed": 1}

    def test_errors_count_as_missed(self):
        t = SLOTracker(slo_ms=1000.0)
        t.observe_request("m", wall_s=0.001, status="INTERNAL")
        assert t.stats()["missed"] == 1

    def test_per_model_override_and_deadline_for(self):
        t = SLOTracker(slo_ms=100.0, per_model={"fast": 10.0})
        assert t.slo_s("fast") == pytest.approx(0.01)
        assert t.slo_s("other") == pytest.approx(0.1)
        assert t.deadline_for("fast", 5.0) == pytest.approx(5.01)
        none = SLOTracker(slo_ms=0.0)
        assert not none.enabled
        assert none.deadline_for("m", 5.0) is None

    def test_set_budget_arms_a_live_tracker(self):
        t = SLOTracker(slo_ms=0.0)
        t.observe_request("m", wall_s=5.0)  # unscored: no budget yet
        t.set_budget(100.0)
        assert t.enabled
        t.observe_request("m", wall_s=5.0)
        t.set_budget(10_000.0, model="m")  # per-model override wins
        t.observe_request("m", wall_s=5.0)
        s = t.stats()
        assert s["requests"]["m|0"] == {"met": 1, "missed": 1}

    def test_unbudgeted_requests_are_not_scored(self):
        t = SLOTracker(slo_ms=0.0)
        t.observe_request("m", wall_s=99.0)
        s = t.stats()
        assert s["met"] == 0 and s["missed"] == 0 and s["requests"] == {}
        assert t.attainment() == 1.0

    def test_priority_splits_the_counter_key(self):
        t = SLOTracker(slo_ms=100.0)
        t.observe_request("m", wall_s=0.01, priority=0)
        t.observe_request("m", wall_s=0.01, priority=2)
        assert set(t.stats()["requests"]) == {"m|0", "m|2"}

    def test_tail_retains_only_violators(self):
        t = SLOTracker(slo_ms=100.0, tail_capacity=8)
        t.observe_request("m", wall_s=0.01, trace="fast")
        t.observe_request("m", wall_s=0.5, trace="slow")
        assert t.violations() == ["slow"]
        s = t.stats()
        assert s["tail_buffered"] == 1 and s["tail_retained"] == 1

    def test_tail_ring_is_bounded(self):
        t = SLOTracker(slo_ms=1.0, tail_capacity=4)
        for i in range(10):
            t.observe_request("m", wall_s=1.0, trace=i)
        assert t.violations() == [6, 7, 8, 9]
        assert t.violations(2) == [8, 9]
        assert t.stats()["tail_retained"] == 10

    def test_p99_criterion_needs_min_samples_then_retains(self):
        fam = HistogramFamily()
        t = SLOTracker(slo_ms=0.0, histograms=fam)
        # below the sample floor: a slow-but-met request is NOT kept
        for _ in range(50):
            fam.observe("m", "e2e", 0.001)
        t.observe_request("m", wall_s=10.0, trace="early")
        assert t.violations() == []
        # past the floor: at/above live p99 qualifies even when met
        for _ in range(100):
            fam.observe("m", "e2e", 0.001)
        t.observe_request("m", wall_s=10.0, trace="late")
        t.observe_request("m", wall_s=0.0001, trace="fast")
        assert t.violations() == ["late"]


# -- live server --------------------------------------------------------------


class TestLiveServer:
    def test_generous_slo_all_met_and_histograms_reconcile(self):
        pytest.importorskip("grpc")
        pytest.importorskip("prometheus_client")
        repo, spec = _repo()
        chan, server = _serving_stack(repo, slo_ms=60_000.0)
        try:
            n = _drive_clients(server, clients=4, rounds=3)
            s = server.slo.stats()
            assert s["met"] == n and s["missed"] == 0
            assert s["requests"] == {f"{spec.name}|0": {"met": n, "missed": 0}}
            snap = server.collector.snapshot()
            hists = snap["histograms"]
            # every finished trace landed exactly one e2e sample, and
            # the batching path produced the attribution stages
            assert hists[f"{spec.name}|e2e"]["count"] == n
            assert snap["tracer"]["finished"] == n
            for stage in ("queue_delay", "merge_wait", "device_execute"):
                assert hists[f"{spec.name}|{stage}"]["count"] >= 1, stage
            # stage spans nest inside e2e: per-request means must too
            e2e = hists[f"{spec.name}|e2e"]
            q = hists[f"{spec.name}|queue_delay"]
            assert q["sum"] <= e2e["sum"]
            base = f"http://127.0.0.1:{server.metrics_port}"
            text = urllib.request.urlopen(
                base + "/metrics", timeout=10
            ).read().decode()
            assert "# TYPE tpu_serving_latency_seconds histogram" in text
            assert (
                f'tpu_serving_latency_seconds_count'
                f'{{model="{spec.name}",stage="e2e"}} {float(n)}'
            ) in text
            assert (
                f'tpu_serving_slo_requests_total'
                f'{{model="{spec.name}",outcome="met",priority="0"}}'
            ) in text
        finally:
            server.stop()
            chan.close()

    def test_impossible_slo_misses_expires_and_exports_violators(self):
        pytest.importorskip("grpc")
        pytest.importorskip("prometheus_client")
        repo, spec = _repo(sleep_s=0.03)
        chan, server = _serving_stack(repo, slo_ms=1.0)
        try:
            n = _drive_clients(server, clients=4, rounds=2)
            s = server.slo.stats()
            assert s["missed"] == n and s["met"] == 0
            # requests queued behind a 30 ms execution launch after
            # their 1 ms deadline: the staged launcher counted them
            snap = server.collector.snapshot()
            assert snap["channel"]["deadline_expired_launches"] >= 1
            assert s["tail_buffered"] >= 1
            base = f"http://127.0.0.1:{server.metrics_port}"
            doc = json.load(urllib.request.urlopen(
                base + "/traces?slo_violations=1", timeout=10
            ))
            reqs = [
                e for e in doc["traceEvents"]
                if e.get("ph") == "X" and e["name"] == "request"
            ]
            assert len(reqs) == min(n, 64)
        finally:
            server.stop()
            chan.close()

    def test_no_slo_scores_nothing_but_histograms_still_fill(self):
        pytest.importorskip("grpc")
        repo, spec = _repo()
        chan, server = _serving_stack(repo)  # slo_ms defaults to 0
        try:
            n = _drive_clients(server, clients=2, rounds=2)
            s = server.slo.stats()
            assert s["met"] == 0 and s["missed"] == 0
            snap = server.collector.snapshot()
            assert snap["histograms"][f"{spec.name}|e2e"]["count"] == n
        finally:
            server.stop()
            chan.close()


# -- open-loop against the live server ---------------------------------------


@pytest.mark.slow
def test_open_loop_window_feeds_the_ring():
    pytest.importorskip("grpc")
    from triton_client_tpu.utils.loadgen import run_open_loop

    repo, spec = _repo()
    chan, server = _serving_stack(repo, slo_ms=30_000.0)
    try:
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        res = run_open_loop(
            f"127.0.0.1:{server.port}",
            [(spec.name, {"x": x})],
            rate_qps=40.0,
            duration_s=1.5,
            seed=5,
            deadline_s=30.0,
        )
        # the schedule is the seed's: same seed, same population
        off, _ = poisson_schedule(40.0, 1.5, seed=5, weights=[1.0])
        assert res.scheduled == len(off)
        assert res.completed == res.scheduled, res.errors
        assert math.isfinite(res.percentile(99.0))
        assert res.attainment(30_000.0) == 1.0
        # server side scored and measured the same population (+1 warm)
        assert server.slo.stats()["met"] == res.scheduled + 1
        snap = server.collector.snapshot()
        assert snap["histograms"][f"{spec.name}|e2e"]["count"] == (
            res.scheduled + 1
        )
    finally:
        server.stop()
        chan.close()
