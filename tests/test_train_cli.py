"""train CLI: loss decreases, checkpoints land, export serves."""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_train_cli_synthetic_checkpoint_and_export(tmp_path, capsys):
    from triton_client_tpu.cli.train import main

    ckpt = tmp_path / "ckpts"
    repo = tmp_path / "repo"
    main(
        [
            "-i", "synthetic:8:64x64",
            "--input-size", "64",
            "-c", "2",
            "-b", str(len(jax.devices())),
            "--steps", "4",
            "--mesh", f"data={len(jax.devices())}",
            "--checkpoint-dir", str(ckpt),
            "--save-every", "2",
            "--export", str(repo),
            "-m", "trained_tiny",
            "--log-every", "2",
        ]
    )
    out = capsys.readouterr().out
    assert "step 4/4" in out
    assert "exported" in out

    from triton_client_tpu.runtime.checkpoint import CheckpointManager

    assert CheckpointManager(str(ckpt)).latest_step() == 4

    from triton_client_tpu.runtime import disk_repository as dr

    served = dr.scan_disk(repo)
    assert served.list_models() == [("trained_tiny", "1")]
    got = served.get("trained_tiny").infer_fn(
        {"images": np.zeros((1, 64, 64, 3), np.float32)}
    )
    assert got["detections"].shape[-1] == 6


def test_train_cli_gt_jsonl_and_resume(tmp_path, capsys):
    from triton_client_tpu.cli.train import main

    gt = tmp_path / "gt.jsonl"
    with open(gt, "w") as f:
        for i in range(8):
            f.write(json.dumps(
                {"frame_id": i, "boxes": [[8, 8, 40, 40, 1]]}
            ) + "\n")
    ckpt = tmp_path / "ckpts"
    base = [
        "-i", "synthetic:8:64x64",
        "--input-size", "64",
        "-c", "2",
        "-b", "2",
        "--mesh", "data=2",
        "--gt", str(gt),
        "--checkpoint-dir", str(ckpt),
        "--save-every", "2",
        "--log-every", "1",
    ]
    main(base + ["--steps", "2"])
    capsys.readouterr()
    main(base + ["--steps", "4", "--resume"])
    out = capsys.readouterr().out
    assert "resumed from step 2" in out
    assert "step 4/4" in out


def test_train_cli_rejects_indivisible_batch():
    from triton_client_tpu.cli.train import main

    with pytest.raises(SystemExit, match="divide"):
        main(["-b", "3", "--mesh", "data=2", "--steps", "1"])


def _loader_args(frames_dir):
    import types

    return types.SimpleNamespace(
        input=str(frames_dir), input_size=64, batch_size=4,
        max_boxes=4, classes=2, gt="",
    )


def _write_frames(frames_dir, n=8):
    cv2 = pytest.importorskip("cv2")

    frames_dir.mkdir(exist_ok=True)
    for i in range(n):
        cv2.imwrite(
            str(frames_dir / f"{i:02d}.png"),
            np.full((64, 64, 3), i * 30, np.uint8),
        )


def _vals(images):
    # loader normalizes to [0,1]; recover the written frame index marker
    return [int(round(float(im[0, 0, 0]) * 255)) for im in images]


def test_load_batches_shared_source_windows_global_batch(tmp_path):
    """Multi-host shared source: host p decodes rows [p*per_host,
    (p+1)*per_host) of a stream that advances by the GLOBAL batch, so
    hosts see disjoint frames and no frame is decoded twice."""
    from triton_client_tpu.cli.train import _load_batches

    _write_frames(tmp_path / "frames")
    args = _loader_args(tmp_path / "frames")
    # host 1 of 2: per_host=2, row0=2
    batches = _load_batches(args, np.random.default_rng(0), row0=2, rows=2)
    first, _ = next(batches)
    second, _ = next(batches)
    assert first.shape[0] == 2
    assert _vals(first) == [60, 90]     # rows 2,3 of global batch 0
    assert _vals(second) == [180, 210]  # rows 2,3 of global batch 1


def test_load_batches_per_host_source_consumes_every_frame(tmp_path):
    """--per-host-source: the stream advances by per_host only, so a
    host pointed at its own cameras/bags consumes every frame (the
    ADVICE.md round-1 finding: a global stride here would silently
    discard (P-1)/P of each host's frames)."""
    from triton_client_tpu.cli.train import _load_batches

    _write_frames(tmp_path / "frames")
    args = _loader_args(tmp_path / "frames")
    batches = _load_batches(
        args, np.random.default_rng(0), row0=0, rows=2, stride=2
    )
    seen = []
    for _ in range(4):
        images, _ = next(batches)
        seen += _vals(images)
    assert seen == [0, 30, 60, 90, 120, 150, 180, 210]
