"""train CLI: loss decreases, checkpoints land, export serves."""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_train_cli_synthetic_checkpoint_and_export(tmp_path, capsys):
    from triton_client_tpu.cli.train import main

    ckpt = tmp_path / "ckpts"
    repo = tmp_path / "repo"
    main(
        [
            "-i", "synthetic:8:64x64",
            "--input-size", "64",
            "-c", "2",
            "-b", str(len(jax.devices())),
            "--steps", "4",
            "--mesh", f"data={len(jax.devices())}",
            "--checkpoint-dir", str(ckpt),
            "--save-every", "2",
            "--export", str(repo),
            "-m", "trained_tiny",
            "--log-every", "2",
        ]
    )
    out = capsys.readouterr().out
    assert "step 4/4" in out
    assert "exported" in out

    from triton_client_tpu.runtime.checkpoint import CheckpointManager

    assert CheckpointManager(str(ckpt)).latest_step() == 4

    from triton_client_tpu.runtime import disk_repository as dr

    served = dr.scan_disk(repo)
    assert served.list_models() == [("trained_tiny", "1")]
    got = served.get("trained_tiny").infer_fn(
        {"images": np.zeros((1, 64, 64, 3), np.float32)}
    )
    assert got["detections"].shape[-1] == 6


def test_train_cli_gt_jsonl_and_resume(tmp_path, capsys):
    from triton_client_tpu.cli.train import main

    gt = tmp_path / "gt.jsonl"
    with open(gt, "w") as f:
        for i in range(8):
            f.write(json.dumps(
                {"frame_id": i, "boxes": [[8, 8, 40, 40, 1]]}
            ) + "\n")
    ckpt = tmp_path / "ckpts"
    base = [
        "-i", "synthetic:8:64x64",
        "--input-size", "64",
        "-c", "2",
        "-b", "2",
        "--mesh", "data=2",
        "--gt", str(gt),
        "--checkpoint-dir", str(ckpt),
        "--save-every", "2",
        "--log-every", "1",
    ]
    main(base + ["--steps", "2"])
    capsys.readouterr()
    main(base + ["--steps", "4", "--resume"])
    out = capsys.readouterr().out
    assert "resumed from step 2" in out
    assert "step 4/4" in out


def test_train_cli_rejects_indivisible_batch():
    from triton_client_tpu.cli.train import main

    with pytest.raises(SystemExit, match="divide"):
        main(["-b", "3", "--mesh", "data=2", "--steps", "1"])
