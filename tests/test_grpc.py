"""KServe v2 gRPC façade: codec round-trips and a live loopback server.

The reference's transport is tritonclient gRPC against a remote Triton
(communicator/channel/grpc_channel.py); here the same protocol is
served in-tree (runtime/server.py) and consumed by GRPCChannel, so the
test drives a real localhost RPC round-trip over the registered model.
"""

import numpy as np
import pytest

from triton_client_tpu.channel.base import InferRequest
from triton_client_tpu.channel.grpc_channel import GRPCChannel
from triton_client_tpu.channel.kserve import codec, pb
from triton_client_tpu.channel.tpu_channel import TPUChannel
from triton_client_tpu.config import ModelSpec, TensorSpec
from triton_client_tpu.runtime.repository import ModelRepository
from triton_client_tpu.runtime.server import InferenceServer, message_limit


def _spec():
    return ModelSpec(
        name="addone",
        version="1",
        platform="jax",
        inputs=(TensorSpec("x", (-1, 4), "FP32"),),
        outputs=(TensorSpec("y", (-1, 4), "FP32"),),
        max_batch_size=8,
    )


def _repo():
    repo = ModelRepository()
    repo.register(_spec(), lambda inputs: {"y": np.asarray(inputs["x"]) + 1.0})
    return repo


class TestCodec:
    def test_roundtrip_dtypes(self, rng):
        for dtype in [np.float32, np.float16, np.int32, np.int64, np.uint8]:
            arr = rng.normal(0, 10, (3, 5)).astype(dtype)
            raw = codec.serialize_tensor(arr)
            back = codec.deserialize_tensor(raw, codec.datatype_of(arr), arr.shape)
            np.testing.assert_array_equal(arr, back)

    def test_request_roundtrip(self, rng):
        inputs = {
            "images": rng.random((2, 8, 8, 3)).astype(np.float32),
            "count": np.array([7], np.int32),
        }
        req = codec.build_infer_request("m", inputs, request_id="42")
        wire = pb.ModelInferRequest.FromString(req.SerializeToString())
        parsed = codec.parse_infer_request(wire)
        assert set(parsed) == set(inputs)
        for k in inputs:
            np.testing.assert_array_equal(parsed[k], inputs[k])

    def test_zero_copy_deserialize(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        back = codec.deserialize_tensor(arr.tobytes(), "FP32", (3, 4))
        assert not back.flags.writeable  # view over the wire buffer

    def test_roundtrip_matrix_every_config_dtype(self, rng):
        """Every entry in the canonical dtype table round-trips bitwise
        — including the precision-policy wire dtypes: BF16 (ml_dtypes;
        the bf16 policy's wire words) and INT8 (the int8 policy's
        quantized activations) — and the deserialize side stays a
        zero-copy view over the wire buffer."""
        import ml_dtypes

        from triton_client_tpu.config import config_dtypes

        for datatype, np_dtype in config_dtypes().items():
            dtype = (
                np.dtype(ml_dtypes.bfloat16)
                if np_dtype is None  # the BF16 entry
                else np.dtype(np_dtype)
            )
            if dtype == np.bool_:
                arr = rng.random((3, 5)) > 0.5
            elif np.issubdtype(dtype, np.floating) or np_dtype is None:
                arr = rng.normal(0, 10, (3, 5)).astype(dtype)
            else:
                info = np.iinfo(dtype)
                arr = rng.integers(
                    max(info.min, -100), min(info.max, 100) + 1, (3, 5)
                ).astype(dtype)
            assert codec.datatype_of(arr) == datatype
            raw = codec.serialize_tensor(arr)
            assert len(raw) == arr.nbytes
            back = codec.deserialize_tensor(raw, datatype, arr.shape)
            assert back.dtype == dtype
            np.testing.assert_array_equal(
                back.view(np.uint8), arr.view(np.uint8)
            )
            # np.frombuffer view over the wire bytes, never a copy:
            # read-only, backed by the buffer object itself
            assert not back.flags.writeable, datatype
            assert back.base is not None, datatype
            assert np.shares_memory(
                back, np.frombuffer(raw, np.uint8)
            ), datatype

    def test_mismatched_raw_buffers_rejected(self):
        req = pb.ModelInferRequest(model_name="m")
        req.inputs.add(name="x", datatype="FP32", shape=[1])
        with pytest.raises(ValueError):
            codec.parse_infer_request(req)


class TestLoopbackServer:
    @pytest.fixture()
    def server_and_channel(self):
        repo = _repo()
        server = InferenceServer(
            repo, TPUChannel(repo), address="127.0.0.1:0", max_workers=2
        )
        server.start()
        channel = GRPCChannel(f"127.0.0.1:{server.port}", timeout_s=10.0)
        yield server, channel
        channel.close()
        server.stop()

    def test_health_and_metadata(self, server_and_channel):
        _, channel = server_and_channel
        assert channel.server_live()
        spec = channel.get_metadata("addone")
        assert spec.name == "addone"
        assert [t.name for t in spec.inputs] == ["x"]
        assert spec.inputs[0].dtype == "FP32"
        assert spec.max_batch_size == 8

    def test_infer_roundtrip(self, server_and_channel, rng):
        _, channel = server_and_channel
        x = rng.random((2, 4)).astype(np.float32)
        resp = channel.do_inference(
            InferRequest(model_name="addone", inputs={"x": x}, request_id="7")
        )
        np.testing.assert_allclose(resp.outputs["y"], x + 1.0, rtol=1e-6)
        assert resp.request_id == "7"

    def test_infer_unknown_model_raises(self, server_and_channel):
        import grpc

        _, channel = server_and_channel
        with pytest.raises(grpc.RpcError):
            channel.do_inference(
                InferRequest(
                    model_name="nope", inputs={"x": np.zeros((1, 4), np.float32)}
                )
            )

    def test_streaming(self, server_and_channel, rng):
        _, channel = server_and_channel
        frames = [rng.random((1, 4)).astype(np.float32) for _ in range(3)]
        reqs = (
            InferRequest(model_name="addone", inputs={"x": f}, request_id=str(i))
            for i, f in enumerate(frames)
        )
        outs = list(channel.infer_stream(reqs))
        assert len(outs) == 3
        for i, (frame, out) in enumerate(zip(frames, outs)):
            np.testing.assert_allclose(out.outputs["y"], frame + 1.0, rtol=1e-6)
            assert out.request_id == str(i)


def test_message_limit_scales_with_specs():
    repo = _repo()
    assert message_limit(repo) >= 64 << 20
    big = ModelSpec(
        name="big",
        inputs=(TensorSpec("x", (3, 2048, 2048), "FP32"),),
        outputs=(TensorSpec("y", (3, 2048, 2048), "FP32"),),
        max_batch_size=4,
    )
    repo.register(big, lambda i: i)
    assert message_limit(repo) >= 2 * 2 * 4 * 3 * 2048 * 2048 * 4
