"""Evaluator: AP math, greedy matching, aggregation (parity targets:
communicator/evaluate_inference.py:131-218,400-446)."""

import numpy as np
import pytest

from triton_client_tpu.eval import (
    DetectionEvaluator,
    ap_per_class,
    compute_ap,
    match_predictions,
)
from triton_client_tpu.eval.detection_map import IOU_THRESHOLDS, box_iou_np


def test_box_iou_np():
    a = np.array([[0, 0, 10, 10]], np.float64)
    b = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]], np.float64)
    iou = box_iou_np(a, b)
    np.testing.assert_allclose(iou[0], [1.0, 25 / 175, 0.0], atol=1e-9)


def test_compute_ap_perfect_detector():
    # One TP covering all GT: recall hits 1.0 at precision 1.0. The
    # 101-pt trapz with the closing (1.0 -> precision 0) sentinel gives
    # 1 - 0.005 (half of the last 0.01 bin), the COCO-interp ceiling.
    ap = compute_ap(np.array([1.0]), np.array([1.0]))
    assert ap == pytest.approx(0.995, abs=1e-6)


def test_compute_ap_monotone_envelope():
    # Precision dips are flattened by the running-max envelope.
    recall = np.array([0.2, 0.4, 0.6, 0.8, 1.0])
    precision = np.array([1.0, 0.4, 0.9, 0.4, 0.9])
    ap = compute_ap(recall, precision)
    # Envelope makes precision >= 0.9 up to recall 1.0.
    assert 0.89 < ap < 0.96


def test_match_predictions_basic():
    gt = np.array([[0, 0, 10, 10]], np.float64)
    gt_cls = np.array([1.0])
    preds = np.array([[0, 0, 10, 10], [0.5, 0, 10.5, 10], [20, 20, 30, 30]])
    pred_cls = np.array([1.0, 1.0, 1.0])
    correct = match_predictions(preds, pred_cls, gt, gt_cls)
    assert correct.shape == (3, 10)
    # Only the best-IoU detection matches the single gt.
    assert correct[0].all()
    assert not correct[1].any()
    assert not correct[2].any()


def test_match_predictions_class_gate():
    gt = np.array([[0, 0, 10, 10]], np.float64)
    preds = np.array([[0, 0, 10, 10]])
    correct = match_predictions(preds, np.array([2.0]), gt, np.array([1.0]))
    assert not correct.any()


def test_match_predictions_iou_ladder():
    # IoU ~0.667 clears thresholds 0.5-0.65 only.
    gt = np.array([[0, 0, 10, 10]], np.float64)
    preds = np.array([[0, 2, 10, 12]])  # inter 80, union 120
    correct = match_predictions(preds, np.array([0.0]), gt, np.array([0.0]))
    want = (80 / 120) >= IOU_THRESHOLDS
    np.testing.assert_array_equal(correct[0], want)


def test_ap_per_class_perfect():
    tp = np.ones((4, 10), bool)
    conf = np.array([0.9, 0.8, 0.7, 0.6])
    cls = np.array([0.0, 0.0, 1.0, 1.0])
    p, r, ap, f1, classes = ap_per_class(tp, conf, cls, cls)
    np.testing.assert_array_equal(classes, [0, 1])
    assert ap[:, 0] == pytest.approx([0.995, 0.995], abs=1e-6)
    assert p == pytest.approx([1.0, 1.0])
    assert r == pytest.approx([1.0, 1.0])
    assert f1 == pytest.approx([1.0, 1.0], abs=1e-3)


def test_ap_per_class_all_false_positives():
    tp = np.zeros((3, 10), bool)
    conf = np.array([0.9, 0.8, 0.7])
    pred_cls = np.zeros(3)
    target_cls = np.zeros(5)
    p, r, ap, f1, classes = ap_per_class(tp, conf, pred_cls, target_cls)
    assert ap[0, 0] == pytest.approx(0.0, abs=1e-6)
    assert r[0] == pytest.approx(0.0)


def test_evaluator_end_to_end_perfect():
    ev = DetectionEvaluator()
    rng = np.random.default_rng(0)
    for _ in range(5):
        n = rng.integers(1, 6)
        xy = rng.uniform(0, 400, (n, 2))
        wh = rng.uniform(20, 80, (n, 2))
        cls = rng.integers(0, 3, n).astype(np.float64)
        gts = np.concatenate([xy, xy + wh, cls[:, None]], axis=1)
        dets = np.concatenate(
            [xy, xy + wh, np.full((n, 1), 0.9), cls[:, None]], axis=1
        )
        ev.add_frame(dets, None, gts)
    s = ev.summary()
    assert s["frames"] == 5
    assert s["map50"] == pytest.approx(0.995, abs=1e-3)
    assert s["map"] == pytest.approx(0.995, abs=1e-3)
    assert s["precision"] == pytest.approx(1.0, abs=1e-6)


def test_evaluator_mixed_quality():
    ev = DetectionEvaluator()
    gts = np.array([[0, 0, 100, 100, 0], [200, 200, 300, 300, 0]], np.float64)
    # one perfect, one badly offset (IoU < 0.5), one false positive
    dets = np.array(
        [
            [0, 0, 100, 100, 0.9, 0],
            [260, 260, 360, 360, 0.8, 0],
            [400, 400, 450, 450, 0.7, 0],
        ]
    )
    ev.add_frame(dets, None, gts)
    s = ev.summary()
    assert 0.2 < s["map50"] < 0.6  # 1 of 2 gts found
    assert s["recall"] == pytest.approx(0.5, abs=0.01)


def test_evaluator_valid_mask_and_empty_frames():
    ev = DetectionEvaluator()
    gts = np.array([[0, 0, 10, 10, 1]], np.float64)
    dets = np.array([[0, 0, 10, 10, 0.9, 1], [0, 0, 0, 0, 0.0, 0]])
    valid = np.array([True, False])
    ev.add_frame(dets, valid, gts)
    ev.add_frame(np.zeros((0, 6)), None, np.zeros((0, 5)))
    s = ev.summary()
    assert s["map50"] == pytest.approx(0.995, abs=1e-3)


def test_prometheus_exporter_gated():
    from triton_client_tpu.eval import prometheus_export

    if not prometheus_export.available():
        pytest.skip("prometheus_client not installed")
    ex = prometheus_export.EvalPrometheusExporter(start_server=False)
    ev = DetectionEvaluator()
    gts = np.array([[0, 0, 10, 10, 0]], np.float64)
    dets = np.array([[0, 0, 10, 10, 0.9, 0]])
    ev.add_frame(dets, None, gts)
    for frame_stats in ev.per_frame_summaries():
        ex.observe(*frame_stats)
    collected = {m.name for m in ex.registry.collect()}
    assert "model_precision" in collected
    assert "model_f1" in collected
